"""Token-lease fast path tests (core/lease.py — SURVEY §7 hard part #1).

Host-side admission must be device-exact for eligible resources, stream
its statistics to the device, and conservatively refuse every case where
another rule family (or another process) could see different state.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.lease import LocalLease, build_lease_table


def _leased(engine, resource):
    return resource in engine._leases


def test_simple_qps_rule_is_leased(engine):
    st.load_flow_rules([st.FlowRule(resource="fast", count=5)])
    assert _leased(engine, "fast")


def test_ineligible_shapes_stay_on_device_path(engine):
    st.load_flow_rules([
        st.FlowRule(resource="wrl", count=5,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER),
        st.FlowRule(resource="rlim", count=5,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER),
        st.FlowRule(resource="thr", count=5, grade=C.FLOW_GRADE_THREAD),
        st.FlowRule(resource="orig", count=5, limit_app="appA"),
        st.FlowRule(resource="clus", count=5, cluster_mode=True,
                    cluster_config={"flowId": 1}),
        st.FlowRule(resource="rel", count=5,
                    strategy=C.FLOW_STRATEGY_RELATE, ref_resource="ref"),
        st.FlowRule(resource="ref", count=5),  # RELATE target
        st.FlowRule(resource="ok", count=5),
        # WARM_UP is leaseable since ISSUE 8 (ROADMAP 3c)
        st.FlowRule(resource="warm", count=5,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP),
    ])
    for r in ("wrl", "rlim", "thr", "orig", "clus", "rel", "ref"):
        assert not _leased(engine, r), r
    assert _leased(engine, "ok")
    assert _leased(engine, "warm")


def test_other_rule_families_disable_lease(engine):
    st.load_flow_rules([st.FlowRule(resource="d", count=5),
                        st.FlowRule(resource="p", count=5)])
    assert _leased(engine, "d") and _leased(engine, "p")
    st.load_degrade_rules([st.DegradeRule(resource="d", count=1,
                                          time_window=5)])
    assert not _leased(engine, "d")
    assert _leased(engine, "p")
    # ONE QPS/DEFAULT param rule is leaseable since ISSUE 8; shapes the
    # host mirror cannot serve still force the device path:
    st.load_param_flow_rules([st.ParamFlowRule("p", param_idx=0, count=5)])
    assert _leased(engine, "p")
    st.load_param_flow_rules([  # two rules on one resource
        st.ParamFlowRule("p", param_idx=0, count=5),
        st.ParamFlowRule("p", param_idx=1, count=9),
    ])
    assert not _leased(engine, "p")
    st.load_param_flow_rules([st.ParamFlowRule(  # THREAD grade
        "p", param_idx=0, count=5, grade=C.PARAM_FLOW_GRADE_THREAD)])
    assert not _leased(engine, "p")
    st.load_param_flow_rules([st.ParamFlowRule(  # per-value pacing
        "p", param_idx=0, count=5,
        control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER)])
    assert not _leased(engine, "p")
    st.load_param_flow_rules([st.ParamFlowRule(  # cluster mode
        "p", param_idx=0, count=5, cluster_mode=True,
        cluster_config={"flowId": 9})])
    assert not _leased(engine, "p")


def test_system_rules_disable_all_leases(engine):
    st.load_flow_rules([st.FlowRule(resource="s", count=5)])
    assert _leased(engine, "s")
    st.load_system_rules([st.SystemRule(qps=1e6)])
    assert not _leased(engine, "s")
    st.load_system_rules([])
    assert _leased(engine, "s")


def test_lease_admission_is_exact(engine, frozen_time):
    """Same verdicts as the device DEFAULT controller, serially exact."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=3)])
    got = [bool(st.entry_ok("fast")) for _ in range(6)]
    assert got == [True] * 3 + [False] * 3
    frozen_time.advance_time(1100)  # window rolls -> quota refreshed
    assert st.entry_ok("fast")


def test_lease_stats_reach_the_device(engine, frozen_time):
    """Leased admissions + exits land in device stats (flush-on-read)."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=3)])
    for _ in range(5):
        h = st.entry_ok("fast")
        if h:
            h.exit()
    snap = engine.node_snapshot()["fast"]
    assert snap["passQps"] == 3
    assert snap["blockQps"] == 2
    assert snap["successQps"] == 3
    assert snap["curThreadNum"] == 0


def test_lease_blocks_feed_metric_log(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="fast", count=1)])
    for _ in range(3):
        st.entry_ok("fast")
    frozen_time.advance_time(2000)
    lines = [str(n) for n in engine.seal_metrics()]
    assert any("fast" in ln for ln in lines)


def test_device_path_verdicts_keep_mirror_in_sync(engine, frozen_time):
    """Entries served while the PIPELINE owns admission must still count
    against the lease mirror once the pipeline stops."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=2)])
    engine.start_pipeline()
    assert st.entry_ok("fast") is not None  # device path (pipeline)
    engine.stop_pipeline()
    assert st.entry_ok("fast") is not None  # lease path
    assert st.entry_ok("fast") is None      # quota shared across modes


def test_mixed_rules_on_one_resource_disable_lease(engine):
    st.load_flow_rules([
        st.FlowRule(resource="mix", count=100),
        st.FlowRule(resource="mix", count=50,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER),
    ])
    assert not _leased(engine, "mix")


def test_multiple_default_rules_all_enforced(engine, frozen_time):
    st.load_flow_rules([
        st.FlowRule(resource="two", count=10),
        st.FlowRule(resource="two", count=4),
    ])
    assert _leased(engine, "two")
    got = sum(1 for _ in range(8) if st.entry_ok("two"))
    assert got == 4  # tightest rule wins


def test_local_lease_window_mirror_math():
    lease = LocalLease([3.0], interval_ms=1000, buckets=2)
    t0 = 1_700_000_000_000
    assert all(lease.try_acquire(1, t0) for _ in range(3))
    assert not lease.try_acquire(1, t0)
    # sliding, not tumbling: 500ms later the first bucket still counts
    assert not lease.try_acquire(1, t0 + 500)
    # 1s later the old bucket expired
    assert lease.try_acquire(1, t0 + 1000)


def _python_ring(thresholds, interval_ms, buckets) -> LocalLease:
    lease = LocalLease.__new__(LocalLease)
    lease.thresholds = thresholds
    lease.interval_ms = interval_ms
    lease.buckets = buckets
    lease.bucket_ms = interval_ms // buckets
    lease._counts = [0] * buckets
    lease._starts = [-1] * buckets
    import threading

    lease._lock = threading.Lock()
    lease._ring = None  # force the pure-Python path
    return lease


def test_native_ring_matches_python_ring_differentially():
    """The C extension ring (native/lease_ext.c) and the Python fallback
    must make IDENTICAL decisions on identical traffic — randomized
    acquire/add/rotation sequences, compared call by call."""
    import random

    from sentinel_tpu.native import load_lease_ext

    if load_lease_ext() is None:
        pytest.skip("native lease extension unavailable")
    rng = random.Random(7)
    for trial in range(20):
        buckets = rng.choice([1, 2, 4, 5])
        interval = buckets * rng.choice([100, 250, 500])
        thresholds = [float(rng.randint(1, 30))
                      for _ in range(rng.randint(1, 3))]
        native = LocalLease(thresholds, interval, buckets)
        if native._ring is None:
            pytest.skip("native lease extension unavailable")
        oracle = _python_ring(thresholds, interval, buckets)
        now = 1_700_000_000_000
        for step in range(300):
            now += rng.choice([0, 1, 7, interval // buckets,
                               interval, 3 * interval])
            op = rng.random()
            count = rng.randint(1, 3)
            if op < 0.75:
                got = native.try_acquire(count, now)
                want = oracle.try_acquire(count, now)
                assert got == want, (trial, step, thresholds, interval)
            elif op < 0.9:
                native.add(count, now)
                oracle.add(count, now)
            else:
                assert native.usage(now) == pytest.approx(
                    oracle.usage(now)), (trial, step)
        assert native.snapshot() == (oracle._starts, oracle._counts)


def test_native_ring_seed_and_snapshot_round_trip():
    from sentinel_tpu.native import load_lease_ext

    if load_lease_ext() is None:
        pytest.skip("native lease extension unavailable")
    lease = LocalLease([100.0], 1000, 2)
    lease.seed([1_700_000_000_000, 1_699_999_999_500], [5, 7])
    assert lease.snapshot() == ([1_700_000_000_000, 1_699_999_999_500],
                                [5, 7])
    # geometry-mismatched seeds drop, like the Python ring
    lease.seed([0], [1])
    assert lease.snapshot() == ([1_700_000_000_000, 1_699_999_999_500],
                                [5, 7])


def test_auto_context_pooled_per_thread(engine, frozen_time):
    """entry_ok() with no explicit context reuses ONE pooled auto
    context per thread (r5 fast-path optimization) — but an explicit
    context is never pooled, and an engine reset invalidates the pool
    via the generation stamp."""
    from sentinel_tpu.core import context as ctx_mod

    st.load_flow_rules([st.FlowRule(resource="pool", count=1e9)])
    h1 = st.entry_ok("pool")
    ctx1 = h1.context
    h1.exit()
    assert ctx_mod.get_context() is None  # auto context detached on exit
    h2 = st.entry_ok("pool")
    ctx2 = h2.context
    h2.exit()
    assert ctx1 is ctx2  # pooled: same object reused
    assert ctx1.entrance_row >= 0  # entrance resolution cached with it

    # explicit contexts bypass the pool
    st.context_enter("my_ctx")
    h3 = st.entry_ok("pool")
    assert h3.context is not ctx1 and h3.context.name == "my_ctx"
    h3.exit()
    st.exit_context()

    # engine reset -> generation bump -> pooled context discarded
    st.reset(capacity=512)
    st.load_flow_rules([st.FlowRule(resource="pool", count=1e9)])
    h4 = st.entry_ok("pool")
    assert h4.context is not ctx1
    h4.exit()


def test_lease_disabled_by_config(engine, monkeypatch):
    from sentinel_tpu.core.config import config

    monkeypatch.setenv("CSP_SENTINEL_LEASE_ENABLED", "false")
    config.reset_for_tests()
    try:
        eng = st.reset(capacity=256)
        st.load_flow_rules([st.FlowRule(resource="fast", count=5)])
        assert not eng._leases
    finally:
        monkeypatch.delenv("CSP_SENTINEL_LEASE_ENABLED")
        config.reset_for_tests()
        st.reset(capacity=256)


def test_lease_latency_is_sub_millisecond(engine, frozen_time):
    """The point of the feature: admission without a device dispatch."""
    import time as _time

    st.load_flow_rules([st.FlowRule(resource="fast", count=10_000_000)])
    h = st.entry_ok("fast")  # absorb any lazy init
    if h:
        h.exit()
    t0 = _time.perf_counter()
    n = 200
    for _ in range(n):
        h = st.entry_ok("fast")
        if h:
            h.exit()
    per_entry_us = (_time.perf_counter() - t0) / n * 1e6
    assert per_entry_us < 1000, f"leased entry took {per_entry_us:.0f}µs"


def test_rule_push_does_not_regrant_spent_quota(engine, frozen_time):
    """Rebuilding leases on a rule push must carry the mirror over —
    a zeroed mirror would admit 2x the quota in the current window."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=3)])
    assert sum(1 for _ in range(3) if st.entry_ok("fast")) == 3
    # unrelated rule push for ANOTHER family rebuilds the lease table
    st.load_degrade_rules([st.DegradeRule(resource="other", count=1,
                                          time_window=5)])
    assert _leased(engine, "fast")
    assert st.entry_ok("fast") is None  # quota still spent


def test_newly_eligible_resource_seeds_from_device_window(engine,
                                                          frozen_time):
    """A resource that WAS ineligible (device path) and becomes eligible
    must inherit the device window, not a zero mirror."""
    st.load_flow_rules([
        st.FlowRule(resource="born", count=3),
        st.FlowRule(resource="born", count=3,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                    max_queueing_time_ms=0),
    ])
    assert not _leased(engine, "born")
    assert st.entry_ok("born") is not None  # device path, 1 pass committed
    # drop the pacing rule: resource becomes lease-eligible
    st.load_flow_rules([st.FlowRule(resource="born", count=3)])
    assert _leased(engine, "born")
    got = sum(1 for _ in range(4) if st.entry_ok("born"))
    assert got == 2  # 1 device-path pass + 2 leased = 3 total, 4th blocks


def test_leases_ops_command(engine, frozen_time):
    """The `leases` command exposes fast-path membership + live usage."""
    import json
    import urllib.request

    from sentinel_tpu.transport.command_center import CommandCenter

    st.load_flow_rules([st.FlowRule(resource="fast", count=10)])
    for _ in range(4):
        h = st.entry_ok("fast")
        if h:
            h.exit()
    center = CommandCenter(engine, port=0).start()
    try:
        url = f"http://127.0.0.1:{center.bound_port}/leases"
        with urllib.request.urlopen(url, timeout=5) as r:
            out = json.loads(r.read().decode())
        assert out["enabled"] is True
        row = out["resources"]["fast"]
        assert row["thresholds"] == [10.0]
        assert row["usageQps"] == 4.0
    finally:
        center.stop()


def test_unruled_resource_skips_device_dispatch(engine, frozen_time):
    """A resource with NO rules always passes host-side; stats converge."""
    import time as _time

    h = st.entry_ok("free")  # absorb committer start
    if h:
        h.exit()
    t0 = _time.perf_counter()
    for _ in range(100):
        h = st.entry_ok("free")
        if h:
            h.exit()
    per_entry_us = (_time.perf_counter() - t0) / 100 * 1e6
    assert per_entry_us < 1000, f"unruled entry took {per_entry_us:.0f}µs"
    snap = engine.node_snapshot()["free"]
    assert snap["passQps"] == 101
    assert snap["curThreadNum"] == 0


def test_unruled_relate_ref_stays_on_device_path(engine, frozen_time):
    """An unruled resource another rule RELATEs to must keep committing
    synchronously — its window feeds that rule's device check."""
    st.load_flow_rules([
        st.FlowRule(resource="write_db", count=3,
                    strategy=C.FLOW_STRATEGY_RELATE, ref_resource="read_db")
    ])
    assert "read_db" in engine._guarded_resources
    for _ in range(4):  # read_db busy: must be visible IMMEDIATELY
        with st.entry("read_db"):
            pass
    with pytest.raises(st.FlowException):
        st.entry("write_db")


def test_system_rules_disable_unruled_fastpath(engine):
    assert engine._unruled_fastpath
    st.load_system_rules([st.SystemRule(qps=10)])
    assert not engine._unruled_fastpath
    st.load_system_rules([])
    assert engine._unruled_fastpath


def test_rule_on_previously_unruled_resource_counts_queued_traffic(
        engine, frozen_time):
    """Un-flushed always-pass commits must count when a rule first lands
    on the resource — otherwise the brand-new limit over-admits."""
    for _ in range(5):  # unruled fast path: commits queue in the committer
        h = st.entry_ok("newly")
        if h:
            h.exit()
    # push a rule WITHOUT flushing: seeding must add the queued 5
    st.load_flow_rules([st.FlowRule(resource="newly", count=6)])
    assert "newly" in engine._leases
    got = sum(1 for _ in range(4) if st.entry_ok("newly"))
    assert got == 1  # 5 queued + 1 = 6; the 7th would exceed the limit


def test_leases_command_reports_effective_state(engine):
    from sentinel_tpu.transport.command_center import (
        CommandCenter, CommandRequest,
    )
    from sentinel_tpu.transport.handlers import cmd_leases
    import json

    out = json.loads(cmd_leases(CommandRequest(engine=engine)).result)
    assert out["enabled"] and out["effective"] and out["unruledFastpath"]
    st.load_system_rules([st.SystemRule(qps=10)])
    out = json.loads(cmd_leases(CommandRequest(engine=engine)).result)
    assert out["enabled"] is True  # configured on...
    assert out["effective"] is False  # ...but system rules disable it
    assert out["unruledFastpath"] is False


def test_retune_with_compiled_leased_engine(engine, frozen_time):
    """Round-3 advisor high: retuning a COMPILED engine with an active
    lease seeded old-geometry buckets into new-geometry mirrors, so the
    next entry raised IndexError and admission died on the resource.
    Grow and shrink must both leave a clean, full-quota window."""
    st.load_flow_rules([st.FlowRule(resource="ret", count=5)])
    for _ in range(3):
        assert st.entry_ok("ret")
    engine._flush_committer()          # device state now exists (compiled)

    engine.set_window_geometry(interval_ms=2000, sample_count=4)
    # Window reset: the 2s window smooths the burst (used rises 0.5/entry),
    # so i*0.5 + 1 <= 5 admits i=0..8 — and, crucially, no IndexError.
    got = [bool(st.entry_ok("ret")) for _ in range(12)]
    assert got == [True] * 9 + [False] * 3

    engine.set_window_geometry(interval_ms=1000, sample_count=2)
    # Shrink: no stale tail buckets survive; full fresh quota again.
    got = [bool(st.entry_ok("ret")) for _ in range(7)]
    assert got == [True] * 5 + [False] * 2


def test_retune_drops_pre_retune_queued_usage_from_mirror(engine,
                                                          frozen_time):
    """Usage queued in the committer before a retune belongs to the OLD
    window; the reset window (and its fresh mirror) must not inherit it."""
    st.load_flow_rules([st.FlowRule(resource="retq", count=4)])
    for _ in range(3):
        assert st.entry_ok("retq")     # queued, not yet flushed
    engine.set_window_geometry(interval_ms=2000, sample_count=4)
    from sentinel_tpu.utils import time_util

    assert engine._leases["retq"].usage(
        time_util.current_time_millis()) == pytest.approx(0.0)


def test_warmup_precompiles_ladder_widths(engine, frozen_time):
    """engine.warmup() pays every (width, rule-shape) compile up front and
    commits nothing; a rule push right after is not blocked behind XLA
    (the datasource-demo stall: the committer's first wide flush compiled
    under the engine lock while a push waited)."""
    import time as _time

    st.load_flow_rules([st.FlowRule(resource="wu", count=5)])
    engine.warmup((1, 8, 64))
    # no-op batches committed nothing (the row exists from rule compile)
    snap = engine.node_snapshot().get("wu", {})
    assert snap.get("passQps", 0) == 0 and snap.get("blockQps", 0) == 0

    for _ in range(30):                       # a wide burst queues commits
        st.entry_ok("wu")
    t0 = _time.perf_counter()
    st.load_flow_rules([st.FlowRule(resource="wu", count=20)])
    push_s = _time.perf_counter() - t0
    assert engine._leases["wu"].thresholds == [20.0]
    assert push_s < 2.0, f"rule push stalled {push_s:.1f}s behind a compile"


# -- widened leases: warm-up + single-param (ISSUE 8 / ROADMAP 3c) ----------


def _device_twin(rules=None, param_rules=None, capacity=256):
    """A second engine with the lease forced OFF: the device-path oracle
    the widened host mirrors must match verdict for verdict."""
    from sentinel_tpu.core.engine import SentinelEngine

    eng = SentinelEngine(capacity)
    eng.lease_enabled = False
    eng._rebuild_leases()
    if rules:
        eng.flow_rules.load_rules(rules)
    if param_rules:
        eng.param_rules.load_rules(param_rules)
    return eng


def _device_verdict(eng, resource, count=1, value=None):
    """One width-1 device-path entry (+ exit on pass) on the twin."""
    import numpy as np

    from sentinel_tpu.core.batch import (
        EntryBatch, ExitBatch, make_entry_batch_np, make_exit_batch_np)
    from sentinel_tpu.utils.param_hash import hash_param

    reg = eng.registry
    cr, dr, _orow, _oid = reg.resolve_entry(
        resource, "twin_ctx", "", reg.entrance_row("twin_ctx"), 0)
    buf = make_entry_batch_np(1)
    buf["cluster_row"][0] = cr
    buf["dn_row"][0] = dr
    buf["count"][0] = count
    if value is not None:
        buf["param_hash"][0, 0] = hash_param(value)
        buf["param_present"][0, 0] = True
    dec = eng._run_entry_batch(EntryBatch(**buf))
    passed = int(np.asarray(dec.reason)[0]) == 0
    if passed:
        xb = make_exit_batch_np(1)
        xb["cluster_row"][0] = cr
        xb["dn_row"][0] = dr
        xb["count"][0] = count
        xb["success"][0] = True
        eng._run_exit_batch(ExitBatch(**xb))
    return passed


def test_warmup_rule_is_leased_and_matches_device(engine, frozen_time):
    """Oracle parity: the host warm-up mirror must reproduce the device
    WarmUpController verdict for verdict across the cold throttle, the
    ramp, and the warm plateau (same float32 math, same 1 Hz sync)."""
    from sentinel_tpu.core.lease import WideLease
    from sentinel_tpu.utils import time_util

    rule = st.FlowRule(resource="w", count=30,
                       control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                       warm_up_period_sec=4)
    st.load_flow_rules([rule])
    assert isinstance(engine._leases["w"], WideLease)
    twin = _device_twin(rules=[rule])
    for sec in range(7):  # cold second, 4s ramp, 2 warm-plateau seconds
        for i in range(30):
            if i:
                time_util.advance_time(33)
            got = bool(st.entry_ok("w"))
            want = _device_verdict(twin, "w")
            assert got == want, (sec, i)
        time_util.advance_time(1000 - 29 * 33)


def test_warmup_lease_cold_start_throttles(engine, frozen_time):
    """The whole point of WARM_UP: a cold resource admits well below its
    threshold in the first window (warning-zone QPS ≈ count/coldFactor),
    never the full count."""
    st.load_flow_rules([st.FlowRule(
        resource="cold", count=90,
        control_behavior=C.CONTROL_BEHAVIOR_WARM_UP, warm_up_period_sec=10)])
    admitted = sum(1 for _ in range(90) if st.entry_ok("cold"))
    assert 0 < admitted < 90
    assert admitted <= 90 / C.COLD_FACTOR + 1


@pytest.mark.parametrize("seed", [
    3,
    # Second seed slow-tier'd (ISSUE 11 tier-1 wall-time trim): ~18s
    # for the same randomized param-lease regimes as seed 3.
    pytest.param(17, marks=pytest.mark.slow),
])
def test_single_param_rule_is_leased_and_matches_device(engine,
                                                        frozen_time, seed):
    """Oracle parity for the param mirror: per-value windowed token
    buckets (burst included) must match the device verdicts over a
    randomized multi-value stream with idle gaps and window rolls."""
    import random

    from sentinel_tpu.core.lease import WideLease
    from sentinel_tpu.utils import time_util

    rule = st.ParamFlowRule("pp", param_idx=0, count=3, burst_count=1)
    st.load_param_flow_rules([rule])
    assert isinstance(engine._leases["pp"], WideLease)
    twin = _device_twin(param_rules=[rule])
    rng = random.Random(seed)
    for step in range(160):
        time_util.advance_time(rng.choice([0, 50, 200, 1000]))
        v = rng.choice(["a", "b", "c"])
        got = bool(st.entry_ok("pp", args=[v]))
        want = _device_verdict(twin, "pp", value=v)
        assert got == want, (seed, step, v)


def test_param_lease_block_raises_param_flow_exception(engine, frozen_time):
    st.load_param_flow_rules([st.ParamFlowRule("px", param_idx=0, count=2)])
    assert st.entry_ok("px", args=["k"]) is not None
    assert st.entry_ok("px", args=["k"]) is not None
    with pytest.raises(st.ParamFlowException):
        st.entry("px", args=["k"])
    # a DIFFERENT value has its own bucket
    assert st.entry_ok("px", args=["other"]) is not None
    # no value argument at all: the rule does not apply — always pass
    assert st.entry_ok("px") is not None


def test_param_lease_blocks_attribute_to_param_flow_channel(engine,
                                                            frozen_time):
    """A host param block must land in the PARAM_FLOW attribution
    channel on device (pre_reason), not the historical FLOW bucket —
    operators chase the right rule family."""
    from sentinel_tpu.telemetry.attribution import ATTR_REASON_NAMES

    st.load_param_flow_rules([st.ParamFlowRule("pa", param_idx=0, count=1)])
    assert st.entry_ok("pa", args=["k"]) is not None
    assert st.entry_ok("pa", args=["k"]) is None  # host PARAM_FLOW block
    counts = engine.telemetry_counts()
    row = engine.registry.get_cluster_row("pa")
    param_ch = ATTR_REASON_NAMES.index("PARAM_FLOW")
    flow_ch = ATTR_REASON_NAMES.index("FLOW")
    assert counts["blockByReason"][param_ch, row] == 1
    assert counts["blockByReason"][flow_ch, row] == 0


def test_device_path_pass_consumes_param_mirror(engine, frozen_time):
    """Mixed traffic must not double the per-value quota: a PRIORITIZED
    entry takes the device path, and its pass must consume the host
    param mirror too (lease.add with params)."""
    st.load_param_flow_rules([st.ParamFlowRule("pm", param_idx=0, count=3)])
    assert "pm" in engine._leases
    # 2 leased + 1 device-path (prioritized) = the full quota of 3
    assert st.entry_ok("pm", args=["v"]) is not None
    assert st.entry_ok("pm", args=["v"]) is not None
    h = engine.entry("pm", args=["v"], prioritized=True)  # device path
    assert h is not None
    # 4th must block HOST-side: the mirror saw the device-path pass
    assert st.entry_ok("pm", args=["v"]) is None


def test_param_lease_items_override_threshold(engine, frozen_time):
    from sentinel_tpu.models.param_flow import ParamFlowItem

    st.load_param_flow_rules([st.ParamFlowRule(
        "pi", param_idx=0, count=1,
        items=[ParamFlowItem(object="vip", count=4)])])
    assert sum(1 for _ in range(6) if st.entry_ok("pi", args=["vip"])) == 4
    assert sum(1 for _ in range(3) if st.entry_ok("pi", args=["reg"])) == 1


def test_flow_and_param_rules_lease_together(engine, frozen_time):
    """A resource guarded by a DEFAULT flow rule AND one param rule is
    fully host-admitted, with the device chain's family order: the
    param verdict (and its token consumption) lands before flow."""
    st.load_flow_rules([st.FlowRule(resource="fp", count=4)])
    st.load_param_flow_rules([st.ParamFlowRule("fp", param_idx=0, count=2)])
    assert _leased(engine, "fp")
    # value quota (2) bites first, then the flow quota (4) caps the rest
    got = [bool(st.entry_ok("fp", args=["v"])) for _ in range(3)]
    assert got == [True, True, False]
    with pytest.raises(st.ParamFlowException):
        st.entry("fp", args=["v"])
    assert st.entry_ok("fp", args=["w"]) is not None  # 3rd flow pass
    assert st.entry_ok("fp", args=["x"]) is not None  # 4th flow pass
    with pytest.raises(st.FlowException):  # flow quota exhausted
        st.entry("fp", args=["y"])


def test_leases_command_reports_widened_coverage(engine, frozen_time):
    import json

    from sentinel_tpu.transport.command_center import CommandRequest
    from sentinel_tpu.transport.handlers import cmd_leases

    st.load_flow_rules([
        st.FlowRule(resource="plain", count=10),
        st.FlowRule(resource="wz", count=10,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                    warm_up_period_sec=5),
    ])
    st.load_param_flow_rules([st.ParamFlowRule("wz", param_idx=0, count=3)])
    out = json.loads(cmd_leases(CommandRequest(engine=engine)).result)
    plain = out["resources"]["plain"]
    assert plain["warmupRules"] == 0 and plain["paramLease"] is False
    wz = out["resources"]["wz"]
    assert wz["warmupRules"] == 1 and wz["paramLease"] is True


def test_rule_push_does_not_wait_on_device_dispatch(engine, frozen_time):
    """Config-plane/device-plane lock split: a rule push must retune the
    lease table even while the engine lock is held for a long device
    dispatch (first-dispatch XLA compiles hold it for seconds on CPU,
    20-40s on TPU; before the split, pushes stalled behind them and the
    old thresholds kept being enforced)."""
    import threading
    import time as _time

    st.load_flow_rules([st.FlowRule(resource="r", count=3)])
    assert engine._leases["r"].thresholds == [3.0]

    hold = threading.Event()
    release = threading.Event()

    def dispatcher():
        with engine._lock:  # stands in for a compile-length dispatch
            hold.set()
            release.wait(timeout=10.0)

    t = threading.Thread(target=dispatcher, daemon=True)
    t.start()
    assert hold.wait(timeout=5.0)
    try:
        done = threading.Event()

        def pusher():
            st.load_flow_rules([st.FlowRule(resource="r", count=1000)])
            done.set()

        threading.Thread(target=pusher, daemon=True).start()
        # The push completes while the device lock is STILL held...
        assert done.wait(timeout=2.0), \
            "rule push blocked behind the device dispatch lock"
        # ...and the lease table already serves the new threshold.
        assert engine._leases["r"].thresholds == [1000.0]
    finally:
        release.set()
        t.join(timeout=5.0)

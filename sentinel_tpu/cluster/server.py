"""The token server's TCP frontend (reference:
``cluster-server:netty/NettyTransportServer.java`` + ``TokenServerHandler`` +
``processor/*RequestProcessor`` — SURVEY.md §2.4).

TPU-native twist: concurrent client requests are *micro-batched* — each
connection thread enqueues its decoded request and a collector drains the
queue into one ``DefaultTokenService.request_tokens`` device step, so the
server's cost per acquire amortizes across clients (SURVEY.md §7 hard part
#1). Single-request latency still takes at most ``batch_linger_s``.
"""

from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
import time
from typing import Optional, Tuple

from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.constants import (
    MSG_ENTRY,
    MSG_EXIT,
    MSG_FLEET,
    MSG_FLOW,
    MSG_PARAM_FLOW,
    MSG_PING,
    MSG_STREAM_TICK,
    STREAM_OP_ABORT,
    STREAM_OP_CLOSE,
    STREAM_OP_OPEN,
    STREAM_OP_TICK,
    TokenResultStatus,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.core.config import config
from sentinel_tpu.resilience import DeadlineBudget, faults


def pad_width(n_flat: int) -> int:
    """Device batch width for ``n_flat`` requests: exact below 64 (fast
    compiles; padding the first 1-request acquire to 16 measurably
    outlasted the client's 2s request timeout — r5), then a coarse
    ladder (256, 1024, 4096, +4096...) bounding jit specializations
    against client-controlled burst sizes."""
    if n_flat <= 64:
        return n_flat
    width = 256
    while width < n_flat:
        width = width * 4 if width < 4096 else width + 4096
    return width


class _Batcher:
    """Collects flow-token requests into one device step per linger tick.

    Requests arrive as GROUPS (a pipelined client burst shares one
    group): one Event + one results list per group instead of per
    request — at 512-request bursts the per-request Event alloc/wait
    overhead was the loopback throughput ceiling (~100µs of host work
    per acquire, measured r5). ``max_batch`` is a soft cap at group
    granularity: a drained group is never split across device calls.

    Overload-safe admission (ISSUE 6): the queue is BOUNDED at
    ``max_queue_groups`` and every group carries a ``DeadlineBudget``.
    Submissions over the watermark (or against a full queue) are shed
    immediately — ``box["shed_retry_after_ms"]`` instead of results, the
    frontend replies OVERLOADED — and the drain loop sheds groups whose
    deadline expired while queued BEFORE spending a device step on them.
    Shedding happens strictly before ``request_tokens``: a shed request
    is never half-admitted (docs/SEMANTICS.md "Shed-before-admission").
    """

    def __init__(self, service: DefaultTokenService, linger_s: float, max_batch: int,
                 crash_cb=None, max_queue_groups: Optional[int] = None,
                 watermark_pct: Optional[int] = None,
                 deadline_ms: Optional[int] = None,
                 retry_after_ms: Optional[int] = None,
                 inflight_depth: Optional[int] = None):
        self.service = service
        self.linger_s = linger_s
        self.max_batch = max_batch
        # Pipelined drain (ISSUE 11): up to this many fused batches ride
        # the device stream at once via the token service's enqueue-only
        # dispatch/harvest split (the PR 8 pattern). Depth 1 (or a
        # service without dispatch_tokens) is the old synchronous drain.
        self.inflight_depth = int(
            inflight_depth if inflight_depth is not None
            else config.wire_inflight_depth())
        # Leader-crash seam (resilience/faults.py "cluster.ha.leader.crash"):
        # fired per drained batch; when armed, ``crash_cb`` hard-kills the
        # owning server — the chaos suite's process-crash analog.
        self.crash_cb = crash_cb
        self.max_queue_groups = int(
            max_queue_groups if max_queue_groups is not None
            else config.overload_queue_max_groups())
        pct = int(watermark_pct if watermark_pct is not None
                  else config.overload_queue_watermark_pct())
        self.watermark_groups = max(1, self.max_queue_groups * pct // 100)
        self.deadline_ms = int(deadline_ms if deadline_ms is not None
                               else config.overload_deadline_ms())
        self.retry_after_ms = int(retry_after_ms if retry_after_ms is not None
                                  else config.overload_retry_after_ms())
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.max_queue_groups)
        self._stats_lock = threading.Lock()
        # Submit-time sheds are terminal and identical for every caller,
        # so they share ONE pre-set Event and ONE immutable box — the
        # shed path allocates NOTHING per request or per group (the
        # ISSUE 11 wakeup/allocation-storm fix, pinned by test_wire's
        # allocation-count test). Admitted groups still get their own
        # event: one wakeup per GROUP, never per request.
        self._shed_done = threading.Event()
        self._shed_done.set()
        self._shed_box = {"shed_retry_after_ms": self.retry_after_ms}
        self.groups_allocated = 0
        self.admitted_groups = 0
        self.admitted_requests = 0
        self.shed_watermark = 0
        self.shed_queue_full = 0
        self.shed_deadline_expired = 0
        self.shed_requests = 0
        self.queue_depth_max = 0
        # Latency waterfall recorder (ISSUE 18), attached by the owning
        # server at start when an engine is already up. When set, each
        # fused batch stamps drain/dispatch/device marks into its
        # groups' boxes (three perf_counter reads per BATCH — nothing
        # per request, and nothing at all on the shed path).
        self.waterfall = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _shed(self, box: dict, done: threading.Event, n_requests: int,
              cause: str) -> None:
        with self._stats_lock:
            setattr(self, cause, getattr(self, cause) + 1)
            self.shed_requests += n_requests
        box["shed_retry_after_ms"] = self.retry_after_ms
        done.set()

    def _shed_fast(self, n_requests: int, cause: str):
        """Submit-time shed: counters only — the reply rides the SHARED
        pre-set event + immutable box (zero allocations per shed)."""
        with self._stats_lock:
            setattr(self, cause, getattr(self, cause) + 1)
            self.shed_requests += n_requests
        return self._shed_done, self._shed_box

    def submit_many(self, requests, budget: Optional[DeadlineBudget] = None):
        """One group: ``(done_event, box)``; ``box["results"]`` carries
        one TokenResult per request (absent on a failed device call), or
        ``box["shed_retry_after_ms"]`` when the group was shed instead of
        admitted. ``budget`` is the group's remaining deadline (defaults
        to the configured overload deadline)."""
        reqs = list(requests)
        # Watermark shed: past the high-water mark the queue is already
        # deeper than a healthy drain can clear inside a deadline, so an
        # explicit "not now" beats silently joining the backlog.
        if self._queue.qsize() >= self.watermark_groups:
            return self._shed_fast(len(reqs), "shed_watermark")
        if budget is None:
            budget = DeadlineBudget(self.deadline_ms)
        done = threading.Event()
        box: dict = {}
        try:
            self._queue.put_nowait((reqs, done, box, budget))
        except queue.Full:
            return self._shed_fast(len(reqs), "shed_queue_full")
        with self._stats_lock:
            self.groups_allocated += 1
            self.admitted_groups += 1
            self.admitted_requests += len(reqs)
            depth = self._queue.qsize()
            if depth > self.queue_depth_max:
                self.queue_depth_max = depth
        return done, box

    def shed_rate(self) -> float:
        """Cumulative shed fraction: shed requests over everything that
        reached admission. The SLO engine's health score consumes the
        DELTA of the underlying counters between evaluations; this ratio
        is the ops-glance form (ISSUE 7)."""
        denom = self.shed_requests + self.admitted_requests
        return self.shed_requests / float(denom) if denom else 0.0

    def overload_stats(self) -> dict:
        """Lock-free read (the /metrics scrape path): counters are plain
        ints, a racing scrape just sees a near-instant snapshot."""
        return {
            "queueDepth": self._queue.qsize(),
            "queueDepthMax": self.queue_depth_max,
            "queueLimitGroups": self.max_queue_groups,
            "watermarkGroups": self.watermark_groups,
            "admittedGroups": self.admitted_groups,
            "admittedRequests": self.admitted_requests,
            "shedRate": self.shed_rate(),
            "shedWatermark": self.shed_watermark,
            "shedQueueFull": self.shed_queue_full,
            "shedDeadlineExpired": self.shed_deadline_expired,
            "shedRequests": self.shed_requests,
            "deadlineMs": self.deadline_ms,
        }

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="sentinel-token-batcher", daemon=True)
        self._thread.start()
        return self

    def _fail(self, groups) -> None:
        for _reqs, done, _box, _budget in groups:
            done.set()  # empty box -> handler replies FAIL

    def _complete(self, groups, results, wf_stamps=None) -> None:
        off = 0
        for reqs, done, box, _budget in groups:
            box["results"] = results[off:off + len(reqs)]
            if wf_stamps is not None:
                box["wfStamps"] = wf_stamps
            off += len(reqs)
            done.set()

    def _harvest(self, ticket, groups, n_flat: int,
                 t_drain: float = 0.0, t_dispatch: float = 0.0) -> None:
        """Resolve one in-flight fused batch: the np readback happens
        here, outside the service lock — an async device death fails
        exactly this batch's groups (the drain loop keeps running)."""
        try:
            results = self.service.harvest_tokens(ticket)[:n_flat]
        except Exception as ex:  # noqa: BLE001 — poison harvest
            from sentinel_tpu.log.record_log import record_log

            record_log.warn("token batch harvest failed: %r", ex)
            self._fail(groups)
            return
        wf = self.waterfall
        if wf is not None:
            t_device = time.perf_counter()
            wf.observe_batch((t_device - t_dispatch) * 1e3, n_flat)
            self._complete(groups, results, (t_drain, t_dispatch, t_device))
        else:
            self._complete(groups, results)

    def _run(self):
        from collections import deque

        # In-flight fused batches (ticket, groups, n_flat, t_drain,
        # t_dispatch), oldest first.
        inflight: "deque" = deque()
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                while inflight:  # idle: resolve whatever still rides
                    self._harvest(*inflight.popleft())
                continue
            # Waterfall "queue" stage boundary: one drain stamp per
            # fused batch (groups folded in during the linger below
            # attribute their residual queue time to "dispatch" — the
            # stage chain stays gap-free either way, SEMANTICS.md).
            t_drain = time.perf_counter()
            groups = [first]
            try:
                faults.fire("cluster.ha.leader.crash")
            except OSError:
                # The "process" dies mid-batch: fail the in-flight group
                # fast (its handler replies FAIL an instant before the
                # sockets close) and hard-stop the server off-thread.
                # Requests granted but not yet checkpointed are exactly
                # the over-admission margin failover is allowed.
                first[1].set()
                if self.crash_cb is not None:
                    threading.Thread(target=self.crash_cb,
                                     daemon=True).start()
                self._stop.set()
                return
            # Linger briefly so concurrent clients fold into one step.
            deadline = threading.Event()
            deadline.wait(self.linger_s)
            n = len(first[0])
            while n < self.max_batch:
                try:
                    g = self._queue.get_nowait()
                except queue.Empty:
                    break
                groups.append(g)
                n += len(g[0])
            # Deadline-aware shed BEFORE the device step: a group whose
            # budget expired while queued is dead weight — its client
            # already timed out — and spending a device step on it only
            # delays the still-live groups behind it. Shed here is also
            # the half-admission proof point: expiry is checked strictly
            # before request_tokens, so no shed request ever holds a
            # granted token (docs/SEMANTICS.md "Shed-before-admission").
            live = []
            for g in groups:
                if g[3].expired:
                    self._shed(g[2], g[1], len(g[0]),
                               "shed_deadline_expired")
                else:
                    live.append(g)
            groups = live
            if not groups:
                continue
            flat = [r for g in groups for r in g[0]]
            # Bound jit specializations: request_tokens jits per batch
            # LENGTH, and group granularity makes lengths client-
            # controlled — unpadded, a client sending varying burst
            # sizes would drive unbounded recompilation (and stall all
            # token traffic per new width). pad_width keeps small
            # batches (<= 64) at their EXACT width (their compiles are
            # fast; padding the first 1-request acquire to 16 measurably
            # outlasted the client's 2s request timeout — r5 review),
            # larger bursts ride a coarse ladder; padding rows carry a
            # None flow id -> slot -1 -> NO_RULE_EXISTS, get sliced off.
            n_flat = len(flat)
            width = pad_width(n_flat)
            padded = flat + [(None, 0, False)] * (width - n_flat)
            dispatch = getattr(self.service, "dispatch_tokens", None)
            if dispatch is None or self.inflight_depth <= 1:
                # Synchronous drain: services without the dispatch/
                # harvest split (stubs), or depth pinned to 1.
                t_dispatch = time.perf_counter()
                try:
                    results = self.service.request_tokens(padded)[:n_flat]
                except Exception as ex:  # a poison batch must not kill the loop
                    from sentinel_tpu.log.record_log import record_log

                    record_log.warn("token batch failed: %r", ex)
                    self._fail(groups)
                    continue
                wf = self.waterfall
                if wf is not None:
                    t_device = time.perf_counter()
                    wf.observe_batch((t_device - t_dispatch) * 1e3, n_flat)
                    self._complete(groups, results,
                                   (t_drain, t_dispatch, t_device))
                else:
                    self._complete(groups, results)
                continue
            # Pipelined drain: keep at most inflight_depth fused batches
            # on the device stream. Each dispatch consumes the DONATED
            # previous state, so execution order is forced by the data
            # dependency — verdicts stay bit-identical to the sync drain
            # (same argument as docs/SEMANTICS.md "Pipeline ordering").
            while len(inflight) >= self.inflight_depth:
                self._harvest(*inflight.popleft())
            try:
                ticket = dispatch(padded)
            except Exception as ex:  # a poison dispatch must not kill the loop
                from sentinel_tpu.log.record_log import record_log

                record_log.warn("token batch dispatch failed: %r", ex)
                self._fail(groups)
                continue
            inflight.append((ticket, groups, n_flat,
                             t_drain, time.perf_counter()))
            if self._queue.empty():
                # Idle queue ⇒ immediate harvest: the no-concurrency
                # latency floor stays one step, overlap only engages
                # when there is follow-on work to overlap with.
                while inflight:
                    self._harvest(*inflight.popleft())
        while inflight:  # stop(): every submitted group still resolves
            self._harvest(*inflight.popleft())

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)


def stamp_epoch(server: "ClusterTokenServer", entity: bytes,
                epoch: Optional[int] = None) -> bytes:
    """Append the leader's epoch TLV (cluster/ha.py fencing) to a
    token response entity; epoch 0 (pre-HA) keeps the wire format
    byte-identical. ``epoch`` overrides the stamped value with a
    PER-SLICE term (cluster/sharding.py: each verdict carries the
    fencing epoch of the slice it was granted under). With no override,
    a SHARDED service stamps nothing — its flat service epoch is the
    max over owned slices, and stamping that under another slice's
    fence lane would poison honest lower-epoch slices. The payload
    passes the ``cluster.ha.stale.epoch`` mutate seam so the chaos
    suite can replay a deposed epoch."""
    if epoch is None:
        epoch = 0 if getattr(server.service, "shard", None) is not None \
            else server.service.epoch
    if not epoch:
        return entity
    return codec.append_epoch_tlv(entity, faults.mutate(
        "cluster.ha.stale.epoch", codec.encode_epoch_value(epoch)))


def mutate_reply(data: bytes) -> bytes:
    """Every reply write passes the ``cluster.server.frame`` fault
    point, so the chaos suite can corrupt/delay/kill server->client
    bytes without a proxy — and the ``cluster.ha.halfopen`` seam,
    whose garbage=b"" mode swallows replies with the connection left
    up (a half-open socket the client must time out of). Shared by the
    legacy handler and the reactor flush path."""
    return faults.mutate("cluster.ha.halfopen",
                         faults.mutate("cluster.server.frame", data))


def build_flow_reply(server: "ClusterTokenServer", xid: int, result,
                     shed_retry) -> bytes:
    """One FLOW response frame from a batcher outcome — the ONE reply
    encoder both frontends (legacy handler, reactor) share, so the wire
    bytes can never drift between them."""
    if shed_retry is not None:
        # Admission-queue shed: explicit OVERLOADED with a retry-after
        # hint in the waitMs field — never a silent queue or hung socket.
        return codec.encode_response(
            xid, MSG_FLOW, TokenResultStatus.OVERLOADED,
            stamp_epoch(server, codec.encode_flow_response(0, shed_retry)))
    if result is None:
        return codec.encode_response(xid, MSG_FLOW, TokenResultStatus.FAIL)
    entity = codec.encode_flow_response(result.remaining, result.wait_ms)
    if result.server_span is not None:
        sp = result.server_span
        entity = codec.append_trace_tlv(
            entity, codec.encode_span_info(
                sp["spanId"], sp["startMs"], sp["durationUs"]))
    if result.status == TokenResultStatus.WRONG_SLICE:
        # Out-of-slice (cluster/sharding.py): no epoch TLV — this
        # leader holds no term for the slice, and stamping one would
        # poison the client's per-slice fence lane. The shard-map
        # version rides a dedicated TLV (and mirrors in waitMs) so the
        # mis-routed client can tell how stale its map is.
        entity = codec.append_map_version_tlv(entity, result.wait_ms)
        return codec.encode_response(xid, MSG_FLOW, result.status, entity)
    # Epoch AFTER the span TLV: pre-HA clients read the span at a
    # fixed offset. Sharded verdicts stamp their PER-SLICE epoch
    # (TokenResult.epoch); unsharded replies keep the service epoch.
    entity = stamp_epoch(server, entity, getattr(result, "epoch", None))
    return codec.encode_response(xid, MSG_FLOW, result.status, entity)


def process_control_frame(server: "ClusterTokenServer", req: codec.Request,
                          remote_entries: dict, namespace):
    """Handle every non-FLOW message type; -> (reply_bytes, namespace').

    Shared by the legacy thread-per-connection handler and the reactor's
    worker pool — one implementation, so the two frontends answer
    byte-identically (pinned by test_wire's wire-compat test)."""
    if req.msg_type == MSG_PING:
        ns = codec.decode_ping(req.entity)
        if namespace is None and ns:
            server.service.connections.connect(ns)
            namespace = ns
        return (codec.encode_response(
            req.xid, MSG_PING, TokenResultStatus.OK), namespace)
    if req.msg_type == MSG_PARAM_FLOW:
        from sentinel_tpu.telemetry.spans import parse_traceparent

        flow_id, count, params = codec.decode_param_flow_request(req.entity)
        tp = codec.read_trace_tlv(
            req.entity, codec.param_flow_request_size(req.entity))
        ctx = parse_traceparent(tp) if tp else None
        result = server.service.request_param_token(
            flow_id, count, params, trace=ctx)
        entity = b""
        if result.server_span is not None:
            sp = result.server_span
            entity = codec.append_trace_tlv(
                b"", codec.encode_span_info(
                    sp["spanId"], sp["startMs"], sp["durationUs"]))
        if result.status == TokenResultStatus.WRONG_SLICE:
            # Param responses have no waitMs field: the map-version TLV
            # is the ONLY carrier here (no epoch TLV — see
            # build_flow_reply's out-of-slice note).
            entity = codec.append_map_version_tlv(entity, result.wait_ms)
        else:
            entity = stamp_epoch(server, entity,
                                 getattr(result, "epoch", None))
        return (codec.encode_response(
            req.xid, MSG_PARAM_FLOW, result.status, entity), namespace)
    if req.msg_type == MSG_ENTRY:
        resource, origin, count, etype, prio, params = \
            codec.decode_entry_request(req.entity)
        handle, reason = server.remote_entry(
            resource, origin, count, etype, prio, params)
        if handle is not None:
            entry_id = server.next_entry_id()
            remote_entries[entry_id] = handle
            return (codec.encode_response(
                req.xid, MSG_ENTRY, TokenResultStatus.OK,
                codec.encode_entry_response(entry_id, 0)), namespace)
        if reason < 0:  # engine unavailable, fail-open on the JVM
            return (codec.encode_response(
                req.xid, MSG_ENTRY, TokenResultStatus.FAIL,
                codec.encode_entry_response(0, 0)), namespace)
        return (codec.encode_response(
            req.xid, MSG_ENTRY, TokenResultStatus.BLOCKED,
            codec.encode_entry_response(0, reason)), namespace)
    if req.msg_type == MSG_FLEET:
        # Fleet telemetry pull (ISSUE 14): this leader's flight-recorder
        # spill page + instance health + shard ownership, epoch-stamped
        # like any token reply. Shared by both frontends, so the reactor
        # serves it off its worker pool with zero-copy ingest for free.
        from sentinel_tpu.telemetry.fleet import (
            leader_fleet_payload,
            leader_population_payload,
        )

        try:
            since_ms, max_s = codec.decode_fleet_request(req.entity)
            # max_seconds == -1 is the population-page sentinel (ISSUE
            # 19): same message, different page — a pre-telescope server
            # falls through to a normal seconds page, which the client
            # detects by the missing "population" key. No new opcode, so
            # mixed-version fleets keep scraping.
            if max_s == -1:
                entity = stamp_epoch(server, leader_population_payload(
                    server))
            else:
                entity = stamp_epoch(
                    server, leader_fleet_payload(server, since_ms, max_s))
            return (codec.encode_response(
                req.xid, MSG_FLEET, TokenResultStatus.OK, entity), namespace)
        except Exception:  # noqa: BLE001 — a read must never kill the conn
            return (codec.encode_response(
                req.xid, MSG_FLEET, TokenResultStatus.FAIL), namespace)
    if req.msg_type == MSG_STREAM_TICK:
        # Streaming reservations (ISSUE 17 — sentinel_tpu/llm/): a
        # remote gateway drives the engine's reservation ledger over
        # the token wire. Shared by both frontends like every branch
        # here; a read must never kill the connection.
        from sentinel_tpu.core.exceptions import BlockException

        try:
            op, sid, model, tokens = codec.decode_stream_request(req.entity)
        except (IndexError, ValueError, struct.error):
            return (codec.encode_response(
                req.xid, MSG_STREAM_TICK,
                TokenResultStatus.BAD_REQUEST), namespace)
        eng = server.engine
        if eng is None:
            return (codec.encode_response(
                req.xid, MSG_STREAM_TICK, TokenResultStatus.FAIL), namespace)
        try:
            if op == STREAM_OP_OPEN:
                lease = eng.stream_open(
                    sid, model, None if tokens < 0 else tokens)
                remaining = int(lease.remaining)
            elif op == STREAM_OP_TICK:
                remaining = int(eng.stream_tick(sid, max(0, tokens)))
            elif op in (STREAM_OP_CLOSE, STREAM_OP_ABORT):
                remaining = int(eng.stream_close(
                    sid, aborted=op == STREAM_OP_ABORT))
            else:
                return (codec.encode_response(
                    req.xid, MSG_STREAM_TICK,
                    TokenResultStatus.BAD_REQUEST), namespace)
        except BlockException:
            return (codec.encode_response(
                req.xid, MSG_STREAM_TICK, TokenResultStatus.BLOCKED,
                codec.encode_stream_response(0)), namespace)
        except (KeyError, ValueError, OverflowError):
            return (codec.encode_response(
                req.xid, MSG_STREAM_TICK,
                TokenResultStatus.BAD_REQUEST), namespace)
        except Exception:  # noqa: BLE001 — a tick must never kill the conn
            return (codec.encode_response(
                req.xid, MSG_STREAM_TICK, TokenResultStatus.FAIL), namespace)
        return (codec.encode_response(
            req.xid, MSG_STREAM_TICK, TokenResultStatus.OK,
            codec.encode_stream_response(remaining)), namespace)
    if req.msg_type == MSG_EXIT:
        entry_id, error, count = codec.decode_exit_request(req.entity)
        handle = remote_entries.pop(entry_id, None)
        if handle is None:
            return (codec.encode_response(
                req.xid, MSG_EXIT, TokenResultStatus.BAD_REQUEST), namespace)
        if error:
            handle.trace(None)  # biz exception on the JVM side
        handle.exit(count if count >= 0 else None)
        return (codec.encode_response(
            req.xid, MSG_EXIT, TokenResultStatus.OK), namespace)
    return (codec.encode_response(
        req.xid, req.msg_type, TokenResultStatus.BAD_REQUEST), namespace)


class _Handler(socketserver.BaseRequestHandler):
    def _send(self, data: bytes) -> None:
        """Reply write through :func:`mutate_reply`'s chaos seams."""
        data = mutate_reply(data)
        if data:
            self.request.sendall(data)

    def _stamp_epoch(self, entity: bytes) -> bytes:
        return stamp_epoch(self.server.token_server, entity)

    def handle(self):
        server: "ClusterTokenServer" = self.server.token_server
        reader = codec.FrameReader()
        namespace: Optional[str] = None
        # Live remote entries on THIS connection (the M4 slot-chain
        # bridge): id -> EntryHandle. Ids come from a SERVER-wide
        # counter: a reconnecting bridge keeps stale ids in its
        # thread-local stacks, and per-connection numbering restarting
        # at 1 would let those stale ids alias (and exit) a fresh
        # entry's id on the new connection (r5 review). Globally-unique
        # ids make a stale exit a harmless BAD_REQUEST instead. The map
        # stays per-connection so one peer can never exit another's.
        self._remote_entries = {}
        # Configurable idle timeout (was a flat 300s): a silent peer
        # holds a handler thread + its remote-entry map for at most this
        # long before the connection is reaped.
        self.request.settimeout(server.idle_timeout_s)
        try:
            while True:
                data = self.request.recv(65536)
                if not data:
                    break
                reqs = [codec.decode_request(b) for b in reader.feed(data)]
                i = 0
                while i < len(reqs):
                    if reqs[i].msg_type == MSG_FLOW:
                        # Pipelined FLOW runs go to the batcher as ONE
                        # group before any reply is awaited — otherwise
                        # a client's burst of N degrades to N sequential
                        # linger+device-step cycles — and the replies go
                        # out as ONE write (per-frame sendall was ~30%
                        # of the r5 loopback ceiling).
                        from sentinel_tpu.telemetry.spans import (
                            parse_traceparent)

                        j = i
                        burst = []
                        # Per-connection concurrency cap: a pipelined
                        # burst larger than conn.max.burst is split into
                        # sequential groups (each awaited before the
                        # next is read), so one connection can occupy at
                        # most one bounded group in the admission queue
                        # — TCP backpressure does the rest.
                        while (j < len(reqs)
                               and reqs[j].msg_type == MSG_FLOW
                               and len(burst) < server.conn_max_burst):
                            # Optional trailing trace TLV (spans): a
                            # traced request becomes a 4-tuple the token
                            # service records a server span for.
                            tp = codec.read_trace_tlv(
                                reqs[j].entity, codec.FLOW_REQ_SIZE)
                            ctx = parse_traceparent(tp) if tp else None
                            r = codec.decode_flow_request(reqs[j].entity)
                            burst.append(
                                (reqs[j].xid,
                                 r + (ctx,) if ctx is not None else r))
                            j += 1
                        done, box = server.batcher.submit_many(
                            [r for _, r in burst])
                        # Wait at least the group's deadline budget: a
                        # shorter wait would reply FAIL while the group
                        # is still live in the queue, and the drain
                        # could then commit its tokens AFTER the reply —
                        # the half-admission window SEMANTICS.md's
                        # deadline-shed bound promises stays closed.
                        done.wait(timeout=max(
                            5, server.batcher.deadline_ms / 1000 + 1)
                            + len(burst) * 0.01)
                        results = box.get("results")
                        shed_retry = box.get("shed_retry_after_ms")
                        server_obj = self.server.token_server
                        replies = [
                            build_flow_reply(
                                server_obj, xid,
                                results[k] if results else None, shed_retry)
                            for k, (xid, _r) in enumerate(burst)
                        ]
                        self._send(b"".join(replies))
                        i = j
                    else:
                        namespace = self._process(server, reqs[i], namespace)
                        i += 1
        except OSError:
            pass
        finally:
            if namespace is not None:
                server.service.connections.disconnect(namespace)
            # A dead JVM must not leak thread counts: exit whatever its
            # connection still holds (reference analog: CtEntry cleanup;
            # the error flag stays False — a dropped link is not a biz
            # exception, and RT for these is honest wall time to now).
            for handle in self._remote_entries.values():
                try:
                    handle.exit()
                except Exception:  # noqa: BLE001 — best-effort drain
                    pass
            self._remote_entries.clear()

    def _process(self, server, req: codec.Request, namespace):
        # NOTE: no MSG_FLOW arm — handle() consumes every FLOW frame in
        # its burst branch (a lone frame is a burst of one). All other
        # types route through the SHARED process_control_frame, the same
        # implementation the reactor's worker pool runs.
        reply, namespace = process_control_frame(
            server, req, self._remote_entries, namespace)
        self._send(reply)
        return namespace


class _ThreadingTCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    # Connection-storm headroom: the socketserver default backlog of 5
    # refuses/falls over under a fleet-wide reconnect (e.g. right after
    # a leader promotion — exactly when every client dials at once).
    # Accepted connections are cheap (one parked thread each until the
    # idle timeout reaps them); the admission QUEUE is what stays
    # bounded.
    request_queue_size = 256


class ClusterTokenServer:
    """Embedded-or-standalone token server (``SentinelDefaultTokenServer``).

    Two frontends share this facade (and every seam: the batcher, the
    chaos fault points, the shared reply encoders):

    * the REACTOR (cluster/reactor.py, default): one selectors-based
      I/O loop multiplexing every connection, zero-copy TLV parse, and
      a coalescing collector folding ALL ready connections into
      pipelined fused-step batches — the ISSUE 11 wire path;
    * the legacy thread-per-connection socketserver (``reactor=False``
      or ``csp.sentinel.wire.reactor.enabled=false``), kept as the
      wire-compat reference implementation.
    """

    def __init__(self, service: Optional[DefaultTokenService] = None,
                 host: str = "0.0.0.0", port: int = 0,
                 batch_linger_s: float = 0.0005, max_batch: int = 256,
                 engine=None, max_queue_groups: Optional[int] = None,
                 watermark_pct: Optional[int] = None,
                 deadline_ms: Optional[int] = None,
                 idle_timeout_s: Optional[int] = None,
                 conn_max_burst: Optional[int] = None,
                 reactor: Optional[bool] = None):
        self.service = service or DefaultTokenService()
        self.host = host
        self.port = port
        self.reactor_enabled = bool(
            config.wire_reactor_enabled() if reactor is None else reactor)
        self.idle_timeout_s = int(
            idle_timeout_s if idle_timeout_s is not None
            else config.overload_idle_timeout_s())
        self.conn_max_burst = int(
            conn_max_burst if conn_max_burst is not None
            else config.overload_conn_max_burst())
        self.batcher = _Batcher(self.service, batch_linger_s, max_batch,
                                crash_cb=self._fault_crash,
                                max_queue_groups=max_queue_groups,
                                watermark_pct=watermark_pct,
                                deadline_ms=deadline_ms)
        self.crashed = False
        self._server: Optional[_ThreadingTCP] = None
        self._thread: Optional[threading.Thread] = None
        self._reactor = None
        # Engine serving MSG_ENTRY/MSG_EXIT (the M4 slot-chain bridge).
        # None -> the process default engine, resolved lazily so merely
        # constructing a token server never boots the engine singleton.
        self._engine = engine
        self._entry_id_lock = threading.Lock()
        self._entry_id = 0

    def next_entry_id(self) -> int:
        """Server-unique remote-entry id (never reused across
        connections — see _Handler.handle's aliasing note)."""
        with self._entry_id_lock:
            self._entry_id += 1
            return self._entry_id

    @property
    def engine(self):
        if self._engine is None:
            import sentinel_tpu

            self._engine = sentinel_tpu.get_engine()
        return self._engine

    def remote_entry(self, resource: str, origin: str, count: int,
                     entry_type: int, prioritized: bool, params):
        """Run the FULL local slot chain for a remote (JVM) caller.

        Returns ``(handle, 0)`` on pass, ``(None, reason>0)`` on block,
        ``(None, -1)`` when the engine is unusable (the bridge's wire
        FAIL -> the JVM falls open, mirroring fallbackToLocalOrPass).

        Each remote entry runs in its OWN context object (name
        ``sentinel_remote_context``, the caller's origin): connection
        threads interleave entries from many JVM threads, so borrowing
        the connection thread's context would corrupt parent/child
        chains. The handle keeps its context alive; exit may happen on
        any thread (engine._do_exit tolerates out-of-order pops)."""
        from sentinel_tpu.core import context as ctx_mod
        from sentinel_tpu.core.exceptions import (
            BlockException,
            reason_for_exception,
        )

        prev = ctx_mod.get_context()
        ctx_mod.replace_context(None)
        try:
            ctx_mod.enter("sentinel_remote_context", origin)
            handle = self.engine.entry(
                resource, entry_type, count, tuple(params), prioritized)
            return handle, 0
        except BlockException as ex:
            return None, reason_for_exception(ex)
        except Exception:  # noqa: BLE001 — engine death must fail open
            return None, -1
        finally:
            ctx_mod.replace_context(prev)

    @property
    def bound_port(self) -> int:
        if self._reactor is not None:
            return self._reactor.bound_port
        return self._server.server_address[1] if self._server else self.port

    def waterfall_recorder(self):
        """The engine's latency-waterfall recorder WITHOUT booting the
        engine singleton: an explicitly-passed engine wins; otherwise
        only an ALREADY-booted process engine attaches (constructing a
        bare token server must stay engine-free). None when there is no
        engine yet or capture is disabled."""
        eng = self._engine
        if eng is None:
            import sentinel_tpu

            eng = sentinel_tpu._default_engine
        wf = getattr(eng, "waterfall", None) if eng is not None else None
        return wf if wf is not None and wf.enabled else None

    def attach_waterfall(self, recorder) -> None:
        """Late attach (an engine booted after ``start()``): hands the
        recorder to the batcher and the reactor frontend."""
        self.batcher.waterfall = recorder
        if self._reactor is not None:
            self._reactor.attach_waterfall(recorder)

    def start(self) -> "ClusterTokenServer":
        # Bind BEFORE starting the batcher drain thread: a failed bind
        # (EADDRINUSE on a role flip) must leave nothing running — the
        # caller retries, and a leaked drain thread per attempt would
        # accumulate (both frontends bind synchronously here).
        self.batcher.waterfall = self.waterfall_recorder()
        if self.reactor_enabled:
            from sentinel_tpu.cluster.reactor import WireReactor

            self._reactor = WireReactor(self).start()
            self.batcher.start()
            return self
        self._server = _ThreadingTCP((self.host, self.port), _Handler)
        self._server.token_server = self
        self.batcher.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="sentinel-token-server", daemon=True)
        self._thread.start()
        return self

    @property
    def epoch(self) -> int:
        """Leadership epoch stamped into every token response (0 = no
        stamp, the pre-HA wire format)."""
        return self.service.epoch

    def overload_stats(self) -> dict:
        """Frontend overload snapshot: admission-queue depth/bounds and
        shed counters (the ``sentinel_tpu_overload_*`` gauges' source)."""
        return {
            **self.batcher.overload_stats(),
            "idleTimeoutS": self.idle_timeout_s,
            "connMaxBurst": self.conn_max_burst,
            "reactor": self.reactor_enabled,
        }

    def wire_stats(self) -> Optional[dict]:
        """Reactor wire-path snapshot (connections, coalesced batch
        sizes, RTT split, outbuf sheds — the ``sentinel_tpu_wire_*``
        gauges' source), or None on the legacy frontend."""
        if self._reactor is None:
            return None
        return self._reactor.wire_stats()

    def _fault_crash(self) -> None:
        """Hard-kill for the ``cluster.ha.leader.crash`` fault point: the
        process-crash analog — listener and connections close, no drain,
        no checkpoint publish. ``crashed`` lets the HA layer distinguish
        this from a graceful stop."""
        self.crashed = True
        self.stop()

    def stop(self) -> None:
        self.batcher.stop()
        if self._reactor is not None:
            self._reactor.stop()
            self._reactor = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

package com.alibaba.csp.sentinel.tpu;

import com.alibaba.csp.sentinel.cluster.ClusterConstants;
import com.alibaba.csp.sentinel.cluster.TokenResult;
import com.alibaba.csp.sentinel.cluster.TokenResultStatus;
import com.alibaba.csp.sentinel.cluster.TokenServerDescriptor;
import com.alibaba.csp.sentinel.cluster.client.ClusterTokenClient;
import com.alibaba.csp.sentinel.cluster.client.config.ClusterClientConfigManager;
import com.alibaba.csp.sentinel.log.RecordLog;
import com.alibaba.csp.sentinel.spi.Spi;
import com.sun.jna.Pointer;
import com.sun.jna.ptr.IntByReference;

import java.util.Collection;

/**
 * {@link ClusterTokenClient} SPI implementation that forwards token
 * acquires to the sentinel-tpu backend through the native shim — the
 * "Java SPI slot" of SURVEY.md §7 M4: drop this jar (plus JNA and
 * {@code libsentinel_shim.so}) on the classpath of ANY app already using
 * the reference, register it in
 * {@code META-INF/services/com.alibaba.csp.sentinel.cluster.client.ClusterTokenClient},
 * and the stock {@code FlowSlot}/{@code ParamFlowSlot} cluster branches
 * ({@code FlowRuleChecker.passClusterCheck},
 * {@code ParamFlowChecker.passClusterCheck}) route to the TPU token
 * server with no further code changes. Failure semantics are preserved:
 * a transport failure returns {@code FAIL}, which the checkers translate
 * into {@code fallbackToLocalOrPass}.
 *
 * <p>Server address/namespace come from the standard
 * {@code ClusterClientConfigManager} (the dashboard's cluster-assign flow
 * feeds it), so operationally this client is indistinguishable from the
 * default Netty one.
 *
 * <p>NOTE (sandbox provenance): written against the documented 1.8-era
 * SPI surface; no JVM exists in this build environment, so method
 * signatures should be re-checked against the fork's sentinel-core before
 * the first compile (see BUILD.md).
 */
@Spi(order = -1000)  // win over the default Netty client when present
public class TpuClusterTokenClient implements ClusterTokenClient {

    /** Failed connects are not retried for this long (the default Netty
     * client reconnects asynchronously; a synchronous connect storm on
     * request threads would turn a limiter outage into app latency). */
    private static final long RECONNECT_BACKOFF_MS = 2000;

    // All state below is guarded by the instance monitor: every request
    // runs synchronized, so a close can never free the native handle
    // while another thread is mid-call on it (the shim serializes
    // per-handle anyway, so the monitor adds no throughput cost — pool
    // TpuClusterTokenClient instances for parallelism).
    // volatile: getState()/currentServer() read these WITHOUT the
    // monitor so a hung native request can't stall observability threads;
    // mutation and every native call still run synchronized.
    private volatile Pointer handle;
    private volatile TokenServerDescriptor descriptor;
    private long lastConnectFailMs;

    private synchronized Pointer connectedHandle() {
        if (handle != null) {
            return handle;
        }
        if (System.currentTimeMillis() - lastConnectFailMs < RECONNECT_BACKOFF_MS) {
            return null;  // fast-fail to fallbackToLocalOrPass during outage
        }
        String host = ClusterClientConfigManager.getServerHost();
        int port = ClusterClientConfigManager.getServerPort();
        if (host == null || port <= 0) {
            return null;
        }
        Pointer fresh = SentinelTpuShim.INSTANCE.st_client_connect(
            host, port, ClusterConstants.DEFAULT_CLUSTER_NAMESPACE /* or app name */,
            ClusterClientConfigManager.getRequestTimeout());
        if (fresh == null) {
            lastConnectFailMs = System.currentTimeMillis();
            return null;
        }
        handle = fresh;
        descriptor = new TokenServerDescriptor(host, port);
        RecordLog.info("[TpuClusterTokenClient] connected to {}:{}", host, port);
        return handle;
    }

    private synchronized void dropConnection() {
        if (handle != null) {
            SentinelTpuShim.INSTANCE.st_client_close(handle);
            handle = null;
        }
    }

    @Override
    public void start() {
        connectedHandle();
    }

    @Override
    public void stop() {
        dropConnection();
    }

    @Override
    public int getState() {
        return handle != null ? ClientState.CLIENT_STATUS_STARTED
                              : ClientState.CLIENT_STATUS_OFF;
    }

    @Override
    public TokenServerDescriptor currentServer() {
        return descriptor;
    }

    @Override
    public synchronized TokenResult requestToken(Long flowId, int acquireCount, boolean prioritized) {
        Pointer h = connectedHandle();
        if (h == null || flowId == null) {
            return new TokenResult(TokenResultStatus.FAIL);
        }
        IntByReference extra = new IntByReference();
        int status = SentinelTpuShim.INSTANCE.st_request_token(
            h, flowId, acquireCount, prioritized ? 1 : 0, extra);
        if (status == -1) {
            // ST_FAIL only: transport failure, reconnect next call. Other
            // negative statuses (TOO_MANY_REQUEST=-2, BAD_REQUEST=-4) are
            // real server replies — dropping the connection on them would
            // turn server load-shedding into a reconnect storm.
            dropConnection();
            return new TokenResult(TokenResultStatus.FAIL);
        }
        TokenResult result = new TokenResult(status);
        if (status == TokenResultStatus.SHOULD_WAIT) {
            result.setWaitInMs(extra.getValue());
        } else {
            result.setRemaining(extra.getValue());
        }
        return result;
    }

    @Override
    public synchronized TokenResult requestParamToken(Long flowId, int acquireCount,
                                         Collection<Object> params) {
        Pointer h = connectedHandle();
        if (h == null || flowId == null) {
            return new TokenResult(TokenResultStatus.FAIL);
        }
        SentinelTpuShim.StParam[] arr =
            (SentinelTpuShim.StParam[]) new SentinelTpuShim.StParam().toArray(
                Math.max(params.size(), 1));
        int n = 0;
        for (Object p : params) {
            SentinelTpuShim.StParam sp = arr[n++];
            if (p instanceof Boolean) {
                sp.tag = 2; sp.i = ((Boolean) p) ? 1 : 0;
            } else if (p instanceof Integer || p instanceof Long
                       || p instanceof Short || p instanceof Byte) {
                sp.tag = 0; sp.i = ((Number) p).longValue();
            } else if (p instanceof Double || p instanceof Float) {
                sp.tag = 3; sp.d = ((Number) p).doubleValue();
            } else {
                sp.tag = 1; sp.s = String.valueOf(p);
            }
        }
        int status = SentinelTpuShim.INSTANCE.st_request_param_token(
            h, flowId, acquireCount, arr, n);
        if (status == -1) {  // ST_FAIL only; see requestToken
            dropConnection();
            return new TokenResult(TokenResultStatus.FAIL);
        }
        return new TokenResult(status);
    }

    /** Client lifecycle states (reference ClusterConstants values). */
    static final class ClientState {
        static final int CLIENT_STATUS_OFF = 0;
        static final int CLIENT_STATUS_STARTED = 2;
    }
}

"""Native shim tests: build the C++ library, then prove wire compatibility
by acquiring tokens from the Python token server through the C client.
"""

import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.native import NativeTokenClient, load_shim, native_now_ms

pytestmark = pytest.mark.skipif(load_shim() is None,
                                reason="native toolchain unavailable")


@pytest.fixture()
def token_server(frozen_time):
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="native-res", count=3, cluster_mode=True,
        cluster_config={"flowId": 4242, "thresholdType": THRESHOLD_GLOBAL})])
    server = ClusterTokenServer(
        DefaultTokenService(rules), host="127.0.0.1", port=0).start()
    yield server
    server.stop()


def test_native_client_acquires_tokens(token_server):
    with NativeTokenClient("127.0.0.1", token_server.bound_port) as client:
        got = [client.request_token(4242).status for _ in range(5)]
    assert got.count(TokenResultStatus.OK) == 3
    assert got.count(TokenResultStatus.BLOCKED) == 2


def test_native_client_unknown_flow(token_server):
    with NativeTokenClient("127.0.0.1", token_server.bound_port) as client:
        assert client.request_token(999).status == TokenResultStatus.NO_RULE_EXISTS


def test_native_client_registers_namespace(token_server):
    with NativeTokenClient("127.0.0.1", token_server.bound_port, "nsZ"):
        deadline = time.time() + 2
        while (token_server.service.connections.connected_count("nsZ") == 0
               and time.time() < deadline):
            time.sleep(0.02)
        assert token_server.service.connections.connected_count("nsZ") == 1


def test_native_connect_failure_raises():
    with pytest.raises((ConnectionError, RuntimeError)):
        NativeTokenClient("127.0.0.1", 1, timeout_ms=300)


def test_native_clock_reasonable():
    now = native_now_ms()
    assert now is not None
    assert abs(now - time.time() * 1000) < 5000


@pytest.fixture()
def param_server(frozen_time):
    """Token server with a THRESHOLD_GLOBAL param rule: 2 tokens/s/value."""
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="native-param", count=2, cluster_mode=True,
        cluster_config={"flowId": 7100, "thresholdType": THRESHOLD_GLOBAL})])
    server = ClusterTokenServer(
        DefaultTokenService(rules), host="127.0.0.1", port=0).start()
    yield server
    server.stop()


def test_native_param_token_acquire(param_server):
    """PARAM_FLOW through the C shim: per-value buckets enforced."""
    with NativeTokenClient("127.0.0.1", param_server.bound_port) as client:
        got = [client.request_param_token(7100, 1, ["hotKey"]).status
               for _ in range(4)]
        assert got.count(TokenResultStatus.OK) == 2
        assert got.count(TokenResultStatus.BLOCKED) == 2
        # a different value has its own bucket
        assert client.request_param_token(7100, 1, ["coldKey"]).status \
            == TokenResultStatus.OK
        # unknown flowId -> NO_RULE_EXISTS (client falls back to local)
        assert client.request_param_token(999, 1, ["x"]).status \
            == TokenResultStatus.NO_RULE_EXISTS


def test_native_concurrent_acquires_one_handle(token_server):
    """Multi-in-flight pipelining (r5): 8 threads share ONE handle; xid
    demux must route every response to its caller — the reference Netty
    client's xid->promise behavior, now in the C shim."""
    import threading

    with NativeTokenClient("127.0.0.1", token_server.bound_port) as client:
        results = [None] * 8
        barrier = threading.Barrier(8)

        def worker(tid):
            barrier.wait()
            # mix known and unknown flow ids so a mis-routed response is
            # detectable by status, not just by count
            if tid % 2 == 0:
                results[tid] = client.request_token(4242).status
            else:
                results[tid] = client.request_token(999).status
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    evens = [results[i] for i in range(0, 8, 2)]
    odds = [results[i] for i in range(1, 8, 2)]
    # odd threads asked for an unknown flow: every one must see
    # NO_RULE_EXISTS (a swapped xid would hand them OK/BLOCKED)
    assert all(s == TokenResultStatus.NO_RULE_EXISTS for s in odds)
    assert evens.count(TokenResultStatus.OK) == 3
    assert evens.count(TokenResultStatus.BLOCKED) == 1


def test_native_batch_acquire(token_server):
    """st_request_tokens_batch: one pipelined wire burst, per-request
    statuses in order."""
    with NativeTokenClient("127.0.0.1", token_server.bound_port) as client:
        results = client.request_tokens_batch(
            [(4242, 1, False)] * 5 + [(999, 1, False)])
    statuses = [r.status for r in results]
    assert statuses[:5].count(TokenResultStatus.OK) == 3
    assert statuses[:5].count(TokenResultStatus.BLOCKED) == 2
    assert statuses[5] == TokenResultStatus.NO_RULE_EXISTS


def test_native_slow_response_does_not_brick_handle():
    """A clean per-call timeout (e.g. the server absorbing an XLA
    compile) fails THAT call only: the connection stays usable and the
    late response is discarded by xid (r5 review — previously one
    timeout marked the shared handle dead forever)."""
    import socket
    import threading

    from sentinel_tpu.cluster import codec as cc

    sock = socket.socket()
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    port = sock.getsockname()[1]

    def serve():
        conn, _ = sock.accept()
        reader = cc.FrameReader()
        try:
            while True:
                data = conn.recv(4096)
                if not data:
                    return
                for body in reader.feed(data):
                    req = cc.decode_request(body)
                    if req.msg_type == 0:  # PING
                        conn.sendall(cc.encode_response(req.xid, 0, 0))
                    elif req.xid == 2:  # first acquire: reply LATE
                        def late(xid=req.xid):
                            time.sleep(1.2)
                            try:
                                conn.sendall(cc.encode_response(
                                    xid, 1, 0, cc.encode_flow_response(9, 0)))
                            except OSError:
                                pass
                        threading.Thread(target=late, daemon=True).start()
                    else:  # later acquires: reply promptly
                        conn.sendall(cc.encode_response(
                            req.xid, 1, 0, cc.encode_flow_response(5, 0)))
        except OSError:
            pass

    threading.Thread(target=serve, daemon=True).start()
    with NativeTokenClient("127.0.0.1", port, timeout_ms=400) as client:
        first = client.request_token(1)
        assert first.status == -1  # timed out, honestly failed
        time.sleep(1.0)  # let the stale xid-2 reply arrive and be dropped
        second = client.request_token(1)
        assert second.status == TokenResultStatus.OK
        assert second.remaining == 5  # xid-matched: NOT the stale reply
    sock.close()


@pytest.fixture()
def bridge_server(engine, frozen_time):
    server = ClusterTokenServer(host="127.0.0.1", port=0,
                                engine=engine).start()
    yield server
    server.stop()


def test_native_remote_entry_exit(bridge_server, frozen_time):
    """The M4 bridge through the C shim: pass with id, typed block
    reason, exit commit."""
    st.load_flow_rules([st.FlowRule(resource="shimRes", count=2)])
    with NativeTokenClient("127.0.0.1", bridge_server.bound_port,
                           timeout_ms=120_000) as client:
        outcomes = [client.remote_entry("shimRes", origin="jvm-app")
                    for _ in range(5)]
        ok = [(s, e, r) for s, e, r in outcomes
              if s == TokenResultStatus.OK]
        blocked = [(s, e, r) for s, e, r in outcomes
                   if s == TokenResultStatus.BLOCKED]
        assert len(ok) == 2 and len(blocked) == 3
        assert all(e > 0 for _, e, _ in ok)
        assert all(r == 1 for _, _, r in blocked)  # BlockReason.FLOW
        for _, eid, _ in ok:
            assert client.remote_exit(eid) == TokenResultStatus.OK
        # consumed ids answer BAD_REQUEST
        assert client.remote_exit(ok[0][1]) == TokenResultStatus.BAD_REQUEST


def test_native_remote_entry_params(bridge_server, frozen_time):
    """Hot params ride the shim's ENTRY frame into the param checker."""
    st.load_param_flow_rules(
        [st.ParamFlowRule("shimHot", param_idx=0, count=1)])
    # generous timeout: the first param-family entry absorbs an XLA
    # compile (tens of seconds on the CPU test topology)
    with NativeTokenClient("127.0.0.1", bridge_server.bound_port,
                           timeout_ms=120_000) as client:
        outcomes = [client.remote_entry("shimHot", params=["k1"])
                    for _ in range(3)]
        blocked = [r for s, _, r in outcomes
                   if s == TokenResultStatus.BLOCKED]
        assert len(blocked) >= 1
        assert all(r == 5 for r in blocked)  # BlockReason.PARAM_FLOW


def test_native_param_buckets_shared_with_python_client(param_server):
    """Typed wire params hash identically from C and Python, so both
    clients drain the SAME (flowId, value) bucket — incl. int vs str
    distinction (42 and "42" are different buckets in both languages)."""
    from sentinel_tpu.cluster.client import ClusterTokenClient

    py = ClusterTokenClient("127.0.0.1", param_server.bound_port).start()
    try:
        with NativeTokenClient("127.0.0.1", param_server.bound_port) as c:
            assert c.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.OK
            assert py.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.OK
            # bucket for int 42 is now full (2/2) from both sides
            assert c.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.BLOCKED
            assert py.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.BLOCKED
            # "42" (string) is a distinct typed bucket, still open
            assert c.request_param_token(7100, 1, ["42"]).status \
                == TokenResultStatus.OK
        # mixed types in one request: bool + float + str
        with NativeTokenClient("127.0.0.1", param_server.bound_port) as c:
            assert c.request_param_token(7100, 1, [True, 1.5, "u"]).status \
                == TokenResultStatus.OK
    finally:
        py.stop()

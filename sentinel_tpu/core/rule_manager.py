"""Shared rule-registry base (reference: the ``XxxRuleManager`` pattern —
SURVEY.md §1 "Rules are data, managers are registries").

Every family keeps a list rebuilt wholesale on load (§3.2 swap semantics),
filters invalid rules, and fans out to engine listeners for tensor rebuild.

Staged sources (sentinel_tpu/rollout/): a rule carrying ``candidate_set``
is part of a named CANDIDATE ruleset — it rides the same datasource/push
pipeline and the same wholesale load, but lands in a per-set staged
partition instead of the live list, so a tagged rule can never leak into
enforcement. The rollout manager reads the staged partitions via
:meth:`get_staged` and compiles them into the shadow pack.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, List, TypeVar

R = TypeVar("R")


class RuleManager(Generic[R]):
    def __init__(self):
        self._lock = threading.RLock()
        self._rules: List[R] = []
        self._staged: Dict[str, List[R]] = {}
        self.version = 0
        self._listeners: List[Callable[[], None]] = []

    def load_rules(self, rules: List[R]) -> None:
        with self._lock:
            live: List[R] = []
            staged: Dict[str, List[R]] = {}
            for r in rules:
                if not r.is_valid():
                    continue
                cs = getattr(r, "candidate_set", None)
                if cs:
                    staged.setdefault(cs, []).append(r)
                else:
                    live.append(r)
            self._rules = live
            self._staged = staged
            self.version += 1
            listeners = list(self._listeners)
        for fn in listeners:
            fn()

    def get_rules(self) -> List[R]:
        """The LIVE (enforced) partition only."""
        with self._lock:
            return list(self._rules)

    def get_staged(self, name: str = None):
        """Staged candidate rules: ``{set_name: rules}`` (or one set's
        list when ``name`` is given). Valid-filtered like the live list."""
        with self._lock:
            if name is not None:
                return list(self._staged.get(name, []))
            return {k: list(v) for k, v in self._staged.items()}

    def add_listener(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

"""Per-thread call context.

Reference: ``core:context/Context.java`` + ``ContextUtil`` (SURVEY.md §2.1).
A context names the entrance (call chain root) and carries the caller origin;
entries nest in a stack per thread. Oversized context names yield a
``NullContext`` → pass-through entries with no protection, exactly like the
reference (``MAX_CONTEXT_NAME_SIZE``).
"""

from __future__ import annotations

import contextvars
from typing import List, Optional

from sentinel_tpu.core.constants import CONTEXT_DEFAULT_NAME, MAX_CONTEXT_NAME_SIZE


# Bumped on every engine reset: a context created under a previous engine
# holds row ids interned in that engine's registry, and using them against
# a fresh (possibly smaller) registry corrupts stats or raises. The stamp
# invalidates stale contexts on EVERY thread, not just the resetting one.
_generation = 0


def bump_generation() -> None:
    global _generation
    _generation += 1


class Context:
    __slots__ = ("name", "origin", "entry_stack", "entrance_row", "is_null",
                 "auto_created", "generation")

    def __init__(self, name: str, origin: str = "", entrance_row: int = -1):
        self.name = name
        self.origin = origin
        self.entrance_row = entrance_row
        self.entry_stack: List = []
        self.is_null = False
        # True when the engine materialized the default context itself; such
        # contexts are torn down automatically when their last entry exits
        # (reference: default-context auto-exit in CtEntry.trueExit).
        self.auto_created = False
        self.generation = _generation

    @property
    def cur_entry(self):
        return self.entry_stack[-1] if self.entry_stack else None


class NullContext(Context):
    def __init__(self):
        super().__init__("", "")
        self.is_null = True


# A ContextVar isolates the call context per thread AND per asyncio task
# (the reference's ThreadLocal only covers threads; async adapters need
# task isolation — concurrent requests interleaved on one event-loop
# thread must not share a context).
_ctx_var: contextvars.ContextVar[Optional[Context]] = contextvars.ContextVar(
    "sentinel_context", default=None)


def get_context() -> Optional[Context]:
    ctx = _ctx_var.get()
    if ctx is not None and ctx.generation != _generation:
        _ctx_var.set(None)  # stale: predates the current engine
        return None
    return ctx


def enter(name: str = CONTEXT_DEFAULT_NAME, origin: str = "") -> Context:
    """``ContextUtil.enter``. Idempotent for the same name on one thread."""
    ctx = get_context()
    if ctx is not None and not ctx.is_null:
        return ctx
    if len(name) > MAX_CONTEXT_NAME_SIZE or not name:
        ctx = NullContext()
    else:
        ctx = Context(name, origin)
    _ctx_var.set(ctx)
    return ctx


# Pool of ONE auto-created default context per thread/task: the
# entry_ok() fast path with no explicit context would otherwise allocate
# a Context AND re-resolve its entrance row (a registry-lock hit) on
# EVERY entry/exit pair — measured ~1.5µs of the leased path's ~9µs
# budget. The pooled object is reused only when its entry stack drained
# (auto_exit_context pops it from the active var but leaves it here) and
# its generation is current; an engine reset invalidates it like any
# other context.
_auto_pool: contextvars.ContextVar[Optional[Context]] = contextvars.ContextVar(
    "sentinel_auto_context", default=None)


def enter_auto() -> Context:
    """Engine-internal: materialize (or reuse) the auto default context."""
    ctx = _auto_pool.get()
    if (ctx is None or ctx.generation != _generation or ctx.entry_stack
            or ctx.origin):
        ctx = Context(CONTEXT_DEFAULT_NAME, "")
        ctx.auto_created = True
        _auto_pool.set(ctx)
    _ctx_var.set(ctx)
    return ctx


def exit_context() -> None:
    """``ContextUtil.exit``: drop the context if no entries remain."""
    ctx = get_context()
    if ctx is not None and not ctx.entry_stack:
        _ctx_var.set(None)


def auto_exit_context() -> None:
    """Drop only an engine-created default context once its entries drain."""
    ctx = get_context()
    if ctx is not None and ctx.auto_created and not ctx.entry_stack:
        _ctx_var.set(None)


def replace_context(ctx: Optional[Context]) -> None:
    _ctx_var.set(ctx)

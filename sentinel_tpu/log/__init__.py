"""Record / block logging (reference: ``core:log/`` — ``RecordLog``,
``LogBase``, plus the block log written by ``LogSlot``; SURVEY.md §2.1, §5).
"""

from sentinel_tpu.log.record_log import RecordLog, block_log, record_log

__all__ = ["RecordLog", "block_log", "record_log"]

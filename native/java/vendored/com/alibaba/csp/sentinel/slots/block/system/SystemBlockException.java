package com.alibaba.csp.sentinel.slots.block.system;

import com.alibaba.csp.sentinel.slots.block.BlockException;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/block/system/SystemBlockException.java. */
public class SystemBlockException extends BlockException {

    private final String resourceName;

    public SystemBlockException(String resourceName, String limitType) {
        super(resourceName, limitType);
        this.resourceName = resourceName;
    }

    public String getResourceName() {
        return resourceName;
    }
}

"""Seedable retry schedules: exponential backoff + decorrelated jitter.

One policy object describes the schedule (base, cap, growth, jitter
mode, seed); each retrying loop gets its own :class:`RetrySession` so
independent loops (token-client reconnect, datasource poll, heartbeat
rotation) never share mutable state. Sessions are deterministic for a
given seed — the chaos suite pins seeds and asserts exact delays.

Jitter modes ("Exponential Backoff And Jitter", AWS architecture blog —
the scheme the reference ecosystem's clients converged on):

* ``decorrelated`` (default): ``next = min(cap, uniform(base, prev * mult))``
  — spreads a thundering herd without ever dropping below ``base``.
* ``full``: ``next = uniform(0, min(cap, base * mult**attempt))``.
* ``none``: plain exponential ``min(cap, base * mult**attempt)`` —
  bit-reproducible schedules for tests that want exact values.

The FIRST delay of every session is exactly ``base_ms`` in all modes, so
swapping a fixed-interval loop for a policy keeps its steady-state
cadence until something actually fails repeatedly.
"""

from __future__ import annotations

import random
from typing import Optional


class RetrySession:
    """Mutable per-loop state: call :meth:`next_delay_ms` before each
    retry, :meth:`reset` after any success."""

    __slots__ = ("policy", "_rng", "_prev_ms", "attempt")

    def __init__(self, policy: "RetryPolicy", rng: random.Random):
        self.policy = policy
        self._rng = rng
        self._prev_ms = None
        self.attempt = 0

    def next_delay_ms(self) -> int:
        p = self.policy
        self.attempt += 1
        if self._prev_ms is None:
            self._prev_ms = p.base_ms
            return p.base_ms
        if p.jitter == "decorrelated":
            nxt = self._rng.uniform(p.base_ms, self._prev_ms * p.multiplier)
        elif p.jitter == "full":
            nxt = self._rng.uniform(
                0, min(p.max_ms, p.base_ms * p.multiplier ** (self.attempt - 1)))
        else:  # "none"
            nxt = self._prev_ms * p.multiplier
        self._prev_ms = min(int(nxt), p.max_ms)
        return max(0, self._prev_ms)

    def reset(self) -> None:
        self._prev_ms = None
        self.attempt = 0


class RetryPolicy:
    """Immutable schedule description; :meth:`session` mints loop state."""

    def __init__(self, base_ms: int = 500, max_ms: int = 30_000,
                 multiplier: float = 3.0, jitter: str = "decorrelated",
                 seed: Optional[int] = None):
        if base_ms <= 0 or max_ms < base_ms or multiplier < 1.0:
            raise ValueError(
                f"invalid retry policy: base={base_ms}ms max={max_ms}ms "
                f"multiplier={multiplier}")
        if jitter not in ("decorrelated", "full", "none"):
            raise ValueError(f"unknown jitter mode {jitter!r}")
        self.base_ms = int(base_ms)
        self.max_ms = int(max_ms)
        self.multiplier = float(multiplier)
        self.jitter = jitter
        self.seed = seed

    def session(self) -> RetrySession:
        # A fresh seeded stream per session: two sessions of one policy
        # replay the same schedule (determinism beats decorrelation
        # between loops of one process — cross-process herds decorrelate
        # via per-process seeds).
        return RetrySession(self, random.Random(self.seed))

    @classmethod
    def from_config(cls, component: str, base_ms: int, max_ms: int,
                    multiplier: float = 3.0,
                    jitter: str = "decorrelated") -> "RetryPolicy":
        """Build from ``csp.sentinel.resilience.*`` config, most-specific
        key first: ``…resilience.<component>.retry.base.ms`` overrides
        ``…resilience.retry.base.ms`` overrides the caller's default.
        The shared ``csp.sentinel.resilience.seed`` pins every policy in
        the process (the chaos suite sets it)."""
        from sentinel_tpu.core.config import RESILIENCE_SEED, config

        def _get(suffix: str, default):
            for key in (f"csp.sentinel.resilience.{component}.{suffix}",
                        f"csp.sentinel.resilience.{suffix}"):
                v = config.get(key)
                if v is not None:
                    try:
                        return type(default)(v)
                    except (TypeError, ValueError):
                        pass
            return default

        seed_raw = config.get(RESILIENCE_SEED)
        try:
            seed = int(seed_raw) if seed_raw is not None else None
        except ValueError:
            seed = None
        cfg_base = _get("retry.base.ms", int(base_ms))
        cfg_max = max(_get("retry.max.ms", int(max_ms)), cfg_base)
        try:
            return cls(base_ms=cfg_base, max_ms=cfg_max,
                       multiplier=_get("retry.multiplier", float(multiplier)),
                       jitter=_get("retry.jitter", jitter),
                       seed=seed)
        except ValueError as ex:
            # A config typo must not turn into a component-startup crash
            # (same warn-and-default stance as the engine's budget key).
            from sentinel_tpu.log.record_log import record_log

            record_log.warn("invalid resilience retry config for %r (%s); "
                            "using defaults", component, ex)
            return cls(base_ms=base_ms, max_ms=max_ms,
                       multiplier=multiplier, jitter=jitter, seed=seed)

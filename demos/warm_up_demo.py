"""WarmUpFlowDemo (reference: ``sentinel-demo-basic``): a cold system is
throttled to count/coldFactor and ramps to the full threshold over the
warm-up period."""

import _demo_env  # noqa: F401

import time

import sentinel_tpu as st
from sentinel_tpu.core import constants as C

st.load_flow_rules([st.FlowRule(
    resource="warm", count=30, control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
    warm_up_period_sec=6)])

h = st.entry_ok("_warmup")  # absorb the XLA compile before timing
if h:
    h.exit()

print("cold start: expect ~10/s (count/coldFactor), ramping to 30/s")
for second in range(8):
    passed = blocked = 0
    t_end = time.time() + 1
    while time.time() < t_end:
        if st.entry_ok("warm"):
            passed += 1
        else:
            blocked += 1
    print(f"t={second}s  pass={passed:3d}  block={blocked:5d}")

# Orderly engine shutdown: a daemon committer thread killed mid-XLA
# call at interpreter exit aborts the process (core/lease.py).
st.get_engine().close()

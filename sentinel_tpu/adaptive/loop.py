"""The closed loop: sense -> propose -> shadow -> canary -> promote.

``AdaptiveLoop`` is the only actuator in ``sentinel_tpu/adaptive/`` and
it owns exactly ZERO rule-mutation paths of its own: every candidate it
emits goes through :class:`~sentinel_tpu.rollout.manager.RolloutManager`
(``load_candidate`` -> shadow would-verdict evaluation -> canary ->
``promote``), so the PR 2 block-rate guardrail and the PR 7 SLO-breach
auto-abort are the blast shield for every autonomous change
(tests/test_lint.py pins that no code in this package calls
``load_rules``). The safety invariants — floor/ceiling, bounded step,
cooldown, hysteresis, global freeze, post-abort backoff — live in
``envelope.py``; the policy brain in ``controller.py``.

Cadence contract (the PR 7 stance): the loop rides the engine's
once-per-second flight-recorder spill (``engine._spill_flight`` calls
:meth:`on_spill`), gated to one evaluation per
``csp.sentinel.adaptive.interval.seconds``, so a disabled or idle loop
adds zero per-step device work and no background thread. The
``adaptive`` ops command's ``op=tick`` forces an evaluation for drills
and tests.

Last-known-good: the loop snapshots the live flow rules at every
promotion (and at ``enable()``). Because candidates are never applied
directly, an abort at ANY stage leaves the live rules exactly at that
snapshot — the loop additionally verifies this (``lkgIntact`` on the
abort decision) and re-proposes nothing for the configured backoff.

Decision log: every propose/escalate/promote/abort/freeze/clamp is one
seq-numbered entry in a bounded deque — the ``adaptive`` command's
``history`` cursor space (same shape as the SLO transition log).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import replace as dc_replace
from typing import Dict, List, Optional

from sentinel_tpu.adaptive.controller import (
    AdaptiveController,
    AdaptiveTarget,
    AimdPolicy,
)
from sentinel_tpu.adaptive.envelope import (
    FreezeGate,
    SafetyEnvelope,
)
from sentinel_tpu.core import constants as C
from sentinel_tpu.log.record_log import record_log
from sentinel_tpu.rollout.manager import (
    ACTIVE_STAGES,
    STAGE_ABORTED,
    STAGE_CANARY,
    STAGE_PROMOTED,
    STAGE_SHADOW,
)
CANDIDATE_PREFIX = "adaptive-"


def _tunable(rule) -> bool:
    """Only plain direct-strategy QPS rules with the default limit-app
    and default control behavior are adaptive-tunable: every other shape
    (warm-up ramps, rate limiters, per-origin carve-outs, cluster-mode
    global budgets) encodes operator intent the loop must not rewrite."""
    return (rule.grade == C.FLOW_GRADE_QPS
            and rule.strategy == C.FLOW_STRATEGY_DIRECT
            and rule.control_behavior == C.CONTROL_BEHAVIOR_DEFAULT
            and rule.limit_app == C.LIMIT_APP_DEFAULT
            and not rule.cluster_mode)


class AdaptiveLoop:
    """Owns the propose->rollout lifecycle + decision log for one engine."""

    def __init__(self, engine):
        from sentinel_tpu.core.config import config as _cfg

        self.engine = engine
        self._lock = threading.RLock()
        # Non-reentrant tick gate: the tick itself refreshes judgement
        # (slo_refresh -> _spill_flight -> on_spill), which would recurse
        # back into tick(); acquire(blocking=False) turns that recursion
        # (and any concurrent ops-plane tick) into a cheap no-op.
        self._tick_gate = threading.Lock()
        self.interval_s = _cfg.adaptive_interval_seconds()
        self.shadow_soak_s = _cfg.adaptive_shadow_seconds()
        self.canary_soak_s = _cfg.adaptive_canary_seconds()
        self.canary_bps = _cfg.adaptive_canary_bps()
        self.backoff_s = _cfg.adaptive_abort_backoff_seconds()
        self.controller = AdaptiveController(AimdPolicy(
            increase_pct=_cfg.adaptive_increase_pct(),
            decrease_pct=_cfg.adaptive_decrease_pct(),
            hysteresis_pct=_cfg.adaptive_hysteresis_pct()))
        self.envelope = SafetyEnvelope(
            step_pct=_cfg.adaptive_step_pct(),
            cooldown_ms=_cfg.adaptive_cooldown_seconds() * 1000)
        self.freeze_gate = FreezeGate(
            stale_after_ms=_cfg.adaptive_freeze_stale_seconds() * 1000)
        self._enabled = _cfg.adaptive_enabled()
        self._manual_frozen = False
        self._freeze_reason: Optional[str] = None
        self._backoff_until_ms = 0
        # In-flight adaptive candidate + the changes it carries.
        self._inflight: Optional[str] = None
        self._inflight_changes: List[Dict] = []
        self._healthy_windows = 0
        self._candidate_seq = 0
        # Monotone counters (exporter families).
        self.proposal_count = 0
        self.promotion_count = 0
        self.abort_count = 0
        self.clamp_count = 0
        # Decision log: bounded, seq-cursored (`adaptive` command history).
        self._events: deque = deque(maxlen=_cfg.adaptive_history_capacity())
        self._seq = 0
        # Control-plane audit journal (ISSUE 14): every decision mirrors
        # into it with causality back-pointers (canary -> its propose,
        # promote -> its canary, abort -> the freeze that killed it),
        # and — the restart fix — a file-backed journal re-seeds the
        # decision log + seq cursor here so `history sinceSeq=` cursors
        # survive a process restart.
        self._journal = getattr(engine, "journal", None)
        self._jseq: Dict[str, int] = {}  # decision kind -> journal seq
        if self._journal is not None:
            for rec in self._journal.replay(kind="adaptiveDecision"):
                ev = rec.get("event")
                if isinstance(ev, dict) and "seq" in ev:
                    self._events.append(ev)
                    self._seq = max(self._seq, int(ev["seq"]))
        # Freeze inputs: fault-channel baseline (deltas, not absolutes —
        # a long-lived engine's historical fallbacks must not freeze the
        # loop forever) and envelope-rejection dedup for the log.
        self._fault_baseline: Optional[int] = None
        self._last_reject: Dict[str, str] = {}
        self._last_senses: Dict = {}
        # Last-known-good: {family: [rules]} snapshot + stamp.
        self._lkg: Optional[Dict[str, list]] = None
        self._lkg_ms = 0
        self._last_tick_ms = 0
        # Aborts/promotions landing OUTSIDE a tick (operator `rollout
        # abort`, a dashboard-driven guardrail tick) arrive through the
        # rollout lifecycle listener; appended lock-free (the listener
        # fires under the engine config lock — taking self._lock there
        # would invert the tick's lock order), drained by the next tick.
        self._rollout_events: deque = deque(maxlen=16)
        engine.rollout.add_lifecycle_listener(self._on_rollout_event)
        if self._enabled:
            self._capture_lkg()

    # -- rollout lifecycle listener (runs under the engine config lock) --

    def _on_rollout_event(self, event: str, cand, reason) -> None:
        if cand.name.startswith(CANDIDATE_PREFIX):
            self._rollout_events.append(
                (event, cand.name, reason,
                 self.engine.now_ms()))

    # -- ops controls ------------------------------------------------------

    def enable(self) -> Dict:
        with self._lock:
            if not self._enabled:
                self._enabled = True
                self._capture_lkg()
                self._log("enabled")
            return {"enabled": True}

    def disable(self) -> Dict:
        """Disable aborts any in-flight adaptive candidate: a canary
        left enforcing with nobody watching the guardrail results would
        be an unsupervised autonomous change — exactly what this
        subsystem exists to prevent."""
        with self._lock:
            inflight = self._inflight
            if self._enabled:
                self._enabled = False
                self._log("disabled")
        if inflight is not None:
            self._abort_inflight("adaptive disabled")
        return {"enabled": False}

    def freeze(self, reason: str = "ops") -> Dict:
        from sentinel_tpu.adaptive.envelope import FREEZE_MANUAL

        with self._lock:
            if not self._manual_frozen:
                self._manual_frozen = True
                # Surface immediately (status must not wait a tick);
                # subsequent ticks recompute and agree (manual has top
                # precedence in the gate).
                self._freeze_reason = FREEZE_MANUAL
                self._log("freeze", reason=f"manual: {reason}")
            inflight = self._inflight
        if inflight is not None:
            self._abort_inflight(f"adaptive freeze: manual ({reason})")
        return {"frozen": True}

    def unfreeze(self) -> Dict:
        from sentinel_tpu.adaptive.envelope import FREEZE_MANUAL

        with self._lock:
            if self._manual_frozen:
                self._manual_frozen = False
                if self._freeze_reason == FREEZE_MANUAL:
                    self._freeze_reason = None
                self._log("unfreeze")
            return {"frozen": False}

    def reset_timebase(self) -> None:
        """Forget absolute-stamp state (the engine's ``set_clock``
        seam): the abort backoff and the envelope's per-resource
        cooldown stamps are wall-clock absolutes — after a backward
        timebase swap `now < backoff_until_ms` would hold for (simulated)
        decades and the loop would report frozen-in-backoff forever.
        An in-flight candidate is aborted FIRST (the freeze stance: its
        ``stage_since_ms`` soak age is meaningless across timebases, so
        it would otherwise sit "soaking" forever and block proposals);
        the backoff that abort arms is then cleared with the rest.
        Counters, targets, and the decision log survive; the LKG
        snapshot's rules survive too (only its stamp is refreshed)."""
        self._abort_inflight("timebase swap")
        now = self.engine.now_ms()
        with self._lock:
            self._backoff_until_ms = 0
            self._last_tick_ms = 0
            self._fault_baseline = None
            if self._lkg is not None:
                self._lkg_ms = now
        self.envelope.reset()

    def load_targets(self, targets: List[AdaptiveTarget]) -> None:
        from sentinel_tpu.datasource.converters import adaptive_target_to_dict
        from sentinel_tpu.telemetry.journal import MAX_RULES_PER_RECORD

        with self._lock:
            self.controller.load_targets(targets)
            # Target dicts ride the decision event into the journal, so
            # a propose's causeSeq walk lands on the exact objective set
            # (with datasource provenance) that shaped it — capped like
            # every other load record (the count stays exact).
            self._log("targets", count=len(targets),
                      targets=[adaptive_target_to_dict(t)
                               for t in targets[:MAX_RULES_PER_RECORD]],
                      targetsTruncated=len(targets) > MAX_RULES_PER_RECORD)

    # -- the loop ----------------------------------------------------------

    def on_spill(self, now_ms: int) -> None:
        """Ride the once-per-second fold: evaluate at most once per
        configured interval. Zero work while disabled beyond two reads.

        The interval gate must survive a clock that stepped BACKWARD
        (NTP slew, a test re-freezing to an earlier epoch, a simulator
        timebase installed on a live engine): with the old stamp ahead
        of ``now_ms`` the subtraction stays negative and the loop would
        silently never tick again — the latent real-time-monotonicity
        assumption ISSUE 13's clock seam flushed out. A backward jump
        re-arms the gate at the new timebase instead."""
        if not self._enabled:
            return
        if now_ms < self._last_tick_ms:
            self._last_tick_ms = now_ms  # clock stepped back: re-arm
        if now_ms - self._last_tick_ms < self.interval_s * 1000:
            return
        self.tick(now_ms)

    def tick(self, now_ms: Optional[int] = None, force: bool = False) -> Dict:
        """One closed-loop evaluation. Reentry-safe (the judgement
        refresh below recurses into on_spill) and concurrency-safe (a
        second caller gets ``busy`` instead of a double actuation)."""
        if not self._tick_gate.acquire(blocking=False):
            return {"status": "busy"}
        try:
            now = (now_ms if now_ms is not None
                   else self.engine.now_ms())
            if force:
                # Ops/test-driven ticks bring judgement current first;
                # spill-driven ticks ride a spill that just did.
                self.engine.slo_refresh(now_ms=now)
            return self._tick(now)
        finally:
            self._tick_gate.release()

    def _tick(self, now: int) -> Dict:
        with self._lock:
            self._last_tick_ms = now
            self._drain_rollout_events()
            if not self._enabled:
                return {"status": "disabled"}
            fault_delta = self._fault_delta()
            freeze = self.freeze_gate.evaluate(
                now,
                manual_frozen=self._manual_frozen,
                recorder_enabled=self.engine.flight_seconds > 0,
                last_second_ms=self.engine.timeseries.last_stamp_ms,
                fault_delta=fault_delta,
                backoff_until_ms=self._backoff_until_ms)
            if freeze.reason != self._freeze_reason:
                self._freeze_reason = freeze.reason
                if freeze.frozen:
                    self._log("freeze", reason=freeze.reason)
                else:
                    self._log("thaw")
            inflight = self._inflight
        if freeze.frozen:
            # Frozen senses cannot be trusted to graduate a candidate
            # either — tear any in-flight one down. Like EVERY abort,
            # this arms the backoff (OPERATIONS: "quiet period after ANY
            # abort"), so a transient freeze that killed a candidate is
            # followed by the full quiet window after the thaw.
            if inflight is not None:
                self._abort_inflight(f"adaptive freeze: {freeze.reason}")
            return {"status": "frozen", "reason": freeze.reason,
                    "timestamp": now}
        if inflight is not None:
            return self._drive_inflight(now)
        return self._propose(now)

    # -- freeze inputs -----------------------------------------------------

    def _fault_delta(self) -> int:
        """Fail-open + cluster-degradation events since the previous
        tick: any of them means entries passed (or degraded) OUTSIDE the
        recorded device path this window, so the series the controller
        would judge is missing exactly the traffic that misbehaved."""
        eng = self.engine
        total = (eng.fail_open_count + eng.cluster_fallback_count
                 + eng.cluster_budget_exhausted_count
                 + eng.cluster_overload_count)
        last, self._fault_baseline = self._fault_baseline, total
        if last is None:
            return 0
        return max(0, total - last)

    # -- in-flight candidate driving ---------------------------------------

    def _drive_inflight(self, now: int) -> Dict:
        rollout = self.engine.rollout
        with self._lock:
            name = self._inflight
        if name is None:
            # disable()/freeze() settled the books between _tick's
            # locked capture and here — nothing left to drive.
            return {"status": "settled", "candidate": None}
        cand = rollout.candidate(name)
        if cand is None or cand.stage not in ACTIVE_STAGES:
            # Ended outside this tick (operator promote/abort, source
            # removal) — the listener queued it; settle the books now.
            self._settle_ended(name, cand, now)
            return {"status": "settled", "candidate": name}
        result = rollout.tick(now_ms=now)
        cand = rollout.candidate(name)
        if cand is None or cand.stage == STAGE_ABORTED:
            self._note_abort(name, cand.ended_reason if cand else "gone", now)
            return {"status": "aborted", "candidate": name,
                    "rollout": result}
        with self._lock:
            if result.get("status") == "ok" and not result.get("breach"):
                self._healthy_windows += 1
            elif result.get("breach"):
                self._healthy_windows = 0
            age_ms = now - cand.stage_since_ms
            healthy = self._healthy_windows >= 1 \
                and rollout.guardrail_state()["breachStreak"] == 0
        if cand.stage == STAGE_SHADOW \
                and age_ms >= self.shadow_soak_s * 1000 and healthy:
            rollout.set_stage(name, STAGE_CANARY, canary_bps=self.canary_bps)
            with self._lock:
                self._healthy_windows = 0
                self._log("canary", candidate=name,
                          canaryBps=self.canary_bps)
            return {"status": "canary", "candidate": name}
        if cand.stage == STAGE_CANARY \
                and age_ms >= self.canary_soak_s * 1000 and healthy:
            rollout.promote(name)
            self._note_promotion(name, now)
            return {"status": "promoted", "candidate": name}
        return {"status": "soaking", "candidate": name,
                "stage": cand.stage, "ageMs": age_ms,
                "rollout": result}

    def _settle_ended(self, name: str, cand, now: int) -> None:
        """The in-flight candidate ended without us driving it."""
        if cand is not None and cand.stage == STAGE_PROMOTED:
            self._note_promotion(name, now)
        else:
            self._note_abort(
                name, cand.ended_reason if cand else "gone", now)

    def _drain_rollout_events(self) -> None:
        """Caller holds self._lock. Listener-queued endings matter only
        when they concern a candidate we still think is in flight —
        everything else was settled by the tick that drove it."""
        while self._rollout_events:
            event, name, reason, _ms = self._rollout_events.popleft()
            if name != self._inflight:
                continue
            now = self.engine.now_ms()
            if event == "promoted":
                self._note_promotion(name, now)
            else:
                self._note_abort(name, reason, now)

    def _note_promotion(self, name: str, now: int) -> None:
        with self._lock:
            if self._inflight != name:
                return  # books already settled (racing settle paths)
            changes = self._inflight_changes
            for ch in changes:
                self.envelope.record_actuation(
                    ch["resource"], ch["from"], ch["to"], now)
            self.promotion_count += 1
            self._inflight = None
            self._inflight_changes = []
            self._healthy_windows = 0
            self._log("promote", candidate=name, changes=[
                {k: ch[k] for k in ("resource", "from", "to")}
                for ch in changes])
            # Next cycle's decisions must not link back to THIS
            # candidate's lifecycle records.
            self._jseq.pop("propose", None)
            self._jseq.pop("canary", None)
        self._capture_lkg()

    def _note_abort(self, name: str, reason, now: int) -> None:
        with self._lock:
            if self._inflight != name:
                return  # books already settled (racing settle paths)
            self.abort_count += 1
            self._backoff_until_ms = now + self.backoff_s * 1000
            self._inflight = None
            self._inflight_changes = []
            self._healthy_windows = 0
            self._log("abort", candidate=name, reason=str(reason),
                      backoffUntilMs=self._backoff_until_ms,
                      lkgIntact=self._lkg_intact())
            self._jseq.pop("propose", None)
            self._jseq.pop("canary", None)
        record_log.warn("adaptive candidate %s aborted: %s (backoff %ss)",
                        name, reason, self.backoff_s)

    def _abort_inflight(self, reason: str) -> None:
        """Abort our in-flight candidate through the rollout manager
        (never any other path). Benign if someone else already ended it."""
        name = self._inflight
        if name is None:
            return
        try:
            self.engine.rollout.abort(name, reason=reason)
        except ValueError:
            pass  # already ended; the listener/queue settles the books
        cand = self.engine.rollout.candidate(name)
        self._note_abort(
            name, cand.ended_reason if cand else reason,
            self.engine.now_ms())

    # -- proposing ---------------------------------------------------------

    def _propose(self, now: int) -> Dict:
        eng = self.engine
        targets = self.controller.targets()
        if not targets:
            return {"status": "no-targets"}
        view = eng.timeseries_view(limit=self.interval_s, now_ms=now)
        with self._lock:
            senses = self.controller.fold_senses(view["seconds"])
            self._last_senses = senses
            currents = self._tunable_counts(
                {t.resource for t in targets})
            desires = self.controller.desired(senses, currents)
            # An active alert on a resource (ANY severity — anomalies
            # vote here even though they don't vote on rollout aborts: a
            # PROPOSAL has no canary blast shield yet) gates it out.
            alerted = {a["resource"] for a in eng.slo.active_alerts_on(
                {d["resource"] for d in desires})} if desires else set()
            changes = []
            for d in desires:
                res = d["resource"]
                if res in alerted:
                    self._log_reject(res, "alert-active", d)
                    continue
                t = d["target"]
                env = self.envelope.admit(
                    res, d["current"], d["proposed"],
                    t.floor, t.ceiling, now)
                if env.clamped:
                    self.clamp_count += 1
                if not env.allowed:
                    self._log_reject(res, env.reason, d)
                    continue
                self._last_reject.pop(res, None)
                changes.append({
                    "resource": res, "from": d["current"],
                    "to": env.value, "clamped": env.clamped,
                    "why": self._why(d),
                })
            if not changes:
                return {"status": "steady", "timestamp": now,
                        "sensedResources": len(senses)}
            self._candidate_seq += 1
            name = f"{CANDIDATE_PREFIX}{self._candidate_seq}"
        rules = self._candidate_rules(changes)
        try:
            eng.rollout.load_candidate(
                name, {"flow": rules}, stage=STAGE_SHADOW, source="adaptive")
        except ValueError as ex:
            # Another candidate (an operator's) holds the device: the
            # human rollout wins, the loop stays out of the way.
            with self._lock:
                self._log("skip", reason=str(ex))
            return {"status": "skipped", "reason": str(ex)}
        with self._lock:
            # disable()/freeze() racing this staging saw no in-flight
            # candidate to abort — if either landed while we were
            # installing, the candidate must not be left stranded in
            # shadow with nobody driving it (the lease fast path stands
            # down while ANY candidate holds the device).
            stranded = not self._enabled or self._manual_frozen
            if not stranded:
                self._inflight = name
                self._inflight_changes = changes
                self._healthy_windows = 0
                self.proposal_count += len(changes)
                self._log("propose", candidate=name, changes=[
                    {k: ch[k] for k in ("resource", "from", "to", "why")}
                    for ch in changes])
        if stranded:
            try:
                eng.rollout.abort(
                    name, reason="adaptive disabled/frozen during staging")
            except ValueError:
                pass  # someone already ended it
            with self._lock:
                self._log("skip", reason="disabled/frozen during staging")
            return {"status": "skipped",
                    "reason": "disabled/frozen during staging"}
        return {"status": "proposed", "candidate": name,
                "changes": len(changes)}

    def _why(self, desire: Dict) -> str:
        s, t = desire["sense"], desire["target"]
        if desire["proposed"] < desire["current"]:
            return (f"rtP99 {s.rt_p99_ms:.1f}ms > target "
                    f"{t.rt_p99_ms:.1f}ms")
        return (f"blockRate {s.block_rate:.4f} > target "
                f"{t.max_block_rate:.4f}")

    def _log_reject(self, resource: str, reason: str, desire: Dict) -> None:
        """Caller holds self._lock. A pinned/cooling resource would
        otherwise re-log the identical rejection every interval — log
        transitions only."""
        if self._last_reject.get(resource) == reason:
            return
        self._last_reject[resource] = reason
        self._log("reject", resource=resource, reason=reason,
                  proposed=round(desire["proposed"], 4),
                  current=desire["current"])

    def _tunable_counts(self, resources) -> Dict[str, float]:
        """resource -> live count of its ONE tunable QPS rule. Resources
        with zero or several tunable rules are skipped (ambiguous —
        which one encodes 'the limit'?); docs/OPERATIONS.md documents
        pinning via target removal or a second rule shape."""
        by_res: Dict[str, list] = {}
        for r in self.engine.flow_rules.get_rules():
            if r.resource in resources and _tunable(r):
                by_res.setdefault(r.resource, []).append(r)
        return {res: float(rules[0].count)
                for res, rules in by_res.items() if len(rules) == 1}

    def _candidate_rules(self, changes: List[Dict]) -> List:
        """The changed rules only: rollout merge semantics keep every
        untouched live rule in force, and a candidate touching ONLY the
        tuned resources keeps the SLO-abort blast radius tight."""
        targeted = {ch["resource"]: ch["to"] for ch in changes}
        out = []
        for r in self.engine.flow_rules.get_rules():
            if r.resource in targeted and _tunable(r):
                out.append(dc_replace(r, count=targeted[r.resource]))
        return out

    # -- last-known-good ---------------------------------------------------

    def _capture_lkg(self) -> None:
        rules = list(self.engine.flow_rules.get_rules())
        with self._lock:
            self._lkg = {"flow": rules}
            self._lkg_ms = self.engine.now_ms()

    def _lkg_intact(self) -> bool:
        """Live rules byte-equal the retained snapshot (rules are frozen
        dataclasses — equality is field-wise). False does NOT trigger
        any actuation: a datasource push is allowed to move the world
        under the loop; this is the abort log's honesty bit."""
        if self._lkg is None:
            return False
        return list(self.engine.flow_rules.get_rules()) == self._lkg["flow"]

    def last_known_good(self) -> Optional[Dict[str, list]]:
        with self._lock:
            return ({fam: list(rs) for fam, rs in self._lkg.items()}
                    if self._lkg is not None else None)

    # -- log + read surfaces -----------------------------------------------

    def _log(self, kind: str, **fields) -> None:
        """Caller holds self._lock."""
        self._seq += 1
        event = {"seq": self._seq, "kind": kind,
                 "timestamp": self.engine.now_ms(), **fields}
        self._events.append(event)
        if self._journal is not None:
            self._jseq[kind] = self._journal.record(
                "adaptiveDecision", cause_seq=self._decision_cause(kind),
                event=dict(event))

    def _decision_cause(self, kind: str) -> Optional[int]:
        """The journal seq that SHAPED this decision: a canary links to
        its propose, a promote to the canary it graduated from, an
        abort to the freeze that killed it (else the stage it died in),
        a propose to the target load it serves. Caller holds _lock."""
        j = self._jseq
        if kind == "canary":
            return j.get("propose")
        if kind == "promote":
            return j.get("canary") or j.get("propose")
        if kind == "abort":
            return j.get("freeze") or j.get("canary") or j.get("propose")
        if kind == "thaw":
            return j.get("freeze")
        if kind == "propose":
            return j.get("targets")
        return None

    def history(self, since_seq: int = 0,
                limit: Optional[int] = None) -> Dict:
        with self._lock:
            events = [dict(e) for e in self._events
                      if e["seq"] > since_seq]
            if limit is not None and limit >= 0:
                # events[-0:] would be the whole log (the SLO alerts
                # lesson): limit=0 means "cursor only".
                events = events[-limit:] if limit > 0 else []
            return {"events": events, "nextSeq": self._seq}

    def status(self) -> Dict:
        from sentinel_tpu.datasource.converters import adaptive_target_to_dict

        now = self.engine.now_ms()
        with self._lock:
            cand = self.engine.rollout.candidate(self._inflight) \
                if self._inflight else None
            return {
                "enabled": self._enabled,
                "frozen": self._freeze_reason is not None,
                "freezeReason": self._freeze_reason,
                "policy": self.controller.policy.name,
                "intervalSeconds": self.interval_s,
                "backoffUntilMs": self._backoff_until_ms,
                "inflight": ({
                    "candidate": self._inflight,
                    "stage": cand.stage if cand else None,
                    "changes": [
                        {k: ch[k] for k in ("resource", "from", "to")}
                        for ch in self._inflight_changes],
                } if self._inflight else None),
                "targets": [adaptive_target_to_dict(t)
                            for t in self.controller.targets()],
                "senses": {
                    res: {"blockRate": round(s.block_rate, 6),
                          "rtP99Ms": round(s.rt_p99_ms, 2),
                          "entries": s.entries, "seconds": s.seconds}
                    for res, s in sorted(self._last_senses.items())},
                "cooldowns": self.envelope.cooldown_state(now),
                "lastKnownGood": ({
                    "capturedMs": self._lkg_ms,
                    "families": {fam: len(rs)
                                 for fam, rs in self._lkg.items()},
                } if self._lkg is not None else None),
                "counters": self._counters(),
            }

    def _counters(self) -> Dict:
        return {
            "proposals": self.proposal_count,
            "promotions": self.promotion_count,
            "aborts": self.abort_count,
            "clamped": self.clamp_count,
        }

    def guardrail_state(self) -> Dict:
        """Compact slice for ``resilience_stats()["adaptive"]``."""
        with self._lock:
            return {
                "enabled": self._enabled,
                "frozen": self._freeze_reason is not None,
                "freezeReason": self._freeze_reason,
                "inflightCandidate": self._inflight,
                "backoffUntilMs": self._backoff_until_ms,
                "targets": len(self.controller.targets()),
                **self._counters(),
            }

    def target_deltas(self) -> Dict[str, float]:
        """Latest sensed block-rate minus target per targeted resource
        (the ``sentinel_tpu_adaptive_target_delta`` gauge): positive =
        still blocking above target, the loop has work left."""
        with self._lock:
            out = {}
            for res, sense in self._last_senses.items():
                t = self.controller.target_for(res)
                if t is not None:
                    out[res] = round(sense.block_rate - t.max_block_rate, 6)
            return out

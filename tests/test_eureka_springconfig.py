"""Eureka + Spring Cloud Config connector tests (SURVEY.md §2.2:
``sentinel-datasource-eureka`` / ``sentinel-datasource-spring-cloud-config``):
real REST payloads over real sockets — initial load, metadata/property
update pushes, sticky URL failover (Eureka), Spring source precedence,
basic auth, bad-payload resilience, and reconnect across a server
restart.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from sentinel_tpu.datasource.converters import (
    flow_rules_from_json,
    flow_rules_to_json,
)
from sentinel_tpu.datasource.eureka import (
    EurekaDataSource,
    EurekaWritableDataSource,
    MiniEurekaServer,
)
from sentinel_tpu.datasource.spring_config import (
    MiniSpringConfigServer,
    SpringCloudConfigDataSource,
)


def _wait_for(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _rules_json(*resources, count=5.0) -> str:
    return json.dumps([{"resource": r, "count": count} for r in resources])


def _resources(prop):
    return {r.resource for r in (prop.value or [])}


RULE_KEY = "sentinel.flow.rules"


# -- Eureka -------------------------------------------------------------------


@pytest.fixture()
def eureka():
    s = MiniEurekaServer().start()
    s.register("demo-app", "i-1", {RULE_KEY: _rules_json("resA")})
    yield s
    s.stop()


def _eureka_source(server, **kw) -> EurekaDataSource:
    kw.setdefault("recommend_refresh_ms", 40)
    return EurekaDataSource([server.service_url], "demo-app", "i-1",
                            RULE_KEY, flow_rules_from_json, **kw)


def test_eureka_initial_load_and_poll_push(eureka):
    src = _eureka_source(eureka).start()
    try:
        assert _resources(src.property) == {"resA"}
        eureka.set_metadata("demo-app", "i-1", RULE_KEY,
                            _rules_json("resA", "resB"))
        assert _wait_for(lambda: _resources(src.property) == {"resA", "resB"})
    finally:
        src.close()


def test_eureka_unregistered_instance_then_first_registration(eureka):
    src = EurekaDataSource([eureka.service_url], "demo-app", "i-ghost",
                           RULE_KEY, flow_rules_from_json,
                           recommend_refresh_ms=40).start()
    try:
        assert src.property.value is None
        eureka.register("demo-app", "i-ghost",
                        {RULE_KEY: _rules_json("late")})
        assert _wait_for(lambda: _resources(src.property) == {"late"})
    finally:
        src.close()


def test_eureka_missing_key_and_bad_payload_keep_last_good(eureka):
    src = _eureka_source(eureka).start()
    try:
        assert _resources(src.property) == {"resA"}
        # Key removed entirely → keep last good rules.
        eureka.register("demo-app", "i-1", {"other": "x"})
        time.sleep(0.2)
        assert _resources(src.property) == {"resA"}
        # Corrupt document → keep last good rules.
        eureka.set_metadata("demo-app", "i-1", RULE_KEY, "{nope")
        time.sleep(0.2)
        assert _resources(src.property) == {"resA"}
        # Recovery.
        eureka.set_metadata("demo-app", "i-1", RULE_KEY, _rules_json("resC"))
        assert _wait_for(lambda: _resources(src.property) == {"resC"})
    finally:
        src.close()


def test_eureka_unchanged_metadata_pushes_nothing(eureka):
    src = _eureka_source(eureka).start()
    try:
        before = src.property.value
        polls_before = eureka.request_count
        time.sleep(0.3)  # many polls, same content
        assert eureka.request_count > polls_before  # the loop IS polling
        assert src.property.value is before         # …but pushed nothing
    finally:
        src.close()


def test_eureka_sticky_failover_between_replicas():
    dead = MiniEurekaServer().start()
    live = MiniEurekaServer().start()
    live.register("demo-app", "i-1", {RULE_KEY: _rules_json("resF")})
    dead_url = dead.service_url
    dead.stop()  # replica 1 is down from the start
    src = EurekaDataSource([dead_url, live.service_url], "demo-app", "i-1",
                           RULE_KEY, flow_rules_from_json,
                           recommend_refresh_ms=40, timeout_s=1.0).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"resF"})
        assert src.failover_count >= 1
        time.sleep(0.2)
        # Sticky: once failed over, later polls stay on the live replica.
        assert src._url_idx == 1
    finally:
        src.close()
        live.stop()


def test_eureka_reconnect_after_server_restart(eureka):
    src = _eureka_source(eureka).start()
    try:
        assert _resources(src.property) == {"resA"}
        eureka.stop()
        time.sleep(0.15)  # polls fail; loop must survive
        eureka.set_metadata("demo-app", "i-1", RULE_KEY, _rules_json("resR"))
        eureka.start()
        assert _wait_for(lambda: _resources(src.property) == {"resR"})
    finally:
        src.close()


def test_eureka_writable_publish_roundtrip(eureka):
    from sentinel_tpu.models.flow import FlowRule

    writer = EurekaWritableDataSource(eureka.service_url, "demo-app", "i-1",
                                      RULE_KEY, flow_rules_to_json)
    writer.write([FlowRule(resource="pushed", count=7.0)])
    assert "pushed" in eureka.metadata("demo-app", "i-1")[RULE_KEY]

    src = _eureka_source(eureka).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"pushed"})
    finally:
        src.close()


def test_eureka_writable_url_size_guard(eureka):
    """The metadata endpoint rides the query string; an oversized rule
    document must fail fast with a clear error, not opaquely at a proxy
    (r4 advisory — common URL caps sit ~8KB)."""
    from sentinel_tpu.models.flow import FlowRule

    writer = EurekaWritableDataSource(eureka.service_url, "demo-app", "i-1",
                                      RULE_KEY, flow_rules_to_json)
    big = [FlowRule(resource=f"res-{i:06d}", count=float(i))
           for i in range(2000)]
    with pytest.raises(ValueError, match="max_url_bytes"):
        writer.write(big)
    # nothing reached the server
    assert "res-000000" not in eureka.metadata("demo-app", "i-1")[RULE_KEY]


def test_eureka_raw_http_shape(eureka):
    req = urllib.request.Request(
        eureka.service_url + "/apps/DEMO-APP/i-1",
        headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=2.0) as resp:
        doc = json.loads(resp.read().decode("utf-8"))
    inst = doc["instance"]
    assert inst["app"] == "DEMO-APP" and inst["status"] == "UP"
    assert RULE_KEY in inst["metadata"]


# -- Spring Cloud Config ------------------------------------------------------


@pytest.fixture()
def config_server():
    s = MiniSpringConfigServer().start()
    s.set_property("demo-app", RULE_KEY, _rules_json("resA"))
    yield s
    s.stop()


def _scc_source(server, **kw) -> SpringCloudConfigDataSource:
    kw.setdefault("recommend_refresh_ms", 40)
    return SpringCloudConfigDataSource(server.addr, "demo-app", RULE_KEY,
                                       flow_rules_from_json, **kw)


def test_scc_initial_load_and_poll_push(config_server):
    src = _scc_source(config_server).start()
    try:
        assert _resources(src.property) == {"resA"}
        config_server.set_property("demo-app", RULE_KEY,
                                   _rules_json("resA", "resB"))
        assert _wait_for(lambda: _resources(src.property) == {"resA", "resB"})
        assert src._version == config_server.version
    finally:
        src.close()


def test_scc_profile_source_beats_default(config_server):
    config_server.set_property("demo-app", RULE_KEY, _rules_json("prod-only"),
                               profile="prod")
    src = SpringCloudConfigDataSource(
        config_server.addr, "demo-app", RULE_KEY, flow_rules_from_json,
        profile="prod", recommend_refresh_ms=40).start()
    try:
        # app-prod.yml wins over app.yml for the prod profile...
        assert _resources(src.property) == {"prod-only"}
    finally:
        src.close()
    # ...while other profiles still see the default source.
    src2 = _scc_source(config_server, profile="dev").start()
    try:
        assert _resources(src2.property) == {"resA"}
    finally:
        src2.close()


def test_scc_deleting_profile_override_falls_back(config_server):
    config_server.set_property("demo-app", RULE_KEY, _rules_json("override"),
                               profile="prod")
    src = SpringCloudConfigDataSource(
        config_server.addr, "demo-app", RULE_KEY, flow_rules_from_json,
        profile="prod", recommend_refresh_ms=40).start()
    try:
        assert _resources(src.property) == {"override"}
        config_server.delete_property("demo-app", RULE_KEY, profile="prod")
        assert _wait_for(lambda: _resources(src.property) == {"resA"})
    finally:
        src.close()


def test_scc_basic_auth(config_server):
    auth_server = MiniSpringConfigServer(auth=("cfg", "secret")).start()
    auth_server.set_property("demo-app", RULE_KEY, _rules_json("authd"))
    try:
        # Wrong/missing credentials → 401 at the wire.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(auth_server.addr + "/demo-app/default",
                                   timeout=2.0)
        assert ei.value.code == 401
        src = SpringCloudConfigDataSource(
            auth_server.addr, "demo-app", RULE_KEY, flow_rules_from_json,
            auth=("cfg", "secret"), recommend_refresh_ms=40).start()
        try:
            assert _resources(src.property) == {"authd"}
        finally:
            src.close()
    finally:
        auth_server.stop()


def test_scc_bad_payload_keeps_last_good(config_server):
    src = _scc_source(config_server).start()
    try:
        assert _resources(src.property) == {"resA"}
        config_server.set_property("demo-app", RULE_KEY, "not json at all")
        time.sleep(0.2)
        assert _resources(src.property) == {"resA"}
        config_server.set_property("demo-app", RULE_KEY, _rules_json("resC"))
        assert _wait_for(lambda: _resources(src.property) == {"resC"})
    finally:
        src.close()


def test_scc_reconnect_after_server_restart(config_server):
    src = _scc_source(config_server).start()
    try:
        assert _resources(src.property) == {"resA"}
        config_server.stop()
        time.sleep(0.15)
        config_server.set_property("demo-app", RULE_KEY, _rules_json("resR"))
        config_server.start()
        assert _wait_for(lambda: _resources(src.property) == {"resR"})
    finally:
        src.close()


def test_scc_label_in_path(config_server):
    config_server.set_property("demo-app", RULE_KEY,
                               _rules_json("feature"), label="feature-x")
    src = _scc_source(config_server, label="feature-x").start()
    try:
        assert _resources(src.property) == {"feature"}
    finally:
        src.close()


def test_scc_slashed_label_uses_spring_encoding(config_server):
    config_server.set_property("demo-app", RULE_KEY,
                               _rules_json("branch"), label="release/1.2")
    src = _scc_source(config_server, label="release/1.2").start()
    try:
        assert "(_)" in src._endpoint()  # wire form, not a path segment
        assert _resources(src.property) == {"branch"}
    finally:
        src.close()


def test_scc_raw_environment_shape(config_server):
    req = urllib.request.Request(
        config_server.addr + "/demo-app/default",
        headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=2.0) as resp:
        doc = json.loads(resp.read().decode("utf-8"))
    assert doc["name"] == "demo-app"
    assert doc["profiles"] == ["default"]
    assert doc["version"].startswith("rev-")
    assert any(RULE_KEY in ps["source"] for ps in doc["propertySources"])

"""Device-side micro-batch layouts.

The host engine expands each ``entry``/``exit`` call into fixed-width rows of
these struct-of-arrays batches (padding with row = -1), so the device step is
a pure function of (state, rules, batch, now) — the TPU-native analog of the
reference's per-request slot-chain walk (SURVEY.md §3.1).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import numpy as np


class EntryBatch(NamedTuple):
    """One admission micro-batch of N entry attempts (padded).

    Row ids refer to the node registry's stats-tensor rows. ``cluster_row``
    < 0 marks padding (or a pass-through resource when the registry is
    full).
    """

    cluster_row: jax.Array  # int32[N] resource ClusterNode row
    dn_row: jax.Array       # int32[N] per-(context,resource) DefaultNode row
    origin_row: jax.Array   # int32[N] per-(resource,origin) row, -1 if none
    origin_id: jax.Array    # int32[N] interned origin (ORIGIN_ID_NONE if "")
    origin_named: jax.Array  # bool[N] origin named by some flow rule on res
    context_id: jax.Array   # int32[N] interned context name
    count: jax.Array        # int32[N] tokens to acquire
    prioritized: jax.Array  # bool[N]
    entry_in: jax.Array     # bool[N] EntryType.IN (system rules apply)
    skip_cluster: jax.Array  # bool[N] cluster-mode rules already enforced by
                             # a remote token server for this request
    pre_blocked: jax.Array   # bool[N] a remote token server already rejected
                             # this request; commit block stats, skip slots
    pre_reason: jax.Array    # int32[N] BlockReason a pre_blocked entry was
                             # rejected WITH (host lease / remote verdict) —
                             # drives block attribution; FLOW when unset
    pre_passed: jax.Array    # bool[N] already admitted host-side (token
                             # lease) or remotely; commit PASS, skip slots
    param_hash: jax.Array   # uint32[N, MAX_PARAMS] hot-param value hashes
    param_present: jax.Array  # bool[N, MAX_PARAMS]

    @property
    def size(self) -> int:
        return self.cluster_row.shape[0]


class ExitBatch(NamedTuple):
    """One completion micro-batch: rt / success / exception commits."""

    cluster_row: jax.Array  # int32[N]
    dn_row: jax.Array
    origin_row: jax.Array
    entry_in: jax.Array     # bool[N]
    count: jax.Array        # int32[N]
    rt_ms: jax.Array        # int32[N] response time
    success: jax.Array      # bool[N] completed without error
    error: jax.Array        # bool[N] business exception recorded (Tracer)
    param_hash: jax.Array   # uint32[N, MAX_PARAMS]
    param_present: jax.Array  # bool[N, MAX_PARAMS]

    @property
    def size(self) -> int:
        return self.cluster_row.shape[0]


class Decisions(NamedTuple):
    """Per-entry verdicts coming back from the device step."""

    reason: jax.Array   # int32[N] BlockReason (0 = pass)
    wait_us: jax.Array  # int64[N] host must sleep this long before admitting
    # First-blocking rule slot within the blocking family (load order per
    # resource; -1 = pass, remote verdict, or slot-less family). With
    # ``reason`` this is the full attribution code — see
    # telemetry/attribution.py encode_reason_code.
    rule_slot: jax.Array  # int32[N]


MAX_PARAMS = 4

# The shared jit-cache width ladder: every batch submitted to the device is
# padded to one of these widths so XLA traces each step a bounded number of
# times. Engine and pipeline must use the same ladder.
BATCH_WIDTHS = (1, 8, 64, 512, 2048)


def _np(x, dtype):
    return np.asarray(x, dtype=dtype)


def make_entry_batch_np(n: int):
    """Host-side numpy staging buffers for an EntryBatch of width n."""
    return dict(
        cluster_row=np.full(n, -1, np.int32),
        dn_row=np.full(n, -1, np.int32),
        origin_row=np.full(n, -1, np.int32),
        origin_id=np.full(n, -3, np.int32),
        origin_named=np.zeros(n, bool),
        context_id=np.zeros(n, np.int32),
        count=np.zeros(n, np.int32),
        prioritized=np.zeros(n, bool),
        entry_in=np.zeros(n, bool),
        skip_cluster=np.zeros(n, bool),
        pre_blocked=np.zeros(n, bool),
        # BlockReason.FLOW: the historical attribution of pre-decided
        # rejections (remote token-server verdicts ARE flow rules).
        pre_reason=np.full(n, 1, np.int32),
        pre_passed=np.zeros(n, bool),
        param_hash=np.zeros((n, MAX_PARAMS), np.uint32),
        param_present=np.zeros((n, MAX_PARAMS), bool),
    )


def make_exit_batch_np(n: int):
    return dict(
        cluster_row=np.full(n, -1, np.int32),
        dn_row=np.full(n, -1, np.int32),
        origin_row=np.full(n, -1, np.int32),
        entry_in=np.zeros(n, bool),
        count=np.zeros(n, np.int32),
        rt_ms=np.zeros(n, np.int32),
        success=np.zeros(n, bool),
        error=np.zeros(n, bool),
        param_hash=np.zeros((n, MAX_PARAMS), np.uint32),
        param_present=np.zeros((n, MAX_PARAMS), bool),
    )


# Per-field padding defaults (the value every row must carry before a
# staging pass writes the live rows): row = -1 marks padding, origin_id
# -3 is "unresolved", everything else zeroes. One table shared by the
# allocators above and the pool reset below so they cannot drift.
_ENTRY_FILL = {"cluster_row": -1, "dn_row": -1, "origin_row": -1,
               "origin_id": -3, "pre_reason": 1}
_EXIT_FILL = {"cluster_row": -1, "dn_row": -1, "origin_row": -1}


class BatchBufferPool:
    """Recycled host staging buffers for the pipelined admission path.

    The collector loop stages one micro-batch per cycle; allocating a
    fresh ``make_*_batch_np`` dict each time costs ~14 numpy allocations
    per cycle on the hot path and (worse) lets the allocator fragment
    under sustained load. The pool hands out per-(kind, ladder-width)
    buffers and takes them back once the cycle that used them has been
    harvested — with JAX's async dispatch a buffer may still back an
    in-flight device transfer until then, so release is tied to harvest,
    never to dispatch.

    ``release`` re-fills every field with its padding default, so
    ``acquire`` returns a buffer indistinguishable from a fresh
    allocation (stale rows beyond the new cycle's fill count would
    otherwise leak the previous cycle's entries into the step).
    """

    __slots__ = ("_free", "allocated", "reused")

    def __init__(self, prealloc_widths: "tuple" = (),
                 prealloc_kinds: "tuple" = ("entry", "exit")):
        # Collector-thread-only by design (acquire/release both run on
        # the pipeline loop or under its stop path): no lock needed.
        self._free = {}
        self.allocated = 0
        self.reused = 0
        for w in prealloc_widths:
            for kind in prealloc_kinds:
                self.release(kind, self._fresh(kind, int(w)))

    @staticmethod
    def _fresh(kind: str, width: int):
        return (make_entry_batch_np(width) if kind == "entry"
                else make_exit_batch_np(width))

    def acquire(self, kind: str, width: int):
        stack = self._free.get((kind, width))
        if stack:
            self.reused += 1
            return stack.pop()
        self.allocated += 1
        return self._fresh(kind, width)

    def release(self, kind: str, buf) -> None:
        fills = _ENTRY_FILL if kind == "entry" else _EXIT_FILL
        for name, arr in buf.items():
            arr.fill(fills.get(name, 0))
        width = buf["cluster_row"].shape[0]
        self._free.setdefault((kind, width), []).append(buf)

"""M2 tests: metric log pipeline, config layer, property system, datasources.

Mirrors the reference's test strategy (SURVEY.md §4): deterministic units
over the writer/searcher pair and the converter round-trips, plus an
end-to-end seal (entries -> sealed second -> byte-compatible line).
"""

import json
import urllib.request
import os

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.config import SentinelConfig
from sentinel_tpu.core.property import DynamicSentinelProperty, SimplePropertyListener
from sentinel_tpu.datasource import (
    FileRefreshableDataSource,
    FileWritableDataSource,
    bind,
    degrade_rules_from_json,
    degrade_rules_to_json,
    flow_rules_from_json,
    flow_rules_to_json,
    param_rules_from_json,
    param_rules_to_json,
)
from sentinel_tpu.metrics import MetricNode, MetricSearcher, MetricTimerListener, MetricWriter


# -- MetricNode line format -------------------------------------------------

def test_metric_node_thin_string_round_trip():
    node = MetricNode(timestamp=1700000000000, resource="getUser", pass_qps=7,
                      block_qps=2, success_qps=6, exception_qps=1, rt=12.5,
                      occupied_pass_qps=0, concurrency=3, classification=1)
    line = node.to_thin_string()
    assert line == "1700000000000|getUser|7|2|6|1|12|0|3|1"
    back = MetricNode.from_thin_string(line)
    assert back.resource == "getUser"
    assert back.pass_qps == 7 and back.block_qps == 2
    assert back.concurrency == 3 and back.classification == 1


def test_metric_node_parses_short_lines():
    back = MetricNode.from_thin_string("1700000000000|r|1|0|1|0|5")
    assert back.rt == 5.0 and back.concurrency == 0


# -- writer + searcher ------------------------------------------------------

def _write_seconds(writer, base_ms, per_second):
    for k, nodes in enumerate(per_second):
        writer.write(base_ms + 1000 * k, nodes)


def test_writer_searcher_range_and_identity(tmp_path):
    base = 1700000000000
    writer = MetricWriter(app="appA", base_dir=str(tmp_path))
    _write_seconds(writer, base, [
        [MetricNode(base, "a", pass_qps=1), MetricNode(base, "b", pass_qps=2)],
        [MetricNode(base, "a", pass_qps=3)],
        [MetricNode(base, "b", pass_qps=4)],
    ])
    writer.close()

    s = MetricSearcher(str(tmp_path), "appA")
    all_nodes = s.find(base)
    assert len(all_nodes) == 4
    only_a = s.find_by_time_and_resource(base, base + 2000, "a")
    assert [n.pass_qps for n in only_a] == [1, 3]
    later = s.find_by_time_and_resource(base + 1000, base + 2000, None)
    assert [n.pass_qps for n in later] == [3, 4]


def test_writer_is_idempotent_per_second(tmp_path):
    base = 1700000000000
    writer = MetricWriter(app="appA", base_dir=str(tmp_path))
    writer.write(base, [MetricNode(base, "a", pass_qps=1)])
    writer.write(base, [MetricNode(base, "a", pass_qps=9)])  # dup second: dropped
    writer.close()
    nodes = MetricSearcher(str(tmp_path), "appA").find(base)
    assert [n.pass_qps for n in nodes] == [1]


def test_writer_rolls_at_midnight_boundary(tmp_path):
    """A date change starts a fresh ``.1`` for the new day; the old day's
    file stays intact and the searcher reads across the boundary."""
    import datetime

    from sentinel_tpu.metrics.writer import metric_file_name

    # Local-time midnight boundary (the writer names files by local date).
    before = datetime.datetime(2023, 11, 14, 23, 59, 59)
    after = datetime.datetime(2023, 11, 15, 0, 0, 1)
    writer = MetricWriter(app="appA", base_dir=str(tmp_path))
    writer.write(int(before.timestamp() * 1000),
                 [MetricNode(0, "r", pass_qps=1)])
    writer.write(int(after.timestamp() * 1000),
                 [MetricNode(0, "r", pass_qps=2)])
    writer.close()

    names = sorted(n for n in os.listdir(tmp_path) if not n.endswith(".idx"))
    assert names == [
        metric_file_name("appA", before.strftime("%Y-%m-%d"), 1),
        metric_file_name("appA", after.strftime("%Y-%m-%d"), 1),
    ]
    # both days' index files exist and the search spans the boundary
    assert all(os.path.exists(os.path.join(tmp_path, n + ".idx"))
               for n in names)
    nodes = MetricSearcher(str(tmp_path), "appA").find(0)
    assert [n.pass_qps for n in nodes] == [1, 2]


def test_writer_index_rolls_at_size_cap(tmp_path):
    """Crossing ``single_file_size`` rolls ``.n`` -> ``.n+1`` within the
    same date, each data file with its own ``.idx`` sibling, and the
    index resumes correct offsets in the new file."""
    import datetime

    day = datetime.datetime(2023, 11, 14, 12, 0, 0)
    base = int(day.timestamp() * 1000)
    writer = MetricWriter(app="appA", base_dir=str(tmp_path),
                          single_file_size=120, total_file_count=10)
    for k in range(6):
        writer.write(base + 1000 * k, [MetricNode(0, f"res{k}", pass_qps=k)])
    writer.close()
    date = day.strftime("%Y-%m-%d")
    data = sorted(n for n in os.listdir(tmp_path) if not n.endswith(".idx"))
    indices = [int(n.rsplit(".", 1)[1]) for n in data]
    assert all(date in n for n in data)
    assert indices == list(range(1, len(data) + 1)) and len(data) >= 2
    for n in data:
        assert os.path.getsize(os.path.join(tmp_path, n + ".idx")) > 0
    # every written second still resolves through the per-file indexes
    nodes = MetricSearcher(str(tmp_path), "appA").find(0)
    assert [n.pass_qps for n in nodes] == list(range(6))


def test_writer_trim_keeps_exactly_file_keep(tmp_path):
    """``_trim_old`` retains exactly ``total_file_count`` data files
    (oldest first to go), and removes their ``.idx`` siblings too."""
    import datetime

    base = int(datetime.datetime(2023, 11, 14, 12, 0, 0).timestamp() * 1000)
    keep = 3
    writer = MetricWriter(app="appA", base_dir=str(tmp_path),
                          single_file_size=1, total_file_count=keep)
    for k in range(9):  # size cap 1 byte: every second rolls a new file
        writer.write(base + 1000 * k, [MetricNode(0, f"res{k}", pass_qps=k)])
    writer.close()
    data = sorted((n for n in os.listdir(tmp_path) if not n.endswith(".idx")),
                  key=lambda n: int(n.rsplit(".", 1)[1]))
    assert len(data) == keep
    idx = sorted(n for n in os.listdir(tmp_path) if n.endswith(".idx"))
    assert idx == sorted(n + ".idx" for n in data)
    # survivors are the NEWEST files
    assert [int(n.rsplit(".", 1)[1]) for n in data] == [7, 8, 9]


def test_writer_rolls_by_size_and_trims(tmp_path):
    base = 1700000000000
    writer = MetricWriter(app="appA", base_dir=str(tmp_path),
                          single_file_size=200, total_file_count=2)
    for k in range(20):
        writer.write(base + 1000 * k, [MetricNode(0, f"res{k}", pass_qps=k)])
    writer.close()
    data_files = [n for n in os.listdir(tmp_path) if not n.endswith(".idx")]
    assert 0 < len(data_files) <= 2
    # Newest data still readable (search across remaining files).
    nodes = MetricSearcher(str(tmp_path), "appA").find(base)
    assert nodes and nodes[-1].resource == "res19"


def test_engine_seal_metrics_end_to_end(engine, frozen_time, tmp_path):
    st.load_flow_rules([st.FlowRule(resource="sealed", count=3)])
    for _ in range(5):
        e = st.entry_ok("sealed")
        if e:
            e.exit()
    frozen_time.advance_time(2000)  # the active second becomes sealed
    writer = MetricWriter(app="appS", base_dir=str(tmp_path))
    timer = MetricTimerListener(engine, writer)
    assert timer.tick(frozen_time.current_time_millis()) >= 1
    writer.close()
    nodes = MetricSearcher(str(tmp_path), "appS").find_by_time_and_resource(
        0, 2**62, "sealed")
    assert len(nodes) == 1
    assert nodes[0].pass_qps == 3
    assert nodes[0].block_qps == 2
    assert nodes[0].success_qps == 3
    # Sealing is monotonic: a second tick writes nothing new.
    assert timer.tick(frozen_time.current_time_millis()) == 0


# -- config -----------------------------------------------------------------

def test_config_precedence_env_over_file(tmp_path, monkeypatch):
    props = tmp_path / "sentinel.properties"
    props.write_text("project.name=fromFile\ncsp.sentinel.api.port=9999\n")
    monkeypatch.setenv("CSP_SENTINEL_CONFIG_FILE", str(props))
    monkeypatch.setenv("PROJECT_NAME", "fromEnv")
    cfg = SentinelConfig()
    assert cfg.app_name() == "fromEnv"          # env beats file
    assert cfg.api_port() == 9999               # file beats default
    assert cfg.heartbeat_interval_ms() == 10000  # default


def test_config_defaults(monkeypatch):
    monkeypatch.setenv("CSP_SENTINEL_CONFIG_FILE", "/nonexistent/x.properties")
    cfg = SentinelConfig()
    assert cfg.api_port() == 8719
    assert cfg.statistic_max_rt() == 4900


# -- property system --------------------------------------------------------

def test_dynamic_property_fanout_and_dedup():
    prop = DynamicSentinelProperty()
    seen = []
    prop.add_listener(SimplePropertyListener(seen.append))
    assert prop.update_value([1, 2])
    assert not prop.update_value([1, 2])  # unchanged: no fan-out
    assert prop.update_value([3])
    assert seen == [[1, 2], [3]]


def test_property_initial_load_on_add():
    prop = DynamicSentinelProperty(value=["x"])
    seen = []
    prop.add_listener(SimplePropertyListener(seen.append))
    assert seen == [["x"]]


# -- converters -------------------------------------------------------------

def test_flow_rule_json_round_trip():
    src = json.dumps([{
        "resource": "getUser", "count": 20, "grade": 1, "limitApp": "appB",
        "strategy": 1, "refResource": "other", "controlBehavior": 2,
        "maxQueueingTimeMs": 250, "clusterMode": True,
        "clusterConfig": {"flowId": 42, "thresholdType": 1},
    }])
    rules = flow_rules_from_json(src)
    assert len(rules) == 1
    r = rules[0]
    assert r.count == 20 and r.limit_app == "appB"
    assert r.ref_resource == "other" and r.max_queueing_time_ms == 250
    assert r.cluster_mode and r.cluster_config["flowId"] == 42
    back = flow_rules_from_json(flow_rules_to_json(rules))
    assert back == rules


def test_degrade_param_rule_json_round_trip():
    d = degrade_rules_from_json(json.dumps([{
        "resource": "r", "grade": 0, "count": 50, "timeWindow": 10,
        "slowRatioThreshold": 0.5, "minRequestAmount": 8, "statIntervalMs": 2000,
    }]))
    assert d[0].slow_ratio_threshold == 0.5 and d[0].stat_interval_ms == 2000
    assert degrade_rules_from_json(degrade_rules_to_json(d)) == d

    p = param_rules_from_json(json.dumps([{
        "resource": "r", "paramIdx": 1, "count": 5, "durationInSec": 2,
        "paramFlowItemList": [
            {"object": "7", "classType": "int", "count": 100},
            {"object": "vip", "classType": "String", "count": 200},
        ],
    }]))
    assert p[0].items[0].object == 7       # classType re-typing
    assert p[0].items[1].object == "vip"
    assert param_rules_from_json(param_rules_to_json(p)) == p


# -- datasources ------------------------------------------------------------

def test_file_datasource_pushes_rules_into_engine(engine, frozen_time, tmp_path):
    path = tmp_path / "flow-rules.json"
    path.write_text(json.dumps([{"resource": "dyn", "count": 2, "grade": 1}]))
    ds = FileRefreshableDataSource(str(path), flow_rules_from_json)
    bind(ds, st.load_flow_rules)
    ds.first_load()

    passed = sum(1 for _ in range(4) if st.entry_ok("dyn"))
    assert passed == 2

    # Config push: rewrite the file, poll once, quota changes wholesale.
    frozen_time.advance_time(1000)
    path.write_text(json.dumps([{"resource": "dyn", "count": 4, "grade": 1}]))
    os.utime(path, (1, 1))  # force a distinct mtime
    ds.refresh()
    passed = sum(1 for _ in range(6) if st.entry_ok("dyn"))
    assert passed == 4
    ds.close()


def test_file_writable_datasource_atomic_write(tmp_path):
    path = tmp_path / "rules.json"
    wds = FileWritableDataSource(str(path), flow_rules_to_json)
    rules = [st.FlowRule(resource="w", count=9)]
    wds.write(rules)
    assert flow_rules_from_json(path.read_text()) == rules


def test_named_origin_rules_fresh_before_first_compile(engine, frozen_time):
    """origin_named is read on entry before compilation; a fresh rule load
    must classify a named-origin caller immediately."""
    from sentinel_tpu.core.context import replace_context

    st.load_flow_rules([
        st.FlowRule(resource="r", count=1, limit_app="appA"),
        st.FlowRule(resource="r", count=100),
    ])
    replace_context(None)
    st.context_enter("ctx", origin="appA")
    assert st.entry_ok("r") is not None
    # appA's own limit (1) governs, not the default rule's 100.
    assert st.entry_ok("r") is None
    st.exit_context()


# -- step timing / profiling (SURVEY §5 tracing) ----------------------------

def test_step_timer_records_and_samples():
    from sentinel_tpu.metrics import StepTimer

    t = StepTimer(ring=4, sync_every=2)
    # sampling cadence: dispatch 0, 2, 4... are sync-sampled
    assert t.should_sync("entry")
    t.record("entry", 8, 0.5, 1.5)
    assert not t.should_sync("entry")
    t.record("entry", 8, 0.6)
    assert t.should_sync("entry")
    snap = t.snapshot()["entry"]
    assert snap["dispatches"] == 2 and snap["entries"] == 16
    assert snap["stepSamples"] == 1 and snap["stepP50Ms"] == 1.5
    assert snap["enqueueP50Ms"] > 0
    t.reset()
    assert t.snapshot() == {}


def test_step_timer_ring_bounded():
    from sentinel_tpu.metrics import StepTimer

    t = StepTimer(ring=4, sync_every=1)
    for i in range(20):
        t.record("exit", 1, float(i), float(i))
    snap = t.snapshot()["exit"]
    assert snap["dispatches"] == 20
    # only the last 4 samples survive: p50 of {16..19}
    assert snap["stepP50Ms"] >= 16.0


def test_step_timer_reports_p95(frozen_time):
    from sentinel_tpu.metrics import StepTimer

    t = StepTimer(ring=128, sync_every=1)
    for i in range(100):
        t.record("entry", 1, float(i), float(i))
    snap = t.snapshot()["entry"]
    assert snap["stepP50Ms"] <= snap["stepP95Ms"] <= snap["stepP99Ms"]
    assert 90 <= snap["stepP95Ms"] <= 99
    assert snap["enqueueP50Ms"] <= snap["enqueueP95Ms"] <= snap["enqueueP99Ms"]


def test_step_timer_small_n_quantiles_are_exact(frozen_time):
    """With fewer samples than the percentile resolution, quantiles are
    exact order statistics (nearest-rank), never interpolated: p99 of 7
    samples IS the max sample — an observed latency, not an invented
    value ε below it."""
    from sentinel_tpu.metrics import StepTimer

    t = StepTimer(ring=128, sync_every=1)
    samples = [3.0, 9.0, 1.0, 7.0, 5.0, 2.0, 100.0]  # 7 samples, one spike
    for s in samples:
        t.record("entry", 1, s, s)
    snap = t.snapshot()["entry"]
    # p99 and p95 of 7 samples = the max (ceil(.99*7)=7th order stat)
    assert snap["stepP99Ms"] == 100.0
    assert snap["stepP95Ms"] == 100.0
    # p50 of 7 = the 4th order statistic (ceil(.5*7)=4) — exactly 5.0
    assert snap["stepP50Ms"] == 5.0
    # every reported quantile is an actually-observed sample
    for q in ("stepP50Ms", "stepP95Ms", "stepP99Ms"):
        assert snap[q] in samples
    # single sample: every quantile is that sample
    t2 = StepTimer(ring=8, sync_every=1)
    t2.record("exit", 1, 4.25, 4.25)
    snap2 = t2.snapshot()["exit"]
    assert snap2["stepP50Ms"] == snap2["stepP99Ms"] == 4.25


def test_profile_sync_every_configurable(frozen_time, monkeypatch):
    """`csp.sentinel.profile.syncEvery` seeds StepTimer's sampling
    cadence; invalid values fall back to the default loudly."""
    from sentinel_tpu.core.config import DEFAULT_PROFILE_SYNC_EVERY

    monkeypatch.setenv("CSP_SENTINEL_PROFILE_SYNCEVERY", "8")
    eng = st.reset(capacity=64)
    assert eng.step_timer.sync_every == 8

    monkeypatch.setenv("CSP_SENTINEL_PROFILE_SYNCEVERY", "-3")
    eng = st.reset(capacity=64)
    assert eng.step_timer.sync_every == DEFAULT_PROFILE_SYNC_EVERY
    monkeypatch.delenv("CSP_SENTINEL_PROFILE_SYNCEVERY")
    st.reset(capacity=64)


def test_engine_step_timing_via_profile_command(engine, frozen_time):
    """Entries produce timing; the `profile` ops command serves + resets."""
    from sentinel_tpu.transport.command_center import CommandCenter

    st.load_flow_rules([st.FlowRule(resource="profRes", count=100)])
    for _ in range(3):
        h = st.entry_ok("profRes")
        if h:
            h.exit()
    # leased entries commit through the async committer in batches: flush,
    # then expect >= 1 dispatch carrying all 3 entries
    engine._flush_committer()
    snap = engine.step_timer.snapshot()
    assert snap["entry"]["dispatches"] >= 1
    assert snap["entry"]["entries"] >= 3
    assert snap["entry"]["stepSamples"] >= 1  # first dispatch is sampled
    assert snap["exit"]["dispatches"] >= 1

    center = CommandCenter(engine, port=0).start()
    try:
        url = f"http://127.0.0.1:{center.bound_port}/profile?reset=true"
        with urllib.request.urlopen(url, timeout=5) as r:
            out = json.loads(r.read().decode())
        assert out["entry"]["dispatches"] >= 1
        assert engine.step_timer.snapshot() == {}  # reset applied
    finally:
        center.stop()

package com.alibaba.csp.sentinel.tpu;

import com.alibaba.csp.sentinel.slotchain.DefaultProcessorSlotChain;
import com.alibaba.csp.sentinel.slotchain.ProcessorSlotChain;
import com.alibaba.csp.sentinel.slotchain.SlotChainBuilder;
import com.alibaba.csp.sentinel.slots.clusterbuilder.ClusterBuilderSlot;
import com.alibaba.csp.sentinel.slots.logger.LogSlot;
import com.alibaba.csp.sentinel.slots.nodeselector.NodeSelectorSlot;
import com.alibaba.csp.sentinel.slots.statistic.StatisticSlot;
import com.alibaba.csp.sentinel.spi.Spi;

/**
 * {@link SlotChainBuilder} SPI that completes SURVEY.md §7 M4: drop the
 * bridge jar on the classpath of an app running the stock framework and
 * {@code SlotChainProvider} picks THIS builder (highest @Spi order), so
 * every {@code SphU.entry} routes its rule checks + stats commits to the
 * sentinel-tpu backend via {@link TpuBridgeSlot}.
 *
 * <p>Chain shape (reference: {@code core:slotchain/DefaultSlotChainBuilder}):
 * NodeSelector → ClusterBuilder → Log → Statistic → TpuBridge. The
 * node-building and statistic slots stay so in-JVM consumers (dashboards
 * reading curNode, adapters inspecting the tree) keep local visibility;
 * the backend's verdicts are authoritative and its stats are the ones
 * the sentinel-tpu dashboard serves. The local FlowSlot/DegradeSlot/
 * SystemSlot/AuthoritySlot/ParamFlowSlot are intentionally ABSENT —
 * their checks happen inside the backend's fused device step.
 *
 * <p>Configure the backend address via {@code -Dcsp.sentinel.tpu.host} /
 * {@code -Dcsp.sentinel.tpu.port} (or the standard cluster-client
 * config). With no address configured every entry fails open locally,
 * so adding the jar before configuring it is harmless.
 */
@Spi(isDefault = false, order = -2000)
public class TpuSlotChainBuilder implements SlotChainBuilder {

    @Override
    public ProcessorSlotChain build() {
        ProcessorSlotChain chain = new DefaultProcessorSlotChain();
        chain.addLast(new NodeSelectorSlot());
        chain.addLast(new ClusterBuilderSlot());
        chain.addLast(new LogSlot());
        chain.addLast(new StatisticSlot());
        chain.addLast(new TpuBridgeSlot());
        return chain;
    }
}

// sentinel_shim: native client shim for the sentinel-tpu token server.
//
// Role (SURVEY.md §2.9, §7 M4): the reference is pure Java, so its cluster
// clients live in-process; our TPU backend serves tokens over the TLV TCP
// protocol, and THIS library is the bridge by which any host runtime — a
// JVM via JNI, C++ services, Python via ctypes — talks to it without a
// Python dependency. It implements:
//
//   * the length-framed binary TLV codec (cluster/codec.py is the Python
//     twin; frame = u16 len | body; request body = i32 xid | u8 type |
//     entity; response body = i32 xid | u8 type | i8 status | entity),
//   * a pipelined token client with xid demultiplexing over one TCP
//     connection — N concurrent callers share one handle (PING namespace
//     registration on connect; FLOW / PARAM_FLOW acquires; batched FLOW
//     acquires; MSG_ENTRY/MSG_EXIT remote slot-chain bridge),
//   * a cached-tick millisecond clock (the reference TimeUtil's dedicated
//     tick thread — avoids a syscall per hot-path read).
//
// C ABI only: every symbol is extern "C" so ctypes/JNI/FFI can bind it.

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t MSG_PING = 0;
constexpr uint8_t MSG_FLOW = 1;
constexpr uint8_t MSG_PARAM_FLOW = 2;
// TPU-extension types (cluster/constants.py MSG_ENTRY/MSG_EXIT): the M4
// remote slot-chain bridge.
constexpr uint8_t MSG_ENTRY = 10;
constexpr uint8_t MSG_EXIT = 11;

constexpr int ST_FAIL = -1;

// -- wire helpers (big-endian, matching cluster/codec.py) --------------------

void put_u16(std::vector<uint8_t>& b, uint16_t v) {
  b.push_back(v >> 8);
  b.push_back(v & 0xff);
}
void put_i32(std::vector<uint8_t>& b, int32_t v) {
  for (int s = 24; s >= 0; s -= 8) b.push_back((uint32_t(v) >> s) & 0xff);
}
void put_i64(std::vector<uint8_t>& b, int64_t v) {
  for (int s = 56; s >= 0; s -= 8) b.push_back((uint64_t(v) >> s) & 0xff);
}
void put_f64(std::vector<uint8_t>& b, double v) {
  // IEEE-754 bits, big-endian (struct ">d" in cluster/codec.py).
  uint64_t bits;
  static_assert(sizeof(bits) == sizeof(v), "double is 64-bit");
  std::memcpy(&bits, &v, sizeof(bits));
  put_i64(b, int64_t(bits));
}
int32_t get_i32(const uint8_t* p) {
  return (int32_t(p[0]) << 24) | (int32_t(p[1]) << 16) | (int32_t(p[2]) << 8) |
         int32_t(p[3]);
}

// One outstanding request's parking slot: the receiver thread-of-the-
// moment fills it by xid and wakes the owner.
struct Waiter {
  bool done = false;
  bool failed = false;
  int8_t status = ST_FAIL;
  std::vector<uint8_t> entity;
};

// Multi-in-flight pipelined client: N threads may call() concurrently on
// ONE handle. Requests are xid-tagged; whichever caller reaches the
// socket first becomes the receiver, demuxes response frames into the
// waiter map by xid, and hands the receiver role off when its own
// response lands (the classic shared-receiver pattern — no dedicated IO
// thread, so a handle is just a socket + a mutex, safe to create per
// worker or to share). The reference's Netty client gets the same
// effect from its xid -> promise map (SURVEY.md §2.11).
struct Client {
  int fd = -1;
  std::mutex send_mu;                // frames hit the wire atomically
  std::mutex mu;                     // waiter map + receiver election
  std::condition_variable cv;
  std::unordered_map<int32_t, Waiter*> waiting;
  int32_t next_xid = 1;              // guarded by mu
  bool rx_active = false;            // someone is blocked in recv()
  bool dead = false;                 // transport failed: fail all callers
  int users = 0;                     // callers inside any entry point

  // RAII presence marker: st_client_close drains `users` to zero before
  // freeing the Client, so no caller can wake up on destroyed state.
  struct Use {
    Client* c;
    explicit Use(Client* c_) : c(c_) {
      std::lock_guard<std::mutex> lock(c->mu);
      ++c->users;
    }
    ~Use() {
      std::lock_guard<std::mutex> lock(c->mu);
      if (--c->users == 0) c->cv.notify_all();
    }
  };

  bool send_all(const uint8_t* data, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t w = ::send(fd, data + off, n - off, MSG_NOSIGNAL);
      if (w <= 0) return false;
      off += size_t(w);
    }
    return true;
  }

  bool recv_all(uint8_t* data, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t r = ::recv(fd, data + off, n - off, 0);
      if (r <= 0) return false;
      off += size_t(r);
    }
    return true;
  }

  // Read ONE response frame off the socket and complete its waiter.
  // Returns 1 on a processed frame, 0 on a CLEAN timeout (SO_RCVTIMEO
  // expired before any byte of the next frame arrived — the stream is
  // intact, only the current caller's patience ran out), -1 on
  // transport death (EOF, error, or a MID-frame timeout, which desyncs
  // the stream). Called with `mu` NOT held.
  int pump_one() {
    uint8_t lenbuf[2];
    ssize_t r = ::recv(fd, lenbuf, 1, 0);
    if (r == 0) return -1;
    if (r < 0) return (errno == EAGAIN || errno == EWOULDBLOCK) ? 0 : -1;
    if (!recv_all(lenbuf + 1, 1)) return -1;
    uint16_t len = (uint16_t(lenbuf[0]) << 8) | lenbuf[1];
    std::vector<uint8_t> resp(len);
    if (len > 0 && !recv_all(resp.data(), len)) return -1;
    if (len < 6) return 1;  // malformed frame: skip, stay alive
    int32_t xid = get_i32(resp.data());
    std::lock_guard<std::mutex> lock(mu);
    auto it = waiting.find(xid);
    if (it == waiting.end()) return 1;  // stale/timed-out xid: drop
    it->second->status = int8_t(resp[5]);
    it->second->entity.assign(resp.begin() + 6, resp.end());
    it->second->done = true;
    waiting.erase(it);
    cv.notify_all();
    return 1;
  }

  void fail_all_locked() {
    dead = true;
    for (auto& kv : waiting) {
      kv.second->failed = true;
      kv.second->done = true;
    }
    waiting.clear();
    cv.notify_all();
  }

  // Register `w`, send the frame, return its xid (or -1 on failure).
  int32_t post(uint8_t type, const std::vector<uint8_t>& entity, Waiter* w) {
    int32_t xid;
    {
      std::lock_guard<std::mutex> lock(mu);
      if (dead) return -1;
      xid = next_xid++;
      waiting.emplace(xid, w);
    }
    std::vector<uint8_t> body;
    put_i32(body, xid);
    body.push_back(type);
    body.insert(body.end(), entity.begin(), entity.end());
    std::vector<uint8_t> frame;
    put_u16(frame, uint16_t(body.size()));
    frame.insert(frame.end(), body.begin(), body.end());
    bool sent;
    {
      std::lock_guard<std::mutex> lock(send_mu);
      sent = send_all(frame.data(), frame.size());
    }
    if (!sent) {
      std::lock_guard<std::mutex> lock(mu);
      waiting.erase(xid);
      fail_all_locked();  // a broken pipe is fatal for every caller
      return -1;
    }
    return xid;
  }

  // Wait until `w` completes, pumping the socket when no one else is.
  // Returns false on failure; the waiter is deregistered either way.
  bool await(Waiter* w, int32_t xid) {
    std::unique_lock<std::mutex> lock(mu);
    for (;;) {
      if (w->done) return !w->failed;
      if (dead) return false;
      if (!rx_active) {
        rx_active = true;
        lock.unlock();
        int got = pump_one();
        lock.lock();
        rx_active = false;
        // A frame landed or the role freed: wake potential successors.
        cv.notify_all();
        if (got < 0) {
          fail_all_locked();
          return false;
        }
        if (got == 0 && !w->done) {
          // Clean timeout: THIS call gives up (its SO_RCVTIMEO budget
          // is spent) but the connection stays usable — a late response
          // is dropped by the stale-xid skip in pump_one. One slow
          // server response (e.g. a first-entry XLA compile) must not
          // brick the shared handle for every later caller.
          waiting.erase(xid);
          w->failed = true;
          w->done = true;
          return false;
        }
      } else {
        cv.wait(lock);
      }
    }
  }

  // Blocking single call; concurrent calls pipeline on the one socket.
  bool call(uint8_t type, const std::vector<uint8_t>& entity, int8_t* status,
            std::vector<uint8_t>* resp_entity) {
    Use use(this);
    Waiter w;
    int32_t xid = post(type, entity, &w);
    if (xid < 0) return false;
    if (!await(&w, xid)) return false;
    *status = w.status;
    *resp_entity = std::move(w.entity);
    return true;
  }
};

}  // namespace

extern "C" {

// -- token client ------------------------------------------------------------

// Connect + register the namespace via PING. NULL on failure.
void* st_client_connect(const char* host, int port, const char* ns,
                        int timeout_ms) {
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host, port_s.c_str(), &hints, &res) != 0) return nullptr;
  int fd = -1;
  for (auto* p = res; p; p = p->ai_next) {
    fd = ::socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv { timeout_ms / 1000, (timeout_ms % 1000) * 1000 };
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;

  auto* c = new Client();
  c->fd = fd;
  // PING entity: u8 len | namespace.
  std::vector<uint8_t> entity;
  std::string nss = ns ? ns : "default";
  if (nss.size() > 255) nss.resize(255);
  entity.push_back(uint8_t(nss.size()));
  entity.insert(entity.end(), nss.begin(), nss.end());
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_PING, entity, &status, &resp)) {
    ::close(c->fd);
    delete c;
    return nullptr;
  }
  return c;
}

// Acquire tokens. Returns the TokenResultStatus (OK=0, BLOCKED=1,
// SHOULD_WAIT=2, ...) or -1 on transport failure. out_extra receives
// remaining (OK) or wait-ms (SHOULD_WAIT) when non-null.
int st_request_token(void* handle, long long flow_id, int count,
                     int prioritized, int* out_extra) {
  if (!handle) return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> entity;
  put_i64(entity, flow_id);
  put_i32(entity, count);
  entity.push_back(prioritized ? 1 : 0);
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_FLOW, entity, &status, &resp)) return ST_FAIL;
  if (out_extra) {
    *out_extra = 0;
    if (resp.size() >= 8) {
      int32_t remaining = get_i32(resp.data());
      int32_t wait_ms = get_i32(resp.data() + 4);
      *out_extra = (status == 2) ? wait_ms : remaining;
    }
  }
  return status;
}

// One hot-parameter value (mirror of sentinel_shim.h's st_param).
struct st_param {
  unsigned char tag;  // 0=int, 1=str, 2=bool, 3=float
  long long i;
  double d;
  const char* s;
};

namespace {
// Shared tagged-params encoder: MSG_PARAM_FLOW and MSG_ENTRY carry the
// identical block (u16 count | per-param u8 tag + typed payload) — one
// implementation so the two frame types can never drift apart. Returns
// false on an unencodable param (oversized string / unknown tag).
bool append_params(std::vector<uint8_t>& entity, const st_param* params,
                   int nparams) {
  entity.push_back(uint8_t(nparams >> 8));
  entity.push_back(uint8_t(nparams & 0xff));
  for (int k = 0; k < nparams; ++k) {
    const st_param& p = params[k];
    entity.push_back(p.tag);
    switch (p.tag) {
      case 0:  // int: i64
        put_i64(entity, p.i);
        break;
      case 1: {  // str: u16 len | utf-8
        size_t n = p.s ? std::strlen(p.s) : 0;
        // Oversized values can't fit the u16 frame anyway (the callers'
        // entity-size check would reject them) — fail fast rather than
        // truncate, which could split a multibyte UTF-8 char.
        if (n > 0xFFF0) return false;
        entity.push_back(uint8_t(n >> 8));
        entity.push_back(uint8_t(n & 0xff));
        if (n > 0) entity.insert(entity.end(), p.s, p.s + n);
        break;
      }
      case 2:  // bool: u8
        entity.push_back(p.i ? 1 : 0);
        break;
      case 3:  // float: f64 bits
        put_f64(entity, p.d);
        break;
      default:
        return false;
    }
  }
  return true;
}
}  // namespace

// Acquire param-flow tokens. Entity (cluster/codec.py
// encode_param_flow_request): flowId:i64 | count:i32 | nparams:u16 |
// per-param u8 tag + typed payload. Returns the TokenResultStatus or -1.
int st_request_param_token(void* handle, long long flow_id, int count,
                           const st_param* params, int nparams) {
  if (!handle || nparams < 0 || (nparams > 0 && !params)) return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> entity;
  put_i64(entity, flow_id);
  put_i32(entity, count);
  if (!append_params(entity, params, nparams)) return ST_FAIL;
  if (entity.size() > 0xFFF0) return ST_FAIL;  // must fit one u16 frame
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_PARAM_FLOW, entity, &status, &resp)) return ST_FAIL;
  return status;
}

// Pipelined batch acquire: all `n` FLOW requests are sent back-to-back on
// the one connection before any response is awaited, so the wire carries
// one RTT for the whole batch (and the server's micro-batcher folds them
// into one device step). out_statuses[i] receives the TokenResultStatus
// (or -1), out_extras[i] (when non-null) remaining/wait-ms as in
// st_request_token. Returns 0 when every response arrived, -1 on
// transport failure (unanswered slots read -1).
int st_request_tokens_batch(void* handle, const long long* flow_ids,
                            const int* counts, const int* prioritized, int n,
                            int* out_statuses, int* out_extras) {
  if (!handle || n <= 0 || !flow_ids || !counts || !out_statuses)
    return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  Client::Use use(c);
  std::vector<Waiter> waiters(n);
  std::vector<int32_t> xids(n, -1);
  for (int k = 0; k < n; ++k) out_statuses[k] = ST_FAIL;
  int posted = 0;
  for (; posted < n; ++posted) {
    std::vector<uint8_t> entity;
    put_i64(entity, flow_ids[posted]);
    put_i32(entity, counts[posted]);
    entity.push_back((prioritized && prioritized[posted]) ? 1 : 0);
    xids[posted] = c->post(MSG_FLOW, entity, &waiters[posted]);
    if (xids[posted] < 0) break;
  }
  bool all_ok = posted == n;
  for (int k = 0; k < posted; ++k) {
    if (!c->await(&waiters[k], xids[k])) {
      all_ok = false;
      continue;
    }
    out_statuses[k] = waiters[k].status;
    if (out_extras) {
      out_extras[k] = 0;
      if (waiters[k].entity.size() >= 8) {
        int32_t remaining = get_i32(waiters[k].entity.data());
        int32_t wait_ms = get_i32(waiters[k].entity.data() + 4);
        out_extras[k] = (waiters[k].status == 2) ? wait_ms : remaining;
      }
    }
  }
  return all_ok ? 0 : ST_FAIL;
}

namespace {
// str8 (u8 len | utf-8), truncated on a CHARACTER boundary like the
// Python codec's _pack_str8 — a mid-sequence cut would cost the peer a
// mangled name at best.
void put_str8(std::vector<uint8_t>& b, const char* s) {
  size_t n = s ? std::strlen(s) : 0;
  if (n > 255) {
    n = 255;
    while (n > 0 && (uint8_t(s[n]) & 0xC0) == 0x80) --n;  // continuation?
  }
  b.push_back(uint8_t(n));
  if (n > 0) b.insert(b.end(), s, s + n);
}
}  // namespace

// Remote slot-chain entry (MSG_ENTRY — the M4 bridge): run the backend's
// FULL rule chain + stats commit for `resource`. Returns the
// TokenResultStatus (OK=0 pass, BLOCKED=1, -1 transport/backend failure
// -> caller falls open). On OK *out_entry_id receives the id to pass to
// st_remote_exit; on BLOCKED *out_reason receives the BlockReason code
// (1=flow 2=degrade 3=system 4=authority 5=param 7=custom).
int st_remote_entry(void* handle, const char* resource, const char* origin,
                    int count, int entry_type, int prioritized,
                    const st_param* params, int nparams,
                    long long* out_entry_id, int* out_reason) {
  if (!handle || !resource || nparams < 0 || (nparams > 0 && !params))
    return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> entity;
  put_str8(entity, resource);
  put_str8(entity, origin);
  put_i32(entity, count);
  entity.push_back(uint8_t(entry_type));
  entity.push_back(prioritized ? 1 : 0);
  if (!append_params(entity, params, nparams)) return ST_FAIL;
  if (entity.size() > 0xFFF0) return ST_FAIL;
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_ENTRY, entity, &status, &resp)) return ST_FAIL;
  if (resp.size() >= 9) {
    int64_t id = 0;
    for (int k = 0; k < 8; ++k) id = (id << 8) | resp[size_t(k)];
    if (out_entry_id) *out_entry_id = id;
    if (out_reason) *out_reason = resp[8];
  } else {
    if (out_entry_id) *out_entry_id = 0;
    if (out_reason) *out_reason = 0;
  }
  return status;
}

// Remote exit (MSG_EXIT): commit RT/success and release the entry.
// `error` non-zero records a business exception; `count` < 0 keeps the
// count given at entry. Returns OK, BAD_REQUEST (unknown/already-exited
// id), or -1 on transport failure.
int st_remote_exit(void* handle, long long entry_id, int error, int count) {
  if (!handle) return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> entity;
  put_i64(entity, entry_id);
  entity.push_back(error ? 1 : 0);
  put_i32(entity, count);
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_EXIT, entity, &status, &resp)) return ST_FAIL;
  return status;
}

// Close contract: no NEW calls may race st_client_close (wrappers
// serialize close against issuing requests); calls already in flight are
// failed and fully drained before the handle is freed.
void st_client_close(void* handle) {
  if (!handle) return;
  auto* c = static_cast<Client*>(handle);
  bool drained;
  {
    std::unique_lock<std::mutex> lock(c->mu);
    c->fail_all_locked();
    ::shutdown(c->fd, SHUT_RDWR);  // kick a receiver blocked in recv()
    // Drain EVERY caller out of the entry points — not just the
    // receiver: a waiter parked in cv.wait (or a sender in send_all)
    // waking on destroyed state would be use-after-free. fail_all woke
    // them; give them a bounded window to unwind through ~Use.
    drained = c->cv.wait_for(
        lock, std::chrono::seconds(5),
        [c] { return !c->rx_active && c->users == 0; });
  }
  ::close(c->fd);
  if (drained) {
    delete c;
  }
  // else: a caller is stuck (e.g. send blocked past its SO_SNDTIMEO);
  // deliberately LEAK this one Client rather than free state under a
  // live thread — close is rare and the fd is already closed.
}

// NOTE: the token-lease admission ring is NOT part of this shim. It
// lives in native/lease_ext.c as a CPython extension — a ctypes route
// through here was measured (r5) and its ~2-4µs trampoline erased the
// win, and a third copy of the admission math would be drift waiting to
// happen.

// -- cached-tick clock (reference: core:util/TimeUtil.java) ------------------

namespace {
std::atomic<long long> g_now_ms{0};
std::atomic<bool> g_tick_running{false};
std::thread g_tick_thread;

long long wall_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}
}  // namespace

void st_time_start(void) {
  bool expected = false;
  if (!g_tick_running.compare_exchange_strong(expected, true)) return;
  g_now_ms.store(wall_ms());
  g_tick_thread = std::thread([] {
    while (g_tick_running.load(std::memory_order_relaxed)) {
      g_now_ms.store(wall_ms(), std::memory_order_relaxed);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  g_tick_thread.detach();
}

void st_time_stop(void) { g_tick_running.store(false); }

// Cached when the tick thread runs; falls back to a syscall otherwise.
long long st_now_ms(void) {
  long long v = g_now_ms.load(std::memory_order_relaxed);
  return (v != 0 && g_tick_running.load(std::memory_order_relaxed))
             ? v
             : wall_ms();
}

}  // extern "C"

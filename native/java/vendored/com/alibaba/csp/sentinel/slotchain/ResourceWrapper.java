package com.alibaba.csp.sentinel.slotchain;

import com.alibaba.csp.sentinel.EntryType;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slotchain/ResourceWrapper.java. */
public abstract class ResourceWrapper {

    protected final String name;
    protected final EntryType entryType;
    protected final int resourceType;

    public ResourceWrapper(String name, EntryType entryType, int resourceType) {
        this.name = name;
        this.entryType = entryType;
        this.resourceType = resourceType;
    }

    public String getName() {
        return name;
    }

    public abstract String getShowName();

    public EntryType getEntryType() {
        return entryType;
    }

    public int getResourceType() {
        return resourceType;
    }
}

"""Within-batch segmented scans.

The reference admits each request against counters that every *earlier*
request has already updated (per-request exactness of ``DefaultController``
/ the token bucket CASes). A micro-batched device step sees N requests at
once, so to reproduce arrival-order semantics we compute, for every request,
the sum of candidate counts of earlier requests that target the same node
row / rule — a segmented exclusive prefix sum in arrival order.

Implementation: stable sort by segment id, cumsum, subtract each segment's
base, scatter back. O(N log N) on tiny N (micro-batch ≤ 4096), fully on
device, no data-dependent shapes.
"""

from __future__ import annotations

from typing import Tuple

import jax.lax
import jax.numpy as jnp


def segmented_prefix(ids: jnp.ndarray, values: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exclusive prefix sum of ``values`` within equal ``ids``, arrival order.

    Returns (prefix_excl, is_first) both aligned with the input order.
    ``is_first`` marks the first occurrence of each id (used e.g. to admit a
    single HALF_OPEN probe per breaker per batch).
    """
    n = ids.shape[0]
    order = jnp.argsort(ids, stable=True)
    sid = ids[order]
    sval = values[order]
    csum = jnp.cumsum(sval)
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    # Exclusive prefix at each segment head; propagate forward with a
    # running max (csum is nondecreasing for nonnegative values).
    head_base = jnp.where(first, csum - sval, -1)
    base = jax.lax.cummax(head_base)
    prefix_sorted = csum - sval - base
    inv = jnp.zeros((n,), order.dtype).at[order].set(jnp.arange(n, dtype=order.dtype))
    return prefix_sorted[inv], first[inv]

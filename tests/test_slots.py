"""Slot-table admission (ISSUE 20): differential verdict oracle vs a
never-evicting twin, generation-leak pins on the rendered history, the
evict -> rehydrate round trip, graceful cold-tail degradation, registry
overflow accounting, the ``slot_conservation`` checker's teeth, the
eviction-storm chaos campaign, checkpoint round-trip, and the ops
command surface.

The oracle tests are DIFFERENTIAL: the same seeded stream is served
twice — once by a tightly budgeted slot engine that must evict and
rehydrate to keep up, once by a large-budget twin that never evicts —
and the verdict streams must match bit-for-bit. Eviction is an
implementation detail; the moment it leaks into a verdict, these fail.
"""

import json
import random

import pytest

from sentinel_tpu.chaos.invariants import (
    CHECKERS,
    History,
    check_all,
    check_slot_conservation,
)
from sentinel_tpu.chaos.slot_storm import SlotStormCampaign
from sentinel_tpu.core.checkpoint import restore_checkpoint, save_checkpoint
from sentinel_tpu.core.context import replace_context
from sentinel_tpu.core.engine import SentinelEngine
from sentinel_tpu.core.exceptions import BlockException, FlowException
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.resilience import FaultInjector
from sentinel_tpu.simulator.clock import SimClock
from sentinel_tpu.transport.command_center import CommandRequest
from sentinel_tpu.transport.handlers import cmd_slots

BASE_MS = 1_700_000_000_000


def _res(out):
    return json.loads(out.result)


def _engine(slot_budget, epoch_ms=BASE_MS, **kw):
    clk = SimClock(epoch_ms)
    eng = SentinelEngine(clock=clk.now_ms, journal_path="",
                         slot_budget=slot_budget, **kw)
    return eng, clk


def _serve(eng, res):
    """One entry/exit; returns 'P' or 'B' (the verdict stream symbol)."""
    try:
        eng.entry(res).exit()
        return "P"
    except BlockException:
        return "B"


# -- differential oracle: tiny budget vs never-evicting twin ---------------


def test_differential_oracle_verdicts_bit_identical_to_unevicted_twin():
    """A 6-usable-slot engine under a 16-resource Zipf stream must
    evict/rehydrate constantly; a 62-usable-slot twin never evicts.
    Same clock, same stream -> the verdict streams must be identical
    (ruled resources are leaseable, so BOTH lanes are host-exact — the
    slot table may only decide WHERE a verdict is computed, never WHAT
    it is)."""
    replace_context(None)
    names = [f"oracle{i}" for i in range(16)]
    rules = [FlowRule(resource=names[i], count=3) for i in (0, 5, 10)]
    weights = [1.0 / (i + 1) ** 1.2 for i in range(16)]
    streams, statuses = [], []
    for budget in (8, 64):
        eng, clk = _engine(budget)
        try:
            eng.flow_rules.load_rules(list(rules))
            rng = random.Random(1234)  # identical draws per engine
            verdicts = []
            for _sec in range(10):
                for _ in range(20):
                    verdicts.append(
                        _serve(eng, rng.choices(names, weights=weights)[0]))
                clk.advance(1000)
                eng.slo_refresh(now_ms=clk.now_ms())
            streams.append("".join(verdicts))
            statuses.append(eng.slots.status())
        finally:
            eng.close()
            replace_context(None)
    assert streams[0] == streams[1], (
        "eviction/rehydration changed a verdict:\n"
        f"  small {streams[0]}\n  twin  {streams[1]}")
    assert "B" in streams[0] and "P" in streams[0]  # both verdicts exercised
    # the small engine actually churned; the twin provably never evicted
    assert statuses[0]["evictionsTotal"] > 0, statuses[0]
    assert statuses[0]["coldPassTotal"] > 0, statuses[0]
    assert statuses[1]["evictionsTotal"] == 0, statuses[1]
    assert statuses[1]["coldPassTotal"] == 0, statuses[1]


# -- storm drill: one scenario, several pins -------------------------------


@pytest.fixture(scope="module")
def storm_drill():
    """One deterministic evict -> cold -> rehydrate drill, shared by the
    generation-leak, round-trip, and invariant pins below.

    Budget 3 = ONE usable slot. alpha runs two seconds, an armed
    ``slots.evict.storm`` evicts it, beta takes the slot (new
    generation), a second storm evicts beta, and alpha re-admits from
    its spill record while beta degrades to the cold tail."""
    replace_context(None)
    history = History()
    eng, clk = _engine(3)
    try:
        with FaultInjector(seed=99, scope_thread=True) as injector:
            injector.arm("slots.evict.storm", mode="error", after=2, times=2)
            eng.slots.event_sink = history.events.append
            plan = [["alpha"] * 3,           # sec 1
                    ["alpha"] * 2,           # sec 2
                    [],                      # sec 3: storm #1 evicts alpha
                    ["beta"] * 3,            # sec 4: beta admits; storm #2
                    ["alpha"] * 2 + ["beta"],  # sec 5: alpha rehydrates,
                    ["alpha", "beta"]]       #   beta rides the cold tail
            for second in plan:
                for res in second:
                    _serve(eng, res)
                clk.advance(1000)
                eng.slo_refresh(now_ms=clk.now_ms())
        # injector uninstalled: downstream tests may arm their own
        view = eng.timeseries_view(now_ms=clk.now_ms())
        yield {"history": history, "view": view,
               "status": eng.slots.status()}
    finally:
        eng.close()
        replace_context(None)


def test_generation_leak_pin_history_renders_under_recorded_tenancy(
        storm_drill):
    """Seconds recorded while alpha held the slot must STILL name alpha
    after beta reuses the same slot row — without the per-stamp meta
    recall, every historical second would re-render under the current
    tenant and book alpha's traffic against beta."""
    by_res = {}
    for sec in storm_drill["view"]["seconds"]:
        names = sorted(sec.get("resources", {}))
        for name in names:
            by_res.setdefault(name, 0)
            by_res[name] += 1
        assert names in (["alpha"], ["beta"], []), (
            "a second attributed to both tenants of one slot", sec)
    # alpha's pre-eviction seconds survived beta's tenancy, and beta's
    # cold-tail entries never landed in a device-attributed second
    assert by_res.get("alpha", 0) >= 3, by_res   # sec 1, 2, 5
    assert by_res.get("beta", 0) == 1, by_res    # sec 4 only


def test_evict_rehydrate_round_trip_conserves_window_state(storm_drill):
    history = storm_drill["history"]
    rehydrates = history.of("slotRehydrate")
    evicts = history.of("slotEvict")
    # alpha was spilled by storm #1 and came back FROM ITS RECORD
    alpha_evict = next(e for e in evicts if e["resource"] == "alpha")
    assert alpha_evict["spilledPass"] >= 5 and not alpha_evict["torn"]
    warm = [r for r in rehydrates
            if r["resource"] == "alpha" and r["fromRecord"]]
    assert len(warm) == 1, rehydrates
    grafted = warm[0]["graftedPass"] + warm[0]["stalePass"]
    assert 0 < grafted <= alpha_evict["spilledPass"], (warm, alpha_evict)
    status = storm_drill["status"]
    assert status["stormsTotal"] == 2
    assert status["evictionsTotal"] >= 2          # alpha + beta
    assert status["rehydrationsTotal"] >= 3       # every admit rehydrates
    assert status["rehydrationsColdTotal"] >= 2   # first touches
    assert status["coldPassTotal"] >= 2, status   # beta's cold-tail rides
    # LOUD degrade, zero raises: cold passes were verdicted, not dropped
    cold = [v for v in history.of("slotVerdict") if v["slot"] < 0]
    assert cold and all(v["gen"] < 0 for v in cold)


def test_storm_drill_history_passes_every_invariant(storm_drill):
    assert check_all(storm_drill["history"], {}, 1) == []


# -- cold tail: host-exact leases past the budget --------------------------


def test_cold_ruled_resource_enforced_host_exact_past_pin_capacity():
    """Four leaseable rules over TWO usable slots: the overflow rules
    cannot pin, so their resources live on the cold tail — and their
    limits must still hold host-exactly (cold means slower, never
    unenforced, for leaseable shapes)."""
    replace_context(None)
    eng, clk = _engine(4)
    try:
        eng.flow_rules.load_rules(
            [FlowRule(resource=f"ruled{i}", count=2) for i in range(4)])
        for i in (0, 1):  # first touches take the two usable slots
            _serve(eng, f"ruled{i}")
        hot = set(eng.slots.checkpoint_dict()["hot"])
        cold_ruled = next(r for r in ("ruled2", "ruled3") if r not in hot)
        verdicts = "".join(_serve(eng, cold_ruled) for _ in range(6))
        assert verdicts == "PPBBBB", verdicts  # count=2, host-exact
        status = eng.slots.status()
        assert status["coldBlockTotal"] >= 4, status
        assert status["coldPassTotal"] >= 2, status
    finally:
        eng.close()
        replace_context(None)


def test_namespace_10x_budget_zero_registration_failures():
    """The headline acceptance: a namespace 10x the usable budget runs
    with ZERO failed registrations and zero raises — extra resources
    degrade to counted cold-tail passes, never to errors."""
    replace_context(None)
    eng, clk = _engine(8)
    try:
        names = [f"wide{i}" for i in range(60)]
        for _sec in range(3):
            for res in names:
                assert _serve(eng, res) == "P"  # unruled: never blocked
            clk.advance(1000)
            eng.slo_refresh(now_ms=clk.now_ms())
        status = eng.slots.status()
        assert status["hot"] <= 6, status
        assert status["coldPassTotal"] > 0, status
        assert 0.0 < status["hitRate"] < 1.0, status
        assert eng.registry.overflow_count == 0
    finally:
        eng.close()
        replace_context(None)


def test_registry_overflow_is_counted_not_raised():
    reg = NodeRegistry(capacity=4)  # ROOT + ENTRY pre-allocated
    assert reg.cluster_row("fits-a") >= 0
    assert reg.cluster_row("fits-b") >= 0
    for i in range(3):  # past capacity: pass-through row, loud counter
        assert reg.cluster_row(f"over-{i}") == -1
    assert reg.overflow_count == 3
    assert reg.cluster_row("fits-a") >= 0  # existing rows keep resolving
    assert reg.overflow_count == 3


# -- the slot_conservation checker must FIRE -------------------------------


def _hist(events):
    h = History()
    for ev in events:
        ev = dict(ev)
        h.add(ev.pop("e"), **ev)
    return h


def _admit(res, slot, gen):
    return {"e": "slotAdmit", "resource": res, "slot": slot, "gen": gen}


def _evict(res, slot, gen, torn=False, spilled=0):
    return {"e": "slotEvict", "resource": res, "slot": slot, "gen": gen,
            "torn": torn, "spilledPass": spilled}


def _rehydrate(res, slot, gen, from_record=False, grafted=0, stale=0):
    return {"e": "slotRehydrate", "resource": res, "slot": slot, "gen": gen,
            "fromRecord": from_record, "graftedPass": grafted,
            "stalePass": stale, "coldPass": 0}


def _verdict(res, slot, gen, sec=1):
    return {"e": "slotVerdict", "resource": res, "slot": slot, "gen": gen,
            "sec": sec, "verdict": "pass", "reason": 0}


def test_slot_conservation_accepts_a_clean_round_trip():
    clean = _hist([
        _rehydrate("a", 2, 1), _admit("a", 2, 1), _verdict("a", 2, 1),
        _evict("a", 2, 1, spilled=5),
        _rehydrate("b", 2, 2), _admit("b", 2, 2), _verdict("b", 2, 2),
        _evict("b", 2, 2, torn=True, spilled=3),
        _rehydrate("a", 2, 3, from_record=True, grafted=3, stale=2),
        _admit("a", 2, 3), _verdict("a", 2, 3),
        _verdict("cold-tail", -1, -2),
    ])
    assert check_slot_conservation(clean, {}, 1) == []


@pytest.mark.parametrize("label,events", [
    ("double admit without evict",
     [_admit("a", 2, 1), _admit("b", 2, 2)]),
    ("generation does not increase",
     [_admit("a", 2, 1), _evict("a", 2, 1), _admit("b", 2, 1)]),
    ("evict names the wrong tenant",
     [_admit("a", 2, 1), _evict("b", 2, 1)]),
    ("evict from an unoccupied slot",
     [_evict("a", 2, 1)]),
    ("verdict leaks to the evicted generation",
     [_admit("a", 2, 1), _evict("a", 2, 1), _admit("b", 2, 2),
      _verdict("a", 2, 1)]),
    ("verdict on an unoccupied slot",
     [_verdict("a", 2, 1)]),
    ("cold-lane verdict claims a device generation",
     [_verdict("a", -1, 0)]),
    ("rehydrate claims a record with no prior evict",
     [_rehydrate("a", 2, 1, from_record=True), _admit("a", 2, 1)]),
    ("torn spill rehydrates warm",
     [_admit("a", 2, 1), _evict("a", 2, 1, torn=True, spilled=5),
      _rehydrate("a", 2, 2, from_record=True), _admit("a", 2, 2)]),
    ("round trip grafts more than was spilled",
     [_admit("a", 2, 1), _evict("a", 2, 1, spilled=3),
      _rehydrate("a", 2, 2, from_record=True, grafted=3, stale=1),
      _admit("a", 2, 2)]),
    ("cold rehydrate reports grafted window state",
     [_rehydrate("a", 2, 1, grafted=1), _admit("a", 2, 1)]),
    ("admit does not claim the rehydrate that preceded it",
     [_rehydrate("a", 2, 1), _admit("b", 2, 1)]),
])
def test_slot_conservation_fires(label, events):
    """A checker that cannot fire is decoration: every clause must
    produce a violation on a history hand-built to break it."""
    violations = check_slot_conservation(_hist(events), {}, 1)
    assert violations, label
    assert all(v.invariant == "slot_conservation" for v in violations)


def test_slot_conservation_registered_in_check_all():
    assert "slot_conservation" in {name for name, _fn in CHECKERS}
    bad = _hist([_admit("a", 2, 1), _admit("b", 2, 2)])
    assert any(v.invariant == "slot_conservation"
               for v in check_all(bad, {}, 1))


# -- eviction-storm campaign: smoke + replay stability ---------------------


def test_storm_campaign_smoke_and_replay_stable():
    replace_context(None)
    camp = SlotStormCampaign(campaign_seed=7, episodes=2, seconds=5)
    try:
        r0 = camp.run_episode(0)
        r1 = camp.run_episode(1)
        assert not r0.violations and not r1.violations
        assert r0.entries == r1.entries == 5 * camp.per_second
        # both faults actually landed somewhere in the pair
        storms = sum(r.status["stormsTotal"] for r in (r0, r1))
        assert storms >= 2 and sum(
            r.status["evictionsTotal"] for r in (r0, r1)) > 0
        # distinct seeds draw distinct streams...
        assert (r0.verdict_sha256, r0.tenancy_sha256) != (
            r1.verdict_sha256, r1.tenancy_sha256)
        # ...and one seed replays BIT-identically
        again = camp.run_episode(0)
        assert again.verdict_sha256 == r0.verdict_sha256
        assert again.tenancy_sha256 == r0.tenancy_sha256
        assert not again.violations
    finally:
        replace_context(None)


@pytest.mark.slow
def test_storm_campaign_certification_100_episodes():
    """The ISSUE 20 acceptance run: 100 eviction-storm episodes with
    both slots.* faults armed — zero invariant violations, replayable
    hashes. (~10 min of engine compiles; tier-1 runs the 2-episode
    smoke above instead.)"""
    replace_context(None)
    try:
        rep = SlotStormCampaign(campaign_seed=20, episodes=100,
                                seconds=6).run()
    finally:
        replace_context(None)
    assert rep["episodes"] == 100
    assert rep["violations"] == 0, rep["firstViolation"]
    assert rep["storms"] >= 100 and rep["spillTorn"] > 0
    assert rep["evictions"] > 0 and rep["rehydrations"] > 0
    assert len(rep["verdictSha256"]) == 64
    assert len(rep["tenancySha256"]) == 64


# -- checkpoint round trip -------------------------------------------------


def test_checkpoint_round_trip_restores_slot_assignment(tmp_path):
    replace_context(None)
    path = str(tmp_path / "slots.ckpt")
    eng, clk = _engine(8)
    try:
        for _sec in range(2):
            for res in ("ck-a", "ck-b", "ck-c"):
                _serve(eng, res)
            clk.advance(1000)
            eng.slo_refresh(now_ms=clk.now_ms())
        save_checkpoint(eng, path)
        saved = eng.slots.checkpoint_dict()
    finally:
        eng.close()
        replace_context(None)
    assert len(saved["hot"]) == 3
    twin, clk2 = _engine(8)
    try:
        restore_checkpoint(twin, path)
        assert twin.slots.checkpoint_dict() == saved
        # the restored table serves: hot resources stay on their slots
        assert _serve(twin, "ck-a") == "P"
        assert twin.slots.checkpoint_dict()["hot"]["ck-a"] == \
            saved["hot"]["ck-a"]
    finally:
        twin.close()
        replace_context(None)
    # mode mismatch is a refusal, not a corruption
    fixed = SentinelEngine(capacity=8, clock=clk2.now_ms, journal_path="")
    try:
        with pytest.raises(ValueError, match="slot"):
            restore_checkpoint(fixed, path)
    finally:
        fixed.close()
        replace_context(None)


# -- ops surface -----------------------------------------------------------


def test_cmd_slots_status_hot_freeze_thaw():
    replace_context(None)
    eng, clk = _engine(8)
    try:
        _serve(eng, "ops-res")
        out = _res(cmd_slots(CommandRequest(
            parameters={"op": "status"}, engine=eng)))
        assert out["budget"] == 8 and out["hot"] == 1
        assert out["freezeReason"] is None
        hot = _res(cmd_slots(CommandRequest(
            parameters={"op": "hot"}, engine=eng)))
        assert set(hot["hot"]) == {"ops-res"}
        assert hot["hot"]["ops-res"]["slot"] >= 2  # reserved rows skipped
        frozen = _res(cmd_slots(CommandRequest(
            parameters={"op": "freeze", "reason": "drill"}, engine=eng)))
        assert frozen["frozen"] is True
        out = _res(cmd_slots(CommandRequest(
            parameters={"op": "status"}, engine=eng)))
        assert out["freezeReason"] == "manual: drill"
        _res(cmd_slots(CommandRequest(
            parameters={"op": "thaw"}, engine=eng)))
        out = _res(cmd_slots(CommandRequest(
            parameters={"op": "status"}, engine=eng)))
        assert out["freezeReason"] is None
        bad = cmd_slots(CommandRequest(
            parameters={"op": "wat"}, engine=eng))
        assert not bad.success
        # the exporter ships the families the runbook names
        from sentinel_tpu.telemetry.exporter import render_engine_metrics

        text = render_engine_metrics(eng)
        assert "sentinel_tpu_slots_budget 8" in text
        assert "sentinel_tpu_slots_admits_total" in text
        assert "sentinel_tpu_registry_overflow_total 0" in text
    finally:
        eng.close()
        replace_context(None)


def test_cmd_slots_refuses_fixed_capacity_engines():
    replace_context(None)
    eng = SentinelEngine(capacity=64, journal_path="")
    try:
        out = cmd_slots(CommandRequest(parameters={}, engine=eng))
        assert not out.success and "slot mode" in out.result
    finally:
        eng.close()
        replace_context(None)

"""Token-lease fast path: microsecond admission for hot resources.

A resource guarded only by simple QPS rules admits host-side
(`core/lease.py`) with device-exact window math; statistics stream to
the device asynchronously. Run and compare the per-entry latency with
what a device dispatch would cost (~ms on CPU, ~65ms through a remote
TPU tunnel).
"""

import _demo_env  # noqa: F401  (pins JAX platform; import first)

import time

import sentinel_tpu as st


def main():
    eng = st.get_engine()
    st.load_flow_rules([st.FlowRule(resource="checkout", count=100)])
    assert "checkout" in eng._leases, "simple QPS rules are lease-eligible"

    h = st.entry_ok("checkout")  # warm (starts the background committer)
    if h:
        h.exit()

    lat = []
    for _ in range(500):
        t0 = time.perf_counter()
        h = st.entry_ok("checkout")
        lat.append((time.perf_counter() - t0) * 1e6)
        if h:
            h.exit()
    lat.sort()
    print(f"leased entry latency over {len(lat)} calls: "
          f"p50={lat[len(lat) // 2]:.1f}µs  p99={lat[int(len(lat) * .99)]:.1f}µs")

    # quota still enforced exactly — burst past 100/s blocks. Sleep a FULL
    # window from here so every latency-loop bucket expires (aligning to
    # the wall second alone would retain the previous 500ms bucket).
    time.sleep(1.1)
    handles = [st.entry_ok("checkout") for _ in range(120)]
    admitted = sum(1 for h in handles if h)
    print(f"burst of 120 against count=100: admitted {admitted}")
    for h in handles:
        if h:
            h.exit()

    # the device converges within a committer flush: ops-plane view
    deadline = time.time() + 30
    while time.time() < deadline:
        snap = eng.node_snapshot().get("checkout", {})
        if snap.get("passQps", 0) > 0:
            print("device stats:", {k: snap[k]
                                    for k in ("passQps", "blockQps")})
            break
        time.sleep(0.2)


if __name__ == "__main__":
    main()

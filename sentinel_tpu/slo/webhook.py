"""Alert webhook fan-out: bounded queue, background worker, retries.

Alert transitions (fired / resolved) POST as JSON to every configured
URL (``csp.sentinel.alert.webhook.urls``, comma-separated). Delivery is
strictly off the evaluation path: the SLO manager enqueues into a
BOUNDED queue (overload stance of ISSUE 6 — a dead webhook endpoint
must never turn into unbounded memory or a stalled evaluator; on a full
queue the oldest event is dropped and counted) and one worker thread
delivers with ``resilience.RetryPolicy`` backoff per attempt.

Payload contract (docs/OPERATIONS.md "SLOs & alerting")::

    POST <url>  Content-Type: application/json
    {"type": "fired" | "resolved", "seq": 17, "timestamp": 1700000000000,
     "source": "<app name>", "alert": {<alert fields — see `alerts`>}}

A 2xx response is delivered; anything else (or a connect failure)
retries up to ``csp.sentinel.alert.webhook.retries`` times with the
policy's jittered backoff, then counts as failed for that URL. Events
are delivered per-URL independently — one dead endpoint never blocks
the others beyond its own retry budget.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from sentinel_tpu.resilience import RetryPolicy

QUEUE_CAPACITY = 256


class AlertWebhook:
    """Fan one engine's alert events out to the configured endpoints."""

    def __init__(self, urls: Optional[List[str]] = None,
                 timeout_ms: Optional[int] = None,
                 retries: Optional[int] = None):
        from sentinel_tpu.core.config import config as _cfg

        self.urls = list(urls) if urls is not None \
            else _cfg.alert_webhook_urls()
        self.timeout_s = (timeout_ms if timeout_ms is not None
                          else _cfg.alert_webhook_timeout_ms()) / 1000.0
        self.retries = (retries if retries is not None
                        else _cfg.alert_webhook_retries())
        # Short, capped backoff: webhook delivery shares its patience
        # budget with the alert's freshness — a minute-old page is noise.
        self.retry_policy = RetryPolicy.from_config(
            "alert.webhook", base_ms=100, max_ms=2_000)
        self._queue: "queue.Queue" = queue.Queue(maxsize=QUEUE_CAPACITY)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.delivered = 0
        self.failed = 0
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        return bool(self.urls)

    def submit(self, event: Dict) -> None:
        """Enqueue one alert event; never blocks. On a full queue the
        OLDEST queued event is dropped (the newest transition is the one
        an operator needs) and counted."""
        if not self.enabled or self._stop.is_set():
            return
        self._ensure_worker()
        while True:
            try:
                self._queue.put_nowait(event)
                return
            except queue.Full:
                try:
                    self._queue.get_nowait()
                    with self._lock:
                        self.dropped += 1
                except queue.Empty:
                    pass

    def _ensure_worker(self) -> None:
        if self._thread is not None:
            return
        with self._lock:
            if self._thread is None and not self._stop.is_set():
                self._thread = threading.Thread(
                    target=self._run, name="sentinel-alert-webhook",
                    daemon=True)
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "urls": len(self.urls),
                "queued": self._queue.qsize(),
                "delivered": self.delivered,
                "failed": self.failed,
                "dropped": self.dropped,
            }

    # -- worker ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                event = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            body = json.dumps(event).encode("utf-8")
            for url in self.urls:
                self._deliver(url, body)

    def _deliver(self, url: str, body: bytes) -> None:
        session = self.retry_policy.session()
        for attempt in range(self.retries + 1):
            if self._stop.is_set() and attempt > 0:
                break  # drain the first try, never a shutdown-blocking loop
            try:
                req = urllib.request.Request(
                    url, data=body, method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                    if 200 <= r.status < 300:
                        with self._lock:
                            self.delivered += 1
                        return
            except (urllib.error.URLError, OSError, ValueError):
                pass
            if attempt < self.retries:
                self._stop.wait(session.next_delay_ms() / 1000.0)
        with self._lock:
            self.failed += 1

package com.alibaba.csp.sentinel.tpu;

import com.alibaba.csp.sentinel.EntryType;
import com.alibaba.csp.sentinel.cluster.ClusterConstants;
import com.alibaba.csp.sentinel.cluster.client.config.ClusterClientConfigManager;
import com.alibaba.csp.sentinel.context.Context;
import com.alibaba.csp.sentinel.log.RecordLog;
import com.alibaba.csp.sentinel.node.DefaultNode;
import com.alibaba.csp.sentinel.slotchain.AbstractLinkedProcessorSlot;
import com.alibaba.csp.sentinel.slotchain.ResourceWrapper;
import com.alibaba.csp.sentinel.slots.block.BlockException;
import com.alibaba.csp.sentinel.slots.block.authority.AuthorityException;
import com.alibaba.csp.sentinel.slots.block.degrade.DegradeException;
import com.alibaba.csp.sentinel.slots.block.flow.FlowException;
import com.alibaba.csp.sentinel.slots.block.flow.param.ParamFlowException;
import com.alibaba.csp.sentinel.slots.block.system.SystemBlockException;
import com.sun.jna.Pointer;
import com.sun.jna.ptr.IntByReference;
import com.sun.jna.ptr.LongByReference;

import java.util.ArrayDeque;
import java.util.Deque;

/**
 * The M4 rule-check forwarding slot (SURVEY.md §7 M4: "SPI-registered
 * slot that forwards StatisticSlot/rule checks to the backend"):
 * replaces the local FlowSlot/DegradeSlot/SystemSlot/AuthoritySlot/
 * ParamFlowSlot tail of the chain with ONE remote MSG_ENTRY check
 * against the sentinel-tpu backend, which runs its full fused slot
 * chain AND commits the StatisticSlot 4-row fan-out there. Exit
 * forwards the RT/success/thread-count release via MSG_EXIT.
 *
 * <p>Reference twins: {@code core:slotchain/ProcessorSlot.java} (the
 * SPI this implements), {@code core:slots/statistic/StatisticSlot.java}
 * (whose commit-inversion the backend performs),
 * {@code core:slots/block/*} (the exception mapping below).
 *
 * <p>Failure semantics: transport failure or a backend FAIL status
 * fails OPEN (fireEntry proceeds locally) — the stance of the
 * reference's {@code fallbackToLocalOrPass} and of the backend's own
 * DeviceDispatchError fail-open (core/engine.py). A BLOCKED status
 * re-raises the exact BlockException subclass the backend's BlockReason
 * code names, so blockHandler/fallback dispatch in user code is
 * unchanged.
 *
 * <p>Entry ids ride a per-thread stack: the sync entry model nests
 * strictly per thread (CtEntry enforces it), so exit order matches.
 * Async entries ({@code context.isAsync()}) are NOT forwarded — they
 * fire through locally (documented limitation; the async context
 * detaches from the thread).
 *
 * <p>NOTE (sandbox provenance): written against the vendored 1.8 SPI
 * surface in {@code native/java/vendored}; re-check against the fork
 * before first compile (BUILD.md).
 */
public class TpuBridgeSlot extends AbstractLinkedProcessorSlot<DefaultNode> {

    /** BlockReason codes (backend core/constants.py BlockReason). */
    static final int REASON_FLOW = 1;
    static final int REASON_DEGRADE = 2;
    static final int REASON_SYSTEM = 3;
    static final int REASON_AUTHORITY = 4;
    static final int REASON_PARAM_FLOW = 5;

    private static final long RECONNECT_BACKOFF_MS = 2000;

    /**
     * Refcounted wrapper around a shim handle. The shim's close contract
     * forbids st_client_close racing NEW requests on the same handle, and
     * the window between reading a shared Pointer and entering the native
     * call can't be covered by a monitor without serializing every entry
     * — so the native close runs only when the LAST borrower releases
     * (native memory is freed exactly once, never under a live caller).
     */
    static final class Conn {
        final Pointer ptr;
        // starts at 1: the static `current` table reference
        private final java.util.concurrent.atomic.AtomicInteger refs =
            new java.util.concurrent.atomic.AtomicInteger(1);

        Conn(Pointer ptr) {
            this.ptr = ptr;
        }

        boolean acquire() {
            for (;;) {
                int r = refs.get();
                if (r <= 0) {
                    return false;  // already fully closed
                }
                if (refs.compareAndSet(r, r + 1)) {
                    return true;
                }
            }
        }

        void release() {
            if (refs.decrementAndGet() == 0) {
                SentinelTpuShim.INSTANCE.st_client_close(ptr);
            }
        }
    }

    private static volatile Conn current;
    private static long lastConnectFailMs;

    private static final ThreadLocal<Deque<Long>> ENTRY_IDS =
        ThreadLocal.withInitial(ArrayDeque::new);

    /** Borrow the live connection (caller MUST release()); null when
     * unconfigured/backing off — the caller fails open. */
    private static Conn borrowConnection() {
        Conn c = current;
        if (c != null && c.acquire()) {
            return c;
        }
        synchronized (TpuBridgeSlot.class) {
            c = current;
            if (c != null && c.acquire()) {
                return c;
            }
            if (System.currentTimeMillis() - lastConnectFailMs
                    < RECONNECT_BACKOFF_MS) {
                return null;
            }
            String host = System.getProperty("csp.sentinel.tpu.host",
                ClusterClientConfigManager.getServerHost());
            int port = Integer.getInteger("csp.sentinel.tpu.port",
                ClusterClientConfigManager.getServerPort());
            if (host == null || port <= 0) {
                return null;
            }
            Pointer fresh = SentinelTpuShim.INSTANCE.st_client_connect(
                host, port, ClusterConstants.DEFAULT_CLUSTER_NAMESPACE,
                ClusterClientConfigManager.getRequestTimeout());
            if (fresh == null) {
                lastConnectFailMs = System.currentTimeMillis();
                return null;
            }
            Conn made = new Conn(fresh);
            made.acquire();  // the caller's borrow
            current = made;
            RecordLog.info("[TpuBridgeSlot] connected to {}:{}", host, port);
            return made;
        }
    }

    /** Retire `failed` (transport death observed on it): drop the table
     * reference so the native handle closes once in-flight borrowers
     * release. Other connections installed since are untouched. */
    private static synchronized void retireConnection(Conn failed) {
        if (current == failed) {
            current = null;
            lastConnectFailMs = System.currentTimeMillis();
            failed.release();  // the table's own reference
        }
    }

    @Override
    public void entry(Context context, ResourceWrapper resourceWrapper,
                      DefaultNode node, int count, boolean prioritized,
                      Object... args) throws Throwable {
        if (context.isAsync()) {
            // Async entries exit on another thread, so the per-thread id
            // stack cannot pair them (exit() has the mirror guard): they
            // fire through locally, nothing pushed.
            fireEntry(context, resourceWrapper, node, count, prioritized, args);
            return;
        }
        Conn conn = borrowConnection();
        if (conn == null) {
            // fail open: no backend -> behave like an unruled resource
            ENTRY_IDS.get().push(0L);
            fireEntry(context, resourceWrapper, node, count, prioritized, args);
            return;
        }
        // Marshalling failures are REQUEST-local (a hostile param type
        // must not retire the healthy shared connection for the whole
        // JVM): marshal before touching the connection, so only a -1
        // from the shim itself — genuine transport death — retires it.
        SentinelTpuShim.StParam[] arr;
        try {
            arr = marshalParams(args);
        } catch (RuntimeException ex) {
            conn.release();
            throw ex;
        }
        int status;
        LongByReference outId = new LongByReference();
        IntByReference outReason = new IntByReference();
        try {
            // Wire entry_type matches the backend's EntryType enum: IN=0,
            // OUT=1 (core/constants.py — note the inversion vs. a naive
            // boolean encoding).
            status = SentinelTpuShim.INSTANCE.st_remote_entry(
                conn.ptr, resourceWrapper.getName(),
                context.getOrigin() == null ? "" : context.getOrigin(), count,
                resourceWrapper.getEntryType() == EntryType.IN ? 0 : 1,
                prioritized ? 1 : 0, arr, args == null ? 0 : args.length,
                outId, outReason);
        } finally {
            conn.release();
        }
        if (status == -1) {
            retireConnection(conn);  // transport death: reconnect later
        }
        if (status == -1) {
            ENTRY_IDS.get().push(0L);
            fireEntry(context, resourceWrapper, node, count, prioritized, args);
            return;
        }
        if (status == 1) {  // BLOCKED: re-raise the typed exception
            // Push a sentinel FIRST: the framework still runs the chain's
            // exit for a blocked entry (CtSph catches the BlockException
            // and calls e.exit()), and that exit must pop THIS entry's
            // slot — not the enclosing entry's live id.
            ENTRY_IDS.get().push(0L);
            throw exceptionFor(outReason.getValue(), resourceWrapper.getName(),
                               context.getOrigin());
        }
        ENTRY_IDS.get().push(outId.getValue());
        fireEntry(context, resourceWrapper, node, count, prioritized, args);
    }

    @Override
    public void exit(Context context, ResourceWrapper resourceWrapper,
                     int count, Object... args) {
        if (context.isAsync()) {
            // Mirror of entry()'s async guard: nothing was pushed for
            // this entry (and this thread's stack may hold OTHER live
            // entries' ids — popping here would exit one of those).
            fireExit(context, resourceWrapper, count, args);
            return;
        }
        Deque<Long> stack = ENTRY_IDS.get();
        Long entryId = stack.isEmpty() ? null : stack.pop();
        if (entryId != null && entryId != 0L) {
            // Borrow WITHOUT dialing: the exit path must never pay a
            // blocking connect (the old invariant) — if the connection
            // died, the server's disconnect drain already released this
            // entry, and a fresh connection would only answer
            // BAD_REQUEST for the stale id anyway.
            Conn conn = current;
            if (conn != null && !conn.acquire()) {
                conn = null;
            }
            if (conn != null) {
                try {
                    boolean error = context.getCurEntry() != null
                        && context.getCurEntry().getError() != null;
                    int rc = SentinelTpuShim.INSTANCE.st_remote_exit(
                        conn.ptr, entryId, error ? 1 : 0, count);
                    if (rc == -1) {
                        retireConnection(conn);
                    }
                } finally {
                    conn.release();
                }
            }
            // else: connection already died; the backend's disconnect
            // drain released this entry server-side. (If the connection
            // CHANGED since this entry, the stale id gets a harmless
            // BAD_REQUEST — ids are server-unique across connections.)
        }
        fireExit(context, resourceWrapper, count, args);
    }

    static BlockException exceptionFor(int reason, String resource,
                                       String origin) {
        String app = origin == null ? "" : origin;
        switch (reason) {
            case REASON_DEGRADE:
                return new DegradeException(app, resource);
            case REASON_SYSTEM:
                return new SystemBlockException(resource, "tpu-backend");
            case REASON_AUTHORITY:
                return new AuthorityException(app, resource);
            case REASON_PARAM_FLOW:
                return new ParamFlowException(resource, "tpu-backend");
            case REASON_FLOW:
            default:
                return new FlowException(app, resource);
        }
    }

    static SentinelTpuShim.StParam[] marshalParams(Object[] args) {
        int n = args == null ? 0 : args.length;
        SentinelTpuShim.StParam[] arr =
            (SentinelTpuShim.StParam[]) new SentinelTpuShim.StParam()
                .toArray(Math.max(n, 1));
        for (int k = 0; k < n; ++k) {
            Object p = args[k];
            SentinelTpuShim.StParam sp = arr[k];
            if (p instanceof Boolean) {
                sp.tag = 2;
                sp.i = ((Boolean) p) ? 1 : 0;
            } else if (p instanceof Integer || p instanceof Long
                       || p instanceof Short || p instanceof Byte) {
                sp.tag = 0;
                sp.i = ((Number) p).longValue();
            } else if (p instanceof Double || p instanceof Float) {
                sp.tag = 3;
                sp.d = ((Number) p).doubleValue();
            } else {
                sp.tag = 1;
                sp.s = String.valueOf(p);
            }
        }
        return arr;
    }
}

"""Outbound HTTP client adapter (reference: ``sentinel-okhttp-adapter`` /
``sentinel-apache-httpclient-adapter`` — SURVEY.md §2.5): guard outgoing
HTTP calls as OUT-type entries named ``METHOD:host/path`` (the reference's
cleaner-configurable convention), tracing non-2xx/transport failures into
exception metrics so degrade rules can break on a failing dependency.
"""

from __future__ import annotations

import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.exceptions import BlockException  # noqa: F401 (re-export)


def default_resource_extractor(method: str, url: str) -> str:
    """``METHOD:host/path`` — query strings dropped (unbounded cardinality)."""
    parts = urllib.parse.urlsplit(url)
    return f"{method.upper()}:{parts.netloc}{parts.path}"


class SentinelHttpClient:
    """A guarded ``urllib`` wrapper; swap in any transport via ``send``."""

    def __init__(self,
                 resource_extractor: Optional[Callable[[str, str], str]] = None,
                 timeout_s: float = 10.0):
        self.extract = resource_extractor or default_resource_extractor
        self.timeout_s = timeout_s

    def request(self, method: str, url: str, data: Optional[bytes] = None,
                headers: Optional[dict] = None):
        """Raises BlockException when the resource is over its rules;
        transport errors / 5xx feed exception metrics and re-raise. 4xx is
        a CALLER error — it re-raises but does NOT count as a dependency
        exception (a degrade rule must not break a healthy dependency), so
        the handle is managed explicitly rather than via the with-block's
        auto-trace."""
        resource = self.extract(method, url)
        handle = st.entry(resource, entry_type=C.EntryType.OUT)
        try:
            req = urllib.request.Request(url, data=data, method=method.upper(),
                                         headers=dict(headers or {}))
            try:
                return urllib.request.urlopen(req, timeout=self.timeout_s)
            except urllib.error.HTTPError as ex:
                if ex.code >= 500:
                    handle.trace(ex)
                raise
            except OSError as ex:
                handle.trace(ex)
                raise
        finally:
            handle.exit()

    def get(self, url: str, **kw):
        return self.request("GET", url, **kw)

    def post(self, url: str, data: bytes = b"", **kw):
        return self.request("POST", url, data=data, **kw)


def guarded(fn: Callable, resource: str,
            entry_type: int = C.EntryType.OUT) -> Callable:
    """Wrap ANY outbound client callable (requests.get, a session method)
    in an entry — the adapter-of-last-resort for clients without a
    dedicated module. Thin alias over :func:`sentinel_resource` (same
    blocking/tracing semantics; use the decorator directly for fallback
    and block-handler routing)."""
    from sentinel_tpu.adapters.annotation import sentinel_resource

    return sentinel_resource(value=resource, entry_type=entry_type)(fn)

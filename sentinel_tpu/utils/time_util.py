"""Host clock with test override.

Reference: ``core:util/TimeUtil.java`` — a daemon thread caching
``System.currentTimeMillis()`` into a volatile long to avoid syscall cost on
the hot path. Python's ``time.time_ns()`` is a vDSO call (~20ns), so no cache
thread is needed; what we *do* keep is a single choke point so tests can pin
time (the reference's static clock was untestable — SURVEY.md §4) and so the
device step receives time as an explicit argument.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

_frozen_ms: Optional[int] = None

# Pre-advance hooks: async machinery (the token-lease stats committer)
# registers here so pending work stamped "now" lands BEFORE the frozen
# clock moves — otherwise a test's advance_time() would time-travel
# commits into the wrong second. No-ops under the real clock.
_pre_advance_hooks: List[Callable[[], None]] = []


def on_advance(hook: Callable[[], None]) -> Callable[[], None]:
    """Register a hook run before every frozen-clock advance; returns an
    unregister callable."""
    _pre_advance_hooks.append(hook)

    def off():
        try:
            _pre_advance_hooks.remove(hook)
        except ValueError:
            pass

    return off


def current_time_millis() -> int:
    if _frozen_ms is not None:
        return _frozen_ms
    return time.time_ns() // 1_000_000


def freeze_time(ms: int) -> None:
    """Pin the clock (tests only)."""
    global _frozen_ms
    _frozen_ms = int(ms)


def advance_time(delta_ms: int) -> None:
    global _frozen_ms
    assert _frozen_ms is not None, "advance_time requires freeze_time first"
    for hook in list(_pre_advance_hooks):
        hook()
    _frozen_ms += int(delta_ms)


def unfreeze_time() -> None:
    global _frozen_ms
    _frozen_ms = None

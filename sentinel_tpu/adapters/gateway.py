"""API-gateway common layer (reference:
``sentinel-api-gateway-adapter-common`` — ``GatewayFlowRule`` /
``GatewayParamFlowItem`` / ``GatewayRuleManager`` (conversion to param-flow
rules) / ``api/ApiDefinition`` + ``GatewayApiDefinitionManager`` /
``param/GatewayParamParser`` — SURVEY.md §2.5).

Gateway rules are enforced through the hot-param machinery: every gateway
rule on a resource gets an assigned param index; rules without a param item
match a generated constant value, and pattern-bearing items rewrite
non-matching values to a pass-through sentinel with an unlimited per-value
item — exactly the reference's conversion trick.
"""

from __future__ import annotations

import json
import re
import threading
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import MAX_PARAMS
from sentinel_tpu.models.param_flow import ParamFlowItem, ParamFlowRule

# resourceMode
RESOURCE_MODE_ROUTE_ID = 0
RESOURCE_MODE_CUSTOM_API_NAME = 1

# parseStrategy
PARAM_PARSE_STRATEGY_CLIENT_IP = 0
PARAM_PARSE_STRATEGY_HOST = 1
PARAM_PARSE_STRATEGY_HEADER = 2
PARAM_PARSE_STRATEGY_URL_PARAM = 3
PARAM_PARSE_STRATEGY_COOKIE = 4

# matchStrategy (URL + param patterns)
PARAM_MATCH_STRATEGY_EXACT = 0
PARAM_MATCH_STRATEGY_PREFIX = 1
PARAM_MATCH_STRATEGY_REGEX = 2
PARAM_MATCH_STRATEGY_CONTAINS = 3

# Generated parser constants (reference: SentinelGatewayConstants).
GATEWAY_DEFAULT_PARAM = "$D"       # rules without a param item
GATEWAY_NOT_MATCH_PARAM = "$NM"    # pattern miss -> pass-through value
NOT_MATCH_PASS_COUNT = 1e9


@dataclass
class GatewayParamFlowItem:
    parse_strategy: int = PARAM_PARSE_STRATEGY_CLIENT_IP
    field_name: Optional[str] = None
    pattern: Optional[str] = None
    match_strategy: int = PARAM_MATCH_STRATEGY_EXACT


@dataclass
class GatewayFlowRule:
    resource: str
    count: float
    resource_mode: int = RESOURCE_MODE_ROUTE_ID
    grade: int = C.PARAM_FLOW_GRADE_QPS
    interval_sec: int = 1
    control_behavior: int = C.CONTROL_BEHAVIOR_DEFAULT
    burst: int = 0
    max_queueing_timeout_ms: int = 500
    param_item: Optional[GatewayParamFlowItem] = None

    def is_valid(self) -> bool:
        return bool(self.resource) and self.count >= 0 and self.interval_sec > 0


@dataclass
class ApiPredicateItem:
    pattern: str
    match_strategy: int = PARAM_MATCH_STRATEGY_EXACT


@dataclass
class ApiDefinition:
    api_name: str
    predicate_items: List[ApiPredicateItem] = field(default_factory=list)


@dataclass
class GatewayRequest:
    """The transport-agnostic request view the param parser reads."""

    path: str = "/"
    client_ip: str = ""
    host: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    params: Dict[str, str] = field(default_factory=dict)
    cookies: Dict[str, str] = field(default_factory=dict)
    route: Optional[str] = None


def _matches(pattern: str, strategy: int, value: str) -> bool:
    if strategy == PARAM_MATCH_STRATEGY_PREFIX:
        return value.startswith(pattern)
    if strategy == PARAM_MATCH_STRATEGY_REGEX:
        return re.fullmatch(pattern, value) is not None
    if strategy == PARAM_MATCH_STRATEGY_CONTAINS:
        return pattern in value
    return value == pattern


class GatewayApiDefinitionManager:
    """Custom API groups (reference: ``GatewayApiDefinitionManager``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._apis: Dict[str, ApiDefinition] = {}

    def load_api_definitions(self, defs: Sequence[ApiDefinition]) -> None:
        with self._lock:
            self._apis = {d.api_name: d for d in defs if d.api_name}

    def get_api_definitions(self) -> List[ApiDefinition]:
        with self._lock:
            return list(self._apis.values())

    def matching_apis(self, path: str) -> List[str]:
        with self._lock:
            apis = list(self._apis.values())
        return [
            a.api_name for a in apis
            if any(_matches(p.pattern, p.match_strategy, path)
                   for p in a.predicate_items)
        ]


class GatewayRuleManager:
    """Converts gateway rules to param-flow rules (``GatewayRuleManager``).

    Each gateway rule on a resource is assigned a param index (capped by the
    batch's MAX_PARAMS); the parser emits the matching argument vector.
    """

    def __init__(self, engine=None, _weak_engine=None):
        self._engine = engine
        self._engine_ref = _weak_engine  # weakref.ref (managers_for)
        self._lock = threading.Lock()
        self._rules: List[GatewayFlowRule] = []
        # resource -> [(gateway_rule, param_idx)]
        self._by_resource: Dict[str, List[Tuple[GatewayFlowRule, int]]] = {}

    @property
    def engine(self):
        if self._engine is not None:
            return self._engine
        if self._engine_ref is not None:
            eng = self._engine_ref()
            if eng is not None:
                return eng
        return st.get_engine()

    def load_rules(self, rules: Sequence[GatewayFlowRule]) -> None:
        by_resource: Dict[str, List[Tuple[GatewayFlowRule, int]]] = {}
        param_rules: List[ParamFlowRule] = []
        enforced: List[GatewayFlowRule] = []
        dropped = 0
        for r in rules:
            if not r.is_valid():
                continue
            assigned = by_resource.setdefault(r.resource, [])
            idx = len(assigned)
            if idx >= MAX_PARAMS:
                dropped += 1
                continue
            assigned.append((r, idx))
            enforced.append(r)
            items = []
            if r.param_item is not None and r.param_item.pattern is not None:
                # Pattern miss rewrites to $NM, which passes unlimited.
                items.append(ParamFlowItem(GATEWAY_NOT_MATCH_PARAM,
                                           NOT_MATCH_PASS_COUNT))
            param_rules.append(ParamFlowRule(
                resource=r.resource,
                param_idx=idx,
                count=r.count,
                grade=r.grade,
                duration_in_sec=r.interval_sec,
                burst_count=r.burst,
                control_behavior=r.control_behavior,
                max_queueing_time_ms=r.max_queueing_timeout_ms,
                items=items,
            ))
        with self._lock:
            # Engine push inside the critical section: the parser's index
            # map and the enforced rule set must publish atomically, and
            # get_rules() only reports rules that are actually enforced.
            self._rules = enforced
            self._by_resource = by_resource
            self.engine.param_rules.load_gateway_rules(param_rules)
        if dropped:
            from sentinel_tpu.log.record_log import record_log

            record_log.warn(
                "gateway: %d rules beyond %d per resource dropped",
                dropped, MAX_PARAMS)

    def get_rules(self) -> List[GatewayFlowRule]:
        with self._lock:
            return list(self._rules)

    # -- param parsing (reference: GatewayParamParser) ---------------------

    def parse_parameters(self, resource: str, request: GatewayRequest) -> Tuple:
        with self._lock:
            assigned = list(self._by_resource.get(resource, ()))
        args: List[str] = [""] * len(assigned)
        for rule, idx in assigned:
            item = rule.param_item
            if item is None:
                value = GATEWAY_DEFAULT_PARAM
            else:
                s = item.parse_strategy
                if s == PARAM_PARSE_STRATEGY_CLIENT_IP:
                    value = request.client_ip
                elif s == PARAM_PARSE_STRATEGY_HOST:
                    value = request.host
                elif s == PARAM_PARSE_STRATEGY_HEADER:
                    value = request.headers.get(item.field_name or "", "")
                elif s == PARAM_PARSE_STRATEGY_URL_PARAM:
                    value = request.params.get(item.field_name or "", "")
                elif s == PARAM_PARSE_STRATEGY_COOKIE:
                    value = request.cookies.get(item.field_name or "", "")
                else:
                    value = ""
                if item.pattern is not None and not _matches(
                        item.pattern, item.match_strategy, value):
                    value = GATEWAY_NOT_MATCH_PARAM
            args[idx] = value
        return tuple(args)


_default_api_manager = GatewayApiDefinitionManager()
_default_rule_manager: Optional[GatewayRuleManager] = None


def get_api_manager() -> GatewayApiDefinitionManager:
    return _default_api_manager


_default_rule_manager_lock = threading.Lock()


def get_gateway_rule_manager() -> GatewayRuleManager:
    global _default_rule_manager
    if _default_rule_manager is None:
        # locked: two racing first touches must not split enforcement
        # and reporting across two manager instances
        with _default_rule_manager_lock:
            if _default_rule_manager is None:
                _default_rule_manager = GatewayRuleManager()
    return _default_rule_manager


_engine_managers: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_engine_managers_lock = threading.Lock()


def managers_for(engine) -> Tuple[GatewayRuleManager,
                                  GatewayApiDefinitionManager]:
    """Gateway managers scoped to ``engine``: the module defaults when it
    IS the current default engine (so ops-plane pushes and
    ``gateway_entry``'s default managers share state), else a per-engine
    memoized pair — a command center bound to a non-default engine must
    not silently load rules into the default one.

    The pair holds only a WEAK reference to the engine (a strong one in
    the value would pin the WeakKeyDictionary key forever, leaking every
    short-lived engine), and the check-then-insert is locked so two
    racing first-touch commands can't split enforcement and reporting
    across different manager pairs."""
    if engine is st.get_engine():
        return get_gateway_rule_manager(), _default_api_manager
    with _engine_managers_lock:
        pair = _engine_managers.get(engine)
        if pair is None:
            pair = (GatewayRuleManager(_weak_engine=weakref.ref(engine)),
                    GatewayApiDefinitionManager())
            _engine_managers[engine] = pair
    return pair


def gateway_entry(request: GatewayRequest,
                  rule_manager: Optional[GatewayRuleManager] = None,
                  api_manager: Optional[GatewayApiDefinitionManager] = None):
    """Enter all gateway resources a request maps to: its route id plus any
    matching custom API groups. Returns the live entries (exit in reverse);
    raises BlockException if any resource rejects (already-taken entries are
    exited first, reference filter semantics).
    """
    rm = rule_manager or get_gateway_rule_manager()
    am = api_manager or _default_api_manager
    resources = []
    if request.route:
        resources.append(request.route)
    resources.extend(am.matching_apis(request.path))
    entries = []
    try:
        for resource in resources:
            args = rm.parse_parameters(resource, request)
            entries.append(st.entry(
                resource, entry_type=C.EntryType.IN, args=args))
    except Exception:
        for e in reversed(entries):
            e.exit()
        raise
    return entries


# -- JSON wire schema (reference fastjson camelCase field names, so
# dashboard payloads written for the reference parse unchanged) ------------


def gateway_rule_from_dict(d: dict) -> GatewayFlowRule:
    item = d.get("paramItem")
    return GatewayFlowRule(
        resource=d.get("resource", ""),
        count=float(d.get("count", 0)),
        resource_mode=int(d.get("resourceMode", RESOURCE_MODE_ROUTE_ID)),
        grade=int(d.get("grade", C.PARAM_FLOW_GRADE_QPS)),
        interval_sec=int(d.get("intervalSec", 1)),
        control_behavior=int(d.get("controlBehavior",
                                   C.CONTROL_BEHAVIOR_DEFAULT)),
        burst=int(d.get("burst", 0)),
        max_queueing_timeout_ms=int(d.get("maxQueueingTimeoutMs", 500)),
        param_item=None if item is None else GatewayParamFlowItem(
            parse_strategy=int(item.get("parseStrategy",
                                        PARAM_PARSE_STRATEGY_CLIENT_IP)),
            field_name=item.get("fieldName"),
            pattern=item.get("pattern"),
            match_strategy=int(item.get("matchStrategy",
                                        PARAM_MATCH_STRATEGY_EXACT)),
        ),
    )


def gateway_rule_to_dict(r: GatewayFlowRule) -> dict:
    out = {
        "resource": r.resource, "resourceMode": r.resource_mode,
        "grade": r.grade, "count": r.count, "intervalSec": r.interval_sec,
        "controlBehavior": r.control_behavior, "burst": r.burst,
        "maxQueueingTimeoutMs": r.max_queueing_timeout_ms,
    }
    if r.param_item is not None:
        out["paramItem"] = {
            "parseStrategy": r.param_item.parse_strategy,
            "fieldName": r.param_item.field_name,
            "pattern": r.param_item.pattern,
            "matchStrategy": r.param_item.match_strategy,
        }
    return out


def gateway_rules_from_json(source) -> List[GatewayFlowRule]:
    data = json.loads(source) if isinstance(source, str) else (source or [])
    return [gateway_rule_from_dict(d) for d in data]


def gateway_rules_to_json(rules: Sequence[GatewayFlowRule]) -> str:
    return json.dumps([gateway_rule_to_dict(r) for r in rules])


def api_definitions_from_json(source) -> List[ApiDefinition]:
    data = json.loads(source) if isinstance(source, str) else (source or [])
    return [
        ApiDefinition(
            api_name=d.get("apiName", ""),
            predicate_items=[
                ApiPredicateItem(
                    pattern=p.get("pattern", ""),
                    match_strategy=int(p.get("matchStrategy",
                                             PARAM_MATCH_STRATEGY_EXACT)))
                for p in (d.get("predicateItems") or [])
            ])
        for d in data
    ]


def api_definition_to_dict(a: ApiDefinition) -> dict:
    return {"apiName": a.api_name,
            "predicateItems": [{"pattern": p.pattern,
                                "matchStrategy": p.match_strategy}
                               for p in a.predicate_items]}


def api_definitions_to_json(defs: Sequence[ApiDefinition]) -> str:
    return json.dumps([api_definition_to_dict(a) for a in defs])

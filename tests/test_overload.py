"""Overload-safe ingestion (ISSUE 6): bounded admission queues,
deadline-aware shedding, per-connection caps, the OVERLOADED client
contract, and the concurrency harness.

Unit pieces run on the frozen clock or direct batcher calls; the
harness scenarios use real sockets + real time with millisecond-scale
knobs. Full-scale runs carry the ``load`` marker (and ``slow``, so the
tier-1 ``-m 'not slow'`` sweep keeps only the scaled-down variants).
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.constants import (
    MSG_FLOW,
    THRESHOLD_GLOBAL,
    TokenResultStatus,
)
from sentinel_tpu.cluster.ha import FailoverTokenClient
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer, _Batcher, pad_width
from sentinel_tpu.cluster.token_service import DefaultTokenService, TokenResult
from sentinel_tpu.core.exceptions import BlockException
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.resilience import DeadlineBudget
from sentinel_tpu.utils import time_util

FLOW_ID = 7001


def _rules(count: float = 1e9, flow_id: int = FLOW_ID,
           fallback: bool = True) -> ClusterFlowRuleManager:
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [FlowRule(
        resource="ov", count=count, cluster_mode=True,
        cluster_config={"flowId": flow_id, "thresholdType": THRESHOLD_GLOBAL,
                        "fallbackToLocalWhenFail": fallback})])
    return rules


class _StubService:
    """Minimal token service for batcher-only tests: every request OK,
    optional per-batch delay, records batch widths."""

    epoch = 0

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.batches = []
        self.calls = 0

    def request_tokens(self, requests, now_ms=None):
        self.calls += 1
        self.batches.append(len(requests))
        if self.delay_s:
            time.sleep(self.delay_s)
        return [TokenResult(TokenResultStatus.OK, remaining=1)
                for _ in requests]


# -- pad-width ladder (satellite: unpinned batcher edge behavior) -------------


def test_pad_width_ladder_pinned():
    """<=64 exact, then 256 / 1024 / 4096 / +4096 steps — and always
    >= n (a width below n would silently drop requests)."""
    assert [pad_width(n) for n in (1, 7, 64)] == [1, 7, 64]
    assert pad_width(65) == 256
    assert pad_width(256) == 256
    assert pad_width(257) == 1024
    assert pad_width(1025) == 4096
    assert pad_width(4097) == 8192
    assert pad_width(8193) == 12288
    for n in range(1, 9000, 61):
        assert pad_width(n) >= n


# -- batcher admission (direct, no sockets) -----------------------------------


def test_watermark_shed_before_queue_full():
    svc = _StubService()
    b = _Batcher(svc, 0.0, 256, max_queue_groups=10, watermark_pct=20,
                 deadline_ms=1000, retry_after_ms=77)
    try:
        boxes = [b.submit_many([(FLOW_ID, 1, False)]) for _ in range(3)]
        # watermark = 2 of 10: the first two groups queue, the third is
        # shed immediately with the configured retry-after hint.
        assert "shed_retry_after_ms" not in boxes[0][1]
        assert "shed_retry_after_ms" not in boxes[1][1]
        assert boxes[2][1]["shed_retry_after_ms"] == 77
        assert boxes[2][0].is_set()  # shed replies are immediate
        stats = b.overload_stats()
        assert stats["shedWatermark"] == 1
        assert stats["admittedGroups"] == 2
        assert stats["queueDepth"] == 2
        assert svc.calls == 0  # never started: nothing reached the device
    finally:
        b.stop()


def test_queue_full_shed_backstop():
    """The put_nowait Full path (reachable only when a racing submitter
    fills the queue between the watermark read and the put)."""
    svc = _StubService()
    b = _Batcher(svc, 0.0, 256, max_queue_groups=1, watermark_pct=100,
                 deadline_ms=1000)
    b._queue.put_nowait(([("x", 1, False)], threading.Event(), {},
                         DeadlineBudget(1000)))
    b._queue.qsize = lambda: 0  # simulate the stale watermark read
    done, box = b.submit_many([(FLOW_ID, 1, False)])
    assert done.is_set() and box["shed_retry_after_ms"] > 0
    assert b.overload_stats()["shedQueueFull"] == 1


def test_deadline_expired_groups_shed_before_device_step():
    """A group whose budget expired while queued is shed by the drain
    loop BEFORE request_tokens — the device never sees it (the
    half-admission proof point), and live groups behind it still get
    verdicts."""
    svc = _StubService()
    b = _Batcher(svc, 0.0, 256, max_queue_groups=10, watermark_pct=100,
                 deadline_ms=5_000)
    expired = DeadlineBudget(0)
    time.sleep(0.002)  # ensure remaining_ms() <= 0
    dead_done, dead_box = b.submit_many([(FLOW_ID, 1, False)] * 3,
                                        budget=expired)
    live_done, live_box = b.submit_many([(FLOW_ID, 1, False)])
    b.start()
    try:
        assert live_done.wait(2.0) and dead_done.wait(2.0)
        assert dead_box["shed_retry_after_ms"] > 0
        assert "results" not in dead_box
        assert len(live_box["results"]) == 1
        assert live_box["results"][0].status == TokenResultStatus.OK
        stats = b.overload_stats()
        assert stats["shedDeadlineExpired"] == 1
        assert stats["shedRequests"] == 3
        # the device batch held ONLY the live group's request
        assert svc.batches == [1]
    finally:
        b.stop()


def test_poison_batch_does_not_kill_drain_loop():
    """The drain loop's ``except Exception`` survival path (previously
    unpinned): a poison batch fails its groups fast (empty box -> wire
    FAIL), and the NEXT batch is served normally."""
    svc = _StubService()
    real = svc.request_tokens
    state = {"poisoned": True}

    def poisoned(requests, now_ms=None):
        if state.pop("poisoned", None):
            raise RuntimeError("poison batch")
        return real(requests, now_ms)

    svc.request_tokens = poisoned
    b = _Batcher(svc, 0.0, 256, max_queue_groups=10)
    b.start()
    try:
        done1, box1 = b.submit_many([(FLOW_ID, 1, False)])
        assert done1.wait(2.0)
        assert "results" not in box1 and "shed_retry_after_ms" not in box1
        done2, box2 = b.submit_many([(FLOW_ID, 1, False)])
        assert done2.wait(2.0)
        assert box2["results"][0].status == TokenResultStatus.OK
    finally:
        b.stop()


def test_max_batch_is_group_granular_never_splits():
    """``max_batch`` is a soft cap at GROUP granularity: the drain may
    overshoot it by finishing the group it started, but a drained group
    is never split across device calls."""
    svc = _StubService()
    b = _Batcher(svc, 0.05, max_batch=4, max_queue_groups=10)
    groups = [b.submit_many([(FLOW_ID, 1, False)] * 3) for _ in range(2)]
    b.start()
    try:
        for done, box in groups:
            assert done.wait(2.0)
            assert len(box["results"]) == 3
        # 3 < max_batch 4 -> the second WHOLE group merges in: one call
        # of 6, not a 4/2 split.
        assert svc.batches == [6]
    finally:
        b.stop()


# -- client socket timeout (satellite: cluster/client.py fix) -----------------


def test_client_socket_timeout_bounded_and_idle_safe():
    """The connected socket carries a BOUNDED timeout derived from the
    request timeout (was ``settimeout(None)`` — a server that stopped
    reading mid-reply parked sendall forever holding the send lock),
    and an idle period longer than that timeout does NOT drop the
    connection (the reader treats it as an idle tick)."""
    service = DefaultTokenService(_rules())
    service.request_tokens([(FLOW_ID, 1, False)])  # warm the width-1 jit:
    # under full-suite load the compile outlasts the tight 0.2s request
    # timeout and reads as a miss, which is not what this test measures
    server = ClusterTokenServer(service, host="127.0.0.1", port=0).start()
    # health_gate=None: this test pins SOCKET behavior; a loaded CI box
    # missing the tight timeout a few times would open the breaker and
    # turn the final assert into a breaker test instead
    c = ClusterTokenClient("127.0.0.1", server.bound_port,
                           request_timeout_s=0.2, health_gate=None).start()
    try:
        deadline = time.monotonic() + 5
        while not c.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert c.is_connected()
        assert c._sock.gettimeout() == pytest.approx(c._io_timeout_s())
        assert c._sock.gettimeout() is not None
        # idle well past the I/O timeout: reader must survive its
        # socket.timeout ticks with the connection up...
        time.sleep(c._io_timeout_s() * 2.5)
        assert c.is_connected()
        # ...and the connection must still serve requests.
        deadline = time.monotonic() + 10
        status = None
        while time.monotonic() < deadline:
            status = c.request_token(FLOW_ID, timeout_s=5.0).status
            if status == TokenResultStatus.OK:
                break
        assert status == TokenResultStatus.OK
    finally:
        c.stop()
        server.stop()


# -- OVERLOADED wire + client contract ----------------------------------------


def _always_shed(server: ClusterTokenServer, retry_after_ms: int = 40):
    """Force every submit to shed (the saturated-server stand-in)."""
    def shed(requests, budget=None):
        done = threading.Event()
        box = {"shed_retry_after_ms": retry_after_ms}
        server.batcher.shed_watermark += 1
        server.batcher.shed_requests += len(list(requests))
        done.set()
        return done, box

    server.batcher.submit_many = shed


def test_overloaded_rides_the_wire_with_retry_after():
    server = ClusterTokenServer(DefaultTokenService(_rules()),
                                host="127.0.0.1", port=0).start()
    _always_shed(server, retry_after_ms=40)
    c = ClusterTokenClient("127.0.0.1", server.bound_port,
                           request_timeout_s=2.0).start()
    try:
        deadline = time.monotonic() + 5
        while not c.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        tr = c.request_token(FLOW_ID)
        assert tr.status == TokenResultStatus.OVERLOADED
        assert tr.wait_ms == 40
        # overload is a breaker SUCCESS: the wire round-tripped
        assert c.health_gate.snapshot()["state"] == "CLOSED"
    finally:
        c.stop()
        server.stop()


class _FakeInner:
    """Stands in for FailoverTokenClient's inner ClusterTokenClient."""

    def __init__(self, status: int, wait_ms: int = 40):
        self.status = status
        self.wait_ms = wait_ms
        self.calls = 0
        self.host, self.port = "fake", 0
        self.health_gate = None
        self.request_timeout_s = 2.0

    def start(self):
        return self

    def stop(self):
        pass

    def is_connected(self):
        return True

    def request_token(self, *a, **k):
        self.calls += 1
        return TokenResult(self.status, wait_ms=self.wait_ms)

    def request_param_token(self, *a, **k):
        self.calls += 1
        return TokenResult(self.status, wait_ms=self.wait_ms)


def test_failover_client_backs_off_overloaded_target(frozen_time):
    overloaded = _FakeInner(TokenResultStatus.OVERLOADED, wait_ms=300)
    healthy = _FakeInner(TokenResultStatus.OK)
    fc = FailoverTokenClient([("a", 1), ("b", 2)])
    fc._clients = [overloaded, healthy]
    fc._backoff_until_ms = [0, 0]

    tr = fc.request_token(FLOW_ID)
    assert tr.status == TokenResultStatus.OK
    assert overloaded.calls == 1 and healthy.calls == 1
    assert fc.overloaded_count == 1
    assert fc.failover_stats()["targetsBackedOff"] == 1
    # inside the backoff window the overloaded target is skipped cold
    tr = fc.request_token(FLOW_ID)
    assert tr.status == TokenResultStatus.OK
    assert overloaded.calls == 1 and healthy.calls == 2
    # past the window (server hint 300ms > config floor) it is retried
    time_util.advance_time(301)
    fc.request_token(FLOW_ID)
    assert overloaded.calls == 2
    # an OVERLOADED reply is NOT a failure toward degraded mode
    assert not fc.is_degraded()


def test_failover_client_all_targets_overloaded_reports_overloaded(frozen_time):
    fc = FailoverTokenClient([("a", 1), ("b", 2)])
    fc._clients = [_FakeInner(TokenResultStatus.OVERLOADED, wait_ms=120),
                   _FakeInner(TokenResultStatus.OVERLOADED, wait_ms=120)]
    fc._backoff_until_ms = [0, 0]
    tr = fc.request_token(FLOW_ID)
    assert tr.status == TokenResultStatus.OVERLOADED
    assert tr.wait_ms == 120
    assert not fc.is_degraded()  # fleet reachable: clock reset, not lost
    # a backoff-only round (no wire touch) still reports OVERLOADED
    tr = fc.request_token(FLOW_ID)
    assert tr.status == TokenResultStatus.OVERLOADED
    assert tr.wait_ms > 0
    assert sum(c.calls for c in fc._clients) == 2  # nothing re-hit


class _OverloadedEngineClient:
    """Engine-facing token client whose every acquire is shed."""

    serves_degraded = False
    health_gate = None

    def start(self):
        return self

    def stop(self):
        pass

    def is_connected(self):
        return True

    def request_token(self, *a, **k):
        return TokenResult(TokenResultStatus.OVERLOADED, wait_ms=50)

    def request_param_token(self, *a, **k):
        return TokenResult(TokenResultStatus.OVERLOADED, wait_ms=50)


def test_engine_degrades_overloaded_entries_to_local_path(engine):
    """The acceptance contract's client half: a caller behind an
    OVERLOADED token server is served by the LOCAL check immediately —
    bounded latency, no sleep on the retry-after hint — with the local
    threshold enforced and the shed counted."""
    st.load_flow_rules([FlowRule(
        resource="ov", count=3.0, cluster_mode=True,
        cluster_config={"flowId": FLOW_ID,
                        "thresholdType": THRESHOLD_GLOBAL,
                        "fallbackToLocalWhenFail": True})])
    engine.cluster.set_client(_OverloadedEngineClient())
    # absorb the width-1 entry-batch jit compile outside the timed
    # window, then roll the frozen clock into a fresh flow window so
    # the warm-up entry's quota spend doesn't skew the counts below
    try:
        with engine.entry("ov"):
            pass
    except BlockException:
        pass
    time_util.advance_time(1_100)
    outcomes = []
    t0 = time.monotonic()
    for _ in range(5):
        try:
            with engine.entry("ov"):
                pass
            outcomes.append("pass")
        except BlockException:
            outcomes.append("block")
    elapsed = time.monotonic() - t0
    # local flow threshold (3/s, frozen clock) enforced via fallback
    assert outcomes.count("pass") == 3
    assert outcomes.count("block") == 2
    assert engine.cluster_overload_count == 6  # warm-up entry + 5
    # bounded latency: no 50ms retry-after sleeps on the data path
    assert elapsed < 2.0
    stats = engine.resilience_stats()
    assert stats["clusterOverloadCount"] == 6
    assert stats["overload"] is None  # not a server


def test_overload_gauges_exported(engine):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    text = render_engine_metrics(engine)
    # not a server: depth renders -1 so one scrape config fits all roles
    assert "sentinel_tpu_overload_queue_depth -1" in text
    engine.cluster.set_to_server(host="127.0.0.1", port=0)
    try:
        text = render_engine_metrics(engine)
        assert "sentinel_tpu_overload_queue_depth 0" in text
        assert 'sentinel_tpu_overload_shed_total{cause="watermark"}' in text
        assert "sentinel_tpu_overload_shed_requests_total" in text
        assert "sentinel_tpu_overload_queue_limit " in text
    finally:
        engine.cluster.stop()


# -- Envoy RLS shed gate ------------------------------------------------------


def test_rls_semaphore_gate_sheds_with_unknown():
    from sentinel_tpu.envoy_rls import proto
    from sentinel_tpu.envoy_rls.service import SentinelEnvoyRlsService

    svc = SentinelEnvoyRlsService(token_service=_StubService(),
                                  max_concurrent=1)
    assert svc._gate.acquire(blocking=False)  # saturate the gate
    try:
        code, statuses = svc.should_rate_limit("d", [[("k", "v")]])
        assert code == proto.CODE_UNKNOWN
        assert statuses == [(proto.CODE_UNKNOWN, 0)]
        assert svc.overload_stats()["shedCount"] == 1
        assert svc.overload_stats()["servedCount"] == 0
    finally:
        svc._gate.release()
    code, statuses = svc.should_rate_limit("d", [[("k", "v")]])
    assert code == proto.CODE_OK
    assert svc.overload_stats()["servedCount"] == 1


# -- concurrency harness ------------------------------------------------------


def _pipelined_burst(port: int, flow_id: int, n: int,
                     timeout_s: float = 10.0):
    """One pipelined TLV connection: send n FLOW frames back-to-back,
    read n responses; -> list of (status, wait_ms)."""
    out = []
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout_s) as sock:
        sock.settimeout(timeout_s)
        frames = b"".join(
            codec.encode_request(xid, MSG_FLOW,
                                 codec.encode_flow_request(flow_id, 1, False))
            for xid in range(1, n + 1))
        sock.sendall(frames)
        reader = codec.FrameReader()
        while len(out) < n:
            data = sock.recv(65536)
            if not data:
                break
            for body in reader.feed(data):
                resp = codec.decode_response(body)
                _rem, wait_ms = codec.decode_flow_response(resp.entity)
                out.append((resp.status, wait_ms))
    return out


def _run_harness(n_conns: int, burst: int, rounds: int, step_delay_s: float,
                 max_queue_groups: int, watermark_pct: int,
                 max_batch: int = 256, deadline_ms: int = 2_000,
                 rls_threads: int = 0, rls_calls: int = 0,
                 conn_max_burst: int = None):
    """Drive concurrent pipelined TLV connections (and optionally RLS
    callers) through a deliberately slowed device step; returns
    (per-burst results, per-burst walls, server stats, rls stats).

    ``conn_max_burst`` below the burst size splits each connection's
    burst into multiple admission groups — the knob that makes the
    bounded queue actually fill under the reactor frontend, whose
    coalescing would otherwise fold a whole drill into a handful of
    groups (ISSUE 11)."""
    service = DefaultTokenService(_rules())
    # absorb the jit compiles for the widths this run can produce, so
    # the timed section measures queueing, not XLA
    for width in sorted({burst, pad_width(burst + 1),
                         pad_width(max_batch)}):
        service.request_tokens([(FLOW_ID, 1, False)] * width)
    real = service.request_tokens
    service.request_tokens = lambda reqs, now_ms=None: (
        time.sleep(step_delay_s), real(reqs, now_ms))[1]
    server = ClusterTokenServer(service, host="127.0.0.1", port=0,
                                max_queue_groups=max_queue_groups,
                                watermark_pct=watermark_pct,
                                max_batch=max_batch,
                                deadline_ms=deadline_ms,
                                conn_max_burst=conn_max_burst).start()
    rls = None
    if rls_threads:
        from sentinel_tpu.envoy_rls.service import SentinelEnvoyRlsService

        rls = SentinelEnvoyRlsService(token_service=_StubService(
            delay_s=step_delay_s), max_concurrent=4)
    results, walls, rls_codes = [], [], []
    lock = threading.Lock()
    barrier = threading.Barrier(n_conns + rls_threads)

    def tlv_worker():
        barrier.wait()
        for _ in range(rounds):
            t0 = time.monotonic()
            got = _pipelined_burst(server.bound_port, FLOW_ID, burst)
            wall = time.monotonic() - t0
            with lock:
                results.append(got)
                walls.append(wall)

    def rls_worker():
        barrier.wait()
        for _ in range(rls_calls):
            code, _statuses = rls.should_rate_limit("d", [[("k", "v")]])
            with lock:
                rls_codes.append(code)

    threads = [threading.Thread(target=tlv_worker)
               for _ in range(n_conns)]
    threads += [threading.Thread(target=rls_worker)
                for _ in range(rls_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = server.overload_stats()
    server.stop()
    return results, walls, stats, (rls.overload_stats() if rls else None,
                                   rls_codes)


def _assert_overload_invariants(results, walls, stats, n_bursts, burst,
                                max_queue_groups, deadline_ms,
                                goodput_floor):
    from sentinel_tpu.envoy_rls import proto  # noqa: F401 — parity import

    # 1. zero silent drops: every burst got a full complement of replies
    assert len(results) == n_bursts
    assert all(len(r) == burst for r in results), \
        f"short bursts: {sorted(set(len(r) for r in results))}"
    flat = [s for r in results for s in r]
    assert set(s for s, _ in flat) <= {int(TokenResultStatus.OK),
                                       int(TokenResultStatus.OVERLOADED)}
    # 2. the queue never grew past its configured bound
    assert stats["queueDepthMax"] <= max_queue_groups
    # 3. shed replies carry a retry-after hint and arrive well inside
    # the deadline budget (they are immediate, not queued)
    ok = sum(1 for s, _ in flat if s == int(TokenResultStatus.OK))
    shed = len(flat) - ok
    for s, wait_ms in flat:
        if s == int(TokenResultStatus.OVERLOADED):
            assert wait_ms > 0
    for r, wall in zip(results, walls):
        if all(s == int(TokenResultStatus.OVERLOADED) for s, _ in r):
            assert wall < deadline_ms / 1000.0, \
                f"fully-shed burst took {wall:.2f}s"
    # 4. goodput floor for in-deadline requests + the shed path really ran
    assert ok >= goodput_floor, f"goodput collapsed: {ok} OK / {shed} shed"
    assert shed + stats["shedRequests"] >= 0
    return ok, shed


def test_overload_harness_small():
    """Scaled-down tier-1 harness: 12 pipelined connections against a
    50ms device step with a 4-group queue — asserts the acceptance
    bullet (bounded queue, zero silent drops, shed-within-deadline,
    goodput floor) at a size the tier-1 budget affords."""
    n_conns, burst, rounds = 12, 32, 3
    results, walls, stats, _ = _run_harness(
        n_conns, burst, rounds, step_delay_s=0.05,
        max_queue_groups=4, watermark_pct=50, max_batch=32,
        conn_max_burst=8)
    ok, shed = _assert_overload_invariants(
        results, walls, stats, n_conns * rounds, burst,
        max_queue_groups=4, deadline_ms=2_000, goodput_floor=burst)
    # 12 simultaneous bursts vs a 2-group watermark and a one-group-per-
    # 50ms drain: shedding must actually engage
    assert shed > 0
    assert stats["shedWatermark"] + stats["shedQueueFull"] \
        + stats["shedDeadlineExpired"] > 0


@pytest.mark.load
@pytest.mark.slow
def test_overload_harness_full():
    """Full-scale load drill (ROADMAP item 4 / ISSUE 6 acceptance):
    hundreds of concurrent pipelined TLV connections PLUS concurrent
    RLS callers through a deliberately slowed device step — no
    unbounded queue growth, every request answered, goodput floor."""
    n_conns, burst, rounds = 200, 64, 2
    results, walls, stats, (rls_stats, rls_codes) = _run_harness(
        n_conns, burst, rounds, step_delay_s=0.02,
        max_queue_groups=16, watermark_pct=50,
        rls_threads=8, rls_calls=25, conn_max_burst=16)
    ok, shed = _assert_overload_invariants(
        results, walls, stats, n_conns * rounds, burst,
        max_queue_groups=16, deadline_ms=2_000,
        goodput_floor=10 * burst)
    assert shed > 0
    # RLS side: every call answered (served or explicitly shed), and the
    # gate kept concurrency bounded without deadlock
    assert len(rls_codes) == 8 * 25
    assert rls_stats["servedCount"] + rls_stats["shedCount"] == 8 * 25
    from sentinel_tpu.envoy_rls import proto

    assert set(rls_codes) <= {proto.CODE_UNKNOWN, proto.CODE_OK,
                              proto.CODE_OVER_LIMIT}


def test_idle_timeout_configurable_and_reaps():
    """The TLV handler's idle timeout follows overload.idle.timeout.s
    (was a flat 300s): an idle connection is reaped after it."""
    server = ClusterTokenServer(DefaultTokenService(_rules()),
                                host="127.0.0.1", port=0,
                                idle_timeout_s=1).start()
    try:
        assert server.idle_timeout_s == 1
        with socket.create_connection(("127.0.0.1", server.bound_port),
                                      timeout=5.0) as sock:
            sock.settimeout(5.0)
            # idle past the server's timeout: the handler times out its
            # recv and closes — we observe EOF
            assert sock.recv(1) == b""
    finally:
        server.stop()


def test_conn_burst_cap_splits_pipelined_bursts_without_loss():
    """A pipelined burst beyond conn.max.burst is processed as multiple
    sequential groups (per-connection concurrency cap) — every request
    still answered, and no single admission group exceeded the cap."""
    service = DefaultTokenService(_rules())
    for width in (8, pad_width(9)):
        service.request_tokens([(FLOW_ID, 1, False)] * width)
    server = ClusterTokenServer(service, host="127.0.0.1", port=0,
                                conn_max_burst=8).start()
    sizes = []
    orig = server.batcher.submit_many

    def spying_submit(requests, budget=None):
        reqs = list(requests)
        sizes.append(len(reqs))
        return orig(reqs, budget)

    server.batcher.submit_many = spying_submit
    try:
        got = _pipelined_burst(server.bound_port, FLOW_ID, 20)
        assert len(got) == 20
        assert all(s == int(TokenResultStatus.OK) for s, _ in got)
        assert sizes and max(sizes) <= 8
        assert sum(sizes) == 20
    finally:
        server.stop()

"""Deterministic trace-replay simulator (ISSUE 13 / ROADMAP item 4).

The offline half of the closed adaptive loop: recorded flight-recorder
seconds (or seedable synthetic scenarios) are re-driven through a REAL
``SentinelEngine`` — CPU tier, production fused-step kernels — on a
fully frozen, program-advanced clock at accelerated wall speed, with
the adaptive loop, SLO judgement, and rollout guardrails all running
in-sim unmodified. On top of the replay engine sits a policy lab that
scores candidate :class:`~sentinel_tpu.adaptive.controller.Policy`
implementations on the multi-objective vector (block-rate, RT-p99,
utilization) the DRL adaptive-rate-limiting literature motivates
(PAPERS.md), entirely offline.

Pieces:

* :mod:`~sentinel_tpu.simulator.clock` — the program-advanced ms clock
  injected through the engine's clock seam (``SentinelEngine(clock=)``).
* :mod:`~sentinel_tpu.simulator.trace` — the versioned, portable trace
  format: capture from a live engine (``export_trace`` / the
  ``flightrec`` ops command), tee live seconds into a file
  (``TraceWriter``), load/save/round-trip.
* :mod:`~sentinel_tpu.simulator.scenarios` — seedable synthetic trace
  generators: diurnal cycles, flash crowds, retry storms (the one
  closed-loop coupling real traces cannot carry), correlated
  multi-resource overload, SLINFER-style heterogeneous token-cost
  mixes.
* :mod:`~sentinel_tpu.simulator.replay` — ``ReplayEngine``: drives the
  engine through the trace second by second, batching each second's
  demand through the production step, and returns the exact verdict
  stream + per-second series + the adaptive decision log.
* :mod:`~sentinel_tpu.simulator.lab` — ``run_lab`` / ``tune_aimd``:
  N policies x M scenarios, scored objective vectors, a comparison
  report (the ``sim`` ops command / dashboard panel source), and
  grid-search AIMD tuning.
"""

from sentinel_tpu.simulator.clock import SimClock
from sentinel_tpu.simulator.lab import (
    LabPolicy,
    last_report,
    run_lab,
    tune_aimd,
)
from sentinel_tpu.simulator.replay import ReplayEngine, ReplayResult
from sentinel_tpu.simulator.scenarios import SCENARIOS, build_scenario
from sentinel_tpu.simulator.trace import (
    TRACE_KIND,
    TRACE_VERSION,
    Trace,
    TraceWriter,
    export_trace,
)

__all__ = [
    "SimClock", "Trace", "TraceWriter", "TRACE_KIND", "TRACE_VERSION",
    "export_trace", "SCENARIOS", "build_scenario", "ReplayEngine",
    "ReplayResult", "LabPolicy", "run_lab", "tune_aimd", "last_report",
]

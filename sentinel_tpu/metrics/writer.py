"""Rotating metric log writer (reference: ``core:node/metric/MetricWriter.java``).

File layout matches the reference: ``{dir}/{app}-metrics.log.{yyyy-MM-dd}.{n}``
plus a sibling ``.idx`` index mapping each written second to the byte offset
of its first line (the searcher seeks by it). Rolls to ``.{n+1}`` when a file
exceeds ``single_file_size``; keeps at most ``total_file_count`` data files
(oldest deleted), and starts a fresh ``.1`` on date change.

Index record format: big-endian ``(second_ts: int64, offset: int64)``.
"""

from __future__ import annotations

import datetime
import os
import struct
import threading
from typing import List, Optional

from sentinel_tpu.core.config import config
from sentinel_tpu.metrics.metric_node import MetricNode

IDX_RECORD = struct.Struct(">qq")


def metric_file_name(app: str, date: str, index: int) -> str:
    return f"{app}-metrics.log.{date}.{index}"


def parse_metric_file(name: str):
    """-> (app, date, index) or None if not a metric data file."""
    if name.endswith(".idx") or ".log." not in name:
        return None
    head, _, tail = name.rpartition(".log.")
    if not head.endswith("-metrics"):
        return None
    parts = tail.rsplit(".", 1)
    if len(parts) != 2:
        return None
    try:
        return head[: -len("-metrics")], parts[0], int(parts[1])
    except ValueError:
        return None


class MetricWriter:
    def __init__(self, app: Optional[str] = None, base_dir: Optional[str] = None,
                 single_file_size: Optional[int] = None,
                 total_file_count: Optional[int] = None):
        self.app = app or config.app_name()
        self.base_dir = base_dir or config.log_dir()
        self.single_file_size = single_file_size or config.single_metric_file_size()
        self.total_file_count = total_file_count or config.total_metric_file_count()
        self._lock = threading.Lock()
        self._data = None
        self._idx = None
        self._cur_date: Optional[str] = None
        self._cur_index = 0
        self._last_second = -1

    # -- file management ---------------------------------------------------

    def _list_data_files(self) -> List[str]:
        try:
            names = os.listdir(self.base_dir)
        except OSError:
            return []
        out = []
        for n in names:
            parsed = parse_metric_file(n)
            if parsed and parsed[0] == self.app:
                out.append(n)
        out.sort(key=lambda n: (parse_metric_file(n)[1], parse_metric_file(n)[2]))
        return out

    def _open(self, date: str, index: int, append: bool):
        os.makedirs(self.base_dir, exist_ok=True)
        path = os.path.join(self.base_dir, metric_file_name(self.app, date, index))
        mode = "ab" if append else "wb"
        self._close_files()
        self._data = open(path, mode)
        self._idx = open(path + ".idx", mode)
        self._cur_date = date
        self._cur_index = index

    def _close_files(self):
        for f in (self._data, self._idx):
            if f is not None:
                f.close()
        self._data = self._idx = None

    def _roll(self, date: str):
        if self._cur_date == date:
            self._open(date, self._cur_index + 1, append=False)
        else:
            self._open(date, 1, append=False)
        self._trim_old()

    def _trim_old(self):
        files = self._list_data_files()
        while len(files) > self.total_file_count:
            victim = files.pop(0)
            for suffix in ("", ".idx"):
                try:
                    os.remove(os.path.join(self.base_dir, victim + suffix))
                except OSError:
                    pass

    def _ensure_open(self, date: str):
        if self._data is None:
            # Resume the newest same-date file, else start .1.
            latest = None
            for n in self._list_data_files():
                _, d, i = parse_metric_file(n)
                if d == date and (latest is None or i > latest):
                    latest = i
            self._open(date, latest or 1, append=latest is not None)
            self._trim_old()
        elif self._cur_date != date or self._data.tell() > self.single_file_size:
            self._roll(date)

    # -- writing -----------------------------------------------------------

    def write(self, timestamp_ms: int, nodes: List[MetricNode]) -> None:
        """Append one sealed second of nodes (idempotent per second)."""
        if not nodes:
            return
        second_ms = timestamp_ms - timestamp_ms % 1000
        with self._lock:
            if second_ms <= self._last_second:
                return
            self._last_second = second_ms
            date = datetime.datetime.fromtimestamp(second_ms / 1000).strftime("%Y-%m-%d")
            self._ensure_open(date)
            self._idx.write(IDX_RECORD.pack(second_ms, self._data.tell()))
            for node in nodes:
                node.timestamp = second_ms
                self._data.write((node.to_thin_string() + "\n").encode("utf-8"))
            self._data.flush()
            self._idx.flush()

    def close(self) -> None:
        with self._lock:
            self._close_files()

// sentinel_shim: native client shim for the sentinel-tpu token server.
//
// Role (SURVEY.md §2.9, §7 M4): the reference is pure Java, so its cluster
// clients live in-process; our TPU backend serves tokens over the TLV TCP
// protocol, and THIS library is the bridge by which any host runtime — a
// JVM via JNI, C++ services, Python via ctypes — talks to it without a
// Python dependency. It implements:
//
//   * the length-framed binary TLV codec (cluster/codec.py is the Python
//     twin; frame = u16 len | body; request body = i32 xid | u8 type |
//     entity; response body = i32 xid | u8 type | i8 status | entity),
//   * a blocking token client with xid correlation over one TCP connection
//     (PING namespace registration on connect, FLOW / PARAM_FLOW acquires),
//   * a cached-tick millisecond clock (the reference TimeUtil's dedicated
//     tick thread — avoids a syscall per hot-path read).
//
// C ABI only: every symbol is extern "C" so ctypes/JNI/FFI can bind it.

#include <arpa/inet.h>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint8_t MSG_PING = 0;
constexpr uint8_t MSG_FLOW = 1;
constexpr uint8_t MSG_PARAM_FLOW = 2;

constexpr int ST_FAIL = -1;

// -- wire helpers (big-endian, matching cluster/codec.py) --------------------

void put_u16(std::vector<uint8_t>& b, uint16_t v) {
  b.push_back(v >> 8);
  b.push_back(v & 0xff);
}
void put_i32(std::vector<uint8_t>& b, int32_t v) {
  for (int s = 24; s >= 0; s -= 8) b.push_back((uint32_t(v) >> s) & 0xff);
}
void put_i64(std::vector<uint8_t>& b, int64_t v) {
  for (int s = 56; s >= 0; s -= 8) b.push_back((uint64_t(v) >> s) & 0xff);
}
void put_f64(std::vector<uint8_t>& b, double v) {
  // IEEE-754 bits, big-endian (struct ">d" in cluster/codec.py).
  uint64_t bits;
  static_assert(sizeof(bits) == sizeof(v), "double is 64-bit");
  std::memcpy(&bits, &v, sizeof(bits));
  put_i64(b, int64_t(bits));
}
int32_t get_i32(const uint8_t* p) {
  return (int32_t(p[0]) << 24) | (int32_t(p[1]) << 16) | (int32_t(p[2]) << 8) |
         int32_t(p[3]);
}

struct Client {
  int fd = -1;
  std::mutex io_mu;  // one in-flight request at a time (blocking client)
  int32_t next_xid = 1;

  bool send_all(const uint8_t* data, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t w = ::send(fd, data + off, n - off, MSG_NOSIGNAL);
      if (w <= 0) return false;
      off += size_t(w);
    }
    return true;
  }

  bool recv_all(uint8_t* data, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t r = ::recv(fd, data + off, n - off, 0);
      if (r <= 0) return false;
      off += size_t(r);
    }
    return true;
  }

  // -> status, fills entity. Returns false on transport failure.
  bool call(uint8_t type, const std::vector<uint8_t>& entity, int8_t* status,
            std::vector<uint8_t>* resp_entity) {
    std::lock_guard<std::mutex> lock(io_mu);
    int32_t xid = next_xid++;
    std::vector<uint8_t> body;
    put_i32(body, xid);
    body.push_back(type);
    body.insert(body.end(), entity.begin(), entity.end());
    std::vector<uint8_t> frame;
    put_u16(frame, uint16_t(body.size()));
    frame.insert(frame.end(), body.begin(), body.end());
    if (!send_all(frame.data(), frame.size())) return false;

    for (;;) {
      uint8_t lenbuf[2];
      if (!recv_all(lenbuf, 2)) return false;
      uint16_t len = (uint16_t(lenbuf[0]) << 8) | lenbuf[1];
      std::vector<uint8_t> resp(len);
      if (len > 0 && !recv_all(resp.data(), len)) return false;
      if (len < 6) continue;  // malformed: skip
      if (get_i32(resp.data()) != xid) continue;  // stale response: skip
      *status = int8_t(resp[5]);
      resp_entity->assign(resp.begin() + 6, resp.end());
      return true;
    }
  }
};

}  // namespace

extern "C" {

// -- token client ------------------------------------------------------------

// Connect + register the namespace via PING. NULL on failure.
void* st_client_connect(const char* host, int port, const char* ns,
                        int timeout_ms) {
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host, port_s.c_str(), &hints, &res) != 0) return nullptr;
  int fd = -1;
  for (auto* p = res; p; p = p->ai_next) {
    fd = ::socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv { timeout_ms / 1000, (timeout_ms % 1000) * 1000 };
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;

  auto* c = new Client();
  c->fd = fd;
  // PING entity: u8 len | namespace.
  std::vector<uint8_t> entity;
  std::string nss = ns ? ns : "default";
  if (nss.size() > 255) nss.resize(255);
  entity.push_back(uint8_t(nss.size()));
  entity.insert(entity.end(), nss.begin(), nss.end());
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_PING, entity, &status, &resp)) {
    ::close(c->fd);
    delete c;
    return nullptr;
  }
  return c;
}

// Acquire tokens. Returns the TokenResultStatus (OK=0, BLOCKED=1,
// SHOULD_WAIT=2, ...) or -1 on transport failure. out_extra receives
// remaining (OK) or wait-ms (SHOULD_WAIT) when non-null.
int st_request_token(void* handle, long long flow_id, int count,
                     int prioritized, int* out_extra) {
  if (!handle) return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> entity;
  put_i64(entity, flow_id);
  put_i32(entity, count);
  entity.push_back(prioritized ? 1 : 0);
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_FLOW, entity, &status, &resp)) return ST_FAIL;
  if (out_extra) {
    *out_extra = 0;
    if (resp.size() >= 8) {
      int32_t remaining = get_i32(resp.data());
      int32_t wait_ms = get_i32(resp.data() + 4);
      *out_extra = (status == 2) ? wait_ms : remaining;
    }
  }
  return status;
}

// One hot-parameter value (mirror of sentinel_shim.h's st_param).
struct st_param {
  unsigned char tag;  // 0=int, 1=str, 2=bool, 3=float
  long long i;
  double d;
  const char* s;
};

// Acquire param-flow tokens. Entity (cluster/codec.py
// encode_param_flow_request): flowId:i64 | count:i32 | nparams:u16 |
// per-param u8 tag + typed payload. Returns the TokenResultStatus or -1.
int st_request_param_token(void* handle, long long flow_id, int count,
                           const st_param* params, int nparams) {
  if (!handle || nparams < 0 || (nparams > 0 && !params)) return ST_FAIL;
  auto* c = static_cast<Client*>(handle);
  std::vector<uint8_t> entity;
  put_i64(entity, flow_id);
  put_i32(entity, count);
  entity.push_back(uint8_t(nparams >> 8));
  entity.push_back(uint8_t(nparams & 0xff));
  for (int k = 0; k < nparams; ++k) {
    const st_param& p = params[k];
    entity.push_back(p.tag);
    switch (p.tag) {
      case 0:  // int: i64
        put_i64(entity, p.i);
        break;
      case 1: {  // str: u16 len | utf-8
        size_t n = p.s ? std::strlen(p.s) : 0;
        // Oversized values can't fit the u16 frame anyway (the entity-size
        // check below would reject them) — fail fast rather than truncate,
        // which could split a multibyte UTF-8 char on the wire.
        if (n > 0xFFF0) return ST_FAIL;
        entity.push_back(uint8_t(n >> 8));
        entity.push_back(uint8_t(n & 0xff));
        if (n > 0) entity.insert(entity.end(), p.s, p.s + n);
        break;
      }
      case 2:  // bool: u8
        entity.push_back(p.i ? 1 : 0);
        break;
      case 3:  // float: f64 bits
        put_f64(entity, p.d);
        break;
      default:
        return ST_FAIL;
    }
  }
  if (entity.size() > 0xFFF0) return ST_FAIL;  // must fit one u16 frame
  int8_t status = ST_FAIL;
  std::vector<uint8_t> resp;
  if (!c->call(MSG_PARAM_FLOW, entity, &status, &resp)) return ST_FAIL;
  return status;
}

void st_client_close(void* handle) {
  if (!handle) return;
  auto* c = static_cast<Client*>(handle);
  ::close(c->fd);
  delete c;
}

// -- cached-tick clock (reference: core:util/TimeUtil.java) ------------------

namespace {
std::atomic<long long> g_now_ms{0};
std::atomic<bool> g_tick_running{false};
std::thread g_tick_thread;

long long wall_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}
}  // namespace

void st_time_start(void) {
  bool expected = false;
  if (!g_tick_running.compare_exchange_strong(expected, true)) return;
  g_now_ms.store(wall_ms());
  g_tick_thread = std::thread([] {
    while (g_tick_running.load(std::memory_order_relaxed)) {
      g_now_ms.store(wall_ms(), std::memory_order_relaxed);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  g_tick_thread.detach();
}

void st_time_stop(void) { g_tick_running.store(false); }

// Cached when the tick thread runs; falls back to a syscall otherwise.
long long st_now_ms(void) {
  long long v = g_now_ms.load(std::memory_order_relaxed);
  return (v != 0 && g_tick_running.load(std::memory_order_relaxed))
             ? v
             : wall_ms();
}

}  // extern "C"

"""Flight recorder (device per-second telemetry ring + host history):
differential exactness vs a host oracle, ring wrap / retention, the
`timeseries` + `explain` ops commands, exporter gauges, and the
within-process marginal-cost A/B.

The load-bearing property is DIFFERENTIAL: every complete second's
recorded deltas (event counts, block attribution, RT-histogram buckets)
must EXACTLY equal a host-side oracle accumulated from the per-step
decisions of the same randomized stream — including mixed acquire
counts (the fixpoint regime) and steps straddling second boundaries.
"""

import json
import urllib.request
from collections import defaultdict

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.telemetry import attribution as AT
from sentinel_tpu.telemetry.timeseries import TimeseriesHistory, compact_second

from tests.test_telemetry import _batch, _exit_batch

BASE_MS = 1_700_000_000_000


def _oracle_cell():
    return {
        "pass": 0, "block": 0, "success": 0, "exception": 0, "rtSumMs": 0,
        "blockByReason": defaultdict(int),
        "rtBuckets": np.zeros(AT.NUM_RT_BUCKETS, np.int64),
    }


def _run_randomized_stream(engine, seed, steps=40, exits=True):
    """Randomized mixed-count traffic; returns the per-second oracle
    accumulated from the step's OWN decisions (the differential
    reference) and the final stream time."""
    rng = np.random.default_rng(seed)
    thr = {"tsA": 9, "tsB": 4}
    st.load_flow_rules([st.FlowRule(resource=r, count=c)
                        for r, c in thr.items()])
    oracle = defaultdict(lambda: defaultdict(_oracle_cell))
    now = BASE_MS
    for _ in range(steps):
        lanes, counts = [], []
        for _ in range(int(rng.integers(6, 14))):
            res = "tsA" if rng.integers(0, 2) else "tsB"
            lanes.append((res, "", None))
            counts.append(int(rng.integers(1, 4)))  # mixed: fixpoint path
        dec = engine.check_batch(_batch(engine, lanes, counts=counts),
                                 now_ms=now)
        reasons = np.asarray(dec.reason)
        second = now - now % 1000
        passed = []
        for i, (res, _o, _p) in enumerate(lanes):
            cell = oracle[second][res]
            if reasons[i] > 0:
                cell["block"] += counts[i]
                cell["blockByReason"]["FLOW"] += counts[i]
            else:
                cell["pass"] += counts[i]
                passed.append((i, res))
        if exits and passed:
            # Complete the admitted lanes in the same step's second.
            rts = [int(rng.integers(1, 3000)) for _ in passed]
            errs = [bool(rng.integers(0, 4) == 0) for _ in passed]
            ex_lanes = [lanes[i] for i, _ in passed]
            ex_counts = [counts[i] for i, _ in passed]
            xb = _exit_batch(engine, ex_lanes, rts)
            import jax.numpy as jnp

            xb = xb._replace(count=jnp.asarray(ex_counts, jnp.int32),
                             error=jnp.asarray(errs))
            engine.complete_batch(xb, now_ms=now)
            for k, (_i, res) in enumerate(passed):
                cell = oracle[second][res]
                cell["success"] += ex_counts[k]
                cell["rtSumMs"] += rts[k]
                if errs[k]:
                    cell["exception"] += ex_counts[k]
                cell["rtBuckets"][int(np.sum(
                    rts[k] > np.asarray(AT.RT_BUCKET_EDGES_MS)))] += 1
        now += int(rng.integers(120, 450))
    return oracle, now


def _assert_second_matches(sec_dict, oracle_second):
    got_resources = sec_dict["resources"]
    want = {r: c for r, c in oracle_second.items()
            if c["pass"] or c["block"] or c["success"] or c["exception"]}
    assert set(got_resources) == set(want)
    for res, cell in want.items():
        got = got_resources[res]
        assert got["pass"] == cell["pass"], (res, got, cell)
        assert got["block"] == cell["block"]
        assert got["success"] == cell["success"]
        assert got["exception"] == cell["exception"]
        assert got["rtSumMs"] == cell["rtSumMs"]
        assert got["blockByReason"] == dict(cell["blockByReason"])
        assert got["rtBuckets"] == cell["rtBuckets"].tolist()


@pytest.mark.parametrize("seed", [
    7,
    # Second seed slow-tier'd (ISSUE 11 tier-1 wall-time trim): ~47s
    # for the same oracle regimes as seed 7; full sweep via -m slow.
    pytest.param(23, marks=pytest.mark.slow),
])
def test_flight_recorder_matches_host_oracle(engine, seed):
    """The recorded per-second series == the host oracle, for every
    complete second of a randomized mixed-count stream with exits —
    checked through the full spill path at MULTIPLE offsets."""
    oracle, end_now = _run_randomized_stream(engine, seed)
    final_now = end_now + 2500  # everything staged becomes complete
    view = engine.timeseries_view(now_ms=final_now)
    by_stamp = {s["timestamp"]: s for s in view["seconds"]}
    complete = [s for s in sorted(oracle) if s < final_now - final_now % 1000]
    assert complete, "stream never crossed a second boundary"
    for stamp in complete:
        assert stamp in by_stamp, f"second {stamp} missing from recorder"
        _assert_second_matches(by_stamp[stamp], oracle[stamp])
    # no phantom seconds either
    assert set(by_stamp) <= set(complete)

    # exact windows at offsets: limit/offset paginate newest-first but
    # stay chronological inside the page
    all_secs = view["seconds"]
    for limit, offset in ((3, 0), (2, 1), (1, len(all_secs) - 1)):
        page = engine.timeseries_view(limit=limit, offset=offset,
                                      now_ms=final_now)["seconds"]
        want = all_secs[:len(all_secs) - offset][-limit:]
        assert [p["timestamp"] for p in page] == [w["timestamp"] for w in want]
        for p, w in zip(page, want):
            assert p == w
    # range query at an arbitrary interior offset
    mid = complete[len(complete) // 2]
    ranged = engine.timeseries_view(start_ms=mid, end_ms=mid + 1000,
                                    now_ms=final_now)["seconds"]
    assert len(ranged) == 1 and ranged[0]["timestamp"] == mid


def test_flight_recorder_in_progress_second_stays_staged(engine):
    """Exactness = COMPLETE seconds only: the in-progress second is not
    served, and becomes servable (exactly once) after it completes."""
    st.load_flow_rules([st.FlowRule(resource="ip", count=1)])
    engine.check_batch(_batch(engine, [("ip", "", None)] * 3),
                       now_ms=BASE_MS)
    view = engine.timeseries_view(now_ms=BASE_MS + 500)
    assert view["seconds"] == []  # second not over yet
    view = engine.timeseries_view(now_ms=BASE_MS + 1000)
    assert [s["timestamp"] for s in view["seconds"]] == [BASE_MS]
    assert view["seconds"][0]["resources"]["ip"]["block"] == 2


def test_flight_recorder_slot_attribution_series(engine):
    """The per-(reason, rule-slot) split: slot-1 blocks of a two-rule
    resource land in the FLOW/slot-1 bin of that second (and in the
    cumulative blockBySlot counters)."""
    st.load_flow_rules([
        st.FlowRule(resource="sl", count=100000),  # slot 0: never blocks
        st.FlowRule(resource="sl", count=2),       # slot 1: blocks
    ])
    engine.check_batch(_batch(engine, [("sl", "", None)] * 5),
                       now_ms=BASE_MS)
    view = engine.timeseries_view(now_ms=BASE_MS + 1000)
    assert view["seconds"][0]["blockBySlot"] == {"FLOW": {"1": 3}}
    assert engine.telemetry_snapshot()["blockBySlot"] == {"FLOW": {"1": 3}}


def test_ring_wrap_spills_to_host_history(engine):
    """Seconds older than the device ring survive in the host history
    when reads keep pace (spill-before-overwrite), and the history
    itself is bounded."""
    from sentinel_tpu.core.config import (
        TELEMETRY_TIMESERIES_SECONDS, config as _cfg)

    prev = _cfg.get(TELEMETRY_TIMESERIES_SECONDS)
    _cfg.set(TELEMETRY_TIMESERIES_SECONDS, "4")  # tiny device ring
    try:
        eng = st.reset(capacity=256)
        assert eng.flight_seconds == 4
        st.load_flow_rules([st.FlowRule(resource="wrap", count=1)])
        now = BASE_MS
        for k in range(10):  # 10 seconds >> 4-slot ring
            eng.check_batch(_batch(eng, [("wrap", "", None)] * 2),
                            now_ms=now)
            now += 1000
            eng.timeseries_view(now_ms=now)  # reader keeps pace: spill
        view = eng.timeseries_view(now_ms=now + 1000)
        stamps = [s["timestamp"] for s in view["seconds"]]
        assert stamps == [BASE_MS + 1000 * k for k in range(10)]
        for s in view["seconds"]:
            assert s["resources"]["wrap"]["pass"] == 1
            assert s["resources"]["wrap"]["block"] == 1
    finally:
        if prev is None:
            _cfg.set(TELEMETRY_TIMESERIES_SECONDS, "")
        else:
            _cfg.set(TELEMETRY_TIMESERIES_SECONDS, prev)
        st.reset(capacity=512)


def test_timeseries_history_bounds_and_order():
    h = TimeseriesHistory(retention_seconds=3)
    E, A, H = C.NUM_EVENTS, AT.NUM_ATTR_REASONS, AT.NUM_RT_BUCKETS
    for k in range(5):
        ev = np.zeros((E, 8), np.int32)
        ev[C.MetricEvent.PASS, 3] = k + 1
        h.append(compact_second(BASE_MS + k * 1000, ev,
                                np.zeros((A, 8), np.int32),
                                np.zeros((H, 8), np.int32),
                                np.zeros((A, AT.NUM_SLOT_BINS), np.int32)))
    assert h.retained() == 3
    recs = h.query()
    assert [r.stamp_ms for r in recs] == [BASE_MS + k * 1000
                                          for k in (2, 3, 4)]
    # out-of-order / duplicate appends are dropped (first wins)
    h.append(compact_second(BASE_MS + 2000, np.ones((E, 8), np.int32),
                            np.zeros((A, 8), np.int32),
                            np.zeros((H, 8), np.int32),
                            np.zeros((A, AT.NUM_SLOT_BINS), np.int32)))
    assert h.retained() == 3 and h.last_stamp_ms == BASE_MS + 4000


def test_page_newest_first_edges():
    """The shared newest-first paginator: a limit beyond the available
    count is the WHOLE list (a negative slice start would wrap and
    silently drop the oldest entries — the `timeseries` command's
    default limit=60 against a young history hit exactly that)."""
    from sentinel_tpu.telemetry.timeseries import page_newest_first

    items = list(range(5))
    assert page_newest_first(items, limit=60) == items
    assert page_newest_first(items) == items
    assert page_newest_first(items, limit=2) == [3, 4]
    assert page_newest_first(items, limit=2, offset=1) == [2, 3]
    assert page_newest_first(items, limit=0) == []
    assert page_newest_first(items, offset=7) == []
    assert page_newest_first(items, limit=60, offset=2) == [0, 1, 2]


def _http(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read().decode())


def test_timeseries_command_pagination_and_cursor(engine):
    """`timeseries` ops command: resource filter, sinceMs cursor
    (strictly-after), and limit/offset pagination."""
    from sentinel_tpu.transport.command_center import CommandCenter

    st.load_flow_rules([st.FlowRule(resource="cmdts", count=2)])
    now = BASE_MS
    for _ in range(4):
        engine.check_batch(_batch(engine, [("cmdts", "", None)] * 4),
                           now_ms=now)
        now += 1000
    engine.timeseries_view(now_ms=now)  # spill the 4 complete seconds
    center = CommandCenter(engine, port=0).start()
    try:
        base = f"http://127.0.0.1:{center.bound_port}"
        out = _http(f"{base}/timeseries?resource=cmdts")
        assert len(out["seconds"]) == 4 and out["total"] == 4
        assert out["recorderSeconds"] == engine.flight_seconds
        assert all(s["resources"]["cmdts"]["pass"] == 2
                   and s["resources"]["cmdts"]["block"] == 2
                   for s in out["seconds"])
        # pagination: newest-first offset, chronological inside the page
        page = _http(f"{base}/timeseries?limit=2&offset=1")
        assert [s["timestamp"] for s in page["seconds"]] == \
            [BASE_MS + 1000, BASE_MS + 2000]
        # sinceMs cursor: strictly after
        tail = _http(f"{base}/timeseries?sinceMs={BASE_MS + 1000}")
        assert [s["timestamp"] for s in tail["seconds"]] == \
            [BASE_MS + 2000, BASE_MS + 3000]
        # unknown resource: empty, not an error
        assert _http(f"{base}/timeseries?resource=nope")["seconds"] == []
    finally:
        center.stop()


def test_traces_command_pagination(engine):
    """`traces` offset pagination composes with limit (newest first)."""
    from sentinel_tpu.transport.command_center import CommandCenter

    engine.traces.sample_every = 1
    st.load_flow_rules([st.FlowRule(resource="pg", count=0)])
    for k in range(6):
        engine.check_batch(_batch(engine, [("pg", f"u{k}", None)]),
                           now_ms=BASE_MS + k)
    engine.traces.drain()
    center = CommandCenter(engine, port=0).start()
    try:
        base = f"http://127.0.0.1:{center.bound_port}"
        all_t = _http(f"{base}/traces")["traces"]
        assert len(all_t) == 6
        page = _http(f"{base}/traces?limit=2&offset=2")["traces"]
        assert page == all_t[2:4]
        # offset beyond the ring: empty page, not an error
        assert _http(f"{base}/traces?limit=2&offset=50")["traces"] == []
    finally:
        center.stop()


def test_explain_joins_trace_with_flight_second(engine):
    """`explain` reconstructs WHY an entry was blocked from recorded
    data alone: the sampled trace, the flight-recorder second it fell
    in, and the blocking family's loaded rules."""
    from sentinel_tpu.transport.command_center import CommandCenter

    engine.traces.sample_every = 1
    st.load_flow_rules([st.FlowRule(resource="why", count=2),
                        st.FlowRule(resource="other", count=1000)])
    engine.check_batch(_batch(engine, [("why", "userX", None)] * 5),
                       now_ms=BASE_MS)
    out = engine.explain_trace(resource="why", now_ms=BASE_MS + 1500)
    assert out is not None
    assert out["trace"]["resource"] == "why"
    assert out["verdict"]["reason"] == "FLOW"
    assert out["verdict"]["ruleSlot"] == 0
    # only the blocking resource's rules of the blocking family
    assert [r["resource"] for r in out["verdict"]["matchedRules"]] == ["why"]
    assert out["verdict"]["matchedRules"][0]["count"] == 2
    # the recorder second carries the occupancy that explains the block
    assert out["second"]["timestamp"] == BASE_MS
    assert out["occupancy"]["passThatSecond"] == 2
    assert out["occupancy"]["blockThatSecond"] == 3
    # served over the ops plane too
    center = CommandCenter(engine, port=0).start()
    try:
        base = f"http://127.0.0.1:{center.bound_port}"
        served = _http(f"{base}/explain?resource=why")
        assert served["verdict"]["reason"] == "FLOW"
        # no trace for an unknown resource -> structured failure (400)
        try:
            urllib.request.urlopen(f"{base}/explain?resource=ghost",
                                   timeout=5)
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as ex:
            assert ex.code == 400
    finally:
        center.stop()


def test_exporter_serves_flight_recorder_gauges(engine):
    """/metrics grows per-second gauges + the (reason, slot) counter and
    still round-trips the reference OpenMetrics parser."""
    from prometheus_client.openmetrics import parser as om_parser

    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    st.load_flow_rules([st.FlowRule(resource="gauge", count=2)])
    engine.check_batch(_batch(engine, [("gauge", "", None)] * 5),
                       now_ms=BASE_MS)
    engine.check_batch(_batch(engine, [("gauge", "", None)] * 6),
                       now_ms=BASE_MS + 1000)
    # the exporter spills at WALL clock (far past both virtual stamps),
    # so the newest complete second is BASE_MS+1000: pass 2, block 4
    text = render_engine_metrics(engine)
    fams = {f.name: f for f in om_parser.text_string_to_metric_families(text)}
    sp = [s for s in fams["sentinel_tpu_second_pass"].samples
          if s.labels.get("resource") == "gauge"]
    sb = [s for s in fams["sentinel_tpu_second_block"].samples
          if s.labels.get("resource") == "gauge"]
    assert sp[0].value == 2 and sb[0].value == 4
    slot = [s for s in fams["sentinel_tpu_block_slot"].samples
            if s.labels == {"reason": "FLOW", "slot": "0"}]
    assert slot[0].value == 7
    assert fams["sentinel_tpu_timeseries_last_second"].samples[0].value \
        == BASE_MS + 1000
    assert "sentinel_tpu_spans_seen" in fams


def test_recording_disabled_is_clean(engine):
    """flight_seconds=0: no device ring, views empty, nothing breaks."""
    from sentinel_tpu.core.config import (
        TELEMETRY_TIMESERIES_SECONDS, config as _cfg)

    prev = _cfg.get(TELEMETRY_TIMESERIES_SECONDS)
    _cfg.set(TELEMETRY_TIMESERIES_SECONDS, "0")
    try:
        eng = st.reset(capacity=128)
        st.load_flow_rules([st.FlowRule(resource="off", count=1)])
        eng.check_batch(_batch(eng, [("off", "", None)] * 3), now_ms=BASE_MS)
        eng.check_batch(_batch(eng, [("off", "", None)]),
                        now_ms=BASE_MS + 1000)
        assert eng._state.flight is None
        view = eng.timeseries_view(now_ms=BASE_MS + 2000)
        assert view["seconds"] == [] and view["recorderSeconds"] == 0
        # cumulative telemetry is unaffected by the recorder being off
        assert eng.telemetry_snapshot()["resources"]["off"]["blockTotal"] == 2
    finally:
        if prev is None:
            _cfg.set(TELEMETRY_TIMESERIES_SECONDS, "")
        else:
            _cfg.set(TELEMETRY_TIMESERIES_SECONDS, prev)
        st.reset(capacity=512)


def test_pod_flight_recorder_folds_device_axis(engine):
    """Pod path: each device records only its own shard's lanes; the
    pod-global per-second series is the device-axis sum (stamps are
    clock-derived, identical across devices)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.models import authority as A
    from sentinel_tpu.models import degrade as Dg
    from sentinel_tpu.models import flow as F
    from sentinel_tpu.models import param_flow as PF
    from sentinel_tpu.models import system as Y
    from sentinel_tpu.ops import step as S
    from sentinel_tpu.parallel import cluster as PC

    ndev, capacity, per_dev = 8, 128, 4
    mesh = Mesh(np.asarray(jax.devices()[:ndev]), (PC.AXIS,))
    reg = NodeRegistry(capacity)
    row = reg.cluster_row("podts")
    ft, _ = F.compile_flow_rules([st.FlowRule(resource="podts", count=2)],
                                 reg, capacity)
    dt, di = Dg.compile_degrade_rules([], reg, capacity)
    pack = S.RulePack(flow=ft, degrade=dt,
                      authority=A.compile_authority_rules([], reg, capacity),
                      system=Y.compile_system_rules([]),
                      param=PF.compile_param_rules([], reg, capacity))
    one = S.make_state(capacity, ft.num_rules, BASE_MS,
                       degrade=Dg.make_degrade_state(dt, di),
                       param=PF.make_param_state(pack.param.num_rules),
                       flight_seconds=8)
    state = PC.make_pod_state(ndev, one)
    entry_fn, _ = PC.make_pod_steps(mesh, cluster_param=False)
    entry_jit = jax.jit(entry_fn, donate_argnums=(0,))

    buf = make_entry_batch_np(ndev * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    state, dec = entry_jit(state, pack, batch, jnp.int64(BASE_MS))
    blocked = int((np.asarray(dec.reason) > 0).sum())
    assert blocked == ndev * (per_dev - 2)  # local rule: 2 pass per device
    # cross the second boundary so the recorder folds
    state, _dec2 = entry_jit(state, pack, batch, jnp.int64(BASE_MS + 1000))

    fl = PC.global_flight_recorder(state)
    stamps = np.asarray(fl.stamps)
    slot = int((BASE_MS // 1000) % 8)
    assert stamps[slot] == BASE_MS
    events = np.asarray(fl.events)[slot]
    flow_ch = AT.ATTR_REASON_NAMES.index("FLOW")
    assert int(events[C.MetricEvent.PASS, row]) == 2 * ndev
    assert int(events[C.MetricEvent.BLOCK, row]) == blocked
    assert int(np.asarray(fl.attr)[slot, flow_ch, row]) == blocked
    assert int(np.asarray(fl.slot_attr)[slot, flow_ch, 0]) == blocked


def test_recording_marginal_cost_within_noise():
    """Within-process A/B: the per-step cost of the flight recorder
    (which only adds one dynamic-slice write per SECOND, nothing per
    step) is inside measurement noise. Direct ops-level harness (no
    engine lock / host plumbing), median-of-runs; the assert is a
    generous noise guard, the printed numbers are the evidence."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.models import authority as A
    from sentinel_tpu.models import degrade as D
    from sentinel_tpu.models import flow as F
    from sentinel_tpu.models import param_flow as P
    from sentinel_tpu.models import system as Y
    from sentinel_tpu.ops import step as S

    capacity, batch_n = 512, 512
    reg = NodeRegistry(capacity)
    rules = [F.FlowRule(resource=f"mc{i}", count=50) for i in range(16)]
    rows = np.asarray([reg.cluster_row(f"mc{i}") for i in range(16)])
    ft, _ = F.compile_flow_rules(rules, reg, capacity)
    dt, di = D.compile_degrade_rules([], reg, capacity)
    pack = S.RulePack(flow=ft, degrade=dt,
                      authority=A.compile_authority_rules([], reg, capacity),
                      system=Y.compile_system_rules([]),
                      param=P.compile_param_rules([], reg, capacity))
    rng = np.random.default_rng(3)
    buf = make_entry_batch_np(batch_n)
    buf["cluster_row"][:] = rows[rng.integers(0, 16, size=batch_n)]
    buf["count"][:] = 1
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    entry = jax.jit(S.entry_step, donate_argnums=(0,))

    def run(flight_seconds, reps=3, steps=24):
        best = float("inf")
        for _ in range(reps):
            state = S.make_state(capacity, ft.num_rules, BASE_MS,
                                 degrade=D.make_degrade_state(dt, di),
                                 param=P.make_param_state(
                                     pack.param.num_rules),
                                 flight_seconds=flight_seconds)
            now = BASE_MS
            state, _dec = entry(state, pack, batch, jnp.int64(now))
            jax.block_until_ready(state)  # compile outside the clock
            t0 = _time.perf_counter()
            for _k in range(steps):
                now += 250  # crosses a second boundary every 4th step
                state, _dec = entry(state, pack, batch, jnp.int64(now))
            jax.block_until_ready(state)
            best = min(best, (_time.perf_counter() - t0) / steps)
        return best

    off = run(0)
    on = run(128)
    # evidence for the PR notes; assert only guards against a gross
    # regression (recording must not multiply the step cost)
    print(f"\nmarginal recording cost: off={off * 1e3:.3f}ms/step "
          f"on={on * 1e3:.3f}ms/step ratio={on / off:.2f}")
    assert on <= off * 2.0 + 1e-3

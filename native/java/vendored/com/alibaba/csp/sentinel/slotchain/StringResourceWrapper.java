package com.alibaba.csp.sentinel.slotchain;

import com.alibaba.csp.sentinel.EntryType;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slotchain/StringResourceWrapper.java. */
public class StringResourceWrapper extends ResourceWrapper {

    public StringResourceWrapper(String name, EntryType e) {
        super(name, e, 0);
    }

    @Override
    public String getShowName() {
        return name;
    }
}

"""Token-lease fast path tests (core/lease.py — SURVEY §7 hard part #1).

Host-side admission must be device-exact for eligible resources, stream
its statistics to the device, and conservatively refuse every case where
another rule family (or another process) could see different state.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.lease import LocalLease, build_lease_table


def _leased(engine, resource):
    return resource in engine._leases


def test_simple_qps_rule_is_leased(engine):
    st.load_flow_rules([st.FlowRule(resource="fast", count=5)])
    assert _leased(engine, "fast")


def test_ineligible_shapes_stay_on_device_path(engine):
    st.load_flow_rules([
        st.FlowRule(resource="warm", count=5,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP),
        st.FlowRule(resource="thr", count=5, grade=C.FLOW_GRADE_THREAD),
        st.FlowRule(resource="orig", count=5, limit_app="appA"),
        st.FlowRule(resource="clus", count=5, cluster_mode=True,
                    cluster_config={"flowId": 1}),
        st.FlowRule(resource="rel", count=5,
                    strategy=C.FLOW_STRATEGY_RELATE, ref_resource="ref"),
        st.FlowRule(resource="ref", count=5),  # RELATE target
        st.FlowRule(resource="ok", count=5),
    ])
    for r in ("warm", "thr", "orig", "clus", "rel", "ref"):
        assert not _leased(engine, r), r
    assert _leased(engine, "ok")


def test_other_rule_families_disable_lease(engine):
    st.load_flow_rules([st.FlowRule(resource="d", count=5),
                        st.FlowRule(resource="p", count=5)])
    assert _leased(engine, "d") and _leased(engine, "p")
    st.load_degrade_rules([st.DegradeRule(resource="d", count=1,
                                          time_window=5)])
    assert not _leased(engine, "d")
    assert _leased(engine, "p")
    st.load_param_flow_rules([st.ParamFlowRule("p", param_idx=0, count=5)])
    assert not _leased(engine, "p")


def test_system_rules_disable_all_leases(engine):
    st.load_flow_rules([st.FlowRule(resource="s", count=5)])
    assert _leased(engine, "s")
    st.load_system_rules([st.SystemRule(qps=1e6)])
    assert not _leased(engine, "s")
    st.load_system_rules([])
    assert _leased(engine, "s")


def test_lease_admission_is_exact(engine, frozen_time):
    """Same verdicts as the device DEFAULT controller, serially exact."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=3)])
    got = [bool(st.entry_ok("fast")) for _ in range(6)]
    assert got == [True] * 3 + [False] * 3
    frozen_time.advance_time(1100)  # window rolls -> quota refreshed
    assert st.entry_ok("fast")


def test_lease_stats_reach_the_device(engine, frozen_time):
    """Leased admissions + exits land in device stats (flush-on-read)."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=3)])
    for _ in range(5):
        h = st.entry_ok("fast")
        if h:
            h.exit()
    snap = engine.node_snapshot()["fast"]
    assert snap["passQps"] == 3
    assert snap["blockQps"] == 2
    assert snap["successQps"] == 3
    assert snap["curThreadNum"] == 0


def test_lease_blocks_feed_metric_log(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="fast", count=1)])
    for _ in range(3):
        st.entry_ok("fast")
    frozen_time.advance_time(2000)
    lines = [str(n) for n in engine.seal_metrics()]
    assert any("fast" in ln for ln in lines)


def test_device_path_verdicts_keep_mirror_in_sync(engine, frozen_time):
    """Entries served while the PIPELINE owns admission must still count
    against the lease mirror once the pipeline stops."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=2)])
    engine.start_pipeline()
    assert st.entry_ok("fast") is not None  # device path (pipeline)
    engine.stop_pipeline()
    assert st.entry_ok("fast") is not None  # lease path
    assert st.entry_ok("fast") is None      # quota shared across modes


def test_mixed_rules_on_one_resource_disable_lease(engine):
    st.load_flow_rules([
        st.FlowRule(resource="mix", count=100),
        st.FlowRule(resource="mix", count=50,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER),
    ])
    assert not _leased(engine, "mix")


def test_multiple_default_rules_all_enforced(engine, frozen_time):
    st.load_flow_rules([
        st.FlowRule(resource="two", count=10),
        st.FlowRule(resource="two", count=4),
    ])
    assert _leased(engine, "two")
    got = sum(1 for _ in range(8) if st.entry_ok("two"))
    assert got == 4  # tightest rule wins


def test_local_lease_window_mirror_math():
    lease = LocalLease([3.0], interval_ms=1000, buckets=2)
    t0 = 1_700_000_000_000
    assert all(lease.try_acquire(1, t0) for _ in range(3))
    assert not lease.try_acquire(1, t0)
    # sliding, not tumbling: 500ms later the first bucket still counts
    assert not lease.try_acquire(1, t0 + 500)
    # 1s later the old bucket expired
    assert lease.try_acquire(1, t0 + 1000)


def _python_ring(thresholds, interval_ms, buckets) -> LocalLease:
    lease = LocalLease.__new__(LocalLease)
    lease.thresholds = thresholds
    lease.interval_ms = interval_ms
    lease.buckets = buckets
    lease.bucket_ms = interval_ms // buckets
    lease._counts = [0] * buckets
    lease._starts = [-1] * buckets
    import threading

    lease._lock = threading.Lock()
    lease._ring = None  # force the pure-Python path
    return lease


def test_native_ring_matches_python_ring_differentially():
    """The C extension ring (native/lease_ext.c) and the Python fallback
    must make IDENTICAL decisions on identical traffic — randomized
    acquire/add/rotation sequences, compared call by call."""
    import random

    from sentinel_tpu.native import load_lease_ext

    if load_lease_ext() is None:
        pytest.skip("native lease extension unavailable")
    rng = random.Random(7)
    for trial in range(20):
        buckets = rng.choice([1, 2, 4, 5])
        interval = buckets * rng.choice([100, 250, 500])
        thresholds = [float(rng.randint(1, 30))
                      for _ in range(rng.randint(1, 3))]
        native = LocalLease(thresholds, interval, buckets)
        if native._ring is None:
            pytest.skip("native lease extension unavailable")
        oracle = _python_ring(thresholds, interval, buckets)
        now = 1_700_000_000_000
        for step in range(300):
            now += rng.choice([0, 1, 7, interval // buckets,
                               interval, 3 * interval])
            op = rng.random()
            count = rng.randint(1, 3)
            if op < 0.75:
                got = native.try_acquire(count, now)
                want = oracle.try_acquire(count, now)
                assert got == want, (trial, step, thresholds, interval)
            elif op < 0.9:
                native.add(count, now)
                oracle.add(count, now)
            else:
                assert native.usage(now) == pytest.approx(
                    oracle.usage(now)), (trial, step)
        assert native.snapshot() == (oracle._starts, oracle._counts)


def test_native_ring_seed_and_snapshot_round_trip():
    from sentinel_tpu.native import load_lease_ext

    if load_lease_ext() is None:
        pytest.skip("native lease extension unavailable")
    lease = LocalLease([100.0], 1000, 2)
    lease.seed([1_700_000_000_000, 1_699_999_999_500], [5, 7])
    assert lease.snapshot() == ([1_700_000_000_000, 1_699_999_999_500],
                                [5, 7])
    # geometry-mismatched seeds drop, like the Python ring
    lease.seed([0], [1])
    assert lease.snapshot() == ([1_700_000_000_000, 1_699_999_999_500],
                                [5, 7])


def test_auto_context_pooled_per_thread(engine, frozen_time):
    """entry_ok() with no explicit context reuses ONE pooled auto
    context per thread (r5 fast-path optimization) — but an explicit
    context is never pooled, and an engine reset invalidates the pool
    via the generation stamp."""
    from sentinel_tpu.core import context as ctx_mod

    st.load_flow_rules([st.FlowRule(resource="pool", count=1e9)])
    h1 = st.entry_ok("pool")
    ctx1 = h1.context
    h1.exit()
    assert ctx_mod.get_context() is None  # auto context detached on exit
    h2 = st.entry_ok("pool")
    ctx2 = h2.context
    h2.exit()
    assert ctx1 is ctx2  # pooled: same object reused
    assert ctx1.entrance_row >= 0  # entrance resolution cached with it

    # explicit contexts bypass the pool
    st.context_enter("my_ctx")
    h3 = st.entry_ok("pool")
    assert h3.context is not ctx1 and h3.context.name == "my_ctx"
    h3.exit()
    st.exit_context()

    # engine reset -> generation bump -> pooled context discarded
    st.reset(capacity=512)
    st.load_flow_rules([st.FlowRule(resource="pool", count=1e9)])
    h4 = st.entry_ok("pool")
    assert h4.context is not ctx1
    h4.exit()


def test_lease_disabled_by_config(engine, monkeypatch):
    from sentinel_tpu.core.config import config

    monkeypatch.setenv("CSP_SENTINEL_LEASE_ENABLED", "false")
    config.reset_for_tests()
    try:
        eng = st.reset(capacity=256)
        st.load_flow_rules([st.FlowRule(resource="fast", count=5)])
        assert not eng._leases
    finally:
        monkeypatch.delenv("CSP_SENTINEL_LEASE_ENABLED")
        config.reset_for_tests()
        st.reset(capacity=256)


def test_lease_latency_is_sub_millisecond(engine, frozen_time):
    """The point of the feature: admission without a device dispatch."""
    import time as _time

    st.load_flow_rules([st.FlowRule(resource="fast", count=10_000_000)])
    h = st.entry_ok("fast")  # absorb any lazy init
    if h:
        h.exit()
    t0 = _time.perf_counter()
    n = 200
    for _ in range(n):
        h = st.entry_ok("fast")
        if h:
            h.exit()
    per_entry_us = (_time.perf_counter() - t0) / n * 1e6
    assert per_entry_us < 1000, f"leased entry took {per_entry_us:.0f}µs"


def test_rule_push_does_not_regrant_spent_quota(engine, frozen_time):
    """Rebuilding leases on a rule push must carry the mirror over —
    a zeroed mirror would admit 2x the quota in the current window."""
    st.load_flow_rules([st.FlowRule(resource="fast", count=3)])
    assert sum(1 for _ in range(3) if st.entry_ok("fast")) == 3
    # unrelated rule push for ANOTHER family rebuilds the lease table
    st.load_degrade_rules([st.DegradeRule(resource="other", count=1,
                                          time_window=5)])
    assert _leased(engine, "fast")
    assert st.entry_ok("fast") is None  # quota still spent


def test_newly_eligible_resource_seeds_from_device_window(engine,
                                                          frozen_time):
    """A resource that WAS ineligible (device path) and becomes eligible
    must inherit the device window, not a zero mirror."""
    st.load_flow_rules([
        st.FlowRule(resource="born", count=3),
        st.FlowRule(resource="born", count=3,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                    max_queueing_time_ms=0),
    ])
    assert not _leased(engine, "born")
    assert st.entry_ok("born") is not None  # device path, 1 pass committed
    # drop the pacing rule: resource becomes lease-eligible
    st.load_flow_rules([st.FlowRule(resource="born", count=3)])
    assert _leased(engine, "born")
    got = sum(1 for _ in range(4) if st.entry_ok("born"))
    assert got == 2  # 1 device-path pass + 2 leased = 3 total, 4th blocks


def test_leases_ops_command(engine, frozen_time):
    """The `leases` command exposes fast-path membership + live usage."""
    import json
    import urllib.request

    from sentinel_tpu.transport.command_center import CommandCenter

    st.load_flow_rules([st.FlowRule(resource="fast", count=10)])
    for _ in range(4):
        h = st.entry_ok("fast")
        if h:
            h.exit()
    center = CommandCenter(engine, port=0).start()
    try:
        url = f"http://127.0.0.1:{center.bound_port}/leases"
        with urllib.request.urlopen(url, timeout=5) as r:
            out = json.loads(r.read().decode())
        assert out["enabled"] is True
        row = out["resources"]["fast"]
        assert row["thresholds"] == [10.0]
        assert row["usageQps"] == 4.0
    finally:
        center.stop()


def test_unruled_resource_skips_device_dispatch(engine, frozen_time):
    """A resource with NO rules always passes host-side; stats converge."""
    import time as _time

    h = st.entry_ok("free")  # absorb committer start
    if h:
        h.exit()
    t0 = _time.perf_counter()
    for _ in range(100):
        h = st.entry_ok("free")
        if h:
            h.exit()
    per_entry_us = (_time.perf_counter() - t0) / 100 * 1e6
    assert per_entry_us < 1000, f"unruled entry took {per_entry_us:.0f}µs"
    snap = engine.node_snapshot()["free"]
    assert snap["passQps"] == 101
    assert snap["curThreadNum"] == 0


def test_unruled_relate_ref_stays_on_device_path(engine, frozen_time):
    """An unruled resource another rule RELATEs to must keep committing
    synchronously — its window feeds that rule's device check."""
    st.load_flow_rules([
        st.FlowRule(resource="write_db", count=3,
                    strategy=C.FLOW_STRATEGY_RELATE, ref_resource="read_db")
    ])
    assert "read_db" in engine._guarded_resources
    for _ in range(4):  # read_db busy: must be visible IMMEDIATELY
        with st.entry("read_db"):
            pass
    with pytest.raises(st.FlowException):
        st.entry("write_db")


def test_system_rules_disable_unruled_fastpath(engine):
    assert engine._unruled_fastpath
    st.load_system_rules([st.SystemRule(qps=10)])
    assert not engine._unruled_fastpath
    st.load_system_rules([])
    assert engine._unruled_fastpath


def test_rule_on_previously_unruled_resource_counts_queued_traffic(
        engine, frozen_time):
    """Un-flushed always-pass commits must count when a rule first lands
    on the resource — otherwise the brand-new limit over-admits."""
    for _ in range(5):  # unruled fast path: commits queue in the committer
        h = st.entry_ok("newly")
        if h:
            h.exit()
    # push a rule WITHOUT flushing: seeding must add the queued 5
    st.load_flow_rules([st.FlowRule(resource="newly", count=6)])
    assert "newly" in engine._leases
    got = sum(1 for _ in range(4) if st.entry_ok("newly"))
    assert got == 1  # 5 queued + 1 = 6; the 7th would exceed the limit


def test_leases_command_reports_effective_state(engine):
    from sentinel_tpu.transport.command_center import (
        CommandCenter, CommandRequest,
    )
    from sentinel_tpu.transport.handlers import cmd_leases
    import json

    out = json.loads(cmd_leases(CommandRequest(engine=engine)).result)
    assert out["enabled"] and out["effective"] and out["unruledFastpath"]
    st.load_system_rules([st.SystemRule(qps=10)])
    out = json.loads(cmd_leases(CommandRequest(engine=engine)).result)
    assert out["enabled"] is True  # configured on...
    assert out["effective"] is False  # ...but system rules disable it
    assert out["unruledFastpath"] is False


def test_retune_with_compiled_leased_engine(engine, frozen_time):
    """Round-3 advisor high: retuning a COMPILED engine with an active
    lease seeded old-geometry buckets into new-geometry mirrors, so the
    next entry raised IndexError and admission died on the resource.
    Grow and shrink must both leave a clean, full-quota window."""
    st.load_flow_rules([st.FlowRule(resource="ret", count=5)])
    for _ in range(3):
        assert st.entry_ok("ret")
    engine._flush_committer()          # device state now exists (compiled)

    engine.set_window_geometry(interval_ms=2000, sample_count=4)
    # Window reset: the 2s window smooths the burst (used rises 0.5/entry),
    # so i*0.5 + 1 <= 5 admits i=0..8 — and, crucially, no IndexError.
    got = [bool(st.entry_ok("ret")) for _ in range(12)]
    assert got == [True] * 9 + [False] * 3

    engine.set_window_geometry(interval_ms=1000, sample_count=2)
    # Shrink: no stale tail buckets survive; full fresh quota again.
    got = [bool(st.entry_ok("ret")) for _ in range(7)]
    assert got == [True] * 5 + [False] * 2


def test_retune_drops_pre_retune_queued_usage_from_mirror(engine,
                                                          frozen_time):
    """Usage queued in the committer before a retune belongs to the OLD
    window; the reset window (and its fresh mirror) must not inherit it."""
    st.load_flow_rules([st.FlowRule(resource="retq", count=4)])
    for _ in range(3):
        assert st.entry_ok("retq")     # queued, not yet flushed
    engine.set_window_geometry(interval_ms=2000, sample_count=4)
    from sentinel_tpu.utils import time_util

    assert engine._leases["retq"].usage(
        time_util.current_time_millis()) == pytest.approx(0.0)


def test_warmup_precompiles_ladder_widths(engine, frozen_time):
    """engine.warmup() pays every (width, rule-shape) compile up front and
    commits nothing; a rule push right after is not blocked behind XLA
    (the datasource-demo stall: the committer's first wide flush compiled
    under the engine lock while a push waited)."""
    import time as _time

    st.load_flow_rules([st.FlowRule(resource="wu", count=5)])
    engine.warmup((1, 8, 64))
    # no-op batches committed nothing (the row exists from rule compile)
    snap = engine.node_snapshot().get("wu", {})
    assert snap.get("passQps", 0) == 0 and snap.get("blockQps", 0) == 0

    for _ in range(30):                       # a wide burst queues commits
        st.entry_ok("wu")
    t0 = _time.perf_counter()
    st.load_flow_rules([st.FlowRule(resource="wu", count=20)])
    push_s = _time.perf_counter() - t0
    assert engine._leases["wu"].thresholds == [20.0]
    assert push_s < 2.0, f"rule push stalled {push_s:.1f}s behind a compile"


def test_rule_push_does_not_wait_on_device_dispatch(engine, frozen_time):
    """Config-plane/device-plane lock split: a rule push must retune the
    lease table even while the engine lock is held for a long device
    dispatch (first-dispatch XLA compiles hold it for seconds on CPU,
    20-40s on TPU; before the split, pushes stalled behind them and the
    old thresholds kept being enforced)."""
    import threading
    import time as _time

    st.load_flow_rules([st.FlowRule(resource="r", count=3)])
    assert engine._leases["r"].thresholds == [3.0]

    hold = threading.Event()
    release = threading.Event()

    def dispatcher():
        with engine._lock:  # stands in for a compile-length dispatch
            hold.set()
            release.wait(timeout=10.0)

    t = threading.Thread(target=dispatcher, daemon=True)
    t.start()
    assert hold.wait(timeout=5.0)
    try:
        done = threading.Event()

        def pusher():
            st.load_flow_rules([st.FlowRule(resource="r", count=1000)])
            done.set()

        threading.Thread(target=pusher, daemon=True).start()
        # The push completes while the device lock is STILL held...
        assert done.wait(timeout=2.0), \
            "rule push blocked behind the device dispatch lock"
        # ...and the lease table already serves the new threshold.
        assert engine._leases["r"].thresholds == [1000.0]
    finally:
        release.set()
        t.join(timeout=5.0)

/* sentinel_shim.h — C ABI of the sentinel-tpu native client shim.
 *
 * The language-neutral client path to the sentinel-tpu token server
 * (SURVEY.md §7 M4): JNI, JNA, ctypes, and plain C/C++ all bind these
 * symbols from libsentinel_shim.so. Wire protocol: the length-framed TLV
 * of cluster/codec.py (the reference's cluster-common Netty protocol
 * re-specified; message types PING=0, FLOW=1, PARAM_FLOW=2).
 *
 * Thread-safety: handles are multi-in-flight — N threads may issue
 * requests on ONE handle concurrently; responses are demuxed by xid
 * (the reference Netty client's xid -> promise map, shared-receiver
 * style, no background thread). Only st_client_close must not race new
 * requests on the same handle.
 */

#ifndef SENTINEL_SHIM_H_
#define SENTINEL_SHIM_H_

#ifdef __cplusplus
extern "C" {
#endif

/* TokenResultStatus values returned by the request calls (wire-visible,
 * reference core:cluster/TokenResultStatus.java):
 *   OK=0, BLOCKED=1, SHOULD_WAIT=2, NO_RULE_EXISTS=3, NO_REF_RULE_EXISTS=4,
 *   NOT_AVAILABLE=5, FAIL=-1, TOO_MANY_REQUEST=-2, BAD_REQUEST=-4.
 * -1 additionally signals local/transport failure. */

/* Connect to a token server and register `ns` via PING.
 * Returns an opaque handle, or NULL on failure. */
void* st_client_connect(const char* host, int port, const char* ns,
                        int timeout_ms);

/* Acquire `count` flow tokens for `flow_id`. Returns the status; when
 * out_extra is non-NULL it receives remaining (OK) or wait-ms
 * (SHOULD_WAIT). */
int st_request_token(void* handle, long long flow_id, int count,
                     int prioritized, int* out_extra);

/* One hot-parameter value for st_request_param_token. `tag` selects the
 * wire encoding AND which field carries the value (the server hashes
 * params typed, so an int param must be sent as an int to share buckets
 * with other clients' ints): */
typedef struct st_param {
  unsigned char tag; /* 0=int (i), 1=utf-8 string (s), 2=bool (i), 3=float (d) */
  long long i;
  double d;
  const char* s;     /* NUL-terminated; used when tag==1 */
} st_param;

/* Acquire `count` param-flow tokens for (`flow_id`, params). Returns the
 * status (PARAM_FLOW responses carry no entity). */
int st_request_param_token(void* handle, long long flow_id, int count,
                           const st_param* params, int nparams);

/* Pipelined batch acquire: all `n` FLOW requests hit the wire before any
 * response is awaited (one RTT per batch; the server micro-batches them
 * into one device step). out_statuses[i] = TokenResultStatus or -1;
 * out_extras[i] (optional) = remaining / wait-ms. Returns 0 when every
 * response arrived, -1 on transport failure. */
int st_request_tokens_batch(void* handle, const long long* flow_ids,
                            const int* counts, const int* prioritized, int n,
                            int* out_statuses, int* out_extras);

/* Remote slot-chain entry (M4 bridge, MSG_ENTRY): run the backend's full
 * rule chain + stats commit. Returns OK(0) with *out_entry_id set,
 * BLOCKED(1) with *out_reason = BlockReason code (1=flow 2=degrade
 * 3=system 4=authority 5=param 7=custom), or -1 (fail open). */
int st_remote_entry(void* handle, const char* resource, const char* origin,
                    int count, int entry_type, int prioritized,
                    const st_param* params, int nparams,
                    long long* out_entry_id, int* out_reason);

/* Remote exit (MSG_EXIT): commit RT/success, release the entry. `error`
 * non-zero records a business exception; `count` < 0 keeps the entry's
 * count. Returns OK, BAD_REQUEST (unknown id), or -1. */
int st_remote_exit(void* handle, long long entry_id, int error, int count);

void st_client_close(void* handle);

/* Cached-tick millisecond clock (reference core:util/TimeUtil.java): a
 * 1ms tick thread caches the wall clock so hot paths avoid syscalls. */
void st_time_start(void);
void st_time_stop(void);
long long st_now_ms(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* SENTINEL_SHIM_H_ */

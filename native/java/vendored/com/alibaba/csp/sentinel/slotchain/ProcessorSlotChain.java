package com.alibaba.csp.sentinel.slotchain;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slotchain/ProcessorSlotChain.java. */
public abstract class ProcessorSlotChain extends AbstractLinkedProcessorSlot<Object> {

    public abstract void addFirst(AbstractLinkedProcessorSlot<?> protocolProcessor);

    public abstract void addLast(AbstractLinkedProcessorSlot<?> protocolProcessor);
}

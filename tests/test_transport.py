"""Transport tests: command center HTTP surface + heartbeat.

Reference analog (SURVEY.md §4 "Transport tests"): start on an ephemeral
port, drive with a bare HTTP client, assert handler semantics.
"""

import json
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

import sentinel_tpu as st
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender


@pytest.fixture()
def center(engine):
    c = CommandCenter(engine, port=0)  # ephemeral port
    c.start()
    yield c
    c.stop()


def _get(center, path):
    url = f"http://127.0.0.1:{center.bound_port}/{path}"
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def _post(center, path, body: str):
    url = f"http://127.0.0.1:{center.bound_port}/{path}"
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.status, r.read().decode()


def test_version_and_basic_info(center):
    status, body = _get(center, "version")
    assert status == 200 and body.startswith("sentinel-tpu/")
    status, body = _get(center, "basicInfo")
    assert json.loads(body)["pid"] > 0


def test_unknown_command_is_400(center):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(center, "noSuchCommand")
    assert e.value.code == 400


def test_get_set_rules_round_trip(center, engine):
    rules = [{"resource": "api", "count": 7.0, "grade": 1}]
    status, body = _post(
        center, "setRules?type=flow", f"data={urllib.parse.quote(json.dumps(rules))}")
    assert status == 200 and body == "success"
    # The engine now enforces the pushed rule.
    passed = sum(1 for _ in range(10) if st.entry_ok("api"))
    assert passed == 7
    status, body = _get(center, "getRules?type=flow")
    got = json.loads(body)
    assert got[0]["resource"] == "api" and got[0]["count"] == 7.0


def test_set_rules_every_family(center, engine):
    payloads = {
        "degrade": [{"resource": "d", "grade": 2, "count": 1, "timeWindow": 5}],
        "system": [{"qps": 1000}],
        "authority": [{"resource": "a", "limitApp": "x", "strategy": 0}],
        "paramFlow": [{"resource": "p", "paramIdx": 0, "count": 3}],
    }
    for rule_type, rules in payloads.items():
        status, body = _post(center, f"setRules?type={rule_type}",
                             f"data={urllib.parse.quote(json.dumps(rules))}")
        assert (status, body) == (200, "success"), rule_type
        status, body = _get(center, f"getRules?type={rule_type}")
        assert len(json.loads(body)) == 1, rule_type


def test_cnode_and_cluster_node(center, engine):
    with st.entry("res1"):
        pass
    # absorb the committer's width compile outside the HTTP timeout
    # (unruled entries stream stats asynchronously)
    engine._flush_committer()
    status, body = _get(center, "cnode?id=res1")
    node = json.loads(body)
    assert node["resource"] == "res1" and node["passQps"] == 1
    status, body = _get(center, "clusterNode")
    assert any(n["resource"] == "res1" for n in json.loads(body))


def test_tree_commands(center, engine):
    st.context_enter("ctxA")
    with st.entry("deep"):
        pass
    st.exit_context()
    engine._flush_committer()  # absorb the width compile (async stats)
    status, body = _get(center, "jsonTree")
    tree = json.loads(body)
    assert tree["resource"] == "machine-root"
    flat = json.dumps(tree)
    assert "ctxA" in flat and "deep" in flat
    status, body = _get(center, "tree")
    assert "deep(" in body


def test_switch_round_trip(center, engine):
    st.load_flow_rules([st.FlowRule(resource="sw", count=0)])
    assert st.entry_ok("sw") is None
    status, body = _get(center, "setSwitch?value=false")
    assert body == "success"
    # Switch off: everything passes unguarded.
    assert st.entry_ok("sw") is not None
    _get(center, "setSwitch?value=true")
    assert st.entry_ok("sw") is None
    status, body = _get(center, "getSwitch")
    assert "true" in body


def test_api_lists_commands(center):
    status, body = _get(center, "api")
    urls = {e["url"] for e in json.loads(body)}
    assert {"/version", "/getRules", "/setRules", "/metric", "/jsonTree",
            "/cnode", "/clusterNode"} <= urls


def test_metric_command_reads_log(center, engine, frozen_time, tmp_path, monkeypatch):
    from sentinel_tpu.metrics.timer import MetricTimerListener
    from sentinel_tpu.metrics.writer import MetricWriter
    from sentinel_tpu.core.config import config

    monkeypatch.setenv("CSP_SENTINEL_LOG_DIR", str(tmp_path))
    monkeypatch.setenv("PROJECT_NAME", "transportApp")
    with st.entry("m1"):
        pass
    frozen_time.advance_time(2000)
    timer = MetricTimerListener(
        engine, MetricWriter(app="transportApp", base_dir=str(tmp_path)))
    assert timer.tick(frozen_time.current_time_millis()) >= 1
    timer.writer.close()
    status, body = _get(center, "metric?startTime=0&identity=m1")
    assert status == 200
    assert "|m1|" in body


# -- heartbeat --------------------------------------------------------------

class _DashboardStub(BaseHTTPRequestHandler):
    received = []

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length).decode()
        _DashboardStub.received.append((self.path, urllib.parse.parse_qs(body)))
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"ok")


def test_heartbeat_posts_registry_machine():
    _DashboardStub.received.clear()
    server = HTTPServer(("127.0.0.1", 0), _DashboardStub)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        hb = HeartbeatSender(
            dashboards=[f"127.0.0.1:{server.server_address[1]}"], api_port=8719)
        assert hb.send_once()
        path, params = _DashboardStub.received[0]
        assert path == "/registry/machine"
        assert params["port"] == ["8719"]
        assert "app" in params and "ip" in params
    finally:
        server.shutdown()
        server.server_close()


def test_heartbeat_rotates_on_failure():
    hb = HeartbeatSender(dashboards=["127.0.0.1:1", "127.0.0.1:2"], api_port=1)
    assert not hb.send_once()
    assert hb._idx == 1  # rotated to the second dashboard


# --- cluster-mode ops commands (reference: setClusterMode/getClusterMode +
# cluster config handlers, SURVEY.md §2.3) ----------------------------------


def test_cluster_mode_flip_via_http(center, engine):
    """Stage server config, flip to SERVER, load cluster rules, read
    metrics; then flip a client engine at it and acquire a real token."""
    status, body = _get(center, "getClusterMode")
    assert json.loads(body)["mode"] == -1  # NOT_STARTED

    # stage + flip to server (ephemeral port)
    status, body = _post(center, "cluster/server/modifyTransportConfig?port=0", "")
    assert body == "success"
    status, body = _post(center, "setClusterMode?mode=1", "")
    assert status == 200 and body == "success"
    mode = json.loads(_get(center, "getClusterMode")[1])
    assert mode["mode"] == 1 and mode["serverRunning"]

    cfg = json.loads(_get(center, "cluster/server/fetchConfig")[1])
    port = cfg["boundPort"]
    assert port > 0

    # push cluster rules into the running server via the ops plane
    rules = [{"resource": "cr", "count": 2.0, "clusterMode": True,
              "clusterConfig": {"flowId": 77, "thresholdType": 1}}]
    status, body = _post(
        center, "cluster/server/modifyFlowRules?namespace=default",
        f"data={urllib.parse.quote(json.dumps(rules))}")
    assert body == "success"

    # flip THIS engine to client mode pointing at its own embedded server
    # (reference: embedded mode does exactly this loop-back)
    status, body = _post(
        center, "cluster/client/modifyConfig",
        json.dumps({"serverHost": "127.0.0.1", "serverPort": port}))
    assert body == "success"
    # modifyConfig staged it; the engine is in SERVER mode, so flipping to
    # client tears down the server — instead talk to the server directly.
    from sentinel_tpu.cluster.client import ClusterTokenClient
    from sentinel_tpu.cluster.constants import TokenResultStatus

    # generous timeout: the embedded server's FIRST acquire pays the
    # token-service XLA compile, which can exceed 2s on a contended box
    client = ClusterTokenClient("127.0.0.1", port, "default",
                                request_timeout_s=60.0).start()
    try:
        r1 = client.request_token(77, 1)
        r2 = client.request_token(77, 1)
        r3 = client.request_token(77, 1)
        assert r1.status == TokenResultStatus.OK
        assert r2.status == TokenResultStatus.OK
        assert r3.status == TokenResultStatus.BLOCKED
    finally:
        client.stop()

    metrics = json.loads(_get(center, "cluster/server/metrics")[1])
    row = {m["flowId"]: m for m in metrics}[77]
    assert row["pass"] == 2.0 and row["blockRequest"] == 1.0

    # flip back down
    status, body = _post(center, "setClusterMode?mode=-1", "")
    assert body == "success"
    assert json.loads(_get(center, "getClusterMode")[1])["mode"] == -1


def test_cluster_client_mode_via_http_against_external_server(center, engine):
    """setClusterMode=0 connects the engine's token client to the staged
    server address (fetchConfig shows it; getClusterMode clientAvailable)."""
    from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
    from sentinel_tpu.cluster.server import ClusterTokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService

    ext = ClusterTokenServer(DefaultTokenService(ClusterFlowRuleManager()),
                             host="127.0.0.1", port=0).start()
    try:
        _post(center, "cluster/client/modifyConfig",
              json.dumps({"serverHost": "127.0.0.1",
                          "serverPort": ext.bound_port}))
        status, body = _post(center, "setClusterMode?mode=0", "")
        assert body == "success"
        cfg = json.loads(_get(center, "cluster/client/fetchConfig")[1])
        assert cfg["serverPort"] == ext.bound_port
        import time
        for _ in range(50):  # PING handshake is async
            if json.loads(_get(center, "getClusterMode")[1])["clientAvailable"]:
                break
            time.sleep(0.05)
        assert json.loads(_get(center, "getClusterMode")[1])["clientAvailable"]
        _post(center, "setClusterMode?mode=-1", "")
    finally:
        ext.stop()


def test_cluster_rules_survive_server_reapply(center, engine):
    """Rules staged before the flip are served after it, and a config
    re-apply (setClusterMode=1 again) must NOT discard loaded rules."""
    rules = [{"resource": "keep", "count": 9.0, "clusterMode": True,
              "clusterConfig": {"flowId": 5150, "thresholdType": 1}}]
    # stage rules BEFORE any server exists
    status, body = _post(
        center, "cluster/server/modifyFlowRules?namespace=default",
        f"data={urllib.parse.quote(json.dumps(rules))}")
    assert body == "success"
    _post(center, "cluster/server/modifyTransportConfig?port=0", "")
    _post(center, "setClusterMode?mode=1", "")
    cfg = json.loads(_get(center, "cluster/server/fetchConfig")[1])
    assert cfg["namespaces"] == ["default"]
    # re-apply (e.g. after a maxAllowedQps change): rules must survive
    _post(center, "cluster/server/modifyTransportConfig?maxAllowedQps=123", "")
    _post(center, "setClusterMode?mode=1", "")
    cfg = json.loads(_get(center, "cluster/server/fetchConfig")[1])
    assert cfg["namespaces"] == ["default"]
    metrics = json.loads(_get(center, "cluster/server/metrics")[1])
    assert {m["flowId"] for m in metrics} == {5150}
    _post(center, "setClusterMode?mode=-1", "")


def test_cluster_client_modify_rejects_bad_port(center, engine):
    """A malformed serverPort must fail cleanly WITHOUT poisoning the
    staged config."""
    _post(center, "cluster/client/modifyConfig",
          json.dumps({"serverHost": "127.0.0.1", "serverPort": 12345}))
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(center, "cluster/client/modifyConfig",
              json.dumps({"serverPort": "abc"}))
    assert e.value.code == 400
    cfg = json.loads(_get(center, "cluster/client/fetchConfig")[1])
    assert cfg["serverPort"] == 12345  # earlier staged value intact


class TestAsyncCommandCenter:
    """Event-loop transport twin (netty-http analog): same command SPI,
    same responses, keep-alive connections."""

    def test_same_commands_as_threaded_center(self, engine):
        import http.client

        from sentinel_tpu.transport.aio_command_center import AsyncCommandCenter

        st.load_flow_rules([st.FlowRule(resource="aioRes", count=7)])
        c = AsyncCommandCenter(engine, port=0).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", c.bound_port,
                                              timeout=5)
            # keep-alive: three commands over ONE connection
            conn.request("GET", "/version")
            v = conn.getresponse().read().decode()
            assert "sentinel" in v.lower()
            conn.request("GET", "/getRules?type=flow")
            rules = json.loads(conn.getresponse().read().decode())
            assert rules[0]["resource"] == "aioRes"
            conn.request("POST", "/setRules", body=json.dumps(
                {"type": "flow",
                 "data": json.dumps([{"resource": "aioRes", "count": 1}])}))
            resp = conn.getresponse()
            body = resp.read().decode()
            assert resp.status in (200, 400)
            conn.close()
            # unknown command -> 400, same as the threaded transport
            conn2 = http.client.HTTPConnection("127.0.0.1", c.bound_port,
                                               timeout=5)
            conn2.request("GET", "/nope")
            assert conn2.getresponse().status == 400
            conn2.close()
        finally:
            c.stop()

    def test_start_async_on_callers_loop(self, engine):
        import asyncio
        import urllib.request

        from sentinel_tpu.transport.aio_command_center import AsyncCommandCenter

        async def run():
            c = await AsyncCommandCenter(engine, port=0).start_async()
            port = c.bound_port
            # do the blocking HTTP call off-loop
            out = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/clusterNode", timeout=5
                ).read().decode())
            await c.stop_async()
            return out

        body = asyncio.run(run())
        assert body.startswith("[") or body.startswith("{")

    def test_bad_content_length_gets_400(self, engine):
        import socket

        from sentinel_tpu.transport.aio_command_center import AsyncCommandCenter

        c = AsyncCommandCenter(engine, port=0).start()
        try:
            s = socket.create_connection(("127.0.0.1", c.bound_port),
                                         timeout=5)
            s.sendall(b"GET /version HTTP/1.1\r\ncontent-length: abc\r\n\r\n")
            data = s.recv(4096)
            assert b"400" in data.split(b"\r\n", 1)[0]
            s.close()
        finally:
            c.stop()


def test_gateway_rules_and_api_definitions_commands(center, engine,
                                                    frozen_time):
    """gateway/* commands (reference: the api-gateway command handlers):
    wholesale update + fetch of gateway rules and custom API groups, with
    the rules actually ENFORCED through the param machinery."""
    from sentinel_tpu.adapters.gateway import (
        get_api_manager,
        get_gateway_rule_manager,
    )

    try:
        _run_gateway_scenario(center)
    finally:
        # the module-level managers outlive the per-test engine
        get_gateway_rule_manager().load_rules([])
        get_api_manager().load_api_definitions([])


def _run_gateway_scenario(center):
    import urllib.parse as _up

    from sentinel_tpu.adapters.gateway import GatewayRequest, gateway_entry

    rules = [{"resource": "route-a", "count": 2, "intervalSec": 1}]
    st_, out = _post(center, "gateway/updateRules",
                     f"data={_up.quote(json.dumps(rules))}")
    assert st_ == 200 and out == "success"
    got = json.loads(_get(center, "gateway/getRules")[1])
    assert got[0]["resource"] == "route-a" and got[0]["count"] == 2

    apis = [{"apiName": "user-api",
             "predicateItems": [{"pattern": "/users/", "matchStrategy": 1}]}]
    st_, out = _post(center, "gateway/updateApiDefinitions",
                     f"data={_up.quote(json.dumps(apis))}")
    assert st_ == 200 and out == "success"
    got = json.loads(_get(center, "gateway/getApiDefinitions")[1])
    assert got == apis

    # pushed rules enforce: 2 QPS on route-a through gateway_entry
    req = GatewayRequest(path="/x", route="route-a", client_ip="1.2.3.4")
    passed = 0
    for _ in range(4):
        try:
            entries = gateway_entry(req)
            passed += 1
            for e in reversed(entries):
                e.exit()
        except st.BlockException:
            pass
    assert passed == 2


def test_gateway_bad_payload_rejected(center, engine):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(center, "gateway/updateRules", "data=%7Bnot-json")
    assert exc.value.code == 400
    assert "parse error" in exc.value.read().decode()


def test_gateway_commands_scope_to_their_engine(center, engine):
    """A command center bound to a NON-default engine must not load
    gateway rules into the default one (round-4 review: the singleton
    manager made center B's pushes land on engine A)."""
    import urllib.parse as _up

    from sentinel_tpu.adapters.gateway import get_gateway_rule_manager

    other = st.SentinelEngine(capacity=256)
    c2 = CommandCenter(other, port=0).start()
    try:
        rules = [{"resource": "route-b", "count": 1}]
        st_, out = _post(c2, "gateway/updateRules",
                         f"data={_up.quote(json.dumps(rules))}")
        assert out == "success"
        # visible on ITS center, absent from the default engine's
        assert json.loads(_get(c2, "gateway/getRules")[1])[0]["resource"] \
            == "route-b"
        assert json.loads(_get(center, "gateway/getRules")[1]) == []
        assert get_gateway_rule_manager().get_rules() == []
        # and the param rules landed in the OTHER engine's manager
        assert other.param_rules._gateway_rules
        assert not engine.param_rules._gateway_rules
    finally:
        c2.stop()
        other.close()


def test_gateway_manager_pair_released_with_engine(center, engine):
    """The per-engine manager memo must not pin dead engines (round-4
    review: a strong engine ref in the WeakKeyDictionary VALUE defeated
    the weak key)."""
    import gc
    import weakref as _wr

    from sentinel_tpu.adapters.gateway import _engine_managers

    other = st.SentinelEngine(capacity=256)
    c2 = CommandCenter(other, port=0).start()
    try:
        _get(c2, "gateway/getRules")  # first touch memoizes the pair
        assert any(k is other for k in _engine_managers.keys())
    finally:
        c2.stop()
        other.close()
    ref = _wr.ref(other)
    del other, c2          # the center itself holds the engine strongly
    gc.collect()
    assert ref() is None, "engine leaked via the gateway manager memo"

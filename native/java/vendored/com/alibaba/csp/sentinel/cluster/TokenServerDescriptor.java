package com.alibaba.csp.sentinel.cluster;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:cluster/TokenServerDescriptor.java. */
public class TokenServerDescriptor {

    private final String host;
    private final int port;

    public TokenServerDescriptor(String host, int port) {
        this.host = host;
        this.port = port;
    }

    public String getHost() {
        return host;
    }

    public int getPort() {
        return port;
    }
}

"""Deterministic 32-bit hashing of hot-param values (CMS/table keys).

Must agree across processes, hosts, and restarts — pod-level param-flow
aggregation and the cluster token protocol compare these hashes — so
Python's salted ``hash()`` is off-limits. Type-tagged CRC32 keeps 1, 1.0,
"1" and True distinct (the reference's ``ParamFlowItem`` distinguishes
values by declared classType — SURVEY.md §2.2).
"""

from __future__ import annotations

import struct
import zlib


def hash_param(value) -> int:
    if isinstance(value, bool):
        data = b"b1" if value else b"b0"
    elif isinstance(value, int):
        data = b"i" + str(value).encode()  # unbounded ints
    elif isinstance(value, float):
        data = b"f" + struct.pack("<d", value)
    elif isinstance(value, str):
        data = b"s" + value.encode("utf-8", "surrogatepass")
    elif isinstance(value, bytes):
        data = b"y" + value
    else:
        data = b"r" + repr(value).encode("utf-8", "backslashreplace")
    h = zlib.crc32(data) & 0xFFFFFFFF
    return h if h != 0 else 1

"""Randomized differential fuzz for the pod-parallel step (VERDICT r4
item #5: the shard_mapped path had only scenario tests).

``parallel/cluster.py`` admits cluster-mode rules against the POD-GLOBAL
window via a psum whose staleness is exactly one step: each device sees
the other devices' committed counts as of step start, admits serially
against its own shard, and commits. The documented envelope
(docs/SEMANTICS.md delta #2) is therefore, per resource and step,

    lower:  admitted >= min(remaining_visible, largest single shard's
            candidate tokens)      (one device alone must fill the gap)
    upper:  admitted <= sum_d min(shard_d candidates, remaining_visible)
            (every device admits at most the remaining quota it can see)

which implies the SEMANTICS.md headline bound
``total <= threshold + (D-1) x max-per-device-per-step``. This fuzz
drives randomized multi-resource traffic with random shard skew and
random clock gaps through the REAL shard_mapped step on the 8-device
CPU mesh and asserts both sides of the envelope every step, feeding the
device's own admissions back into the oracle window (the admission
SPLIT across devices is scheduling-dependent; the envelope is not).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D_
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as PF
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import step as S
from sentinel_tpu.parallel import cluster as PC

NOW0 = 1_700_000_000_000
CAPACITY = 128
NDEV = 8
PER_DEV = 6  # batch rows per device shard


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= NDEV, "conftest must force 8 CPU devices"
    return Mesh(np.asarray(devices[:NDEV]), (PC.AXIS,))


class _GlobalWindow:
    """1s/2-bucket pod-global window mirror (SPEC_1S), fed with the
    DEVICE's actual admitted tokens after every step."""

    def __init__(self):
        self.starts = [-1, -1]
        self.counts = [0, 0]

    def total(self, now):
        idx = (now // 500) % 2
        ws = now - now % 500
        t = 0
        for b in range(2):
            expect = ws if b == idx else ws - 500
            if self.starts[b] == expect:
                t += self.counts[b]
        return t

    def add(self, now, c):
        idx = (now // 500) % 2
        ws = now - now % 500
        if self.starts[idx] != ws:
            self.starts[idx] = ws
            self.counts[idx] = 0
        self.counts[idx] += c


@pytest.mark.parametrize("seed", [
    2,
    # Redundant seeds slow-tier'd (ISSUE 16 tier-1 wall-time trim):
    # 11-15s each for the same overshoot-envelope regimes as seed 2;
    # the full sweep still runs with -m slow.
    pytest.param(23, marks=pytest.mark.slow),
    pytest.param(61, marks=pytest.mark.slow),
    pytest.param(97, marks=pytest.mark.slow),
])
def test_pod_fuzz_overshoot_envelope(mesh, seed):
    rng = np.random.default_rng(seed)
    n_res = 4
    thresholds = [int(rng.integers(3, 25)) for _ in range(n_res)]

    reg = NodeRegistry(CAPACITY)
    rows = [reg.cluster_row(f"res{i}") for i in range(n_res)]
    rules = [F.FlowRule(resource=f"res{i}", count=thresholds[i],
                        cluster_mode=True)
             for i in range(n_res)]
    ft, _ = F.compile_flow_rules(rules, reg, CAPACITY)
    dt, di = D_.compile_degrade_rules([], reg, CAPACITY)
    pt = PF.compile_param_rules([], reg, CAPACITY)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, CAPACITY),
        system=Y.compile_system_rules([]),
        param=pt,
    )
    one = S.make_state(CAPACITY, ft.num_rules, NOW0,
                       degrade=D_.make_degrade_state(dt, di),
                       param=PF.make_param_state(pt.num_rules))
    pod = PC.make_pod_state(NDEV, one)
    entry, _ = PC.make_pod_steps(mesh)
    entry = jax.jit(entry)

    windows = {r: _GlobalWindow() for r in range(n_res)}
    now = NOW0
    row_to_res = {rows[i]: i for i in range(n_res)}

    for step in range(30):
        now += int(rng.choice([0, 120, 450, 700, 1300]))
        buf = make_entry_batch_np(NDEV * PER_DEV)
        buf["cluster_row"][:] = -1
        buf["dn_row"][:] = -1
        buf["count"][:] = 1
        # random shard skew: some devices idle, some concentrated
        shard_cand = np.zeros((NDEV, n_res), np.int64)
        for d in range(NDEV):
            if rng.random() < 0.25:
                continue  # idle shard
            k = int(rng.integers(1, PER_DEV + 1))
            for j in range(k):
                res = int(rng.integers(0, n_res))
                buf["cluster_row"][d * PER_DEV + j] = rows[res]
                shard_cand[d, res] += 1

        pod, dec = entry(pod, pack,
                         EntryBatch(**{k: jnp.asarray(v)
                                       for k, v in buf.items()}),
                         jnp.asarray(now, jnp.int64))
        reasons = np.asarray(dec.reason)

        for res in range(n_res):
            thr = thresholds[res]
            remaining = max(0, thr - windows[res].total(now))
            admitted = int(sum(
                1 for i in range(NDEV * PER_DEV)
                if buf["cluster_row"][i] in row_to_res
                and row_to_res[buf["cluster_row"][i]] == res
                and reasons[i] == C.BlockReason.PASS))
            upper = int(sum(min(int(shard_cand[d, res]), remaining)
                            for d in range(NDEV)))
            lower = min(remaining, int(shard_cand[:, res].max()))
            assert admitted <= upper, (
                f"seed {seed} step {step} res{res}: admitted {admitted} "
                f"> stale-visibility upper {upper} "
                f"(thr {thr}, remaining {remaining}, "
                f"cand {shard_cand[:, res].tolist()})")
            assert admitted >= lower, (
                f"seed {seed} step {step} res{res}: admitted {admitted} "
                f"< single-shard lower {lower} "
                f"(thr {thr}, remaining {remaining}, "
                f"cand {shard_cand[:, res].tolist()})")
            # headline SEMANTICS bound, implied but asserted directly:
            assert windows[res].total(now) + admitted \
                <= thr + (NDEV - 1) * PER_DEV
            windows[res].add(now, admitted)

    # Final sanity: saturate one resource, then verify the pod blocks
    # everything next step (propagated counts stop admission pod-wide).
    res, thr = 0, thresholds[0]
    now += 2000  # fresh window
    buf = make_entry_batch_np(NDEV * PER_DEV)
    buf["cluster_row"][:] = rows[res]
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    pod, dec = entry(pod, pack,
                     EntryBatch(**{k: jnp.asarray(v)
                                   for k, v in buf.items()}),
                     jnp.asarray(now, jnp.int64))
    first = int((np.asarray(dec.reason) == C.BlockReason.PASS).sum())
    assert thr <= first <= thr + (NDEV - 1) * min(PER_DEV, thr)
    pod, dec2 = entry(pod, pack,
                      EntryBatch(**{k: jnp.asarray(v)
                                    for k, v in buf.items()}),
                      jnp.asarray(now + 1, jnp.int64))
    assert int((np.asarray(dec2.reason) == C.BlockReason.PASS).sum()) == 0

"""The token service: global-quota admission (reference:
``cluster-server:DefaultTokenService.java`` + ``flow/ClusterFlowChecker.java``
+ ``flow/statistic/*`` + ``connection/ConnectionManager.java`` +
``flow/statistic/limit/GlobalRequestLimiter.java`` — SURVEY.md §2.4, §3.3).

TPU-native design: all flow rules' global sliding windows live in one
RowWindow tensor; ``acquire_step`` is a jitted pure function evaluating a
whole batch of token requests at once (rotation → per-rule usage + within-
batch arrival prefixes → verdicts → commit). The TCP frontend batches
concurrent client requests into these steps; per-request semantics follow
``ClusterFlowChecker.acquireClusterToken``:

  * effective threshold = count (GLOBAL) or count × connected-client count
    (AVG_LOCAL), compared against the window's per-second pass average;
  * pass → commit PASS/PASS_REQUEST, status OK;
  * over + prioritized → if the waiting backlog is under
    ``maxOccupyRatio × threshold``, commit WAITING and return
    SHOULD_WAIT(ms until the next bucket);
  * otherwise commit BLOCK/BLOCK_REQUEST, status BLOCKED;
  * unknown flowId → NO_RULE_EXISTS (client falls back to local);
  * namespace over ``maxAllowedQps`` → TOO_MANY_REQUEST (GlobalRequestLimiter).

Param-flow tokens (``requestParamToken``) use per-(flowId, param-hash) QPS
buckets server-side, mirroring ``ClusterParamFlowChecker``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.cluster import constants as CC
from sentinel_tpu.cluster.rules import (
    ClusterFlowRuleManager,
    ClusterMetricState,
    ClusterRuleTensors,
)
from sentinel_tpu.ops import window as W
from sentinel_tpu.utils import time_util
from sentinel_tpu.utils.param_hash import hash_param


class TokenTicket(NamedTuple):
    """An in-flight batched acquire (the wire path's analog of PR 8's
    enqueue-only engine dispatch): ``dispatch_tokens`` returns one with
    ``status``/``extra`` still LAZY device arrays (or plain results on
    the sync fallback), ``harvest_tokens`` resolves it OUTSIDE the
    service lock — so the TCP frontend can stage + dispatch batch N+1
    while batch N still computes on the device stream."""

    requests: tuple
    traces: tuple
    pre: tuple          # pre-decided TokenResults (limiter/TOO_MANY), or None
    status: object      # lazy int32[N] (or None on the sync fallback)
    extra: object       # lazy int32[N] (or None on the sync fallback)
    now_ms: int
    t0: float           # dispatch perf_counter (span timing)
    sync_results: object = None  # pre-resolved results (sync fallback)
    shard: object = None  # ShardState snapshot at dispatch (slice epochs)


class TokenResult(NamedTuple):
    """Reference: ``TokenResult`` (status + optional wait hint).

    ``server_span`` rides only on traced requests (telemetry/spans.py):
    the server-side token-service span's identity + timing, shipped back
    over the wire so the client can stitch per-hop latency.

    ``epoch`` (cluster/sharding.py): the PER-SLICE fencing epoch this
    verdict was granted under — the TCP frontend stamps it into the
    reply's epoch TLV instead of the service-global epoch, so each
    slice's leadership fences independently. None keeps the pre-shard
    behavior (the frontend stamps ``service.epoch``)."""

    status: int
    remaining: int = 0
    wait_ms: int = 0
    server_span: Optional[Dict] = None  # {"spanId","startMs","durationUs"}
    epoch: Optional[int] = None         # per-slice fencing epoch


class ConnectionManager:
    """namespace → live client connection count (feeds AVG_LOCAL)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[str, int] = {}

    def connect(self, namespace: str) -> None:
        with self._lock:
            self._groups[namespace] = self._groups.get(namespace, 0) + 1

    def disconnect(self, namespace: str) -> None:
        with self._lock:
            n = self._groups.get(namespace, 0) - 1
            if n <= 0:
                self._groups.pop(namespace, None)
            else:
                self._groups[namespace] = n

    def connected_count(self, namespace: str) -> int:
        with self._lock:
            return self._groups.get(namespace, 0)


class GlobalRequestLimiter:
    """Per-namespace QPS self-protection cap on the token server itself."""

    def __init__(self, max_allowed_qps: float = CC.DEFAULT_MAX_ALLOWED_QPS):
        self.max_allowed_qps = max_allowed_qps
        self._lock = threading.Lock()
        self._counts: Dict[str, Tuple[int, int]] = {}  # ns -> (second, count)

    def try_pass(self, namespace: str, now_ms: int) -> bool:
        sec = now_ms // 1000
        with self._lock:
            cur_sec, count = self._counts.get(namespace, (sec, 0))
            if cur_sec != sec:
                cur_sec, count = sec, 0
            if count + 1 > self.max_allowed_qps:
                self._counts[namespace] = (cur_sec, count)
                return False
            self._counts[namespace] = (cur_sec, count + 1)
            return True


# ---------------------------------------------------------------------------
# Device-side acquire step
# ---------------------------------------------------------------------------


def acquire_step(
    state: ClusterMetricState,
    rt: ClusterRuleTensors,
    conn_counts: jax.Array,   # int32[NS] per-namespace connected clients
    slots: jax.Array,         # int32[N] rule slot per request (-1 = unknown)
    counts: jax.Array,        # int32[N]
    prioritized: jax.Array,   # bool[N]
    now_ms: jax.Array,
    max_occupy_ratio: float = CC.DEFAULT_MAX_OCCUPY_RATIO,
) -> Tuple[ClusterMetricState, jax.Array, jax.Array]:
    """-> (state', status int32[N], wait_ms int32[N]). Jit-compiled."""
    now_ms = jnp.asarray(now_ms, jnp.int64)
    win = W.row_rotate(state.win, now_ms)
    n = slots.shape[0]
    known = slots >= 0

    g = lambda a, fill=0: a.at[W.oob(slots, a.shape[0])].get(mode="fill", fill_value=fill)

    # Per-second pass average of each request's rule window + arrival prefix.
    # WAITING counts (prioritized requests that will pass after their sleep)
    # are charged as usage too, so waited-through admissions can't let the
    # next window over-admit beyond the configured threshold.
    totals = W.row_window_totals(win, slots)  # [N, E]
    interval = jnp.maximum(g(rt.interval_ms, 1000), 1).astype(jnp.float32)
    base = (totals[:, CC.ClusterFlowEvent.PASS].astype(jnp.float32)
            + totals[:, CC.ClusterFlowEvent.WAITING].astype(jnp.float32))

    ns = g(rt.namespace_id, -1)
    conns = conn_counts.at[W.oob(ns, conn_counts.shape[0])].get(
        mode="fill", fill_value=0).astype(jnp.float32)
    thr = jnp.where(
        g(rt.threshold_type) == CC.THRESHOLD_GLOBAL,
        g(rt.threshold, 0.0),
        g(rt.threshold, 0.0) * jnp.maximum(conns, 1.0),
    )

    # Greedy serial admission in arrival order — exactly the reference's
    # per-request CAS semantics: each request sees the usage that every
    # EARLIER ADMITTED request contributed, and admitted requests consume.
    # (A two-pass survivor approximation over-admits: after an oversized
    # request is rejected, later requests would each be judged alone.)
    # The SHOULD_WAIT occupy backlog is serialized the same way: granted
    # waits consume occupy budget for later requests in the batch.
    num_slots = rt.threshold.shape[0]
    qps_scale = 1000.0 / interval
    waiting = totals[:, CC.ClusterFlowEvent.WAITING].astype(jnp.float32)

    def body(carry, x):
        used_tbl, wait_tbl = carry
        slot_i, cnt_i, base_i, thr_i, scale_i, known_i, prio_i, waiting_i = x
        slot_safe = W.oob(slot_i, num_slots)
        passed_i = (base_i + used_tbl.at[slot_safe].get(
            mode="fill", fill_value=0.0)) * scale_i
        ok_i = known_i & (passed_i + cnt_i <= thr_i)
        backlog_i = waiting_i + wait_tbl.at[slot_safe].get(
            mode="fill", fill_value=0.0)
        can_wait_i = (known_i & prio_i & (~ok_i)
                      & (backlog_i + cnt_i <= max_occupy_ratio * thr_i))
        # Granted waits consume USAGE too (base charges WAITING from prior
        # batches; within-batch must match, or a later request would be
        # judged blind to an earlier SHOULD_WAIT grant).
        used_tbl = used_tbl.at[slot_safe].add(
            jnp.where(ok_i | can_wait_i, cnt_i, 0.0), mode="drop")
        wait_tbl = wait_tbl.at[slot_safe].add(
            jnp.where(can_wait_i, cnt_i, 0.0), mode="drop")
        return (used_tbl, wait_tbl), (ok_i, can_wait_i, passed_i)

    zeros = jnp.zeros((num_slots,), jnp.float32)
    _, (ok, can_wait, passed) = jax.lax.scan(
        body, (zeros, zeros),
        (slots, counts.astype(jnp.float32), base, thr, qps_scale, known,
         prioritized, waiting),
    )

    bucket_ms = jnp.maximum(g(win.bucket_ms, 1000), 1)
    wait_ms = (bucket_ms - jnp.mod(now_ms, bucket_ms)).astype(jnp.int32)

    status = jnp.where(ok, CC.TokenResultStatus.OK, CC.TokenResultStatus.BLOCKED)
    status = jnp.where(can_wait, CC.TokenResultStatus.SHOULD_WAIT, status)
    status = jnp.where(~known, CC.TokenResultStatus.NO_RULE_EXISTS, status)
    status = status.astype(jnp.int32)
    wait_ms = jnp.where(status == CC.TokenResultStatus.SHOULD_WAIT, wait_ms, 0)

    # Commit: PASS/BLOCK counts + request tallies + WAITING backlog.
    def add(win, event, values):
        return W.row_window_add(win, now_ms, jnp.where(known, slots, -1),
                                jnp.full((n,), event), values)

    is_ok = status == CC.TokenResultStatus.OK
    is_blocked = status == CC.TokenResultStatus.BLOCKED
    is_wait = status == CC.TokenResultStatus.SHOULD_WAIT
    win = add(win, CC.ClusterFlowEvent.PASS, jnp.where(is_ok, counts, 0))
    win = add(win, CC.ClusterFlowEvent.PASS_REQUEST, jnp.where(is_ok, 1, 0))
    win = add(win, CC.ClusterFlowEvent.BLOCK, jnp.where(is_blocked, counts, 0))
    win = add(win, CC.ClusterFlowEvent.BLOCK_REQUEST, jnp.where(is_blocked, 1, 0))
    win = add(win, CC.ClusterFlowEvent.WAITING, jnp.where(is_wait, counts, 0))

    remaining = jnp.maximum(thr - passed - counts, 0).astype(jnp.int32)
    return ClusterMetricState(win=win), status, jnp.where(is_ok, remaining, wait_ms)


# ---------------------------------------------------------------------------
# Host service
# ---------------------------------------------------------------------------

# One process-wide jit wrapper for the acquire step: every service shares
# its compile cache, so the Nth DefaultTokenService of a process (an HA
# re-promotion, a chaos-campaign episode's fresh mesh) pays ZERO XLA
# compiles for shapes any earlier service already ran. Per-instance
# wrappers each kept a private cache and re-traced identical shapes —
# measurably the dominant cost of building a fresh in-process mesh.
# Donation stays per-call (each service donates ITS OWN state buffer).
_acquire_jit_shared = None


def _shared_acquire_jit():
    global _acquire_jit_shared
    if _acquire_jit_shared is None:
        _acquire_jit_shared = jax.jit(
            acquire_step, static_argnames=("max_occupy_ratio",),
            donate_argnums=(0,))
    return _acquire_jit_shared


class DefaultTokenService:
    """The server-side token service over the jitted acquire step."""

    def __init__(self, rules: Optional[ClusterFlowRuleManager] = None,
                 max_allowed_qps: float = CC.DEFAULT_MAX_ALLOWED_QPS,
                 max_occupy_ratio: float = CC.DEFAULT_MAX_OCCUPY_RATIO,
                 epoch: int = 0):
        self.rules = rules or ClusterFlowRuleManager()
        # Leadership epoch (cluster/ha.py): stamped into every response
        # by the TCP frontend so deposed leaders' replies are fenced;
        # 0 (default) keeps the pre-HA wire format byte-identical.
        self.epoch = int(epoch)
        self.connections = ConnectionManager()
        self.limiter = GlobalRequestLimiter(max_allowed_qps)
        self.max_occupy_ratio = max_occupy_ratio
        self._lock = threading.Lock()
        # Sharded ownership (cluster/sharding.py): when set, requests
        # for flows hashing outside the owned slices are answered
        # WRONG_SLICE (carrying the map version) instead of a verdict,
        # and verdicts carry their slice's fencing epoch. Replaced
        # wholesale by set_shard, read lock-free on the dispatch path.
        self._shard = None
        self.wrong_slice_count = 0
        self._compiled_version = -1
        self._rt: Optional[ClusterRuleTensors] = None
        self._state: Optional[ClusterMetricState] = None
        self._slot_of: Dict[int, int] = {}
        self._ns_of: Dict[int, str] = {}
        self._acquire_jit = _shared_acquire_jit()
        # Param-flow cluster buckets: (flowId, param_hash) -> (window_start, used)
        self._param_buckets: Dict[Tuple[int, int], Tuple[int, float]] = {}
        # Server-side spans (telemetry/spans.py): every TRACED request
        # records a token-service span here — sampling already happened
        # on the client, so the server keeps whatever arrives traced.
        from sentinel_tpu.telemetry.spans import SpanCollector

        self.spans = SpanCollector(sample_every=0)
        # Namespace telescope (telemetry/population.py): the leader's
        # flowId-axis observation point. Bound to the engine's tracker
        # by ClusterStateManager.set_to_server; None (standalone seats,
        # unit harnesses) disables observation entirely.
        self.population = None

    # -- sharded ownership (cluster/sharding.py) ---------------------------

    @property
    def shard(self):
        return self._shard

    def set_shard(self, shard) -> None:
        """Adopt a new slice-ownership view (a ``sharding.ShardState``;
        None returns to unsharded single-leader behavior). The service
        epoch becomes the max owned slice epoch for the epoch-keyed
        consumers that take the SERVICE term when no per-slice one is in
        play — checkpoint-save fencing (``save_cluster_checkpoint``'s
        ``getattr(service, "epoch")`` default) and the flat teardown
        publish. Wire replies are NOT among them: ``stamp_epoch`` stamps
        sharded replies only from each verdict's own slice epoch (sheds
        and pings from a sharded leader go out unstamped)."""
        self._shard = shard
        if shard is not None and shard.epochs:
            self.epoch = int(max(shard.epochs.values()))

    def shard_snapshot(self) -> Optional[dict]:
        """The leader-side shard block of ``ha_stats`` (exporter +
        dashboard source); lock-free like every stats read."""
        shard = self._shard
        if shard is None:
            return None
        return {
            "mode": "server",
            "mapVersion": shard.version,
            "nSlices": shard.n_slices,
            "slicesOwned": len(shard.epochs),
            "sliceEpochs": {str(sl): int(ep)
                            for sl, ep in sorted(shard.epochs.items())},
            "wrongSliceRejected": self.wrong_slice_count,
        }

    def _ensure_compiled(self):
        if self._compiled_version == self.rules.version:
            return
        old_state, old_slots = self._state, self._slot_of
        self._rt, fresh, self._slot_of, self._ns_of = self.rules.compile()
        # A rule push must NOT reset surviving flows' windows (the reference
        # keeps per-flowId ClusterMetrics across updates): carry each
        # surviving flowId's row over — unless its bucket geometry changed.
        if old_state is not None and old_slots:
            counts = np.array(fresh.win.counts)  # writable copies
            starts = np.array(fresh.win.starts)
            old_counts = np.asarray(old_state.win.counts)
            old_starts = np.asarray(old_state.win.starts)
            old_bucket = np.asarray(old_state.win.bucket_ms)
            new_bucket = np.asarray(fresh.win.bucket_ms)
            nbuckets = counts.shape[1]
            for flow_id, new_slot in self._slot_of.items():
                old_slot = old_slots.get(flow_id)
                if (old_slot is None or old_counts.shape[1] != nbuckets
                        or old_bucket[old_slot] != new_bucket[new_slot]):
                    continue
                counts[new_slot] = old_counts[old_slot]
                starts[new_slot] = old_starts[old_slot]
            fresh = ClusterMetricState(win=fresh.win._replace(
                counts=jnp.asarray(counts), starts=jnp.asarray(starts)))
        self._state = fresh
        self._compiled_version = self.rules.version

    def _conn_tensor(self) -> jnp.ndarray:
        ns_ids = self.rules.namespace_ids()
        counts = [0] * max(len(ns_ids), 1)
        for ns, nid in ns_ids.items():
            counts[nid] = self.connections.connected_count(ns)
        return jnp.asarray(counts, jnp.int32)

    def request_token(self, flow_id: int, count: int = 1,
                      prioritized: bool = False,
                      now_ms: Optional[int] = None) -> TokenResult:
        results = self.request_tokens([(flow_id, count, prioritized)], now_ms)
        return results[0]

    def request_tokens(self, requests: Sequence[Tuple],
                       now_ms: Optional[int] = None) -> List[TokenResult]:
        """Batched acquire — the TCP frontend folds concurrent clients in.

        Each request is ``(flow_id, count, prioritized)`` or, for traced
        requests (telemetry/spans.py), ``(flow_id, count, prioritized,
        TraceContext)`` — the trace context from the client's traceparent
        TLV. Traced requests get a server-side span (recorded in
        ``self.spans`` AND returned in ``TokenResult.server_span``)
        timing the actual device acquire step their verdict came from.

        Synchronous form of :meth:`dispatch_tokens` +
        :meth:`harvest_tokens` — one code path, so the pipelined wire
        frontend and direct callers can never drift. When an instance
        override exists, this (class-level) body is only reachable
        THROUGH the override's captured real(), so it goes straight to
        the device path rather than looping back into the override.
        """
        return self.harvest_tokens(self._dispatch_device(requests, now_ms))

    def dispatch_tokens(self, requests: Sequence[Tuple],
                        now_ms: Optional[int] = None) -> TokenTicket:
        """Enqueue-only batched acquire: all host prep + the jitted
        device dispatch happen under the service lock, but the verdict
        arrays come back LAZY — the caller resolves them later with
        :meth:`harvest_tokens` (outside the lock), which is what lets
        the wire frontend keep up to ``wire.inflight.depth`` fused
        batches riding the device stream (the PR 8 dispatch/harvest
        split, applied to the token path).

        When ``request_tokens`` has been overridden on the INSTANCE
        (test harnesses wrap it to inject step latency or faults), the
        override must see every batch — the ticket degrades to a
        pre-resolved synchronous one through it. (No reentry hazard:
        the override's captured real() is the CLASS request_tokens,
        which dispatches via :meth:`_dispatch_device` directly.)
        """
        import time as _time

        if "request_tokens" in self.__dict__:
            t0 = _time.perf_counter()
            results = self.__dict__["request_tokens"](requests, now_ms)
            return TokenTicket(tuple(requests), (), (), None, None,
                               now_ms or 0, t0, sync_results=list(results))
        return self._dispatch_device(requests, now_ms)

    def _dispatch_device(self, requests: Sequence[Tuple],
                         now_ms: Optional[int] = None) -> TokenTicket:
        """The real enqueue-only device dispatch (the body behind both
        :meth:`dispatch_tokens` and :meth:`request_tokens`)."""
        import time as _time

        now = now_ms if now_ms is not None else time_util.current_time_millis()
        traces = tuple(r[3] if len(r) > 3 else None for r in requests)
        shard = self._shard
        population = self.population
        pop_rows = [] if population is not None else None
        with self._lock:
            self._ensure_compiled()
            pre: List[Optional[TokenResult]] = [None] * len(requests)
            slots = np.full(len(requests), -1, np.int32)
            counts = np.zeros(len(requests), np.int32)
            prio = np.zeros(len(requests), bool)
            for i, req in enumerate(requests):
                flow_id, count, prioritized = req[0], req[1], req[2]
                try:
                    flow_id = int(flow_id)
                except (TypeError, ValueError):
                    continue  # slot stays -1 -> NO_RULE_EXISTS
                slice_epoch = None
                if shard is not None:
                    slice_epoch = shard.epoch_for_flow(flow_id)
                    if slice_epoch is None:
                        # Out-of-slice: this leader does not own the
                        # flow's hash slice — answer WRONG_SLICE with
                        # the current map version (NOT a verdict; the
                        # routing client walks the other leaders and
                        # self-heals). Checked strictly before the
                        # limiter and the device step, so a mis-routed
                        # request never consumes quota here.
                        self.wrong_slice_count += 1
                        pre[i] = TokenResult(
                            CC.TokenResultStatus.WRONG_SLICE,
                            wait_ms=shard.version)
                        continue
                ns = self._ns_of.get(flow_id)
                if pop_rows is not None:
                    # Offered load on OWNED slices only (a mis-routed
                    # request is counted by the leader that admits it) —
                    # staged as raw triples, hashed on the spill fold.
                    pop_rows.append((ns, flow_id, count))
                if ns is not None and not self.limiter.try_pass(ns, now):
                    pre[i] = TokenResult(CC.TokenResultStatus.TOO_MANY_REQUEST,
                                         epoch=slice_epoch)
                    continue
                slots[i] = self._slot_of.get(flow_id, -1)
                counts[i] = count
                prio[i] = prioritized
            t0 = _time.perf_counter()
            try:
                self._state, status, extra = self._acquire_jit(
                    self._state, self._rt, self._conn_tensor(),
                    jnp.asarray(slots), jnp.asarray(counts),
                    jnp.asarray(prio), jnp.asarray(now, jnp.int64),
                    max_occupy_ratio=self.max_occupy_ratio,
                )
            except Exception:
                # A failed dispatch may have consumed (donated) the state
                # buffer: drop cold and recompile on the next batch
                # rather than serving from a poisoned tensor.
                self._state = None
                self._compiled_version = -1
                raise
            if pop_rows:
                population.observe_flows(pop_rows)
            return TokenTicket(tuple(requests), traces, tuple(pre),
                               status, extra, now, t0, shard=shard)

    def harvest_tokens(self, ticket: TokenTicket) -> List[TokenResult]:
        """Resolve a dispatched batch to concrete TokenResults. The
        ``np.asarray`` readback happens HERE — outside the service lock,
        so a slow device step never blocks the next batch's dispatch.
        An async device death surfaces here; the service state drops
        cold (recompiled on the next dispatch) exactly like a dispatch
        death, and the caller fails the batch's requests."""
        import time as _time

        if ticket.sync_results is not None:
            return ticket.sync_results
        try:
            status = np.asarray(ticket.status)
            extra = np.asarray(ticket.extra)
        except Exception:
            with self._lock:
                self._state = None
                self._compiled_version = -1
            raise
        # The batch shares one device step; each traced request's span
        # carries the dispatch-to-harvest wall (its verdict's true
        # compute cost, including any pipelined overlap) plus its own
        # verdict attributes.
        step_us = int((_time.perf_counter() - ticket.t0) * 1e6)
        out: List[TokenResult] = []
        for i, req in enumerate(ticket.requests):
            result = ticket.pre[i]
            if result is None:
                s = int(status[i])
                if s == CC.TokenResultStatus.SHOULD_WAIT:
                    result = TokenResult(s, wait_ms=int(extra[i]))
                else:
                    result = TokenResult(s, remaining=int(extra[i]))
                if ticket.shard is not None:
                    # Stamp the verdict with ITS slice's fencing epoch
                    # (the ticket's snapshot — a concurrent rebalance
                    # must not retag an already-granted verdict).
                    result = result._replace(
                        epoch=ticket.shard.epoch_for_flow(req[0]))
            if ticket.traces[i] is not None:
                result = result._replace(server_span=self._record_span(
                    ticket.traces[i], req[0], ticket.now_ms, step_us,
                    int(result.status), len(ticket.requests)))
            out.append(result)
        return out

    def _record_span(self, ctx, flow_id, start_ms: int, duration_us: int,
                     status: int, batch_n: int) -> Dict:
        """One server-side token-service span; returns the wire-shippable
        identity+timing dict (TokenResult.server_span)."""
        child = ctx.child()
        self.spans.record_remote(
            child, "cluster.token_service", ctx.span_id, start_ms,
            duration_us, attrs={"flowId": flow_id, "status": status,
                                "batch": batch_n})
        return {"spanId": child.span_id, "startMs": int(start_ms),
                "durationUs": int(duration_us)}

    def request_param_token(self, flow_id: int, count: int,
                            params: Sequence, now_ms: Optional[int] = None,
                            trace=None) -> TokenResult:
        """Per-(flowId, param) global QPS buckets (``ClusterParamFlowChecker``)."""
        import time as _time

        now = now_ms if now_ms is not None else time_util.current_time_millis()
        t0 = _time.perf_counter()
        result = self._request_param_token(flow_id, count, params, now)
        if trace is not None:
            result = result._replace(server_span=self._record_span(
                trace, flow_id, now, int((_time.perf_counter() - t0) * 1e6),
                int(result.status), 1))
        return result

    def _request_param_token(self, flow_id: int, count: int,
                             params: Sequence, now: int) -> TokenResult:
        try:
            flow_id = int(flow_id)  # one bucket key space for "123" and 123
        except (TypeError, ValueError):
            return TokenResult(CC.TokenResultStatus.NO_RULE_EXISTS)
        shard = self._shard
        slice_epoch = None
        if shard is not None:
            slice_epoch = shard.epoch_for_flow(flow_id)
            if slice_epoch is None:
                # Out-of-slice, same contract as the flow path: checked
                # before the rule lookup and limiter so a mis-routed
                # param request never consumes a bucket here.
                self.wrong_slice_count += 1
                return TokenResult(CC.TokenResultStatus.WRONG_SLICE,
                                   wait_ms=shard.version)
        rule = self.rules.rule_by_flow_id(flow_id)
        if rule is None:
            # Every owned-slice reply carries ITS slice's epoch (None
            # when unsharded): stamping a flat service epoch here would
            # let one slice's term pollute another's fence lane.
            return TokenResult(CC.TokenResultStatus.NO_RULE_EXISTS,
                               epoch=slice_epoch)
        ns = self.rules.namespace_of_flow_id(flow_id)
        if ns is not None and not self.limiter.try_pass(ns, now):
            return TokenResult(CC.TokenResultStatus.TOO_MANY_REQUEST,
                               epoch=slice_epoch)
        # AVG_LOCAL scales the per-value threshold by the namespace's live
        # client count, mirroring the flow-token path (reference:
        # ClusterParamFlowChecker.calcGlobalThreshold).
        thr = rule.count
        cc = rule.cluster_config or {}
        if int(cc.get("thresholdType", CC.THRESHOLD_AVG_LOCAL)) == CC.THRESHOLD_AVG_LOCAL:
            thr *= max(self.connections.connected_count(ns), 1) if ns else 1
        window_start = now - now % 1000
        with self._lock:
            # Check all values first (any over-quota value blocks the whole
            # request, reference ParamFlowChecker semantics), accumulating
            # within-call usage so duplicate params cannot each be judged
            # against the untouched bucket.
            pending: Dict[Tuple[int, int], float] = {}
            blocked = False
            for p in params:
                key = (flow_id, hash_param(p))
                start, used = self._param_buckets.get(key, (window_start, 0.0))
                if start != window_start:
                    used = 0.0
                within = pending.get(key, 0.0)
                if used + within + count > thr:
                    blocked = True
                    break
                pending[key] = within + count
            if blocked:
                return TokenResult(CC.TokenResultStatus.BLOCKED,
                                   epoch=slice_epoch)
            for key, add in pending.items():
                start, used = self._param_buckets.get(key, (window_start, 0.0))
                if start != window_start:
                    used = 0.0
                self._param_buckets[key] = (window_start, used + add)
            if len(self._param_buckets) > 100_000:  # bounded key space
                self._param_buckets.clear()
        return TokenResult(CC.TokenResultStatus.OK, epoch=slice_epoch)

    # -- introspection -----------------------------------------------------

    def metrics_snapshot(self) -> Dict[int, Dict[str, float]]:
        """Per-flowId window totals (cluster command handlers' data source)."""
        with self._lock:
            self._ensure_compiled()
            now = time_util.current_time_millis()
            win = W.row_rotate(self._state.win, jnp.asarray(now, jnp.int64))
            totals = np.asarray(win.counts.sum(axis=1))
        out = {}
        for flow_id, slot in self._slot_of.items():
            t = totals[slot]
            out[flow_id] = {
                "pass": float(t[CC.ClusterFlowEvent.PASS]),
                "block": float(t[CC.ClusterFlowEvent.BLOCK]),
                "passRequest": float(t[CC.ClusterFlowEvent.PASS_REQUEST]),
                "blockRequest": float(t[CC.ClusterFlowEvent.BLOCK_REQUEST]),
                "waiting": float(t[CC.ClusterFlowEvent.WAITING]),
            }
        return out

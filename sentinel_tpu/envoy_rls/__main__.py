"""``python -m sentinel_tpu.envoy_rls`` — standalone RLS token server.

Rules come from a JSON file (``SENTINEL_RLS_RULES`` or ``--rules``),
re-polled on mtime change so a ConfigMap update applies without restart::

    [{"domain": "web", "descriptors": [
        {"resources": [{"key": "path", "value": "/api"}], "count": 100}]}]
"""

from __future__ import annotations

import argparse
import json
import os
import time

from sentinel_tpu.envoy_rls.rule import (
    EnvoyRlsRule,
    EnvoyRlsRuleManager,
    KeyValueResource,
    ResourceDescriptor,
)
from sentinel_tpu.envoy_rls.service import SentinelEnvoyRlsService


def rules_from_json(text: str):
    out = []
    for d in json.loads(text or "[]"):
        out.append(EnvoyRlsRule(
            domain=d["domain"],
            descriptors=[
                ResourceDescriptor(
                    resources=[KeyValueResource(r["key"], r["value"])
                               for r in desc.get("resources", [])],
                    count=float(desc["count"]),
                )
                for desc in d.get("descriptors", [])
            ],
        ))
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description="sentinel-tpu Envoy RLS server")
    ap.add_argument("--address",
                    default=os.environ.get("SENTINEL_RLS_ADDRESS",
                                           "0.0.0.0:10245"))
    ap.add_argument("--rules",
                    default=os.environ.get("SENTINEL_RLS_RULES", ""))
    args = ap.parse_args()

    manager = EnvoyRlsRuleManager()
    service = SentinelEnvoyRlsService(manager)
    mtime = None
    if args.rules:
        with open(args.rules, "r", encoding="utf-8") as f:
            manager.load_rules(rules_from_json(f.read()))
        mtime = os.stat(args.rules).st_mtime
    server = service.serve_grpc(args.address)
    print(f"sentinel-tpu RLS serving on {args.address}", flush=True)
    try:
        while True:
            time.sleep(3)
            if not args.rules:
                continue
            try:
                m = os.stat(args.rules).st_mtime
            except OSError:
                continue
            if m != mtime:
                try:
                    with open(args.rules, "r", encoding="utf-8") as f:
                        manager.load_rules(rules_from_json(f.read()))
                    mtime = m  # recorded only on SUCCESS: a mid-write or
                    # malformed read retries next poll even when the final
                    # write lands in the same coarse mtime tick
                    print("RLS rules reloaded", flush=True)
                except (OSError, ValueError, KeyError, TypeError) as ex:
                    # Malformed/mid-write update: keep serving last-good.
                    print(f"RLS rules reload FAILED (kept last good): {ex!r}",
                          flush=True)
    except KeyboardInterrupt:
        server.stop(grace=1.0)


if __name__ == "__main__":
    main()

"""LLM-inference admission (ISSUE 17 — ROADMAP item 3).

A tokens-per-second (TPS) rule family with streaming reservations, the
cost-aware counterpart of the count-shaped flow family (SLINFER's
workload: wildly varying per-request token cost, per-model and
per-tenant budgets, pacing instead of binary reject — PAPERS.md).

Layout:

* ``rules.py``   — ``TpsRule`` + ``TpsRuleManager`` and the LOWERING:
  every TPS rule compiles onto the existing flow-rule machinery as a
  QPS-grade rule on the synthetic resource ``llm:{model}`` whose window
  debits count *tokens*, not requests (the fused step's mixed-count
  fixpoint path already carries N-token acquires exactly).  Degraded
  tenant-fair shares reuse the HA ``DegradedQuota`` math.
* ``streams.py`` — the host-side streaming-reservation ledger: an
  occupy-style estimate acquired up front as a lease that ticks down as
  output tokens stream, reconciled on completion/abort.

Timebase discipline: nothing in this package reads the wall clock —
every timestamp is the engine's ``now_ms()`` (pinned by test_lint), so
the simulator can drive streams deterministically.
"""

from sentinel_tpu.llm.rules import (
    DERIVED_TPS,
    LLM_RESOURCE_PREFIX,
    TpsRule,
    TpsRuleManager,
    degraded_tps_quota,
    llm_resource,
    lower_tps_rules,
    max_streams_by_resource,
)
from sentinel_tpu.llm.streams import StreamLease, StreamLedger

__all__ = [
    "DERIVED_TPS", "LLM_RESOURCE_PREFIX", "TpsRule", "TpsRuleManager",
    "degraded_tps_quota", "llm_resource", "lower_tps_rules",
    "max_streams_by_resource", "StreamLease", "StreamLedger",
]

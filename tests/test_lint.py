"""Syntax-rot and lint gates (CI/tooling tier-1 smoke).

Most datasource connector modules import lazily (their wire deps are
optional extras), so a syntax error in one can sit unnoticed until a
production config first selects it. ``compileall`` forces every module
through the parser/compiler on every tier-1 run. The ruff gate runs the
repo's pyproject config when a ruff binary is available (the container
image does not ship one; CI images that do get the full lint).
"""

import py_compile
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def test_compileall_package():
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f",
         str(REPO / "sentinel_tpu")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_compile_driver_entry_points():
    for name in ("__graft_entry__.py", "bench.py"):
        py_compile.compile(str(REPO / name), doraise=True)


def test_no_bare_print_in_package():
    """Telemetry goes through the record log / telemetry subsystem, not
    stdout: a bare ``print(`` in library code is invisible to operators
    scraping /metrics and pollutes embedding hosts' stdout. CLI entry
    points (``__main__.py``) are the one legitimate stdout surface."""
    import re

    pattern = re.compile(r"(?<![\w.])print\(")
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        if path.name == "__main__.py":
            continue  # CLI surface: user-facing stdout is the point
        in_doc = False
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            stripped = line.strip()
            # crude but sufficient docstring/comment skip for this gate
            if stripped.count('"""') % 2 == 1 or stripped.count("'''") % 2 == 1:
                in_doc = not in_doc
                continue
            if in_doc or stripped.startswith("#"):
                continue
            code = line.split("#", 1)[0]
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "bare print( in library code (route through record_log): "
        + ", ".join(offenders))


def _code_lines(path: Path):
    """(lineno, code) pairs with comments and (crudely) docstrings
    stripped — the same skip logic the bare-print gate uses."""
    in_doc = False
    for lineno, line in enumerate(path.read_text().splitlines(), 1):
        stripped = line.strip()
        if stripped.count('"""') % 2 == 1 or stripped.count("'''") % 2 == 1:
            in_doc = not in_doc
            continue
        if in_doc or stripped.startswith("#"):
            continue
        yield lineno, line.split("#", 1)[0]


def test_no_wall_clock_in_device_ops():
    """Device code (sentinel_tpu/ops/) must take ``now_ms`` as an
    argument: kernels cannot call clocks under jit, and an ambient
    ``time.time()``/``datetime.now()`` read in ops code either leaks a
    trace-time constant into the compiled program (frozen forever) or
    silently diverges host/device clocks. The module docstring of
    ops/window.py states the contract; this pins it."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(")
    offenders = []
    for path in sorted((REPO / "sentinel_tpu" / "ops").rglob("*.py")):
        for lineno, code in _code_lines(path):
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in device ops code (pass now_ms instead): "
        + ", ".join(offenders))


def test_no_wall_clock_in_simulator():
    """Replay must be deterministic BY CONSTRUCTION: the simulator
    (sentinel_tpu/simulator/) drives everything off the injected
    program clock, so an ambient wall-clock read anywhere in the
    package would silently couple a replay to the host's clock. Same
    rule (and skip logic) as the device-ops gate above; the one
    sanctioned wall read is ``time.perf_counter`` — it MEASURES replay
    speed (the ``sim_replay`` bench metric), it never drives replay."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    offenders = []
    for path in sorted((REPO / "sentinel_tpu" / "simulator").rglob("*.py")):
        for lineno, code in _code_lines(path):
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in simulator code (drive everything off the "
        "SimClock; perf_counter only for speed measurement): "
        + ", ".join(offenders))


def test_sim_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.sim.*`` config key must (a) be defined and
    read ONLY in core/config.py — the rest of the package goes through
    the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md "Trace capture & replay", so the runbook can
    never silently drift from the knobs the code actually reads (same
    rule shape as the cluster-HA / overload / pipeline gates)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.sim\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.sim.* literals outside core/config.py "
        "(use the SentinelConfig sim_* accessors): " + ", ".join(offenders))
    assert keys, "no sim config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "sim config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_fault_points_documented_and_wired():
    """Every name in ``resilience.faults.FAULT_POINTS`` must (a) appear
    in docs/OPERATIONS.md (the fault-point table operators arm in chaos
    drills) and (b) have at least one ``fire(``/``mutate(`` call site in
    the package — a fault point with no call site rots silently: tests
    arm it, nothing ever fires, and the drill asserts nothing."""
    import re
    import sys

    sys.path.insert(0, str(REPO))
    from sentinel_tpu.resilience.faults import FAULT_POINTS

    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(p for p in FAULT_POINTS if p not in ops)
    assert not undocumented, (
        "fault points missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))
    package_text = "\n".join(
        path.read_text()
        for path in sorted((REPO / "sentinel_tpu").rglob("*.py")))
    dead = []
    for point in FAULT_POINTS:
        pat = re.compile(
            r"(?:fire|mutate)(?:_targeted)?\(\s*[\"']"
            + re.escape(point) + r"[\"']")
        if not pat.search(package_text):
            dead.append(point)
    assert not dead, (
        "fault points with no fire(/mutate( call site (dead seams): "
        + ", ".join(dead))


def test_chaos_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.chaos.*`` config key must (a) be defined
    and read ONLY in core/config.py — the rest of the package goes
    through the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md "Chaos campaign", so the runbook can never
    silently drift from the knobs the code actually reads (same rule
    shape as the cluster-HA / overload / sim gates)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.chaos\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.chaos.* literals outside core/config.py "
        "(use the SentinelConfig chaos_* accessors): "
        + ", ".join(offenders))
    assert keys, "no chaos config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "chaos config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_no_wall_clock_in_chaos():
    """Chaos campaigns must be deterministic BY CONSTRUCTION: everything
    in sentinel_tpu/chaos/ runs on the engine timebase (the SimClock the
    campaign advances), so an ambient wall-clock read anywhere in the
    package would couple an episode's verdict stream to the host clock
    and void the seed-replay contract. Same rule (and skip logic) as the
    simulator/journal gates; ``time.perf_counter`` stays sanctioned — it
    MEASURES episodes/s, it never drives an episode."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    offenders = []
    for path in sorted((REPO / "sentinel_tpu" / "chaos").rglob("*.py")):
        for lineno, code in _code_lines(path):
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in chaos code (ride the campaign SimClock; "
        "perf_counter only for speed measurement): " + ", ".join(offenders))


def test_exported_metric_names_registered_exactly_once():
    """Every ``sentinel_tpu_*`` metric family must be declared exactly
    once across the telemetry exporters — a name declared twice renders
    duplicate ``# TYPE`` lines, which strict OpenMetrics parsers reject
    (and which silently splits one series across two declarations)."""
    import re

    # Two declaration sites: builder calls (b.family/b.counter) and the
    # _EVENT_FAMILIES-style tuple tables whose first element is the name.
    decl = re.compile(
        r"(?:b\.(?:family|counter)\(\s*|^\s*\()\"(sentinel_tpu_[a-z0-9_]+)\"")
    seen = {}
    dupes = []
    for path in sorted((REPO / "sentinel_tpu" / "telemetry").rglob("*.py")):
        for lineno, code in _code_lines(path):
            for name in decl.findall(code):
                where = f"{path.relative_to(REPO)}:{lineno}"
                if name in seen:
                    dupes.append(f"{name} ({seen[name]} and {where})")
                else:
                    seen[name] = where
    assert seen, "no exported metric declarations found (regex rot?)"
    assert not dupes, "metric family declared twice: " + ", ".join(dupes)
    # and the declarations must actually cover the families the live
    # exposition renders (catches emission helpers bypassing family())
    assert "sentinel_tpu_pass" in seen
    assert "sentinel_tpu_second_pass" in seen
    # the SLO engine's families (ISSUE 7): every sentinel_tpu_slo_* /
    # sentinel_tpu_alert_* family the exposition renders is declared
    # exactly once (the dupe gate above), and the load-bearing ones exist
    for name in ("sentinel_tpu_slo_burn_rate",
                 "sentinel_tpu_slo_health_score",
                 "sentinel_tpu_slo_instance_health",
                 "sentinel_tpu_alert_active",
                 "sentinel_tpu_alert_fired",
                 "sentinel_tpu_step_duration_ms"):
        assert name in seen, f"{name} not declared in the exporters"
    # adaptive-limiting families (ISSUE 10): declared exactly once (the
    # dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_adaptive_enabled",
                 "sentinel_tpu_adaptive_frozen",
                 "sentinel_tpu_adaptive_proposals",
                 "sentinel_tpu_adaptive_promotions",
                 "sentinel_tpu_adaptive_aborts",
                 "sentinel_tpu_adaptive_clamped",
                 "sentinel_tpu_adaptive_target_delta"):
        assert name in seen, f"{name} not declared in the exporters"
    # wire-path families (ISSUE 11): declared exactly once (the dupe
    # gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_wire_connections",
                 "sentinel_tpu_wire_coalesced_batch",
                 "sentinel_tpu_wire_rtt_ms",
                 "sentinel_tpu_wire_outbuf_shed"):
        assert name in seen, f"{name} not declared in the exporters"
    # sharded-cluster families (ISSUE 12): declared exactly once (the
    # dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_shard_slices_owned",
                 "sentinel_tpu_shard_slice_epoch",
                 "sentinel_tpu_shard_wrong_slice_rejected",
                 "sentinel_tpu_shard_handoffs",
                 "sentinel_tpu_shard_degraded_slices"):
        assert name in seen, f"{name} not declared in the exporters"
    # trace-replay simulator families (ISSUE 13): declared exactly once
    # (the dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_sim_lab_runs",
                 "sentinel_tpu_sim_replayed_seconds",
                 "sentinel_tpu_sim_replay_rate",
                 "sentinel_tpu_sim_policy_score"):
        assert name in seen, f"{name} not declared in the exporters"
    # fleet observability families (ISSUE 14): declared exactly once
    # (the dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_journal_last_seq",
                 "sentinel_tpu_journal_records",
                 "sentinel_tpu_journal_dropped_partial",
                 "sentinel_tpu_journal_rotations",
                 "sentinel_tpu_fleet_leaders",
                 "sentinel_tpu_fleet_stale_leaders",
                 "sentinel_tpu_fleet_health",
                 "sentinel_tpu_fleet_skew_ms",
                 "sentinel_tpu_fleet_polls"):
        assert name in seen, f"{name} not declared in the exporters"
    # chaos-campaign families (ISSUE 15): declared exactly once (the
    # dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_chaos_episodes",
                 "sentinel_tpu_chaos_violations",
                 "sentinel_tpu_chaos_faults_fired",
                 "sentinel_tpu_chaos_shrink_steps"):
        assert name in seen, f"{name} not declared in the exporters"
    # governed-rebalancer families (ISSUE 16): declared exactly once
    # (the dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_rebalance_plans",
                 "sentinel_tpu_rebalance_applies",
                 "sentinel_tpu_rebalance_rollbacks",
                 "sentinel_tpu_rebalance_vetoes",
                 "sentinel_tpu_rebalance_slices_moved",
                 "sentinel_tpu_rebalance_frozen",
                 "sentinel_tpu_rebalance_skew"):
        assert name in seen, f"{name} not declared in the exporters"
    # LLM-admission families (ISSUE 17): declared exactly once (the
    # dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_llm_rules",
                 "sentinel_tpu_llm_streams_active",
                 "sentinel_tpu_llm_streams_opened",
                 "sentinel_tpu_llm_streams_blocked",
                 "sentinel_tpu_llm_streams_aborted",
                 "sentinel_tpu_llm_streams_evicted",
                 "sentinel_tpu_llm_tokens_debited",
                 "sentinel_tpu_llm_tokens_streamed",
                 "sentinel_tpu_llm_tokens_released",
                 "sentinel_tpu_llm_reservation_outstanding",
                 "sentinel_tpu_llm_credit_tokens"):
        assert name in seen, f"{name} not declared in the exporters"
    # latency-waterfall families (ISSUE 18): declared exactly once (the
    # dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_waterfall_stage_ms",
                 "sentinel_tpu_waterfall_rtt_ms",
                 "sentinel_tpu_waterfall_stage_concurrency",
                 "sentinel_tpu_waterfall_device_utilization",
                 "sentinel_tpu_waterfall_coalesce_efficiency",
                 "sentinel_tpu_waterfall_seconds",
                 "sentinel_tpu_waterfall_exemplars",
                 "sentinel_tpu_waterfall_budget_ms"):
        assert name in seen, f"{name} not declared in the exporters"
    # namespace-telescope families (ISSUE 19): declared exactly once
    # (the dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_population_enabled",
                 "sentinel_tpu_population_observed",
                 "sentinel_tpu_population_distinct",
                 "sentinel_tpu_population_window_distinct",
                 "sentinel_tpu_population_ss_floor",
                 "sentinel_tpu_population_hot_mass",
                 "sentinel_tpu_population_churn_entered",
                 "sentinel_tpu_population_churn_exited",
                 "sentinel_tpu_population_cardinality_z",
                 "sentinel_tpu_population_cardinality_alarm",
                 "sentinel_tpu_population_fold_ms"):
        assert name in seen, f"{name} not declared in the exporters"
    # slot-table admission families (ISSUE 20): declared exactly once
    # (the dupe gate above) and every family the ISSUE names exists
    for name in ("sentinel_tpu_slots_budget",
                 "sentinel_tpu_slots_hot",
                 "sentinel_tpu_slots_free",
                 "sentinel_tpu_slots_pinned",
                 "sentinel_tpu_slots_frozen",
                 "sentinel_tpu_slots_admits",
                 "sentinel_tpu_slots_evictions",
                 "sentinel_tpu_slots_rehydrations",
                 "sentinel_tpu_slots_rehydrations_cold",
                 "sentinel_tpu_slots_steals",
                 "sentinel_tpu_slots_storms",
                 "sentinel_tpu_slots_hot_hits",
                 "sentinel_tpu_slots_cold_pass",
                 "sentinel_tpu_slots_cold_block",
                 "sentinel_tpu_slots_cold_unenforced",
                 "sentinel_tpu_slots_spill_torn",
                 "sentinel_tpu_slots_spill_dropped",
                 "sentinel_tpu_slots_spill_records",
                 "sentinel_tpu_slots_late_exits",
                 "sentinel_tpu_slots_pin_overflow",
                 "sentinel_tpu_slots_hit_rate",
                 "sentinel_tpu_registry_overflow"):
        assert name in seen, f"{name} not declared in the exporters"
    # pipelined-admission families (ISSUE 8): declared exactly once (the
    # dupe gate above) and the load-bearing ones exist
    for name in ("sentinel_tpu_pipeline_active",
                 "sentinel_tpu_pipeline_inflight_depth",
                 "sentinel_tpu_pipeline_inflight_depth_max",
                 "sentinel_tpu_pipeline_cycles",
                 "sentinel_tpu_pipeline_entries",
                 "sentinel_tpu_pipeline_fail_open_cycles",
                 "sentinel_tpu_pipeline_queue_wait_ms",
                 "sentinel_tpu_pipeline_device_wait_ms"):
        assert name in seen, f"{name} not declared in the exporters"


def test_cluster_ha_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.cluster.ha.*`` config key must (a) be defined
    and read ONLY in core/config.py — the rest of the package goes
    through the ``SentinelConfig`` accessors, so defaults/validation
    live in exactly one place — and (b) appear in docs/OPERATIONS.md,
    so the failover-drill runbook can never silently drift from the
    knobs the code actually reads."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.cluster\.ha\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.cluster.ha.* literals outside core/config.py "
        "(use the SentinelConfig cluster_ha_* accessors): "
        + ", ".join(offenders))
    assert keys, "no cluster HA config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "cluster HA config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_shard_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.cluster.shard.*`` config key must (a) be
    defined and read ONLY in core/config.py — the rest of the package
    goes through the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md, so the sharded-cluster runbook can never
    silently drift from the knobs the code actually reads (same rule
    shape as the cluster-HA gate above)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.cluster\.shard\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.cluster.shard.* literals outside core/config.py "
        "(use the SentinelConfig cluster_shard_* accessors): "
        + ", ".join(offenders))
    assert keys, "no cluster shard config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "cluster shard config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_slice_hashing_only_in_the_shared_routing_helper():
    """Client-side routing and server-side ownership checks must agree
    BYTE-FOR-BYTE on the flowId→slice mapping, so there is exactly one
    implementation: ``sharding.slice_of``. A re-implementation anywhere
    else in the package (a copied hash constant, a second ``slice_of``
    definition, or a bare flowId modulus) can silently diverge and void
    the per-slice fencing bound."""
    import re

    helper = Path("sentinel_tpu") / "cluster" / "sharding.py"
    mix = re.compile(r"0x9E3779B97F4A7C15", re.IGNORECASE)
    # Module-level definitions only: parallel/namespaces.py's
    # NamespaceShardMap.slice_of METHOD hashes NAMESPACES for host-side
    # pod routing — a different domain with no wire-agreement contract.
    defn = re.compile(r"^def\s+slice_of\s*\(")
    modulus = re.compile(r"flow_id\s*%|fid\s*%\s*n_slices")
    offenders = []
    seen_helper = False
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        is_helper = rel == helper
        for lineno, code in _code_lines(path):
            if is_helper:
                seen_helper = seen_helper or bool(defn.search(code))
                continue
            for pat, what in ((mix, "the slice-hash constant"),
                              (defn, "a second slice_of definition"),
                              (modulus, "a bare flowId modulus")):
                if pat.search(code):
                    offenders.append(f"{rel}:{lineno} carries {what}")
    assert seen_helper, "sharding.slice_of not found (helper moved?)"
    assert not offenders, (
        "flowId→slice hashing outside cluster/sharding.py "
        "(route through sharding.slice_of): " + ", ".join(offenders))


def test_no_unbounded_queues_in_serving_paths():
    """Serving-path code (the TLV token server, command plane, Envoy
    RLS, dashboard) must never hold an unbounded ``queue.Queue()``: an
    unbounded admission queue converts overload into unbounded latency
    and memory — the queue-collapse failure mode ISSUE 6 closed. Every
    queue on a request path needs an explicit ``maxsize`` (and a shed
    story for when it fills)."""
    import re

    pattern = re.compile(r"queue\.Queue\(\s*\)")
    offenders = []
    for sub in ("cluster", "transport", "envoy_rls", "dashboard"):
        for path in sorted((REPO / "sentinel_tpu" / sub).rglob("*.py")):
            for lineno, code in _code_lines(path):
                if pattern.search(code):
                    offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "unbounded queue.Queue() in a serving path (pass maxsize= and "
        "shed on full): " + ", ".join(offenders))


def test_overload_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.overload.*`` config key must (a) be defined
    and read ONLY in core/config.py — the rest of the package goes
    through the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md, so the overload runbook can never silently
    drift from the knobs the code actually reads (same rule shape as
    the cluster-HA gate above)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.overload\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.overload.* literals outside core/config.py "
        "(use the SentinelConfig overload_* accessors): "
        + ", ".join(offenders))
    assert keys, "no overload config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "overload config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_pipeline_cycle_path_never_allocates_staging_buffers():
    """The pipeline's cycle path must stage into the recycled
    ``BatchBufferPool`` (core/batch.py), never allocate: a
    ``make_entry_batch_np``/``make_exit_batch_np`` call inside
    core/pipeline.py re-introduces the per-cycle allocation ISSUE 8
    removed (and, with async dispatch, risks mutating a buffer a live
    transfer still reads)."""
    import re

    pattern = re.compile(r"\bmake_(?:entry|exit)_batch_np\s*\(")
    path = REPO / "sentinel_tpu" / "core" / "pipeline.py"
    offenders = [f"{path.relative_to(REPO)}:{lineno}"
                 for lineno, code in _code_lines(path)
                 if pattern.search(code)]
    assert not offenders, (
        "staging-buffer allocation in the pipeline cycle path (acquire "
        "from BatchBufferPool instead): " + ", ".join(offenders))


def test_pipeline_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.pipeline.*`` config key must (a) be defined
    and read ONLY in core/config.py — the rest of the package goes
    through the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md "Pipelined admission tuning", so the runbook can
    never silently drift from the knobs the code actually reads (same
    rule shape as the cluster-HA / overload / SLO gates)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.pipeline\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.pipeline.* literals outside core/config.py "
        "(use the SentinelConfig pipeline_* accessors): "
        + ", ".join(offenders))
    assert keys, "no pipeline config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "pipeline config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_slo_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.slo.*`` / ``csp.sentinel.alert.*`` config
    key must (a) be defined and read ONLY in core/config.py — the rest
    of the package goes through the ``SentinelConfig`` accessors — and
    (b) appear in docs/OPERATIONS.md "SLOs & alerting", so the runbook
    can never silently drift from the knobs the code actually reads
    (same rule shape as the cluster-HA and overload gates above)."""
    import re

    pattern = re.compile(
        r"[\"']csp\.sentinel\.(?:slo|alert)\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.slo.* / csp.sentinel.alert.* literals outside "
        "core/config.py (use the SentinelConfig slo_* / alert_* "
        "accessors): " + ", ".join(offenders))
    assert keys, "no SLO/alert config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "SLO/alert config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_adaptive_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.adaptive.*`` config key must (a) be defined
    and read ONLY in core/config.py — the rest of the package goes
    through the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md "Adaptive limiting", so the runbook can never
    silently drift from the knobs the code actually reads (same rule
    shape as the cluster-HA / overload / SLO / pipeline gates)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.adaptive\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.adaptive.* literals outside core/config.py "
        "(use the SentinelConfig adaptive_* accessors): "
        + ", ".join(offenders))
    assert keys, "no adaptive config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "adaptive config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_adaptive_actuates_only_through_the_rollout_manager():
    """The safety story of sentinel_tpu/adaptive/ is that EVERY rule
    change rides the staged-rollout lifecycle (shadow evaluation, the
    block-rate guardrail, the SLO auto-abort). A ``load_rules`` call —
    or any direct write into an engine rule manager — from inside the
    adaptive package would be an actuation path with no blast shield;
    so would constructing its own RolloutManager (a private manager
    shares no device state with the engine's). Forbid all three."""
    import re

    patterns = [
        # the wholesale rule-application entry point every family shares
        (re.compile(r"\.load_rules\s*\("), "load_rules("),
        # direct replacement of a rule manager on the engine
        (re.compile(r"\.(?:flow|degrade|authority|system|param)_rules\s*="),
         "rule-manager assignment"),
        (re.compile(r"RolloutManager\s*\("), "private RolloutManager"),
    ]
    offenders = []
    for path in sorted((REPO / "sentinel_tpu" / "adaptive").rglob("*.py")):
        for lineno, code in _code_lines(path):
            for pattern, what in patterns:
                if pattern.search(code):
                    offenders.append(
                        f"{path.relative_to(REPO)}:{lineno} ({what})")
    assert not offenders, (
        "adaptive code must actuate ONLY via the engine's RolloutManager "
        "(load_candidate/set_stage/promote/abort): " + ", ".join(offenders))


def test_wire_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.wire.*`` config key must (a) be defined and
    read ONLY in core/config.py — the rest of the package goes through
    the ``SentinelConfig`` accessors — and (b) appear in
    docs/OPERATIONS.md "Wire-path tuning", so the runbook can never
    silently drift from the knobs the code actually reads (same rule
    shape as the cluster-HA / overload / pipeline gates)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.wire\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.wire.* literals outside core/config.py "
        "(use the SentinelConfig wire_* accessors): " + ", ".join(offenders))
    assert keys, "no wire config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "wire config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_reactor_path_zero_copy_and_coalesced_writes():
    """The reactor ingest/egress hygiene gates (ISSUE 11):

    * no ``sendall(`` — every write must go through the per-connection
      coalesced non-blocking flush (one buffer per connection per
      flush), never a blocking per-request write;
    * no ``+= b...`` / rolling bytes accumulation — frame parsing is
      the zero-copy ``FrameScanner`` (memoryview slices), and reply
      buffers are chunk deques, not growing byte strings.
    """
    import re

    patterns = [
        (re.compile(r"\.sendall\s*\("), "per-request sendall"),
        (re.compile(r"\+=\s*(?:b[\"']|data\b|chunk\b|frame\b|body\b|"
                    r"raw\b|reply\b|payload\b)"),
         "rolling bytes accumulation"),
    ]
    path = REPO / "sentinel_tpu" / "cluster" / "reactor.py"
    offenders = []
    for lineno, code in _code_lines(path):
        for pattern, what in patterns:
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno} ({what})")
    assert not offenders, (
        "reactor wire path must stay zero-copy with coalesced "
        "non-blocking writes: " + ", ".join(offenders))


def test_journal_fleet_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.journal.*`` / ``csp.sentinel.fleet.*``
    config key must (a) be defined and read ONLY in core/config.py —
    the rest of the package goes through the ``SentinelConfig``
    accessors — and (b) appear in docs/OPERATIONS.md "Fleet
    observability & forensics", so the runbook can never silently
    drift from the knobs the code actually reads (same rule shape as
    the cluster-HA / overload / SLO / sim gates)."""
    import re

    pattern = re.compile(
        r"[\"']csp\.sentinel\.(?:journal|fleet)\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.journal.* / csp.sentinel.fleet.* literals outside "
        "core/config.py (use the SentinelConfig journal_* / fleet_* "
        "accessors): " + ", ".join(offenders))
    assert keys, "no journal/fleet config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "journal/fleet config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_no_wall_clock_in_journal_and_fleet():
    """The audit journal and the fleet collector must ride the ENGINE
    timebase only (injected clock callables): an ambient wall-clock
    read in either would (a) break the simulator's journal-determinism
    contract — the same trace + seed must replay to an identical
    record stream — and (b) let a collector's staleness/skew math mix
    two clocks. Same rule (and skip logic) as the simulator gate;
    ``time.perf_counter`` stays sanctioned for speed measurement."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    offenders = []
    for name in ("journal.py", "fleet.py"):
        path = REPO / "sentinel_tpu" / "telemetry" / name
        for lineno, code in _code_lines(path):
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in journal/fleet code (ride the injected "
        "engine clock): " + ", ".join(offenders))


def test_waterfall_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.waterfall.*`` config key must (a) be
    defined and read ONLY in core/config.py — the rest of the package
    goes through the ``SentinelConfig`` ``waterfall_*`` accessors — and
    (b) appear in docs/OPERATIONS.md "Latency waterfall & saturation
    probe", so the runbook can never silently drift from the knobs the
    code actually reads (same rule shape as the journal/fleet gate)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.waterfall\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.waterfall.* literals outside core/config.py (use "
        "the SentinelConfig waterfall_* accessors): "
        + ", ".join(offenders))
    assert keys, "no waterfall config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "waterfall config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_no_wall_clock_in_waterfall():
    """The waterfall recorder must ride the ENGINE timebase only: its
    per-second staging cells are what the simulator-inertness contract
    (ISSUE 13) seals, and an ambient wall-clock read would stamp them
    with a second clock. ``time.perf_counter`` stays sanctioned — it is
    the module's DURATION source (stage deltas, probe windows), never a
    timestamp. Same rule shape as the journal/fleet gate."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    path = REPO / "sentinel_tpu" / "telemetry" / "waterfall.py"
    offenders = []
    for lineno, code in _code_lines(path):
        if pattern.search(code):
            offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in the waterfall recorder (ride the injected "
        "engine clock; perf_counter is for durations only): "
        + ", ".join(offenders))


def test_population_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.population.*`` config key must (a) be
    defined and read ONLY in core/config.py — the rest of the package
    goes through the ``SentinelConfig`` ``population_*`` accessors —
    and (b) appear in docs/OPERATIONS.md "Namespace telescope &
    admission readiness", so the runbook can never silently drift from
    the knobs the code actually reads (same rule shape as the
    waterfall gate above)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.population\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.population.* literals outside core/config.py (use "
        "the SentinelConfig population_* accessors): "
        + ", ".join(offenders))
    assert keys, "no population config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "population config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_no_wall_clock_in_population():
    """The namespace telescope must ride the ENGINE timebase only: its
    churn windows and cardinality series are part of the replay-
    determinism contract (two runs of the same trace produce identical
    population series), and an ambient wall-clock read would stamp
    them with a second clock. ``time.perf_counter`` stays sanctioned —
    it is the fold's DURATION source (self-timed overhead counter),
    never a timestamp. Same rule shape as the waterfall gate."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    path = REPO / "sentinel_tpu" / "telemetry" / "population.py"
    offenders = []
    for lineno, code in _code_lines(path):
        if pattern.search(code):
            offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in the namespace telescope (ride the injected "
        "engine clock; perf_counter is for durations only): "
        + ", ".join(offenders))


def test_sketch_hashing_only_in_the_population_module():
    """Leader pages merge EXACTLY only if every tracker places a given
    key in the same count-min cells and HLL register, so there is
    exactly one sketch-hash implementation: ``population.sketch_hash``
    plus its splitmix64 row finalizer. A re-implementation anywhere
    else in the package (a copied mix constant or a second
    ``sketch_hash`` definition) can silently diverge and void the
    cell-wise merge identity (same rule shape as the slice-hashing
    gate)."""
    import re

    helper = Path("sentinel_tpu") / "telemetry" / "population.py"
    mix = re.compile(r"0xBF58476D1CE4E5B9", re.IGNORECASE)
    defn = re.compile(r"^def\s+sketch_hash\s*\(")
    offenders = []
    seen_helper = False
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        is_helper = rel == helper
        for lineno, code in _code_lines(path):
            if is_helper:
                seen_helper = seen_helper or bool(defn.search(code))
                continue
            for pat, what in ((mix, "the sketch-mix constant"),
                              (defn, "a second sketch_hash definition")):
                if pat.search(code):
                    offenders.append(f"{rel}:{lineno} carries {what}")
    assert seen_helper, "population.sketch_hash not found (helper moved?)"
    assert not offenders, (
        "sketch hashing outside telemetry/population.py (route through "
        "population.sketch_hash): " + ", ".join(offenders))


def test_slots_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.slots.*`` config key must (a) be defined
    and read ONLY in core/config.py — the rest of the package goes
    through the ``SentinelConfig`` ``slots_*`` accessors — and (b)
    appear in docs/OPERATIONS.md "Slot-table admission", so the
    runbook can never silently drift from the knobs the code actually
    reads (same rule shape as the population gate above)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.slots\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.slots.* literals outside core/config.py (use the "
        "SentinelConfig slots_* accessors): " + ", ".join(offenders))
    assert keys, "no slots config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "slots config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_no_wall_clock_in_slots():
    """The slot table must ride the ENGINE timebase only: admit/evict
    stamps, spill-record ages, the rebalance throttle, and the
    staleness freeze gate are all part of the replay-determinism
    contract (the SlotStormCampaign's sha256 oracles replay episodes
    bit-identically), and an ambient wall-clock read would stamp them
    with a second clock. Same rule shape as the population gate."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    path = REPO / "sentinel_tpu" / "core" / "slots.py"
    offenders = []
    for lineno, code in _code_lines(path):
        if pattern.search(code):
            offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in the slot table (take now_ms from the "
        "caller — the engine clock): " + ", ".join(offenders))


def test_slot_translation_single_implementation():
    """There is exactly ONE resource -> device-slot translation:
    ``SlotTable.device_row`` (plus the engine's thin ``_device_row_of``
    dispatcher that falls back to the registry in fixed-capacity
    mode). A second ``def device_row`` — or any module outside
    core/slots.py and core/engine.py reaching into the private
    ``_hot`` tenancy map — could translate against stale tenancy and
    book state onto a reused slot's successor, the exact leak the
    generation stamps exist to prevent."""
    import re

    defn = re.compile(r"^\s*def\s+device_row\s*\(")
    hot = re.compile(r"\bslots?\._hot\b|\.slots\._hot\b")
    sanctioned = {Path("sentinel_tpu") / "core" / "slots.py"}
    hot_ok = sanctioned | {Path("sentinel_tpu") / "core" / "engine.py"}
    defs = []
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            if defn.search(code):
                defs.append((rel, lineno))
            if rel not in hot_ok and hot.search(code):
                offenders.append(f"{rel}:{lineno} touches the private "
                                 "tenancy map")
    assert [d for d in defs if d[0] in sanctioned], \
        "SlotTable.device_row not found (helper moved?)"
    stray = [f"{rel}:{line}" for rel, line in defs
             if rel not in sanctioned]
    assert not stray, ("second device_row translation implementation: "
                      + ", ".join(stray))
    assert not offenders, (
        "slot tenancy read outside the sanctioned modules (go through "
        "SlotTable's accessors): " + ", ".join(offenders))


def test_rebalance_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.rebalance.*`` config key must (a) be
    defined and read ONLY in core/config.py — the rest of the package
    goes through the ``SentinelConfig`` rebalance_* accessors — and
    (b) appear in docs/OPERATIONS.md "Self-driving rebalancing", so the
    runbook can never silently drift from the knobs the code actually
    reads (same rule shape as the journal/fleet gate)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.rebalance\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.rebalance.* literals outside core/config.py (use "
        "the SentinelConfig rebalance_* accessors): "
        + ", ".join(offenders))
    assert keys, "no rebalance config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "rebalance config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_rebalancer_actuates_only_through_ha_apply():
    """The rebalancer's ONLY shard-state mutation is ``ha.apply_map``:
    it must never call the HA internals, assign a shard map, or touch
    a token service's shard state directly — everything it does to the
    cluster flows through the same journal-audited, fault-seamed map
    path the datasource uses (the provenance + veto story depends on
    this single choke point)."""
    import re

    patterns = [
        (re.compile(r"apply_shard_map\s*\("), "apply_shard_map call"),
        (re.compile(r"\.shard_map\s*="), "shard_map assignment"),
        (re.compile(r"_become_"), "HA transition internal"),
        (re.compile(r"set_shard\s*\("), "set_shard call"),
        (re.compile(r"\.slice_epochs\s*="), "epoch table assignment"),
    ]
    path = REPO / "sentinel_tpu" / "cluster" / "rebalance.py"
    offenders = []
    for lineno, code in _code_lines(path):
        for pattern, what in patterns:
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno} ({what})")
    assert not offenders, (
        "rebalancer must mutate shard state only via ha.apply_map: "
        + ", ".join(offenders))


def test_no_wall_clock_in_rebalance():
    """The rebalancer rides the injected clock / engine timebase only:
    its cooldown stamps, freeze-gate staleness math, and certify
    episodes must all replay deterministically — one ambient wall-clock
    read would make a certify veto (or a cooldown) irreproducible from
    the campaign seed. Same rule as the journal/fleet gate."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    path = REPO / "sentinel_tpu" / "cluster" / "rebalance.py"
    offenders = []
    for lineno, code in _code_lines(path):
        if pattern.search(code):
            offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in rebalance.py (ride the injected clock): "
        + ", ".join(offenders))


def test_llm_config_keys_accessor_only_and_documented():
    """Every ``csp.sentinel.llm.*`` config key must (a) be defined and
    read ONLY in core/config.py — the rest of the package goes through
    the ``SentinelConfig`` llm_* accessors — and (b) appear in
    docs/OPERATIONS.md "LLM admission & streaming reservations", so the
    runbook can never silently drift from the knobs the code actually
    reads (same rule shape as the cluster-HA / overload / sim gates)."""
    import re

    pattern = re.compile(r"[\"']csp\.sentinel\.llm\.[a-z.]+[\"']")
    keys = set()
    offenders = []
    for path in sorted((REPO / "sentinel_tpu").rglob("*.py")):
        rel = path.relative_to(REPO)
        for lineno, code in _code_lines(path):
            for m in pattern.findall(code):
                key = m.strip("\"'")
                keys.add(key)
                if path.name != "config.py":
                    offenders.append(f"{rel}:{lineno} reads {key!r}")
    assert not offenders, (
        "csp.sentinel.llm.* literals outside core/config.py "
        "(use the SentinelConfig llm_* accessors): " + ", ".join(offenders))
    assert keys, "no llm config keys found (regex rot?)"
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    undocumented = sorted(k for k in keys if k not in ops)
    assert not undocumented, (
        "llm config keys missing from docs/OPERATIONS.md: "
        + ", ".join(undocumented))


def test_no_wall_clock_in_llm():
    """The streaming-reservation ledger (sentinel_tpu/llm/) rides the
    engine timebase only — every public entry point takes ``now_ms``.
    An ambient wall-clock read would couple credit expiry / idle
    eviction to the host clock and void both the replay-determinism
    contract and the numpy differential oracle (tests/test_llm.py).
    Same rule (and skip logic) as the simulator/chaos gates."""
    import re

    pattern = re.compile(
        r"\btime\.time\(|\bdatetime\.now\(|\btime\.monotonic\(|"
        r"\btime_util\.current_time_millis\(")
    offenders = []
    for path in sorted((REPO / "sentinel_tpu" / "llm").rglob("*.py")):
        for lineno, code in _code_lines(path):
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno}")
    assert not offenders, (
        "wall-clock read in llm code (take now_ms from the engine "
        "timebase): " + ", ".join(offenders))


def test_journal_writes_append_only():
    """The journal's crash-safety story is append-only JSONL: recovery
    may terminate a torn line (an append) and rotation may RENAME the
    live file aside, but nothing ever seeks, truncates, or reopens the
    file in a write-from-scratch mode — a rewrite would turn 'crash
    leaves every committed record intact' into a race."""
    import re

    patterns = [
        (re.compile(r"\.seek\s*\("), "seek"),
        (re.compile(r"\.truncate\s*\("), "truncate"),
        (re.compile(r"open\s*\([^)]*[\"']w\+?b?[\"']"),
         "write-mode open"),
        (re.compile(r"open\s*\([^)]*[\"']r\+"), "read-write open"),
    ]
    path = REPO / "sentinel_tpu" / "telemetry" / "journal.py"
    offenders = []
    for lineno, code in _code_lines(path):
        for pattern, what in patterns:
            if pattern.search(code):
                offenders.append(f"{path.relative_to(REPO)}:{lineno} ({what})")
    assert not offenders, (
        "journal file writes must stay append-only: " + ", ".join(offenders))


@pytest.mark.skipif(shutil.which("ruff") is None,
                    reason="ruff binary not in this image")
def test_ruff_clean():
    proc = subprocess.run(
        ["ruff", "check", "--no-cache", str(REPO)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr

"""Cross-pod namespace sharding over a (dcn, ici) device mesh.

Reference architecture being replaced (SURVEY.md §2.10 "Namespace
sharding"): the token server groups clients/rules/limits by namespace
(``cluster-server:connection/ConnectionGroup.java`` +
``ClusterServerConfigManager``'s namespace set) — one server process owns
each namespace's global windows.

TPU-native design, two layers:

* **Device layer** — :func:`make_dcn_pod_steps` shard_maps the admission
  step over a 2-axis mesh ``("dcn", "ici")``: one ``ici`` row per pod
  (slice), the ``dcn`` axis spanning pods. Cluster rules choose their
  reduction scope per rule: default pod scope psums over ``ici`` only
  (each slice enforces its own quota — a sharded namespace), while
  ``cluster_config={"scope": "global"}`` rules psum over BOTH axes, so
  one quota spans every pod. On real hardware XLA routes the inner
  reduction over ICI and the outer one over DCN — exactly the
  "collectives ride ICI, cross-pod goes DCN" recipe; the virtual CPU
  mesh proves the same program shape.
* **Host layer** — :class:`NamespaceShardMap` assigns namespaces to pod
  slices (explicit pins or stable hashing) so host frontends (TCP token
  server, RLS, engines' cluster clients) route each namespace's acquire
  stream to the slice that owns its windows; reassignment on slice loss
  is a map update, mirroring the reference's ops-driven server flips.
"""

from __future__ import annotations

import functools
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.ops import step as S
from sentinel_tpu.ops import window as W
from sentinel_tpu.parallel.cluster import (
    _shard_map,
    global_next_window,
    global_pass_counts,
)

from jax.sharding import Mesh, PartitionSpec as P

DCN_AXIS = "dcn"
ICI_AXIS = "ici"


# ---------------------------------------------------------------------------
# Host layer: namespace -> pod-slice routing
# ---------------------------------------------------------------------------


class NamespaceShardMap:
    """namespace -> slice assignment (ConnectionGroup analog, host side)."""

    def __init__(self, n_slices: int):
        if n_slices <= 0:
            raise ValueError("need at least one slice")
        self.n_slices = n_slices
        self._lock = threading.Lock()
        self._pins: Dict[str, int] = {}
        self._down: set = set()

    def _hash_slice(self, namespace: str) -> int:
        digest = hashlib.sha1(namespace.encode("utf-8")).digest()
        return int.from_bytes(digest[:4], "big") % self.n_slices

    def slice_of(self, namespace: str) -> int:
        """Owning slice: explicit pin wins, else stable hash; a down slice
        fails over deterministically to the next live one."""
        with self._lock:
            s = self._pins.get(namespace, self._hash_slice(namespace))
            if s not in self._down:
                return s
            for step in range(1, self.n_slices):
                cand = (s + step) % self.n_slices
                if cand not in self._down:
                    return cand
            raise RuntimeError("all slices down")

    def pin(self, namespace: str, slice_id: int) -> None:
        if not (0 <= slice_id < self.n_slices):
            raise ValueError(f"slice {slice_id} out of range")
        with self._lock:
            self._pins[namespace] = slice_id

    def mark_down(self, slice_id: int) -> None:
        with self._lock:
            self._down.add(slice_id)

    def mark_up(self, slice_id: int) -> None:
        with self._lock:
            self._down.discard(slice_id)

    def assignments(self, namespaces: List[str]) -> Dict[str, int]:
        return {ns: self.slice_of(ns) for ns in namespaces}


# ---------------------------------------------------------------------------
# Device layer: 2-axis pod steps
# ---------------------------------------------------------------------------


def _squeeze2(tree):
    return jax.tree.map(lambda x: jnp.squeeze(jnp.squeeze(x, 0), 0), tree)


def _expand2(tree):
    return jax.tree.map(lambda x: x[None, None], tree)


def _dcn_entry(state, rules, batch, now_ms, *, cluster_param: bool,
               global_scope: bool, extra_checkers: tuple):
    # Inside shard_map each leaf carries leading [1, 1] (dcn, ici) axes.
    local = _squeeze2(state)
    now_ms = jnp.asarray(now_ms, jnp.int64)
    w1 = W.rotate(local.w1, now_ms, S.SPEC_1S)

    # Pod scope reduces over ICI only; global scope over both axes (psum
    # takes the axis tuple — same helpers as the 1-axis pod path, so the
    # window/borrow geometry cannot diverge between the two).
    extra_pass, _ = global_pass_counts(w1, ICI_AXIS)
    extra_next = global_next_window(w1, local.occupied_next, now_ms, ICI_AXIS)
    extra_pass_global = extra_next_global = None
    if global_scope:
        extra_pass_global, _ = global_pass_counts(w1, (DCN_AXIS, ICI_AXIS))
        extra_next_global = global_next_window(
            w1, local.occupied_next, now_ms, (DCN_AXIS, ICI_AXIS))

    extra_cms = None
    if cluster_param:
        from sentinel_tpu.models import param_flow as PF

        local = local._replace(param=PF.roll_sketch_windows(
            rules.param, local.param, now_ms))
        # Param sketches reduce pod-wide; global-scope param rules would
        # psum over DCN too — kept pod-scope until a rule asks for it.
        extra_cms = jax.lax.psum(local.param.cms, ICI_AXIS) - local.param.cms

    new_local, dec = S.entry_step(
        local._replace(w1=w1), rules, batch, now_ms,
        extra_pass=extra_pass, extra_next=extra_next, extra_cms=extra_cms,
        extra_checkers=extra_checkers,
        extra_pass_global=extra_pass_global,
        extra_next_global=extra_next_global)
    return _expand2(new_local), dec


def _dcn_exit(state, rules, batch, now_ms):
    return _expand2(S.exit_step(_squeeze2(state), rules, batch, now_ms))


def make_dcn_mesh(n_slices: int, per_slice: int,
                  devices: Optional[list] = None) -> Mesh:
    """(dcn, ici) mesh from the first n_slices*per_slice devices."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    need = n_slices * per_slice
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_slices, per_slice)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def make_dcn_pod_steps(mesh: Mesh, cluster_param: bool = True,
                       global_scope: bool = True):
    """(entry_step, exit_step) shard_mapped over a (dcn, ici) mesh.

    State leaves carry leading [n_slices, per_slice] axes
    (see :func:`make_dcn_pod_state`); batches shard over both axes
    flattened (request i goes to device i // per_dev — the host router
    places each namespace's requests on its owning slice's rows).

    ``global_scope=False`` drops the DCN-axis all-reduces (the slow
    inter-slice hop) for deployments whose cluster rules are all
    pod-scope — a static choice like ``cluster_param``.
    """
    from sentinel_tpu.core import spi as _spi

    entry = _shard_map(
        functools.partial(_dcn_entry, cluster_param=cluster_param,
                          global_scope=global_scope,
                          extra_checkers=_spi.device_checkers()),
        mesh=mesh,
        in_specs=(P(DCN_AXIS, ICI_AXIS), P(), P((DCN_AXIS, ICI_AXIS)), P()),
        out_specs=(P(DCN_AXIS, ICI_AXIS), P((DCN_AXIS, ICI_AXIS))),
        # No shard_map replication rule for the fixpoint while_loop —
        # see make_pod_steps (parallel/cluster.py) for the rationale.
        check_rep=False,
    )
    exit_ = _shard_map(
        _dcn_exit,
        mesh=mesh,
        in_specs=(P(DCN_AXIS, ICI_AXIS), P(), P((DCN_AXIS, ICI_AXIS)), P()),
        out_specs=P(DCN_AXIS, ICI_AXIS),
        check_rep=False,
    )
    return entry, exit_


def make_dcn_pod_state(n_slices: int, per_slice: int,
                       one: S.SentinelState) -> S.SentinelState:
    """Replicated-structure state with leading [n_slices, per_slice]."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None, None],
                                   (n_slices, per_slice) + x.shape), one)

"""Marginal on-chip cost of each rule family inside the scanned bench step.

Times the bench_throughput configuration (capacity 32768, batch 8192,
16-step scan) with one family removed at a time; the delta vs full is the
family's true fused cost. Scratch tool, not a test.
"""

import time

import numpy as np
import jax
import jax.numpy as jnp


def build(n_resources=10_000, capacity=32_768, batch_n=8192,
          with_flow=True, with_degrade=True, with_param=True,
          with_system=True):
    from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.models import authority as A
    from sentinel_tpu.models import degrade as D
    from sentinel_tpu.models import flow as F
    from sentinel_tpu.models import param_flow as P
    from sentinel_tpu.models import system as Y
    from sentinel_tpu.ops import step as S

    now0 = 1_700_000_000_000
    reg = NodeRegistry(capacity)
    rules = ([F.FlowRule(resource=f"res{i}", count=1e9, control_behavior=0)
              for i in range(0, n_resources, 10)] if with_flow else [])
    degrade_rules = ([D.DegradeRule(resource=f"res{i}", count=100,
                                    grade=i % 3, time_window=10)
                      for i in range(0, n_resources, 20)]
                     if with_degrade else [])
    param_rules = ([P.ParamFlowRule(f"res{i}", param_idx=0, count=1e9)
                    for i in range(0, n_resources, 40)] if with_param else [])
    sys_rules = [Y.SystemRule(qps=1e12)] if with_system else []
    ctx = "sentinel_default_context"
    ent_row = reg.entrance_row(ctx)
    c_rows = np.asarray([reg.cluster_row(f"res{i}")
                         for i in range(n_resources)])
    d_rows = np.asarray([reg.default_row(ctx, f"res{i}", ent_row)
                         for i in range(n_resources)])
    ft, _ = F.compile_flow_rules(rules, reg, capacity)
    dt, di = D.compile_degrade_rules(degrade_rules, reg, capacity)
    pt = P.compile_param_rules(param_rules, reg, capacity)
    pack = S.RulePack(flow=ft, degrade=dt,
                      authority=A.compile_authority_rules([], reg, capacity),
                      system=Y.compile_system_rules(sys_rules),
                      param=pt)
    state = S.make_state(capacity, ft.num_rules, now0,
                         degrade=D.make_degrade_state(dt, di),
                         param=P.make_param_state(pt.num_rules))
    rng = np.random.default_rng(0)
    buf = make_entry_batch_np(batch_n)
    pick = rng.integers(0, n_resources, size=batch_n)
    buf["cluster_row"][:] = c_rows[pick]
    buf["dn_row"][:] = d_rows[pick]
    buf["count"][:] = 1
    buf["param_hash"][:, 0] = rng.integers(1, 1 << 31, size=batch_n)
    buf["param_present"][:, 0] = True
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    return S, pack, state, batch, now0


def time_config(scan_steps=16, iters=10, **kw):
    S, pack, state, batch, now0 = build(**kw)

    def multi(state, now_start):
        def body(st_, i):
            st_, dec = S.entry_step(st_, pack, batch, now_start + i)
            return st_, dec.reason[0]
        return jax.lax.scan(body, state,
                            jnp.arange(scan_steps, dtype=jnp.int64))

    step = jax.jit(multi, donate_argnums=(0,))
    state, _ = step(state, jnp.asarray(now0, jnp.int64))
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for i in range(1, iters + 1):
        state, last = step(state, jnp.asarray(now0 + i * scan_steps,
                                              jnp.int64))
    jax.block_until_ready(last)
    dt_ = time.perf_counter() - t0
    per_step_ms = dt_ / (iters * scan_steps) * 1e3
    return per_step_ms


if __name__ == "__main__":
    print(f"platform: {jax.devices()[0].platform}")
    full = time_config()
    print(f"full:        {full:7.3f} ms/step")
    for name, kw in [("no_param", dict(with_param=False)),
                     ("no_degrade", dict(with_degrade=False)),
                     ("no_system", dict(with_system=False)),
                     ("no_flow", dict(with_flow=False)),
                     ("flow_only", dict(with_param=False,
                                        with_degrade=False,
                                        with_system=False))]:
        ms = time_config(**kw)
        print(f"{name:12s} {ms:7.3f} ms/step   (marginal {full - ms:+6.3f})")

"""SLO engine: burn-rate alerting, anomaly baselines, health scoring.

The flight recorder (PRs 3-4) gave the system exact per-second senses;
this package gives it judgement. Declarative per-resource objectives
(:mod:`objectives`) are evaluated every COMPLETE second from the exact
``telemetry/timeseries.py`` series with SRE-style multi-window burn-rate
rules; resources with no explicit objective get a rolling EWMA baseline
(:mod:`baseline`) with z-score breach detection; both roll up into a
composite health score per resource and per instance (:mod:`manager`).
Alert transitions fan out to webhooks (:mod:`webhook`), the ``alerts``
ops command, ``sentinel_tpu_slo_*``/``sentinel_tpu_alert_*`` gauges,
the dashboard's ``/alerts.json`` + SSE ``event: alert`` frames, and the
rollout guardrail's auto-abort signal.

Everything here is host-side arithmetic over seconds the device already
folded once per second — SLO evaluation adds ZERO per-step device work
(pinned by the A/B guard in tests/test_slo.py).
"""

from sentinel_tpu.slo.baseline import EwmaBaseline
from sentinel_tpu.slo.manager import SloManager
from sentinel_tpu.slo.objectives import (
    BurnWindow,
    DEFAULT_BURN_WINDOWS,
    SloObjective,
)
from sentinel_tpu.slo.webhook import AlertWebhook

__all__ = [
    "AlertWebhook",
    "BurnWindow",
    "DEFAULT_BURN_WINDOWS",
    "EwmaBaseline",
    "SloManager",
    "SloObjective",
]

"""Pipelined admission: async double-buffered micro-batched device steps.

SURVEY.md §7 hard part #1: a device dispatch costs ~10-100µs, so per-request
synchronous steps cap throughput at ~1/dispatch and serialize callers on the
engine lock. This module runs a collector thread that drains concurrently
submitted entries/exits into ONE fused step per cycle: p99 latency ≈ queue
wait + one step, and throughput scales with batch width instead of dispatch
rate — the host-side half of the reference's "statistics are lock-free"
property (all mutation rides one linearized step stream).

Double buffering (ISSUE 8): the collector never blocks on a verdict right
after dispatching it. Each cycle splits into three overlapped phases —

  * **stage** cycle N+1's batch into a recycled buffer-pool slot
    (``core/batch.py::BatchBufferPool`` — no per-cycle allocation) while
  * **compute** for cycle N is still in flight on the device (JAX async
    dispatch returns lazy ``Decisions``; the engine-lock critical section
    is enqueue-only), and
  * **harvest** resolves cycle N−1's tickets from the now-materialized
    device arrays.

Up to ``inflight_depth`` entry cycles ride the stream at once (default 2 =
classic double buffering, ``csp.sentinel.pipeline.inflight.depth``). Steps
are dispatched in submission order on one device stream with a strict data
dependency through the donated engine state, so completion order equals
dispatch order and the width-1 ordering proof extends unchanged to depth>1
(docs/SEMANTICS.md "Pipeline ordering").

Ordering guarantees: exits drain BEFORE entries each cycle, and submissions
are drained FIFO, so a thread's exit→entry program order is preserved
(THREAD-grade concurrency gauges stay exact). Batch widths come from the
engine's jit-cache ladder; a cycle never splits one submission. An idle
queue triggers an immediate harvest of everything in flight, so the
latency floor without concurrency stays one step, exactly as before.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Deque, List, Optional

from sentinel_tpu.core.batch import (
    BATCH_WIDTHS as LADDER,
    BatchBufferPool,
    EntryBatch,
    ExitBatch,
    MAX_PARAMS,
)


def _ladder_width(n: int) -> int:
    for w in LADDER:
        if n <= w:
            return w
    return LADDER[-1]


class _EntryTicket:
    __slots__ = ("fields", "done", "reason", "wait_us", "submit_ts")

    def __init__(self, fields):
        self.fields = fields  # dict of scalar batch fields (+params tuple)
        self.done = threading.Event()
        self.reason = -1
        self.wait_us = 0
        self.submit_ts = time.perf_counter()


class _ExitTicket:
    __slots__ = ("fields", "retried")

    def __init__(self, fields):
        self.fields = fields
        self.retried = False


class _InFlight:
    """One dispatched entry cycle awaiting harvest: its tickets, the lazy
    device Decisions, the pooled buffers the dispatch may still be
    reading, and the queue-wait already accrued at dispatch time."""

    __slots__ = ("entries", "dec", "bufs", "queue_wait_ms")

    def __init__(self, entries, dec, bufs, queue_wait_ms):
        self.entries = entries
        self.dec = dec
        self.bufs = bufs  # [(kind, buf), ...] released on harvest
        self.queue_wait_ms = queue_wait_ms


class Pipeline:
    """The collector loop bound to one engine."""

    def __init__(self, engine, max_batch: int = LADDER[-1],
                 linger_s: Optional[float] = None,
                 inflight_depth: Optional[int] = None,
                 pool_widths: Optional[tuple] = None):
        from sentinel_tpu.core.config import config as _cfg

        self.engine = engine
        self.max_batch = max_batch
        self.linger_s = (linger_s if linger_s is not None
                         else _cfg.pipeline_linger_us() / 1e6)
        self.inflight_depth = max(1, int(
            inflight_depth if inflight_depth is not None
            else _cfg.pipeline_inflight_depth()))
        widths = pool_widths
        if widths is None:
            # Every ladder width a cycle can actually hit: item counts
            # cap at max_batch, but the staged width rounds UP the
            # ladder (16 items -> a width-64 buffer).
            widths = _cfg.pipeline_pool_widths() \
                or tuple(w for w in LADDER
                         if w <= _ladder_width(max_batch))
        self.pool = BatchBufferPool(prealloc_widths=widths)
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.join_timeout_s = 2.0
        self.closed = False
        self.cycles = 0
        self.batched = 0
        self.harvests = 0
        self.fail_open_cycles = 0
        # In-flight bookkeeping: collector-thread-only mutation; readers
        # (stats, gauges) take len() snapshots, which the GIL keeps safe.
        self._inflight: Deque[_InFlight] = collections.deque()
        self.max_inflight = 0
        # Exit-only cycles have no harvest point of their own; their
        # buffers ride here until they can be folded into the NEXT
        # dispatched entry cycle's record — that cycle dispatches after
        # them on the ordered stream, so ITS harvest (not an older
        # cycle's) proves the exit transfer completed. Never released
        # from here directly.
        self._orphan_bufs: List[tuple] = []

    # -- submission (any thread) ------------------------------------------

    def submit_entry(self, fields) -> Optional[_EntryTicket]:
        """None once the pipeline is closed (caller takes the sync path)."""
        if self.closed:
            return None
        ticket = _EntryTicket(fields)
        self._queue.put(ticket)
        return ticket

    def submit_exit(self, fields) -> bool:
        if self.closed:
            return False
        self._queue.put(_ExitTicket(fields))
        return True

    # -- stats (any thread) ------------------------------------------------

    def inflight_depth_now(self) -> int:
        return len(self._inflight)

    def stats(self) -> dict:
        return {
            "cycles": self.cycles,
            "batched": self.batched,
            "harvests": self.harvests,
            "failOpenCycles": self.fail_open_cycles,
            "inflightDepth": len(self._inflight),
            "inflightDepthMax": self.max_inflight,
            "configuredDepth": self.inflight_depth,
            "poolAllocated": self.pool.allocated,
            "poolReused": self.pool.reused,
        }

    # -- the loop ----------------------------------------------------------

    def start(self) -> "Pipeline":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="sentinel-pipeline", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        from sentinel_tpu.log.record_log import record_log

        self.closed = True  # reject new submissions first
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=self.join_timeout_s)
            if thread.is_alive():
                # The collector is wedged mid-cycle (a hung dispatch or a
                # compile that outlived the join budget). Running the
                # inline drain now would have TWO threads calling _cycle
                # against one engine state — the double-drain race. Refuse
                # loudly: stragglers resolve when (if) the collector's
                # final drain runs; callers time out into the documented
                # fail-open path either way.
                record_log.warn(
                    "pipeline collector still alive after %.1fs join; "
                    "refusing inline drain (collector owns the cycle)",
                    self.join_timeout_s)
                return
        # Collector is gone: flush stragglers that beat the closed flag,
        # then resolve anything still in flight. No harvest can run after
        # stop() returns — the deque is empty and the thread is dead.
        # Orphaned exit buffers are deliberately NOT recycled (nothing
        # proved their transfers done); the pool dies with the pipeline.
        # A dead backend mid-drain fails that cycle's tickets open inside
        # _cycle — swallow the re-raise and keep draining, so stop()
        # always returns with every ticket resolved and the caller's
        # stats fold always runs.
        while True:
            try:
                if not self._drain_cycle():
                    break
            except Exception as ex:  # noqa: BLE001 — keep draining
                record_log.warn("pipeline stop drain failed: %r", ex)
        self._harvest_all()
        self._orphan_bufs = []

    def _run(self):
        from sentinel_tpu.log.record_log import record_log

        while not self._stop.is_set():
            try:
                if not self._drain_cycle():
                    if self._inflight:
                        # Queue idle with work in flight: resolve the
                        # oldest cycle now — the no-concurrency latency
                        # floor stays one step.
                        self._harvest_one()
                        continue
                    # Nothing pending: block until the next submission,
                    # then fold it into a normal lingered cycle so a
                    # burst's first arrival doesn't run as its own
                    # width-1 step.
                    try:
                        item = self._queue.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    self._drain_cycle(initial=[item])
            except Exception as ex:  # keep the loop alive, fail the cycle
                record_log.warn("pipeline cycle failed: %r", ex)
        self._harvest_all()  # resolve every in-flight ticket before exit

    def _drain_cycle(self, initial=None) -> bool:
        items = list(initial) if initial else []
        while len(items) < self.max_batch:
            try:
                items.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if not items:
            return False
        if self.linger_s and len(items) < self.max_batch:
            # Brief linger folds late-arriving concurrent callers in.
            deadline = threading.Event()
            deadline.wait(self.linger_s)
            while len(items) < self.max_batch:
                try:
                    items.append(self._queue.get_nowait())
                except queue.Empty:
                    break
        self._cycle(items)
        # Depth cap: with the configured number of cycles already in
        # flight, resolve the oldest BEFORE staging another — this wait
        # overlaps the younger cycles' device compute, which is the whole
        # point of the double buffer.
        while len(self._inflight) >= self.inflight_depth:
            self._harvest_one()
        return True

    def _cycle(self, items: List):
        exits = [t for t in items if isinstance(t, _ExitTicket)]
        entries = [t for t in items if isinstance(t, _EntryTicket)]
        exit_bufs: List[tuple] = []
        # Exits first: program order for exit→entry on one thread. A failed
        # exit flush is re-enqueued once — dropping exits would leak the
        # concurrency gauge permanently.
        if exits:
            try:
                exit_bufs.append(("exit", self._flush_exits(exits)))
            except Exception:
                retry = [t for t in exits if not t.retried]
                for t in retry:
                    t.retried = True
                    self._queue.put(t)
                if not retry:  # second failure: give up loudly
                    raise
        if entries:
            try:
                self._flush_entries(entries, exit_bufs)
            except Exception:
                # The exit dispatch (if any) succeeded — its buffers are
                # merely awaiting a later sync point, like any orphan.
                self._orphan_bufs.extend(exit_bufs)
                for t in entries:
                    t.reason = -2  # engine error: caller passes unguarded
                    t.done.set()
                self.fail_open_cycles += 1
                raise
        elif exit_bufs:
            self._orphan_bufs.extend(exit_bufs)

    def _flush_exits(self, exits: List[_ExitTicket]):
        width = _ladder_width(len(exits))
        buf = self.pool.acquire("exit", width)
        for i, t in enumerate(exits):
            f = t.fields
            for k in ("cluster_row", "dn_row", "origin_row", "entry_in",
                      "count", "rt_ms", "success", "error"):
                buf[k][i] = f[k]
            for j, h in enumerate(f.get("params", ())[:MAX_PARAMS]):
                buf["param_hash"][i, j] = h
                buf["param_present"][i, j] = True
        try:
            self.engine._run_exit_batch(ExitBatch(**buf))
        except Exception:
            self.pool.release("exit", buf)
            raise
        return buf

    def _flush_entries(self, entries: List[_EntryTicket],
                       exit_bufs: List[tuple]):
        t0 = time.perf_counter()
        width = _ladder_width(len(entries))
        buf = self.pool.acquire("entry", width)
        for i, t in enumerate(entries):
            f = t.fields
            for k in ("cluster_row", "dn_row", "origin_row", "origin_id",
                      "origin_named", "context_id", "count", "prioritized",
                      "entry_in", "skip_cluster", "pre_blocked"):
                buf[k][i] = f[k]
            for j, h in enumerate(f.get("params", ())[:MAX_PARAMS]):
                buf["param_hash"][i, j] = h
                buf["param_present"][i, j] = True
        try:
            # Enqueue-only under the engine lock: JAX async dispatch
            # returns lazy Decisions; nothing blocks on the verdict here.
            dec = self.engine._run_entry_batch(EntryBatch(**buf))
        except Exception:
            self.pool.release("entry", buf)
            raise
        queue_wait_ms = (t0 - entries[0].submit_ts) * 1e3
        self.cycles += 1
        self.batched += len(entries)
        # Fold pending exit-only-cycle buffers in: they dispatched
        # BEFORE this entry step, so this record's harvest proves their
        # transfers completed too.
        bufs = [("entry", buf)] + exit_bufs + self._orphan_bufs
        self._orphan_bufs = []
        self._inflight.append(_InFlight(entries, dec, bufs, queue_wait_ms))
        if len(self._inflight) > self.max_inflight:
            self.max_inflight = len(self._inflight)

    # -- harvest -----------------------------------------------------------

    def _harvest_one(self) -> None:
        """Materialize the OLDEST in-flight cycle's verdicts and resolve
        its tickets. Blocking here overlaps every younger cycle's device
        compute; once this cycle's arrays are ready, the ordered stream
        guarantees every dispatch enqueued before it has completed, so
        its buffers (and any orphaned exit buffers) return to the pool."""
        rec = self._inflight.popleft()
        t0 = time.perf_counter()
        try:
            reasons, waits = self.engine.harvest_decisions(rec.dec)
        except Exception:
            # The async compute died after dispatch (backend/tunnel
            # failure surfacing at materialization): fail this cycle's
            # tickets open — the engine has already dropped to a cold
            # state, and the next dispatch recovers. Buffers are NOT
            # recycled (the failed stream may still reference them);
            # losing a few pool slots to a rare outage beats corruption.
            for t in rec.entries:
                t.reason = -2
                t.done.set()
            self.fail_open_cycles += 1
            self.harvests += 1
            raise
        device_wait_ms = (time.perf_counter() - t0) * 1e3
        self.harvests += 1
        for i, t in enumerate(rec.entries):
            t.reason = int(reasons[i])
            t.wait_us = int(waits[i])
            t.done.set()
        self.engine.step_timer.record_pipeline(
            depth=len(self._inflight) + 1,
            queue_wait_ms=rec.queue_wait_ms,
            device_wait_ms=device_wait_ms)
        # The same split feeds the unified latency waterfall (ISSUE 18):
        # pipeline queue/device waits share the wire stages' log2
        # geometry and exporter family instead of a parallel one-off
        # pair. getattr: harvest is reachable during engine construction.
        waterfall = getattr(self.engine, "waterfall", None)
        if waterfall is not None:
            waterfall.observe_pipeline(rec.queue_wait_ms, device_wait_ms)
        for kind, buf in rec.bufs:
            self.pool.release(kind, buf)

    def _harvest_all(self) -> None:
        from sentinel_tpu.log.record_log import record_log

        while self._inflight:
            try:
                self._harvest_one()
            except Exception as ex:  # noqa: BLE001 — keep draining
                record_log.warn("pipeline drain harvest failed: %r", ex)

package com.alibaba.csp.sentinel.cluster.client.config;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:cluster/client/config/ClusterClientConfigManager.java — only
 * the static getters the bridge reads. */
public final class ClusterClientConfigManager {

    public static String getServerHost() {
        return null;
    }

    public static int getServerPort() {
        return -1;
    }

    public static int getRequestTimeout() {
        return 3000;
    }

    private ClusterClientConfigManager() {
    }
}

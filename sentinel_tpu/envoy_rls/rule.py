"""Envoy RLS rules + conversion to cluster flow rules (reference:
``…/envoy/rls/rule/EnvoyRlsRule.java``, ``EnvoyRlsRuleManager.java``,
``EnvoySentinelRuleConverter.java``): each (domain, descriptor key/value
set) maps to one generated cluster ``FlowRule`` whose ``flowId`` is a stable
hash of the descriptor identity, enforced GLOBAL-threshold by the token
service.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.models.flow import FlowRule

SEPARATOR = "|"


@dataclass
class KeyValueResource:
    key: str
    value: str


@dataclass
class ResourceDescriptor:
    resources: List[KeyValueResource]
    count: float  # permitted QPS for this descriptor


@dataclass
class EnvoyRlsRule:
    domain: str
    descriptors: List[ResourceDescriptor] = field(default_factory=list)

    def is_valid(self) -> bool:
        return bool(self.domain) and all(
            d.count >= 0 and d.resources for d in self.descriptors)


def descriptor_identity(domain: str, entries: Sequence[Tuple[str, str]]) -> str:
    parts = [domain] + [f"{k}:{v}" for k, v in entries]
    return SEPARATOR.join(parts)


def descriptor_flow_id(domain: str, entries: Sequence[Tuple[str, str]]) -> int:
    """Stable 63-bit flowId from the descriptor identity (converter analog)."""
    digest = hashlib.sha1(
        descriptor_identity(domain, entries).encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") & ((1 << 63) - 1)


def to_cluster_flow_rules(rule: EnvoyRlsRule) -> List[FlowRule]:
    """``EnvoySentinelRuleConverter.toSentinelFlowRules`` analog."""
    out = []
    for d in rule.descriptors:
        entries = [(r.key, r.value) for r in d.resources]
        identity = descriptor_identity(rule.domain, entries)
        out.append(FlowRule(
            resource=identity,
            count=d.count,
            cluster_mode=True,
            cluster_config={
                "flowId": descriptor_flow_id(rule.domain, entries),
                "thresholdType": THRESHOLD_GLOBAL,
                "fallbackToLocalWhenFail": False,
            },
        ))
    return out


class EnvoyRlsRuleManager:
    """Holds RLS rules per domain; regenerates the token-service rule set
    (one namespace per domain) on every load — wholesale swap semantics."""

    def __init__(self, cluster_rules: Optional[ClusterFlowRuleManager] = None):
        self.cluster_rules = cluster_rules or ClusterFlowRuleManager()
        self._lock = threading.Lock()
        self._rules: Dict[str, EnvoyRlsRule] = {}

    def load_rules(self, rules: List[EnvoyRlsRule]) -> None:
        valid = [r for r in rules if r.is_valid()]
        with self._lock:
            old_domains = set(self._rules)
            self._rules = {r.domain: r for r in valid}
            for r in valid:
                self.cluster_rules.load_rules(
                    r.domain, to_cluster_flow_rules(r))
            for gone in old_domains - set(self._rules):
                self.cluster_rules.load_rules(gone, [])

    def get_rules(self) -> List[EnvoyRlsRule]:
        with self._lock:
            return list(self._rules.values())

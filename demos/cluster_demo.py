"""Cluster demo (reference: ``sentinel-demo-cluster``): an embedded token
server serves a GLOBAL quota over TCP; this process flips to SERVER mode,
loads a cluster rule, and a token client (the same path every other
instance would use) acquires against the shared window."""

import _demo_env  # noqa: F401

import sentinel_tpu as st
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus

eng = st.get_engine()

# Stage cluster rules, then flip this instance to SERVER (the ops plane
# does the same via cluster/server/modifyFlowRules + setClusterMode=1).
eng.cluster.server_rules().load_rules("default", [st.FlowRule(
    resource="sharedApi", count=5, cluster_mode=True,
    cluster_config={"flowId": 101, "thresholdType": THRESHOLD_GLOBAL})])
eng.cluster.apply_mode(1)
port = eng.cluster.token_server.bound_port
print(f"embedded token server on :{port}")

client = ClusterTokenClient("127.0.0.1", port, "default").start()
names = {TokenResultStatus.OK: "OK", TokenResultStatus.BLOCKED: "BLOCKED"}
try:
    for i in range(8):
        r = client.request_token(101, 1)
        print(f"acquire #{i + 1}: {names.get(r.status, r.status)}"
              + (f" (remaining={r.remaining})" if r.status == 0 else ""))
finally:
    client.stop()
    eng.cluster.stop()

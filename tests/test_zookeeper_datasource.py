"""ZooKeeper (jute-protocol) datasource connector tests (SURVEY.md §2.2,
reference ``sentinel-datasource-zookeeper``): real wire frames over a
real socket — connect handshake, initial getData, one-shot watch
re-reads, node-created/deleted handling, writable setData/create,
reconnect with catch-up across a server restart, and version-conflict
errors.
"""

import json
import struct
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import bind
from sentinel_tpu.datasource.converters import (
    flow_rules_from_json,
    flow_rules_to_json,
)
from sentinel_tpu.datasource.zookeeper import (
    ERR_BADVERSION,
    ERR_NONODE,
    MiniZooKeeperServer,
    ZkConnection,
    ZkError,
    ZookeeperDataSource,
    ZookeeperWritableDataSource,
)

PATH = "/sentinel/rules/flow"


@pytest.fixture()
def server():
    s = MiniZooKeeperServer().start()
    yield s
    s.stop()


def _wait_for(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _rules_json(*resources, count=5.0) -> str:
    return json.dumps([{"resource": r, "count": count} for r in resources])


def _addr(server) -> str:
    return f"127.0.0.1:{server.port}"


def test_jute_ops_basics(server):
    conn = ZkConnection("127.0.0.1", server.port)
    try:
        assert conn.session_id > 0
        assert not conn.exists("/nope")
        assert conn.create("/a", b"v0") == "/a"
        assert conn.exists("/a")
        assert conn.get_data("/a") == b"v0"
        conn.set_data("/a", b"v1")
        assert conn.get_data("/a") == b"v1"
        with pytest.raises(ZkError) as ei:
            conn.get_data("/nope")
        assert ei.value.code == ERR_NONODE
        with pytest.raises(ZkError) as ei:
            conn.set_data("/a", b"x", version=99)
        assert ei.value.code == ERR_BADVERSION
        conn.delete("/a")
        assert not conn.exists("/a")
    finally:
        conn.close()


def test_watch_fires_once_and_rearms_on_read(server):
    """One-shot semantics at the wire level: a fired watch does not fire
    again until re-armed by another watched read."""
    conn = ZkConnection("127.0.0.1", server.port, timeout_s=None)
    try:
        conn.create(PATH, b"v0")
        assert conn.get_data(PATH, watch=True) == b"v0"
        server.set_node(PATH, b"v1")
        etype, _state, path = conn.next_event()
        assert path == PATH
        # second change without re-arming: no event queued
        server.set_node(PATH, b"v2")
        time.sleep(0.1)
        assert conn.events == []
        # re-arm and change again: event arrives
        assert conn.get_data(PATH, watch=True) == b"v2"
        server.set_node(PATH, b"v3")
        assert conn.next_event()[2] == PATH
    finally:
        conn.close()


def test_initial_read_loads_rules(server, engine):
    server.set_node(PATH, _rules_json("pre").encode())
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    try:
        bind(src, st.load_flow_rules)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["pre"]
    finally:
        src.close()


def test_set_node_pushes_rules(server, engine):
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    try:
        bind(src, st.load_flow_rules)
        server.set_node(PATH, _rules_json("pushed").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["pushed"])
        server.set_node(PATH, _rules_json("again").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["again"])
    finally:
        src.close()


def test_writable_creates_then_updates(server, engine):
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    writer = ZookeeperWritableDataSource(_addr(server), PATH,
                                         flow_rules_to_json)
    try:
        bind(src, st.load_flow_rules)
        writer.write([st.FlowRule(resource="created", count=7)])  # create
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()]
                         == ["created"])
        writer.write([st.FlowRule(resource="updated", count=8)])  # setData
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()]
                         == ["updated"])
        # a later cold reader sees the write (durability half)
        assert b"updated" in ZookeeperDataSource(
            _addr(server), PATH, flow_rules_from_json).read_source()
    finally:
        src.close()


def test_node_created_after_start_is_picked_up(server, engine):
    """The connector parks on an exists-watch when the rule znode does
    not exist yet (reference NodeCache created-event behavior)."""
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    try:
        bind(src, st.load_flow_rules)
        assert engine.flow_rules.get_rules() == []
        server.set_node(PATH, _rules_json("late").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["late"])
    finally:
        src.close()


def test_delete_keeps_last_good_and_recreate_recovers(server, engine):
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    try:
        bind(src, st.load_flow_rules)
        server.set_node(PATH, _rules_json("good").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["good"])
        # delete: last good rules stay (NodeCache parity)
        conn = ZkConnection("127.0.0.1", server.port)
        conn.delete(PATH)
        conn.close()
        time.sleep(0.15)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["good"]
        # re-create: new rules land via the exists-watch
        server.set_node(PATH, _rules_json("reborn").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["reborn"])
    finally:
        src.close()


def test_bad_payload_keeps_last_good(server, engine):
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    try:
        bind(src, st.load_flow_rules)
        server.set_node(PATH, _rules_json("good").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["good"])
        server.set_node(PATH, b"{not json!")
        time.sleep(0.1)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["good"]
    finally:
        src.close()


def test_server_restart_reconnects_and_catches_up(server, engine):
    src = ZookeeperDataSource(_addr(server), PATH, flow_rules_from_json,
                              reconnect_backoff_ms=(20, 100)).start()
    try:
        bind(src, st.load_flow_rules)
        server.set_node(PATH, _rules_json("before").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["before"])
        server.stop()
        # update lands while the connector is down (znode data survives
        # the restart, as a real ensemble's would)
        server._nodes[PATH] = (_rules_json("during").encode(), 0)
        time.sleep(0.2)
        server.start()
        # reconnect re-reads immediately: the missed update is recovered
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["during"])
        assert src.reconnect_count >= 1
        # and pushes keep working on the new session
        server.set_node(PATH, _rules_json("after").encode())
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["after"])
    finally:
        src.close()


def test_large_payload_reassembled(server, engine):
    """A rules payload far beyond one TCP segment survives fragmentation
    (the jute frame reader's partial-read reassembly)."""
    many = _rules_json(*[f"res-{i:04d}" for i in range(3000)])
    assert len(many) > 100_000
    server.set_node(PATH, many.encode())
    src = ZookeeperDataSource(_addr(server), PATH,
                              flow_rules_from_json).start()
    try:
        bind(src, st.load_flow_rules)
        assert len(engine.flow_rules.get_rules()) == 3000
    finally:
        src.close()


def test_frame_length_guard(server):
    """An insane frame length tears the connection down instead of
    allocating gigabytes (defensive parity with the TLV FrameReader)."""
    conn = ZkConnection("127.0.0.1", server.port)
    try:
        conn._buf = struct.pack(">i", 1 << 30)
        with pytest.raises(ConnectionError):
            conn._read_frame()
    finally:
        conn.close()

"""The offline policy lab: N policies x M scenarios, scored and ranked.

"Multi-Objective Adaptive Rate Limiting using DRL" (PAPERS.md) frames
the objective: a policy is judged on the vector (block-rate, RT-p99,
utilization), not on any single number. The lab runs each candidate
:class:`~sentinel_tpu.adaptive.controller.Policy` through the replay
engine over a scenario suite — the full in-sim closed loop, every
actuation riding the standard shadow->canary->promote path behind the
rollout guardrail — and scores the resulting vectors with an explicit
weighted scalarization (weights are part of the report: a different
operator trade-off is a re-rank, not a re-run).

Safety is a GATE, not a score term: a run with any band violation
(promoted or final count outside the declared [floor, ceiling]) is
disqualified from winning outright, and guardrail aborts are reported
per run so a "winner" that churned candidates is visible.

``tune_aimd`` is the shipped offline tuner: a deterministic grid search
over AIMD gains on a scenario, returning the best-scoring parameters —
the "tuned AIMD" the acceptance criteria pit against the default.

The last completed report is retained module-wide (``last_report``) for
the ``sim`` ops command, the dashboard Simulator panel, and the
``sentinel_tpu_sim_*`` exporter families.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from sentinel_tpu.adaptive.controller import AdaptiveTarget, AimdPolicy
from sentinel_tpu.simulator.replay import ReplayEngine
from sentinel_tpu.simulator.trace import Trace

# Scalarization defaults: utilization and block-rate trade 1:1 (both are
# fractions of offered demand), RT-p99 priced per second of latency.
DEFAULT_WEIGHTS = {"utilization": 1.0, "blockRate": 1.0, "rtP99": 0.25}

# Small by design: each cell is a full closed-loop replay. The axes are
# the two AIMD gains that dominate convergence speed vs overshoot.
DEFAULT_AIMD_GRID = (
    {"increase_pct": 0.10, "decrease_pct": 0.30, "hysteresis_pct": 0.10},
    {"increase_pct": 0.25, "decrease_pct": 0.30, "hysteresis_pct": 0.10},
    {"increase_pct": 0.50, "decrease_pct": 0.30, "hysteresis_pct": 0.10},
    {"increase_pct": 0.50, "decrease_pct": 0.50, "hysteresis_pct": 0.05},
    {"increase_pct": 1.00, "decrease_pct": 0.30, "hysteresis_pct": 0.05},
)

_report_lock = threading.Lock()
_last_report: Optional[Dict] = None
# Process-wide monotone counters (the sentinel_tpu_sim_* exporter
# families): lab runs completed + total simulated seconds replayed.
_counters = {"labRuns": 0, "replayedSeconds": 0}


class LabPolicy:
    """One policy under test: a Policy instance (or AIMD gains to build
    one), optional per-policy adaptive knob overrides and targets."""

    __slots__ = ("name", "policy", "knobs", "targets")

    def __init__(self, name: str, policy=None,
                 aimd: Optional[Dict] = None,
                 knobs: Optional[Dict] = None,
                 targets: Optional[List[AdaptiveTarget]] = None):
        if policy is None:
            params = {"increase_pct": 0.10, "decrease_pct": 0.30,
                      "hysteresis_pct": 0.10}
            params.update(aimd or {})
            policy = AimdPolicy(**params)
        self.name = name
        self.policy = policy
        self.knobs = dict(knobs or {})
        self.targets = targets


def default_targets(trace: Trace, max_block_rate: float = 0.05,
                    ceiling_factor: float = 16.0) -> List[AdaptiveTarget]:
    """One availability target per tunable flow rule the trace carries:
    hold block-rate at/below ``max_block_rate``, band = [count/4,
    count*ceiling_factor] around the trace's initial limit. TPS rules
    (ISSUE 17) target their LOWERED resource (``llm:<model>``) at the
    lowered count (tps + burst) — the adaptive loop tunes the lowered
    flow rule, which is how a per-model tokensPerSecond gets retuned."""
    out = []
    tps_lowered = [
        {"resource": "llm:" + r.get("model", ""),
         "count": float(r.get("tokensPerSecond", 0))
         + float(r.get("burstTokens", 0))}
        for r in trace.rules.get("tps", ())]
    for rule in list(trace.rules.get("flow", ())) + tps_lowered:
        count = float(rule.get("count", 0))
        if count <= 0:
            continue
        out.append(AdaptiveTarget(
            resource=rule["resource"],
            max_block_rate=max_block_rate,
            floor=max(1.0, count / 4.0),
            ceiling=count * ceiling_factor))
    return out


def score_vector(vector: Dict[str, float],
                 weights: Optional[Dict] = None) -> float:
    """Higher is better: weighted utilization minus weighted block-rate
    minus weighted RT-p99 (priced in seconds)."""
    w = dict(DEFAULT_WEIGHTS, **(weights or {}))
    return (w["utilization"] * vector["utilization"]
            - w["blockRate"] * vector["blockRate"]
            - w["rtP99"] * vector["rtP99Ms"] / 1000.0)


def _run_one(trace: Trace, policy: LabPolicy,
             weights: Optional[Dict], replay_kw: Dict) -> Dict:
    targets = policy.targets if policy.targets is not None \
        else default_targets(trace)
    result = ReplayEngine(
        trace, adaptive=policy.knobs, policy=policy.policy,
        targets=targets, **replay_kw).run()
    vector = result.objective_vector()
    return {
        "objective": vector,
        "score": round(score_vector(vector, weights), 6),
        "promotions": result.counters.get("promotions", 0),
        "aborts": result.counters.get("aborts", 0),
        "clamped": result.counters.get("clamped", 0),
        "bandViolations": result.band_violations,
        "finalCounts": result.final_counts,
        "retried": result.retried,
        "verdictSha256": result.verdict_sha256,
        "seconds": result.seconds,
        "secondsPerWallSecond": round(
            result.seconds / result.total_wall_s, 1),
    }


def run_lab(scenarios: Dict[str, Trace], policies: List[LabPolicy],
            weights: Optional[Dict] = None,
            replay_kw: Optional[Dict] = None,
            stamp_ms: Optional[int] = None) -> Dict:
    """The comparison harness: every policy over every scenario, one
    report. Deterministic given the traces and policies (the replay
    engine is; wall-rate fields are the only measured numbers)."""
    replay_kw = dict(replay_kw or {})
    results: Dict[str, Dict] = {}
    winners: Dict[str, str] = {}
    replayed = 0
    t0 = time.perf_counter()
    for scen_name in sorted(scenarios):
        trace = scenarios[scen_name]
        cell: Dict[str, Dict] = {}
        for pol in policies:
            cell[pol.name] = _run_one(trace, pol, weights, replay_kw)
            replayed += cell[pol.name]["seconds"]
        results[scen_name] = cell
        # Safety gates the win: band violations disqualify. With NO
        # safe run the scenario has no winner (None — the dashboard
        # stars nothing); crowning the least-bad violator would put an
        # envelope-escaping policy behind the ★.
        safe = {name: r for name, r in cell.items()
                if r["bandViolations"] == 0}
        winners[scen_name] = max(
            sorted(safe), key=lambda name: safe[name]["score"]) \
            if safe else None
    wall_s = max(time.perf_counter() - t0, 1e-9)
    report = {
        "stampMs": stamp_ms,
        "weights": dict(DEFAULT_WEIGHTS, **(weights or {})),
        "scenarios": {
            name: {"seconds": scenarios[name].duration_s,
                   "meta": {k: v for k, v in scenarios[name].meta.items()
                            if k in ("scenario", "seed", "retry")}}
            for name in sorted(scenarios)},
        "policies": [p.name for p in policies],
        "results": results,
        "winners": winners,
        "replayedSeconds": replayed,
        "wallSeconds": round(wall_s, 3),
        "secondsPerWallSecond": round(replayed / wall_s, 1),
    }
    set_last_report(report)
    return report


def tune_aimd(trace: Trace, grid=DEFAULT_AIMD_GRID,
              targets: Optional[List[AdaptiveTarget]] = None,
              weights: Optional[Dict] = None,
              replay_kw: Optional[Dict] = None) -> Dict:
    """Deterministic grid search over AIMD gains on one scenario.
    Returns the best parameters + every trial's score; build the tuned
    contender with ``LabPolicy("aimd-tuned", aimd=out["best"])``.
    Unsafe trials (band violations) are disqualified, so the tuner can
    never hand back parameters that escaped the envelope."""
    replay_kw = dict(replay_kw or {})
    trials = []
    for params in grid:
        pol = LabPolicy(f"aimd-{params['increase_pct']:g}-"
                        f"{params['decrease_pct']:g}-"
                        f"{params['hysteresis_pct']:g}",
                        aimd=params, targets=targets)
        run = _run_one(trace, pol, weights, replay_kw)
        trials.append({"params": dict(params), "name": pol.name, **run})
    safe = [tr for tr in trials if tr["bandViolations"] == 0]
    if not safe:
        # The guarantee is absolute: the tuner NEVER hands back
        # envelope-escaping gains. All-violating grids are a caller
        # error (bad band/grid combination) and must fail loudly.
        raise ValueError(
            "every tune_aimd trial violated the safety envelope "
            f"({len(trials)} trials) — widen the targets' band or "
            "shrink the grid's gains")
    best = max(safe, key=lambda tr: tr["score"])
    return {"best": best["params"], "bestScore": best["score"],
            "trials": trials}


def set_last_report(report: Dict) -> None:
    global _last_report
    with _report_lock:
        _last_report = report
        _counters["labRuns"] += 1
        _counters["replayedSeconds"] += int(
            report.get("replayedSeconds", 0))


def last_report() -> Optional[Dict]:
    with _report_lock:
        return _last_report


def counters() -> Dict[str, int]:
    with _report_lock:
        return dict(_counters)

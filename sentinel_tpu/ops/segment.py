"""Within-batch segmented scans.

The reference admits each request against counters that every *earlier*
request has already updated (per-request exactness of ``DefaultController``
/ the token bucket CASes). A micro-batched device step sees N requests at
once, so to reproduce arrival-order semantics we compute, for every request,
the sum of candidate counts of earlier requests that target the same node
row / rule — a segmented exclusive prefix sum in arrival order.

Two implementations:

``segmented_prefix``       — stable sort + cumsum + cummax. O(N log N) and
                             exact for any integer magnitudes; the right
                             shape for host-side (CPU) callers such as the
                             cluster token server's micro-batcher.

``segmented_prefix_dense`` — the TPU-native path. On TPU, sorts lower to
                             bitonic networks and cumulative ops lower to
                             ``reduce-window``, which is both slow and blew
                             scoped VMEM inside the fused ``lax.scan`` step
                             (BENCH_r01: "scoped allocation 19.09M > 16.00M
                             limit"). Instead we compute the prefix as a
                             *blocked triangular masked matmul*: for a row
                             block I, ``prefix[i] = Σ_j  eq(id_i, id_j) ·
                             earlier(j, i) · v[j]`` — an [B, N] @ [N, M]
                             product that runs on the MXU with the mask
                             generated on the VPU. Total work is O(N²·M)
                             FLOPs, which for micro-batches (N ≤ 8192) is
                             microseconds of MXU time and, critically, has
                             a static, fusion-friendly memory footprint of
                             O(B·N) per scan block. Multiple value columns
                             (M) share one mask evaluation — flow needs
                             token + entry prefixes over the same rows.

Exactness: the mask is {0,1} and values are cast to bfloat16 with float32
accumulation, so results are exact for per-request counts ≤ 256 (bf16
integer range) — counts are 1 in every reference code path (`SphU.entry`
acquires batch=1; larger acquireCount stays far below 256).

Measured dead end (r4, real v5e chip): a two-level "bounded" variant —
per-block bincounts + cross-block cumsum + block-local triangular mask,
O(N·block) mask work instead of O(N²) — benched 0.60ms vs 0.53ms for
the dense form at N=8192/block=512 inside a 16-step scan: the per-block
bincount scan overhead eats the mask savings at these sizes. Don't
re-derive it below N≈32k.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.lax
import jax.numpy as jnp

_ID_SENTINEL = jnp.int32(-(2**31))


def prep_prefix_pair(ids: jnp.ndarray, values: jnp.ndarray, npad: int):
    """Shared prep for the dense-prefix implementations (XLA scan and the
    Pallas kernel): squeeze 1-D values, pad ids with the sentinel (padded
    rows match only each other and carry zero values), and append the
    ones column whose prefix is the earlier-same-id count that yields
    ``is_first`` for free. Returns ``(squeeze, m, ids_p, vals_p)`` with
    ``vals_p`` float32 [npad, m+1].
    """
    n = ids.shape[0]
    squeeze = values.ndim == 1
    if squeeze:
        values = values[:, None]
    m = values.shape[1]
    ids_p = jnp.pad(ids.astype(jnp.int32), (0, npad - n),
                    constant_values=_ID_SENTINEL)
    vals_p = jnp.pad(
        jnp.concatenate(
            [values.astype(jnp.float32), jnp.ones((n, 1), jnp.float32)],
            axis=1),
        ((0, npad - n), (0, 0)),
    )
    return squeeze, m, ids_p, vals_p


def segmented_prefix(ids: jnp.ndarray, values: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exclusive prefix sum of ``values`` within equal ``ids``, arrival order.

    Returns (prefix_excl, is_first) both aligned with the input order.
    ``is_first`` marks the first occurrence of each id (used e.g. to admit a
    single HALF_OPEN probe per breaker per batch).

    Sort-based host/CPU path; see ``segmented_prefix_dense`` for the device
    hot path.
    """
    n = ids.shape[0]
    order = jnp.argsort(ids, stable=True)
    sid = ids[order]
    sval = values[order]
    csum = jnp.cumsum(sval)
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    # Exclusive prefix at each segment head; propagate forward with a
    # running max (csum is nondecreasing for nonnegative values).
    head_base = jnp.where(first, csum - sval, -1)
    base = jax.lax.cummax(head_base)
    prefix_sorted = csum - sval - base
    inv = jnp.zeros((n,), order.dtype).at[order].set(jnp.arange(n, dtype=order.dtype))
    return prefix_sorted[inv], first[inv]


def segmented_prefix_dense(
    ids: jnp.ndarray,
    values: jnp.ndarray,
    block: int = 512,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked-matmul segmented exclusive prefix (the MXU path).

    ``ids``: int32[N] segment ids (< 0 entries form their own shared segment
    but their values are expected to be 0 by callers, so they contribute
    nothing). ``values``: [N] or [N, M] — M value columns computed against
    one shared mask. Returns ``(prefix, is_first)`` with ``prefix`` shaped
    like ``values`` (float32) and ``is_first`` bool[N].
    """
    (prefix, is_first), = segmented_prefix_dense_multi([(ids, values)],
                                                       block=block)
    return prefix, is_first


def _read_pallas_flag() -> bool:
    import os

    return os.environ.get("SENTINEL_TPU_PALLAS", "").lower() in (
        "1", "true", "yes", "on")


# Captured ONCE at import: jit caches traces, and a trace bakes in the
# routing decision — re-reading the env var per trace would let one
# process mix both prefix implementations across already-compiled vs
# freshly-traced batch widths (r4 advisory). Set SENTINEL_TPU_PALLAS
# before importing sentinel_tpu; later changes are intentionally inert.
_PALLAS_OPTED_IN = _read_pallas_flag()


def _read_force_dense_flag() -> bool:
    import os

    return os.environ.get("SENTINEL_TPU_FORCE_DENSE", "").lower() in (
        "1", "true", "yes", "on")


# Same capture-at-import discipline as the Pallas flag above.
_FORCE_DENSE = _read_force_dense_flag()


def _use_cpu_exact() -> bool:
    """Route prefix/bincount work through the sort/scatter forms on the
    CPU backend (trace-time decision, like ``_use_pallas``).

    The dense masked-matmul forms exist because TPU sorts lower to
    bitonic networks and TPU scatters serialize — neither is true on
    CPU, where the O(N²) mask materialization is the pathology instead:
    the 3-space flow prefix at N=8192 measured ~1.2 s/step on the CPU
    backend vs ~2 ms for stable-sort + cumsum, and the one-hot bincount
    ~0.4 s vs microseconds for a scatter-add. Tier-1 tests and the CPU
    bench path take this exact-integer route; real devices keep the MXU
    forms. ``SENTINEL_TPU_FORCE_DENSE=1`` (at import) pins the dense
    forms on CPU — used by the kernel-exactness tests.
    """
    if _FORCE_DENSE:
        return False
    try:
        return jax.default_backend() == "cpu"
    except Exception:  # pragma: no cover — uninitialized backend
        return False


def _sorted_prefix_multi(ids: jnp.ndarray, values: jnp.ndarray):
    """Multi-column twin of :func:`segmented_prefix` (sort + cumsum +
    cummax): exclusive per-segment prefix of ``values`` [N, M] in arrival
    order, plus ``is_first``. Exact for nonnegative integer values with
    segment sums < 2^24 (f32 cumsum) — the same bound the dense form
    carries."""
    n = ids.shape[0]
    order = jnp.argsort(ids, stable=True)
    sid = ids[order]
    sval = values[order].astype(jnp.float32)          # [N, M]
    csum = jnp.cumsum(sval, axis=0)
    first = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    # Exclusive prefix at each segment head; propagate with a running max
    # (csum is nondecreasing per column for nonnegative values).
    head_base = jnp.where(first[:, None], csum - sval, -1.0)
    base = jax.lax.cummax(head_base, axis=0)
    prefix_sorted = csum - sval - base
    inv = jnp.zeros((n,), order.dtype).at[order].set(
        jnp.arange(n, dtype=order.dtype))
    return prefix_sorted[inv], first[inv]


def _use_pallas() -> bool:
    """Opt-in routing of the dense prefix through the Pallas kernel
    (``SENTINEL_TPU_PALLAS=1`` at import time, on a real TPU). Standalone
    the kernel measured 1.71x the XLA scan (ops/pallas_prefix.py), but
    embedded in the donated 16-step fused-step scan it crashed this
    image's backend with a non-unwinding runtime panic (r4; the tunnel
    needed recovery) — so the XLA path stays the default until the
    in-step embedding is proven on hardware. The kernel itself is
    correctness-tested in interpret mode on CPU (test_pallas_prefix.py)."""
    if not _PALLAS_OPTED_IN:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover — uninitialized backend
        return False


def segmented_prefix_dense_multi(pairs, block: int = 512):
    """K independent dense segmented prefixes fused into ONE scan loop.

    ``pairs``: list of ``(ids, values)`` as in ``segmented_prefix_dense``,
    all with the same leading length N. Every separate prefix call is its
    own ``lax.scan`` over mask/matmul blocks, and XLA does not CSE across
    scans — so callers that need several segmentations of the SAME batch
    (the flow sweep's cluster/dn/origin row spaces) fuse them here: one
    loop, K masks + K matmuls per block, one pass over the batch's VMEM
    working set. Returns a list of ``(prefix, is_first)``.

    With ``SENTINEL_TPU_PALLAS=1`` on a real TPU the work routes through
    the Pallas kernel instead (same contract, measured 1.71x standalone;
    opt-in pending an in-step backend-panic fix — see ``_use_pallas``).
    """
    n = pairs[0][0].shape[0]
    for ids_k, values_k in pairs:
        if ids_k.shape[0] != n or values_k.shape[0] != n:
            raise ValueError(
                "segmented_prefix_dense_multi: all pairs must share the "
                f"same leading length (got {ids_k.shape[0]} / "
                f"{values_k.shape[0]}, expected {n})")
    if n == 0:
        # Zero-width batches (empty pipeline flushes) must trace: the
        # blocked scan below still traces its body once, and indexing a
        # (0, block) array raises. Outputs derived from the inputs (not
        # literal zeros) keep shard_map varying-axes typing.
        out0 = []
        for ids, values in pairs:
            squeeze = values.ndim == 1
            v = values if not squeeze else values[:, None]
            p = v.astype(jnp.float32) * 0
            out0.append((p[:, 0] if squeeze else p, ids < jnp.int32(0)))
        return out0
    if _use_pallas():
        from sentinel_tpu.ops.pallas_prefix import prefix_pallas_multi

        return prefix_pallas_multi(pairs)
    if _use_cpu_exact():
        out = []
        for ids, values in pairs:
            squeeze = values.ndim == 1
            v = values[:, None] if squeeze else values
            prefix, is_first = _sorted_prefix_multi(ids, v)
            out.append((prefix[:, 0] if squeeze else prefix, is_first))
        return out
    nb = -(-n // block)
    npad = nb * block
    pos = jnp.arange(npad, dtype=jnp.int32)
    off = jnp.arange(block, dtype=jnp.int32)

    prepped = []
    for ids, values in pairs:
        squeeze, m, ids_p, vals_p = prep_prefix_pair(ids, values, npad)
        v16 = vals_p.astype(jnp.bfloat16)  # exact for integer counts ≤ 256
        prepped.append((squeeze, m, ids_p, ids_p.reshape(nb, block), v16))

    def body(_, b):
        my_pos = b * block + off                           # [B]
        outs = []
        for _sq, _m, ids_p, idsb, v16 in prepped:
            my_ids = idsb[b]                               # [B]
            mask = (my_ids[:, None] == ids_p[None, :]) & (
                pos[None, :] < my_pos[:, None])
            outs.append(jax.lax.dot_general(
                mask.astype(jnp.bfloat16), v16,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))                                             # [B, M_k+1]
        return _, tuple(outs)

    _, outs_all = jax.lax.scan(body, None, jnp.arange(nb, dtype=jnp.int32))
    results = []
    for (squeeze, m, _ids_p, _idsb, _v16), outs in zip(prepped, outs_all):
        outs = outs.reshape(npad, m + 1)[:n]
        prefix, earlier_count = outs[:, :m], outs[:, m]
        is_first = earlier_count == 0
        if squeeze:
            prefix = prefix[:, 0]
        results.append((prefix, is_first))
    return results


def bincount_matmul(
    ids: jnp.ndarray,
    values: jnp.ndarray,
    num_bins: int,
    lo: int = 128,
) -> jnp.ndarray:
    """Weighted bincount as a two-level one-hot outer product (MXU path).

    ``Σ_n values[n] into bin ids[n]`` without a scatter: decompose
    ``id = hi·lo + lo_part`` and compute ``out[hi, lo] = Aᵀ @ B`` with
    ``A[n, hi] = onehot_hi[n, hi]·v[n]`` and ``B[n, lo] = onehot_lo``. TPU
    scatters serialize (~7ns/update — measured 0.4ms for a 64k-update
    commit); this form is two [N, 128]-ish bf16 matmul operands and a tiny
    MXU contraction instead.

    ``ids``: int32[N], negative or >= num_bins dropped. ``values``: [N] or
    [N, M] — M columns share the one-hot operands. Returns float32
    [num_bins] or [M, num_bins]. Exact for integer |values| ≤ 256 (bf16);
    callers with wider integers split them into byte limbs.
    """
    squeeze = values.ndim == 1
    if squeeze:
        values = values[:, None]
    n, m = values.shape
    if _use_cpu_exact():
        # CPU scatter-add: exact f32 integer accumulation, no one-hot
        # materialization (see _use_cpu_exact for the measured gap).
        valid = (ids >= 0) & (ids < num_bins)
        idc = jnp.where(valid, ids, num_bins)  # spill bucket, sliced off
        v = jnp.where(valid[:, None], values.astype(jnp.float32), 0.0)
        out = jnp.zeros((num_bins + 1, m), jnp.float32).at[idc].add(v)
        out = out[:num_bins].T
        return out[0] if squeeze else out
    nb_hi = -(-num_bins // lo)
    valid = (ids >= 0) & (ids < num_bins)
    idc = jnp.where(valid, ids, 0)
    v = jnp.where(valid[:, None], values, 0).astype(jnp.bfloat16)  # [N, M]
    hi_id = idc // lo
    lo_id = idc % lo
    onehot_hi = (hi_id[:, None] == jnp.arange(nb_hi, dtype=jnp.int32)[None, :])
    onehot_lo = (lo_id[:, None] == jnp.arange(lo, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
    # A: [N, M·nb_hi] — per-column weighted hi one-hots, stacked.
    a = (onehot_hi[:, None, :] & valid[:, None, None]).astype(jnp.bfloat16) * v[:, :, None]
    a = a.reshape(n, m * nb_hi)
    out = jax.lax.dot_general(
        a, onehot_lo, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [M·nb_hi, lo]
    out = out.reshape(m, nb_hi * lo)[:, :num_bins]
    return out[0] if squeeze else out


def first_in_segment(ids: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    """bool[N]: is this the first occurrence of its (non-negative) id?

    Negative ids always return False. O(N) via a scatter-min of positions —
    far cheaper than a full prefix when only first-arrival matters (e.g. one
    HALF_OPEN probe per breaker per batch).
    """
    n = ids.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    oob = jnp.where((ids >= 0) & (ids < num_segments), ids, num_segments)
    first_pos = jnp.full((num_segments,), n, jnp.int32).at[oob].min(pos, mode="drop")
    return first_pos.at[oob].get(mode="fill", fill_value=-1) == pos

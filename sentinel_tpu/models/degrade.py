"""Circuit breaking (degrade rules) as a vectorized state machine.

Reference surface (SURVEY.md §2.1 "DegradeSlot + circuit breaker", 1.8
semantics): per-rule ``CircuitBreaker`` with CLOSED → OPEN → HALF_OPEN
states over a private ``statIntervalMs`` sliding window —
``ResponseTimeCircuitBreaker`` (slow-call ratio: rt > count ⇒ slow; open
when slowRatio ≥ slowRatioThreshold) and ``ExceptionCircuitBreaker``
(error ratio / error count). Checked at **entry** (``tryPass``), fed at
**exit** (``onRequestComplete`` with the completed request's RT + error).

TPU-native design: every breaker is one row of
  * ``state  int32[DR]``      CLOSED=0 / OPEN=1 / HALF_OPEN=2
  * ``next_retry_ms int64[DR]``
  * a :class:`~sentinel_tpu.ops.window.RowWindow` ``[DR, 1, 3]`` (one
    tumbling ``statIntervalMs`` bucket per rule — the reference breaker
    LeapArray uses sampleCount 1 — with TOTAL/ERROR/SLOW channels),
and all transitions are ``where``-selects over the whole rule axis.

Entry semantics: CLOSED passes; OPEN passes a single probe per rule once
``next_retry_ms`` elapses (the batch's *first* arrival wins — segmented
first-occurrence flag), flipping the rule to HALF_OPEN; HALF_OPEN blocks.
Exit semantics: completions feed the window; a completion while HALF_OPEN
decides the probe verdict (bad ⇒ re-OPEN with a fresh retry window, good ⇒
CLOSED with stats reset) — including completions of requests admitted
before the flip, matching the reference's observer behavior; CLOSED rules
re-evaluate their threshold and may trip OPEN. With several completions of
one HALF_OPEN rule in a batch, any bad outcome wins (the serial reference's
final state depends on arrival order; documented delta).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.rule_manager import RuleManager
from sentinel_tpu.core.batch import EntryBatch, ExitBatch
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.ops import window as W
from sentinel_tpu.ops.segment import first_in_segment
from sentinel_tpu.utils.shapes import round_up as _round_up

# RowWindow channels
CH_TOTAL = 0
CH_ERROR = 1
CH_SLOW = 2
NUM_CH = 3

BREAKER_BUCKETS = 1  # tumbling statIntervalMs bucket (reference sampleCount=1)


@dataclass
class DegradeRule:
    """Reference: ``DegradeRule.java`` (1.8 field set)."""

    resource: str
    count: float                      # RT grade: max allowed rt (ms); ratio/count grades: threshold
    grade: int = C.DEGRADE_GRADE_RT
    time_window: int = 0              # recovery timeout (seconds)
    slow_ratio_threshold: float = C.DEGRADE_DEFAULT_SLOW_RATIO_THRESHOLD
    min_request_amount: int = C.DEGRADE_DEFAULT_MIN_REQUEST_AMOUNT
    stat_interval_ms: int = C.DEGRADE_DEFAULT_STAT_INTERVAL_MS
    limit_app: str = C.LIMIT_APP_DEFAULT
    # Staged rollout (sentinel_tpu/rollout/): see FlowRule.candidate_set.
    candidate_set: Optional[str] = None
    rollout_stage: Optional[str] = None

    def is_valid(self) -> bool:
        if not self.resource or self.count < 0 or self.time_window < 0:
            return False
        if self.grade not in (C.DEGRADE_GRADE_RT, C.DEGRADE_GRADE_EXCEPTION_RATIO,
                              C.DEGRADE_GRADE_EXCEPTION_COUNT):
            return False
        if self.grade == C.DEGRADE_GRADE_EXCEPTION_RATIO and self.count > 1.0:
            return False
        if self.min_request_amount <= 0 or self.stat_interval_ms <= 0:
            return False
        return True


class DegradeRuleTensors(NamedTuple):
    resource_row: jax.Array    # int32[DR]
    grade: jax.Array           # int32[DR]
    threshold: jax.Array       # float32[DR] (max rt | ratio | count)
    slow_ratio: jax.Array      # float32[DR]
    min_request: jax.Array     # int32[DR]
    time_window_ms: jax.Array  # int64[DR]
    rules_by_row: jax.Array    # int32[R, K] degrade-rule ids per resource row

    @property
    def num_rules(self) -> int:
        return self.resource_row.shape[0]

    @property
    def slots(self) -> int:
        return self.rules_by_row.shape[1]


class DegradeState(NamedTuple):
    state: jax.Array          # int32[DR] BREAKER_*
    next_retry_ms: jax.Array  # int64[DR]
    win: W.RowWindow          # [DR, 1, 3] per-rule statIntervalMs window


def make_degrade_state(rt: DegradeRuleTensors, stat_interval_ms: np.ndarray) -> DegradeState:
    dr = rt.num_rules
    # Each rule's statIntervalMs rides in the RowWindow bucket_ms vector.
    return DegradeState(
        state=jnp.zeros((dr,), jnp.int32),
        next_retry_ms=jnp.zeros((dr,), jnp.int64),
        win=W.make_row_window(dr, BREAKER_BUCKETS, NUM_CH, stat_interval_ms),
    )


def compile_degrade_rules(
    rules: List[DegradeRule], registry: NodeRegistry, num_rows: int,
    min_slots: int = 0,
) -> Tuple[DegradeRuleTensors, np.ndarray]:
    """Returns (tensors, per-rule statIntervalMs host array — the window
    geometry is static per compile and feeds state construction)."""
    valid = [r for r in rules if r.is_valid()]
    dr = _round_up(len(valid), 8)
    res_row = np.full(dr, -1, np.int32)
    grade = np.zeros(dr, np.int32)
    threshold = np.zeros(dr, np.float32)
    slow_ratio = np.ones(dr, np.float32)
    min_request = np.full(dr, C.DEGRADE_DEFAULT_MIN_REQUEST_AMOUNT, np.int32)
    time_window_ms = np.zeros(dr, np.int64)
    stat_interval = np.zeros(dr, np.int64)  # 0 => unused row
    by_row: Dict[int, List[int]] = {}

    for i, r in enumerate(valid):
        row = registry.cluster_row(r.resource)
        res_row[i] = row
        grade[i] = r.grade
        threshold[i] = r.count
        slow_ratio[i] = r.slow_ratio_threshold
        min_request[i] = r.min_request_amount
        time_window_ms[i] = r.time_window * 1000
        stat_interval[i] = r.stat_interval_ms
        if row >= 0:
            by_row.setdefault(row, []).append(i)

    # 0 when no rules: the per-slot loop then vanishes at trace time,
    # so rule-free deployments pay nothing for this family (the
    # dropped-index scatters of an empty table still cost ~0.1ms/step
    # per scatter at batch 8192 on TPU). ``min_slots`` is the engine's
    # ratchet: crossing 0 -> 1 slots is a SHAPE change that retraces the
    # fused step, so the engine floors this at the widest slot count it
    # has ever compiled — one retrace when a family is first used, none
    # on later pushes (including dropping back to zero rules).
    k = max(min_slots, max((len(v) for v in by_row.values()), default=0))
    rules_by_row = np.full((num_rows, k), -1, np.int32)
    for row, ids in by_row.items():
        rules_by_row[row, : len(ids)] = ids

    t = DegradeRuleTensors(
        resource_row=jnp.asarray(res_row),
        grade=jnp.asarray(grade),
        threshold=jnp.asarray(threshold),
        slow_ratio=jnp.asarray(slow_ratio),
        min_request=jnp.asarray(min_request),
        time_window_ms=jnp.asarray(time_window_ms),
        rules_by_row=jnp.asarray(rules_by_row),
    )
    return t, stat_interval


class DegradeRuleManager(RuleManager):
    """Wholesale-swap registry (reference: ``DegradeRuleManager``)."""


# ---------------------------------------------------------------------------
# Device-side check (entry) and feed (exit)
# ---------------------------------------------------------------------------


class DegradeVerdict(NamedTuple):
    blocked: jax.Array  # bool[N]
    state: DegradeState
    slot: jax.Array  # int32[N] first-blocking rule slot (-1 = not blocked)


def check_degrade(
    rt: DegradeRuleTensors,
    ds: DegradeState,
    batch: EntryBatch,
    now_ms: jax.Array,
    candidate: jax.Array,  # bool[N] not blocked by earlier slots
) -> DegradeVerdict:
    """Vectorized ``CircuitBreaker.tryPass`` over the micro-batch."""
    n = batch.size
    blocked = jnp.zeros((n,), bool)
    # First blocking rule slot per request (sequential chain's throw
    # site) for decision attribution; -1 while unblocked.
    first_slot = jnp.full((n,), -1, jnp.int32)
    state = ds.state
    next_retry = ds.next_retry_ms
    probe_rules = []  # per-slot int32[N]: rule id probed by request i, or -1

    for k in range(rt.slots):
        rule_id = rt.rules_by_row.at[
            W.oob(batch.cluster_row, rt.rules_by_row.shape[0]), jnp.full((n,), k)
        ].get(mode="fill", fill_value=-1)
        has_rule = (rule_id >= 0) & candidate & (~blocked)

        st = state.at[W.oob(rule_id, rt.num_rules)].get(mode="fill", fill_value=C.BREAKER_CLOSED)
        nr = next_retry.at[W.oob(rule_id, rt.num_rules)].get(mode="fill", fill_value=0)

        is_open = st == C.BREAKER_OPEN
        is_half = st == C.BREAKER_HALF_OPEN
        retry_due = is_open & (now_ms >= nr)

        # One probe per rule per batch: first arrival with a due retry.
        # (Scatter-min of positions — O(N), no prefix machinery needed.)
        probe_ids = jnp.where(has_rule & retry_due, rule_id, -1)
        probe = has_rule & retry_due & first_in_segment(probe_ids, rt.num_rules)

        blocked_k = has_rule & (is_half | (is_open & ~probe))
        # has_rule already excludes earlier-slot blocks, so blocked_k is
        # true at most once per request across the loop.
        first_slot = jnp.where(blocked_k, k, first_slot)
        blocked = blocked | blocked_k

        # OPEN -> HALF_OPEN where a probe was admitted.
        state = state.at[W.oob(jnp.where(probe, rule_id, -1), rt.num_rules)].set(
            C.BREAKER_HALF_OPEN, mode="drop"
        )
        probe_rules.append(jnp.where(probe, rule_id, -1))

    # A probe granted at one slot whose request another slot then blocked
    # never completes, so its breaker would be stuck HALF_OPEN forever.
    # Revert those to OPEN (retry time untouched → re-probe-eligible at
    # once), the vectorized analog of the reference's terminate-hook
    # workaround for alibaba/Sentinel#1638.
    for pr in probe_rules:
        dead = jnp.where(blocked, pr, -1)
        state = state.at[W.oob(dead, rt.num_rules)].set(C.BREAKER_OPEN, mode="drop")

    return DegradeVerdict(blocked=blocked, state=ds._replace(state=state),
                          slot=first_slot)


def feed_degrade(
    rt: DegradeRuleTensors,
    ds: DegradeState,
    batch: ExitBatch,
    now_ms: jax.Array,
) -> DegradeState:
    """Vectorized ``onRequestComplete``: window feed + state transitions."""
    n = batch.cluster_row.shape[0]
    win = W.row_rotate(ds.win, now_ms)
    state = ds.state
    next_retry = ds.next_retry_ms

    valid = batch.cluster_row >= 0
    err = valid & batch.error

    # Varying-typed seeds so the lax.cond below type-checks under
    # shard_map (W.varying_zeros carries the rationale).
    half_bad = W.varying_zeros(batch.count, (rt.num_rules,), bool)
    half_good = W.varying_zeros(batch.count, (rt.num_rules,), bool)

    for k in range(rt.slots):
        rule_id = rt.rules_by_row.at[
            W.oob(batch.cluster_row, rt.rules_by_row.shape[0]), jnp.full((n,), k)
        ].get(mode="fill", fill_value=-1)
        has_rule = (rule_id >= 0) & valid

        # Exit batches with no breaker-ruled completions (degrade rules
        # are sparse in mixed deployments; small pipeline batches miss
        # them routinely) leave the window and probe votes provably
        # unchanged — skip the three window scatters via the cond.
        def _feed(args, rule_id=rule_id, has_rule=has_rule):
            win_, half_bad_, half_good_ = args
            rid = jnp.where(has_rule, rule_id, -1)
            thr = rt.threshold.at[W.oob(rule_id, rt.num_rules)].get(
                mode="fill", fill_value=0.0)
            grade = rt.grade.at[W.oob(rule_id, rt.num_rules)].get(
                mode="fill", fill_value=0)
            slow = has_rule & (grade == C.DEGRADE_GRADE_RT) & (
                batch.rt_ms.astype(jnp.float32) > thr
            )
            bad = jnp.where(grade == C.DEGRADE_GRADE_RT, slow, err & has_rule)

            cnt = jnp.where(has_rule, batch.count, 0)
            win_ = W.row_window_add(win_, now_ms, rid,
                                    jnp.full((n,), CH_TOTAL), cnt)
            win_ = W.row_window_add(win_, now_ms, rid,
                                    jnp.full((n,), CH_ERROR),
                                    jnp.where(err & has_rule, batch.count, 0))
            win_ = W.row_window_add(win_, now_ms, rid,
                                    jnp.full((n,), CH_SLOW),
                                    jnp.where(slow, batch.count, 0))

            # HALF_OPEN probe verdicts: any completion of the rule votes.
            st = state.at[W.oob(rule_id, rt.num_rules)].get(
                mode="fill", fill_value=-1)
            on_half = has_rule & (st == C.BREAKER_HALF_OPEN)
            half_bad_ = half_bad_.at[W.oob(
                jnp.where(on_half & bad, rule_id, -1), rt.num_rules)].set(
                True, mode="drop")
            half_good_ = half_good_.at[W.oob(
                jnp.where(on_half & ~bad, rule_id, -1), rt.num_rules)].set(
                True, mode="drop")
            return win_, half_bad_, half_good_

        win, half_bad, half_good = jax.lax.cond(
            jnp.any(has_rule), _feed, lambda args: args,
            (win, half_bad, half_good))

    # --- rule-axis transitions -------------------------------------------
    totals = W.row_window_totals(win, jnp.arange(rt.num_rules))  # [DR, 3]
    total = totals[:, CH_TOTAL].astype(jnp.float32)
    error = totals[:, CH_ERROR].astype(jnp.float32)
    slowc = totals[:, CH_SLOW].astype(jnp.float32)
    enough = totals[:, CH_TOTAL] >= rt.min_request

    # Strictly-greater comparisons per the reference breakers; the slow-call
    # breaker additionally trips at ratio == threshold when threshold is 1.0
    # (a 100% threshold would otherwise never fire).
    ratio_den = jnp.maximum(total, 1.0)
    slow_r = slowc / ratio_den
    err_r = error / ratio_den
    trip_slow = (slow_r > rt.slow_ratio) | ((rt.slow_ratio >= 1.0) & (slow_r >= 1.0))
    trip = jnp.where(rt.grade == C.DEGRADE_GRADE_RT, trip_slow, err_r > rt.threshold)
    trip = jnp.where(rt.grade == C.DEGRADE_GRADE_EXCEPTION_COUNT, error > rt.threshold, trip)
    trip = trip & enough

    is_closed = state == C.BREAKER_CLOSED
    is_half = state == C.BREAKER_HALF_OPEN

    # HALF_OPEN verdict: bad wins over good.
    to_open = (is_closed & trip) | (is_half & half_bad)
    to_closed = is_half & half_good & (~half_bad)

    state = jnp.where(to_open, C.BREAKER_OPEN, state)
    state = jnp.where(to_closed, C.BREAKER_CLOSED, state)
    next_retry = jnp.where(to_open, now_ms + rt.time_window_ms, next_retry)

    # Closing resets the breaker's stats window (reference: resetStat()).
    win = win._replace(
        counts=jnp.where(to_closed[:, None, None], 0, win.counts)
    )
    return DegradeState(state=state, next_retry_ms=next_retry, win=win)

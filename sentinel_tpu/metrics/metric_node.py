"""``MetricNode``: one resource-second of aggregated statistics.

The line format is an API (SURVEY.md §5: "this format is an API: dashboard
and ops tooling parse it"), byte-compatible with the reference's thin form::

    timestamp|resource|passQps|blockQps|successQps|exceptionQps|rt|occupiedPassQps|concurrency|classification

(reference: ``core:node/metric/MetricNode.java`` format/parse pair).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class MetricNode:
    timestamp: int        # second-aligned epoch millis
    resource: str
    pass_qps: int = 0
    block_qps: int = 0
    success_qps: int = 0
    exception_qps: int = 0
    rt: float = 0.0       # average RT over the second (ms)
    occupied_pass_qps: int = 0
    concurrency: int = 0
    classification: int = 0  # ResourceType

    def to_thin_string(self) -> str:
        # Resource names may not contain the separator; scrub like the
        # reference does for illegal characters.
        res = self.resource.replace("|", "_")
        return (
            f"{self.timestamp}|{res}|{self.pass_qps}|{self.block_qps}|"
            f"{self.success_qps}|{self.exception_qps}|{int(self.rt)}|"
            f"{self.occupied_pass_qps}|{self.concurrency}|{self.classification}"
        )

    @classmethod
    def from_thin_string(cls, line: str) -> "MetricNode":
        parts = line.strip().split("|")
        if len(parts) < 7:
            raise ValueError(f"malformed metric line: {line!r}")
        node = cls(
            timestamp=int(parts[0]),
            resource=parts[1],
            pass_qps=int(parts[2]),
            block_qps=int(parts[3]),
            success_qps=int(parts[4]),
            exception_qps=int(parts[5]),
            rt=float(parts[6]),
        )
        if len(parts) > 7:
            node.occupied_pass_qps = int(parts[7])
        if len(parts) > 8:
            node.concurrency = int(parts[8])
        if len(parts) > 9:
            node.classification = int(parts[9])
        return node

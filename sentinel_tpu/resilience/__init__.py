"""Unified resilience layer for every remote touchpoint.

The framework exists to keep OTHER services degrading gracefully; this
package applies the same discipline to its own remote dependencies
(token server, datasources, dashboard):

* :class:`RetryPolicy` / :class:`RetrySession` — seedable exponential
  backoff with decorrelated jitter, shared by the token-client
  reconnect loop, the datasource poll loop, and the heartbeat rotation.
* :class:`HealthGate` — the repo's CLOSED/OPEN/HALF_OPEN breaker
  semantics as a host-side gate for remote clients.
* :class:`DeadlineBudget` — aggregate latency bound for the remote work
  one ``entry()`` may perform.
* :mod:`faults` — deterministic fault injection at named remote seams,
  zero-overhead when disabled (drives ``tests/test_chaos.py``).
* a process-wide health-probe registry, so long-lived remote loops
  (datasource pollers, heartbeat) surface liveness through
  ``engine.resilience_stats()`` next to ``fail_open_count``.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Tuple

from sentinel_tpu.resilience import faults
from sentinel_tpu.resilience.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    HealthGate,
)
from sentinel_tpu.resilience.budget import DeadlineBudget
from sentinel_tpu.resilience.faults import FaultInjected, FaultInjector
from sentinel_tpu.resilience.retry import RetryPolicy, RetrySession

__all__ = [
    "DeadlineBudget", "FaultInjected", "FaultInjector", "HealthGate",
    "RetryPolicy", "RetrySession", "STATE_CLOSED", "STATE_HALF_OPEN",
    "STATE_OPEN", "faults", "health_probes", "health_snapshot",
    "register_probe",
]

# -- health-probe registry ----------------------------------------------------
# Remote loops register a zero-arg callable returning a small dict of
# liveness facts (e.g. {"lastSuccessMs": ..., "consecutiveFailures": ...}).
# The engine's resilience_stats() walks this to report datasource /
# heartbeat health without owning those objects.

_probe_lock = threading.Lock()
_probes: Dict[str, Callable[[], dict]] = {}


def register_probe(name: str, probe: Callable[[], dict]) -> Callable[[], None]:
    """Register a named liveness probe; returns an unregister callable.
    A re-registered name replaces the old probe (restart-friendly).

    Bound methods are held via ``weakref.WeakMethod``: a source that is
    started and then dropped without ``close()`` must not be pinned alive
    by this process-global registry forever — its entry self-prunes on
    the next snapshot once the owner is collected."""
    if hasattr(probe, "__self__"):
        probe = weakref.WeakMethod(probe)
    else:
        strong = probe
        probe = lambda: strong  # noqa: E731 — uniform deref shape
    with _probe_lock:
        _probes[name] = probe

    def off() -> None:
        with _probe_lock:
            if _probes.get(name) is probe:
                del _probes[name]

    return off


def health_probes() -> List[Tuple[str, Callable[[], dict]]]:
    """Live probes, deref'd; entries whose owner died are pruned."""
    out, dead = [], []
    with _probe_lock:
        for name, ref in sorted(_probes.items()):
            fn = ref()
            if fn is None:
                dead.append(name)
            else:
                out.append((name, fn))
        for name in dead:
            del _probes[name]
    return out


def health_snapshot() -> Dict[str, dict]:
    """Evaluate every probe; a broken probe reports its error rather than
    hiding the rest."""
    out: Dict[str, dict] = {}
    for name, probe in health_probes():
        try:
            out[name] = dict(probe())
        except Exception as ex:  # noqa: BLE001 — ops surface, never raises
            out[name] = {"error": repr(ex)}
    return out

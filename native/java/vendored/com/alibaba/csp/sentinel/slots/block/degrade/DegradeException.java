package com.alibaba.csp.sentinel.slots.block.degrade;

import com.alibaba.csp.sentinel.slots.block.BlockException;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/block/degrade/DegradeException.java. */
public class DegradeException extends BlockException {

    public DegradeException(String ruleLimitApp) {
        super(ruleLimitApp);
    }

    public DegradeException(String ruleLimitApp, String message) {
        super(ruleLimitApp, message);
    }
}

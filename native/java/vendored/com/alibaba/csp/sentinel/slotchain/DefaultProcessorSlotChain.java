package com.alibaba.csp.sentinel.slotchain;

import com.alibaba.csp.sentinel.context.Context;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slotchain/DefaultProcessorSlotChain.java. Minimal but
 * functional linking so the conformance harness can exercise a real
 * chain without the fork. */
public class DefaultProcessorSlotChain extends ProcessorSlotChain {

    AbstractLinkedProcessorSlot<?> first = new AbstractLinkedProcessorSlot<Object>() {
        @Override
        public void entry(Context context, ResourceWrapper resourceWrapper,
                          Object t, int count, boolean prioritized,
                          Object... args) throws Throwable {
            fireEntry(context, resourceWrapper, t, count, prioritized, args);
        }

        @Override
        public void exit(Context context, ResourceWrapper resourceWrapper,
                         int count, Object... args) {
            fireExit(context, resourceWrapper, count, args);
        }
    };
    AbstractLinkedProcessorSlot<?> end = first;

    @Override
    public void addFirst(AbstractLinkedProcessorSlot<?> protocolProcessor) {
        protocolProcessor.setNext(first.getNext());
        first.setNext(protocolProcessor);
        if (end == first) {
            end = protocolProcessor;
        }
    }

    @Override
    public void addLast(AbstractLinkedProcessorSlot<?> protocolProcessor) {
        end.setNext(protocolProcessor);
        end = protocolProcessor;
    }

    @Override
    public void setNext(AbstractLinkedProcessorSlot<?> next) {
        addLast(next);
    }

    @Override
    public AbstractLinkedProcessorSlot<?> getNext() {
        return first.getNext();
    }

    @Override
    public void entry(Context context, ResourceWrapper resourceWrapper,
                      Object t, int count, boolean prioritized,
                      Object... args) throws Throwable {
        first.transformEntry(context, resourceWrapper, t, count, prioritized,
                             args);
    }

    @Override
    public void exit(Context context, ResourceWrapper resourceWrapper,
                     int count, Object... args) {
        first.exit(context, resourceWrapper, count, args);
    }
}

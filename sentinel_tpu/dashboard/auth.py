"""Dashboard authentication.

Reference: ``dashboard:auth/AuthService.java`` +
``SimpleWebAuthServiceImpl`` + ``LoginAuthenticationFilter`` +
``AuthorizationInterceptor`` (SURVEY.md §2.6 "Boot + auth"). Semantics
preserved:

  * credentials come from config (``sentinel.dashboard.auth.username`` /
    ``…password``, env-overridable like every other key); when the
    username is unset/empty, auth is DISABLED and every request passes —
    the reference's ``FakeAuthServiceImpl`` fallback wired by
    ``WebConfig`` when ``auth.username`` is blank;
  * login mints an opaque session token (the reference stores the
    ``AuthUser`` in the servlet session; here the token travels as a
    cookie or ``Authorization: Bearer`` header);
  * the filter exempts the login endpoint, static assets, and the
    machine-registry heartbeat endpoint (engines are not browsers);
    everything else requires a live session;
  * the simple impl grants a logged-in user all privileges
    (``SimpleWebAuthServiceImpl.AuthUserImpl.authTarget`` returns true),
    so there is no per-app ACL here either.

Sessions expire after ``ttl_s`` (default 8h) of age; expiry uses the
injected monotonic clock so tests don't sleep.
"""

from __future__ import annotations

import hmac
import secrets
import threading
import time
from typing import Callable, Dict, NamedTuple, Optional

from sentinel_tpu.core.config import config

AUTH_USERNAME = "sentinel.dashboard.auth.username"
AUTH_PASSWORD = "sentinel.dashboard.auth.password"
DEFAULT_SESSION_TTL_S = 8 * 3600

COOKIE_NAME = "sentinel_dashboard_token"


class AuthUser(NamedTuple):
    username: str

    def auth_target(self, target: str, privilege: str) -> bool:
        """All-privileges once logged in, like ``SimpleWebAuthServiceImpl``."""
        return True


class _Session(NamedTuple):
    user: AuthUser
    expires_at: float


class AuthService:
    """Credential check + in-memory session store."""

    def __init__(self, username: Optional[str] = None,
                 password: Optional[str] = None,
                 ttl_s: float = DEFAULT_SESSION_TTL_S,
                 clock: Callable[[], float] = time.monotonic):
        if username is None:
            username = config.get(AUTH_USERNAME, "") or ""
            password = config.get(AUTH_PASSWORD, "") or ""
        self._username = username
        self._password = password or ""
        self._ttl_s = ttl_s
        self._clock = clock
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        # Both parts must be configured: a username with a blank password
        # would otherwise enable auth that accepts an empty password.
        return bool(self._username) and bool(self._password)

    def login(self, username: str, password: str) -> Optional[str]:
        """Return a session token, or None on bad credentials."""
        if not self.enabled:
            return None
        # bytes operands: compare_digest refuses non-ASCII str
        ok_user = hmac.compare_digest(
            (username or "").encode("utf-8"), self._username.encode("utf-8"))
        ok_pass = hmac.compare_digest(
            (password or "").encode("utf-8"), self._password.encode("utf-8"))
        if not (ok_user and ok_pass):
            return None
        token = secrets.token_urlsafe(24)
        with self._lock:
            self._prune()
            self._sessions[token] = _Session(
                AuthUser(username), self._clock() + self._ttl_s)
        return token

    def validate(self, token: Optional[str]) -> Optional[AuthUser]:
        """The logged-in user for ``token``, or None (expired/unknown)."""
        if not token:
            return None
        with self._lock:
            sess = self._sessions.get(token)
            if sess is None:
                return None
            if self._clock() >= sess.expires_at:
                del self._sessions[token]
                return None
            return sess.user

    def logout(self, token: Optional[str]) -> None:
        if token:
            with self._lock:
                self._sessions.pop(token, None)

    def _prune(self) -> None:
        now = self._clock()
        dead = [t for t, s in self._sessions.items() if now >= s.expires_at]
        for t in dead:
            del self._sessions[t]

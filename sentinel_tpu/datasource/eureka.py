"""Eureka datasource: rules carried in instance **metadata** (reference:
``sentinel-datasource-eureka``'s ``EurekaDataSource`` — poll
``GET {serviceUrl}/apps/{appId}/{instanceId}`` across a failover list of
service URLs and extract ``instance.metadata[ruleKey]`` — SURVEY.md §2.2).

This speaks the actual Eureka REST API (JSON representation), not an SDK:

- ``GET /apps/<APP>/<instanceId>`` with ``Accept: application/json`` →
  ``{"instance": {"instanceId": ..., "app": "<APP>", "status": "UP",
  "metadata": {"<ruleKey>": "<rules json>", ...}, ...}}``; 404 when the
  instance is not registered.
- ``PUT /apps/<APP>/<instanceId>/metadata?<key>=<value>`` updates one
  metadata entry (the writable path).

Reference semantics preserved: the service-URL list is tried in order
with sticky failover (stay on the first URL that answers; rotate on
error), polling is ``AutoRefreshDataSource``-shaped (default 3s), bad or
missing payloads keep the last good rules, and unchanged metadata pushes
nothing (content dedup — Eureka has no change-index to key on).

``MiniEurekaServer`` is the in-repo fake (apps registry subset with real
JSON representation + metadata PUT); point the datasource at a real
Eureka server and no line of the connector changes.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Sequence

from sentinel_tpu.datasource._mini_http import (
    JsonResponderMixin,
    RestartableHTTPServer,
    normalize_base,
)
from sentinel_tpu.datasource.base import (
    AutoRefreshDataSource,
    ContentDedupPollMixin,
    Converter,
    T,
    WritableDataSource,
)


class EurekaDataSource(ContentDedupPollMixin, AutoRefreshDataSource[str, T]):
    """Poll instance metadata across a failover list of service URLs.

    ``service_urls`` mirrors the reference constructor's ``serviceUrls``
    (each the Eureka context base, e.g. ``http://host:8761/eureka``).
    The poller is sticky: it stays on the URL that last answered and
    advances to the next only on a network error, so one dead replica
    costs one failed request per poll at worst, not per-request fanout.
    """

    def __init__(self, service_urls: Sequence[str], app_id: str,
                 instance_id: str, rule_key: str, converter: Converter,
                 recommend_refresh_ms: int = 3000, timeout_s: float = 5.0,
                 retry_policy=None):
        super().__init__(converter, recommend_refresh_ms,
                         retry_policy=retry_policy)
        if not service_urls:
            raise ValueError("service_urls can't be empty")
        self.service_urls = [normalize_base(u) for u in service_urls]
        self.app_id = app_id
        self.instance_id = instance_id
        self.rule_key = rule_key
        self.timeout_s = timeout_s
        self._url_idx = 0
        self.failover_count = 0  # ops visibility + test hook

    # -- ReadableDataSource ------------------------------------------------

    def _instance_url(self, base: str) -> str:
        return "%s/apps/%s/%s" % (
            base,
            urllib.parse.quote(self.app_id),
            urllib.parse.quote(self.instance_id),
        )

    def _fetch_one(self, base: str) -> Optional[str]:
        """One service URL → metadata[rule_key] (None when unregistered
        or key absent — both keep last good rules, like the reference)."""
        req = urllib.request.Request(
            self._instance_url(base), headers={"Accept": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as ex:
            if ex.code == 404:
                return None
            raise
        meta = (doc.get("instance") or {}).get("metadata") or {}
        value = meta.get(self.rule_key)
        return value if isinstance(value, str) else None

    def read_source(self) -> Optional[str]:
        """Sticky-failover read: every URL gets one try per poll; the
        poll fails (and the auto-refresh loop logs + survives) only when
        ALL replicas are down."""
        last_err: Optional[Exception] = None
        for attempt in range(len(self.service_urls)):
            base = self.service_urls[self._url_idx]
            try:
                return self._fetch_one(base)
            except (OSError, urllib.error.URLError, ValueError) as ex:
                last_err = ex
                self._url_idx = (self._url_idx + 1) % len(self.service_urls)
                self.failover_count += 1
        raise last_err if last_err is not None else OSError("no replicas")

    # load_config: ContentDedupPollMixin — Eureka has no ModifyIndex/
    # releaseKey, so the bytes are the only change signal; an absent
    # instance/key keeps the last good rules rather than clearing them.


class EurekaWritableDataSource(WritableDataSource[T]):
    """Publish via ``PUT /apps/<APP>/<id>/metadata?<ruleKey>=<encoded>``
    (Eureka's real metadata-update endpoint — the value rides a query
    parameter, so it is URL-encoded).

    Size limitation (inherent to the endpoint, not this client): the
    whole encoded rule document travels in the request URL, and common
    servers/proxies cap URLs around 8KB — a few hundred JSON rules.
    Writes whose URL exceeds ``max_url_bytes`` (default 7KB, leaving
    headroom under the usual 8KB cap) raise ``ValueError`` up front
    rather than failing opaquely server-side; raise the limit only if
    every hop to your Eureka server is known to accept more."""

    def __init__(self, service_url: str, app_id: str, instance_id: str,
                 rule_key: str, encoder: Converter, timeout_s: float = 5.0,
                 max_url_bytes: int = 7168):
        self.base = normalize_base(service_url)
        self.app_id = app_id
        self.instance_id = instance_id
        self.rule_key = rule_key
        self.encoder = encoder
        self.timeout_s = timeout_s
        self.max_url_bytes = max_url_bytes

    def write(self, value: T) -> None:
        qs = urllib.parse.urlencode({self.rule_key: self.encoder(value)})
        url = "%s/apps/%s/%s/metadata?%s" % (
            self.base, urllib.parse.quote(self.app_id),
            urllib.parse.quote(self.instance_id), qs)
        if len(url.encode("utf-8")) > self.max_url_bytes:
            raise ValueError(
                "eureka metadata write: encoded URL is "
                f"{len(url.encode('utf-8'))} bytes > max_url_bytes="
                f"{self.max_url_bytes}; Eureka's metadata endpoint rides "
                "the query string and servers/proxies commonly cap URLs "
                "~8KB — shrink the rule set or use another datasource")
        req = urllib.request.Request(url, method="PUT")
        # urlopen raises on >=400; any 2xx (200 or a proxy's 204) is a
        # successful write.
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if not (200 <= resp.status < 300):
                raise OSError(f"eureka metadata put -> {resp.status}")


# -- in-repo fake server ------------------------------------------------------


class _EurekaHandler(JsonResponderMixin, BaseHTTPRequestHandler):
    def _parse_instance_path(self, path: str):
        # /<context…>/apps/<APP>/<instanceId>[/metadata] — real service
        # URLs carry a context base ("/eureka" or "/eureka/v2"); anything
        # before the "apps" segment is that context.
        parts = [urllib.parse.unquote(p) for p in path.split("/") if p]
        if "apps" in parts:
            parts = parts[parts.index("apps"):]
        if len(parts) >= 3 and parts[0] == "apps":
            return parts[1].upper(), parts[2], parts[3:]
        return None, None, None

    def do_GET(self):  # noqa: N802 — http.server API
        server: "MiniEurekaServer" = self.server  # type: ignore
        path = self.path.partition("?")[0]
        app, inst, rest = self._parse_instance_path(path)
        if app is None or rest:
            return self._send_json(404, {"error": "not found"})
        with server._cond:
            server.request_count += 1
            meta = server._apps.get((app, inst))
            if meta is None:
                return self._send_json(404, {"error": "instance not found"})
            doc = {"instance": {
                "instanceId": inst, "app": app, "status": "UP",
                "hostName": "127.0.0.1", "ipAddr": "127.0.0.1",
                "metadata": dict(meta),
            }}
        self._send_json(200, doc)

    def do_PUT(self):  # noqa: N802 — http.server API
        server: "MiniEurekaServer" = self.server  # type: ignore
        path, _, query = self.path.partition("?")
        app, inst, rest = self._parse_instance_path(path)
        if app is None or rest != ["metadata"]:
            return self._send_json(404, {"error": "not found"})
        updates = {k: v[0] for k, v in
                   urllib.parse.parse_qs(query, keep_blank_values=True).items()}
        with server._cond:
            meta = server._apps.get((app, inst))
            if meta is None:
                return self._send_json(404, {"error": "instance not found"})
            meta.update(updates)
        self._send_json(200, {"ok": True})

    def log_message(self, fmt, *args):  # quiet
        pass


class MiniEurekaServer(RestartableHTTPServer):
    """Eureka apps-registry subset: JSON instance representation +
    metadata PUT. App names are case-normalized to upper like the real
    server. The registry survives ``stop()``/``start()`` cycles (restart
    = same replica coming back with its registry intact)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port, _EurekaHandler)
        self._apps: Dict[tuple, Dict[str, str]] = {}
        self.request_count = 0

    @property
    def service_url(self) -> str:
        return f"{self.addr}/eureka"

    def register(self, app_id: str, instance_id: str,
                 metadata: Optional[Dict[str, str]] = None) -> None:
        with self._cond:
            self._apps[(app_id.upper(), instance_id)] = dict(metadata or {})

    def set_metadata(self, app_id: str, instance_id: str,
                     key: str, value: str) -> None:
        with self._cond:
            self._apps[(app_id.upper(), instance_id)][key] = value

    def metadata(self, app_id: str, instance_id: str) -> Dict[str, str]:
        with self._cond:
            return dict(self._apps[(app_id.upper(), instance_id)])

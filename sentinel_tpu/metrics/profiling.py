"""Step timing + kernel tracing (SURVEY.md §5 "Tracing / profiling").

The reference has no in-process profiler (introspection stops at the
command API's live-stat dumps); the survey's TPU plan adds two things the
tensor design makes natural:

  * **per-step timing** — every device dispatch (entry/exit batch) is
    recorded: an enqueue wall time always (JAX dispatch is async, so this
    measures host-side submit cost), and a *sampled* synchronous wall
    time every ``sync_every``-th dispatch (block on the decisions) that
    estimates true end-to-end step latency without serializing the
    steady-state stream. The cadence is config-tunable
    (``csp.sentinel.profile.syncEvery``). Snapshots report p50/p95/p99
    per kind and feed the ``profile`` ops command plus the OpenMetrics
    exporter (sentinel_tpu/telemetry/).
  * **kernel traces** — :func:`trace` wraps ``jax.profiler`` so a window
    of real traffic can be captured for TensorBoard/Perfetto kernel-level
    inspection.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Dict, Optional

import numpy as np


def _pct(values, q: float) -> float:
    """Exact nearest-rank quantile: the ceil(q·n/100)-th order statistic.

    ``np.percentile``'s default linear interpolation invents values
    between samples — p99 of 7 samples reported ~max-ε, a latency no
    dispatch ever exhibited, and under-reported the true worst sample.
    Nearest-rank always returns an OBSERVED sample: exact at any n
    (p99 of 7 samples = the max), and converging to the interpolated
    estimate as the ring fills."""
    vals = np.sort(np.asarray(values, dtype=np.float64))
    idx = max(0, math.ceil(q / 100.0 * vals.size) - 1)
    return float(vals[idx])


# Cumulative step-duration histogram geometry (log2 edges, ms, + +Inf
# overflow): sub-ms resolution at the bottom because a routed CPU/TPU
# step is tens of µs to tens of ms; the top edge clears any cold-compile
# outlier a sampled dispatch can observe.
STEP_DURATION_EDGES_MS = (
    0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1024.0, 2048.0, 4096.0)
NUM_STEP_DURATION_BUCKETS = len(STEP_DURATION_EDGES_MS) + 1


class StepTimer:
    """Lock-guarded rolling timing stats for device step dispatches."""

    def __init__(self, ring: int = 512, sync_every: int = 64):
        self._lock = threading.Lock()
        self._ring = ring
        self.sync_every = sync_every
        self._counts: Dict[str, int] = {}
        self._entries: Dict[str, int] = {}
        self._enqueue: Dict[str, list] = {}
        self._sync: Dict[str, list] = {}
        # CUMULATIVE per-kind histogram of the sampled synchronous step
        # walls. The rolling rings above answer "what did recent steps
        # look like" (post-hoc, cleared on reset); these counters are
        # monotone for the engine's lifetime so scrapers — and step-
        # latency SLO burn rates over them — can rate() the series
        # (`sentinel_tpu_step_duration_*`). Deliberately NOT cleared by
        # reset(): a profile-command reset must never make a counter
        # family go backwards mid-scrape.
        self._duration_hist: Dict[str, list] = {}
        self._duration_sum_ms: Dict[str, float] = {}
        # Pipelined-admission decomposition (ISSUE 8): per harvested
        # cycle, the queue wait (oldest ticket submit -> dispatch) and
        # the device wait (harvest blocking on the materialized
        # verdicts), plus the in-flight depth observed at harvest. The
        # split answers the question BENCH_7's t1 pathology raised:
        # is a slow pipelined op queue wait (host serialization) or
        # device wait (step wall)?
        self._pl_queue: list = []
        self._pl_device: list = []
        self._pl_depth_sum = 0
        self._pl_cycles = 0

    def record(self, kind: str, batch_n: int, enqueue_ms: float,
               sync_ms: Optional[float] = None) -> None:
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._entries[kind] = self._entries.get(kind, 0) + batch_n
            buf = self._enqueue.setdefault(kind, [])
            buf.append(enqueue_ms)
            del buf[:-self._ring]
            if sync_ms is not None:
                sbuf = self._sync.setdefault(kind, [])
                sbuf.append(sync_ms)
                del sbuf[:-self._ring]
                hist = self._duration_hist.setdefault(
                    kind, [0] * NUM_STEP_DURATION_BUCKETS)
                b = 0
                while b < len(STEP_DURATION_EDGES_MS) \
                        and sync_ms > STEP_DURATION_EDGES_MS[b]:
                    b += 1
                hist[b] += 1
                self._duration_sum_ms[kind] = \
                    self._duration_sum_ms.get(kind, 0.0) + sync_ms

    def record_pipeline(self, depth: int, queue_wait_ms: float,
                        device_wait_ms: float) -> None:
        """Record one harvested pipeline cycle's wait decomposition."""
        with self._lock:
            self._pl_cycles += 1
            self._pl_depth_sum += depth
            self._pl_queue.append(queue_wait_ms)
            del self._pl_queue[:-self._ring]
            self._pl_device.append(device_wait_ms)
            del self._pl_device[:-self._ring]

    def pipeline_snapshot(self) -> Dict[str, float]:
        """Queue-wait vs device-wait split + mean achieved in-flight
        depth over recorded harvests (empty-safe zeros)."""
        with self._lock:
            out: Dict[str, float] = {
                "harvestedCycles": self._pl_cycles,
                "meanInflightDepth": round(
                    self._pl_depth_sum / self._pl_cycles, 3)
                if self._pl_cycles else 0.0,
            }
            for name, ring in (("queueWait", self._pl_queue),
                               ("deviceWait", self._pl_device)):
                if ring:
                    out[f"{name}P50Ms"] = round(_pct(ring, 50), 3)
                    out[f"{name}P95Ms"] = round(_pct(ring, 95), 3)
                else:
                    out[f"{name}P50Ms"] = 0.0
                    out[f"{name}P95Ms"] = 0.0
            return out

    def duration_histogram(self) -> Dict[str, Dict]:
        """Cumulative sampled-step-wall histogram per kind:
        ``{kind: {"buckets": [per-bucket counts], "sumMs": float,
        "count": int}}`` indexed like :data:`STEP_DURATION_EDGES_MS`
        plus the +Inf overflow."""
        with self._lock:
            return {
                kind: {
                    "buckets": list(hist),
                    "sumMs": self._duration_sum_ms.get(kind, 0.0),
                    "count": sum(hist),
                }
                for kind, hist in self._duration_hist.items()
            }

    def should_sync(self, kind: str) -> bool:
        """True on the sampled dispatches that should block and measure."""
        with self._lock:
            return self._counts.get(kind, 0) % self.sync_every == 0

    def snapshot(self, reset: bool = False) -> Dict[str, Dict[str, float]]:
        """Read (and with ``reset=True`` atomically clear) the stats —
        one lock acquisition, so a poller doing read-and-clear never
        drops dispatches recorded between the two operations."""
        with self._lock:
            out = {}
            for kind, n in self._counts.items():
                enq = self._enqueue.get(kind, []) or [0.0]
                sync = self._sync.get(kind)
                row = {
                    "dispatches": n,
                    "entries": self._entries.get(kind, 0),
                    "enqueueP50Ms": round(_pct(enq, 50), 3),
                    "enqueueP95Ms": round(_pct(enq, 95), 3),
                    "enqueueP99Ms": round(_pct(enq, 99), 3),
                }
                if sync:
                    row["stepP50Ms"] = round(_pct(sync, 50), 3)
                    row["stepP95Ms"] = round(_pct(sync, 95), 3)
                    row["stepP99Ms"] = round(_pct(sync, 99), 3)
                    row["stepSamples"] = len(sync)
                out[kind] = row
            if reset:
                self._counts.clear()
                self._entries.clear()
                self._enqueue.clear()
                self._sync.clear()
            return out

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._entries.clear()
            self._enqueue.clear()
            self._sync.clear()


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a kernel-level device trace of everything inside the block.

    ``with profiling.trace("/tmp/sentinel-trace"): ...`` then open the
    directory in TensorBoard (or xprof) to see per-kernel timing of the
    fused step. Thin wrapper so callers don't import jax.profiler
    directly; swallows nothing — an unsupported backend raises.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def timed_call(timer: StepTimer, kind: str, batch_n: int, fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` (a jitted dispatch returning a pytree),
    recording enqueue wall always and blocking for a true step wall on
    sampled dispatches."""
    do_sync = timer.should_sync(kind)
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    enqueue_ms = (time.perf_counter() - t0) * 1e3
    sync_ms = None
    if do_sync:
        import jax

        jax.block_until_ready(out)
        sync_ms = (time.perf_counter() - t0) * 1e3
    timer.record(kind, batch_n, enqueue_ms, sync_ms)
    return out

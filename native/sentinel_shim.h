/* sentinel_shim.h — C ABI of the sentinel-tpu native client shim.
 *
 * The language-neutral client path to the sentinel-tpu token server
 * (SURVEY.md §7 M4): JNI, JNA, ctypes, and plain C/C++ all bind these
 * symbols from libsentinel_shim.so. Wire protocol: the length-framed TLV
 * of cluster/codec.py (the reference's cluster-common Netty protocol
 * re-specified; message types PING=0, FLOW=1, PARAM_FLOW=2).
 *
 * Thread-safety: one in-flight request per client handle (an internal
 * mutex serializes callers, matching the blocking-client design); create
 * one handle per worker for parallelism.
 */

#ifndef SENTINEL_SHIM_H_
#define SENTINEL_SHIM_H_

#ifdef __cplusplus
extern "C" {
#endif

/* TokenResultStatus values returned by the request calls (wire-visible,
 * reference core:cluster/TokenResultStatus.java):
 *   OK=0, BLOCKED=1, SHOULD_WAIT=2, NO_RULE_EXISTS=3, NO_REF_RULE_EXISTS=4,
 *   NOT_AVAILABLE=5, FAIL=-1, TOO_MANY_REQUEST=-2, BAD_REQUEST=-4.
 * -1 additionally signals local/transport failure. */

/* Connect to a token server and register `ns` via PING.
 * Returns an opaque handle, or NULL on failure. */
void* st_client_connect(const char* host, int port, const char* ns,
                        int timeout_ms);

/* Acquire `count` flow tokens for `flow_id`. Returns the status; when
 * out_extra is non-NULL it receives remaining (OK) or wait-ms
 * (SHOULD_WAIT). */
int st_request_token(void* handle, long long flow_id, int count,
                     int prioritized, int* out_extra);

/* One hot-parameter value for st_request_param_token. `tag` selects the
 * wire encoding AND which field carries the value (the server hashes
 * params typed, so an int param must be sent as an int to share buckets
 * with other clients' ints): */
typedef struct st_param {
  unsigned char tag; /* 0=int (i), 1=utf-8 string (s), 2=bool (i), 3=float (d) */
  long long i;
  double d;
  const char* s;     /* NUL-terminated; used when tag==1 */
} st_param;

/* Acquire `count` param-flow tokens for (`flow_id`, params). Returns the
 * status (PARAM_FLOW responses carry no entity). */
int st_request_param_token(void* handle, long long flow_id, int count,
                           const st_param* params, int nparams);

void st_client_close(void* handle);

/* Cached-tick millisecond clock (reference core:util/TimeUtil.java): a
 * 1ms tick thread caches the wall clock so hot paths avoid syscalls. */
void st_time_start(void);
void st_time_stop(void);
long long st_now_ms(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* SENTINEL_SHIM_H_ */

"""Cluster token-server high availability (ISSUE 5 tentpole; upstream
analog: embedded-mode ``ClusterStateManager`` + the dashboard's cluster
assign map — SURVEY.md §"sentinel-cluster").

Four cooperating pieces close the single-token-server availability gap:

* **Embedded mode-flipping** — :class:`ClusterHAManager` drives an
  instance's CLIENT<->SERVER role from a :class:`ClusterMap` (pushed by
  any datasource through the ``clusterMap`` converter in
  ``datasource/converters.py``), draining the old role cleanly: an
  outgoing leader publishes a final window checkpoint before its
  listener closes.
* **Epoch-fenced leadership** — every leadership term carries a
  monotonic epoch (the map's, or minted above everything observed).
  Servers stamp it into each token response as a trailing TLV old peers
  ignore (``codec.TLV_EPOCH``); clients share one
  :class:`~sentinel_tpu.cluster.state.EpochFence` and reject responses
  below its high-water mark, so a deposed leader can never double-grant
  quota (split-brain fencing).
* **Client failover** — :class:`FailoverTokenClient` walks the map's
  ordered server list (leader first) using the existing
  ``RetryPolicy``/``HealthGate`` primitives per target; past the
  ``csp.sentinel.cluster.ha.failover.deadline.ms`` budget with no
  server reachable it enters **degraded-quota mode**: verdicts come
  from :class:`DegradedQuota`, a per-client share of the global
  threshold (sum of shares <= global threshold — proof in
  docs/SEMANTICS.md), not full-local amnesty.
* **State-preserving recovery** — a promoted leader warm-starts its
  per-flow windows from the checkpoint the old leader published
  (``core/checkpoint.py`` ``save_cluster_checkpoint`` — periodically
  via :class:`~sentinel_tpu.core.checkpoint.CheckpointTimer` and on
  graceful drain), bounding failover over-admission to the grants made
  since the last publish.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from sentinel_tpu.telemetry.journal import causing as journal_causing
from sentinel_tpu.telemetry.journal import current_cause as journal_cause

# Known-fixed-bug reintroduction flags (chaos shrinker proof-of-life —
# ISSUE 15). Bound ONCE at import: the check sits on the degraded-mode
# request path, and a deployment that strips the chaos tooling must
# keep serving with the seam permanently off.
try:
    from sentinel_tpu.chaos.regressions import (
        reintroduced as _chaos_reintroduced,
    )
except ImportError:  # chaos package absent: the fixed behavior, always
    def _chaos_reintroduced(_name: str) -> bool:
        return False

from sentinel_tpu.cluster.state import (
    CLUSTER_CLIENT,
    CLUSTER_SERVER,
    ClusterStateManager,
    EpochFence,
)
from sentinel_tpu.cluster.token_service import TokenResult
from sentinel_tpu.core.config import config
from sentinel_tpu.utils import time_util


class ClusterServerSpec(NamedTuple):
    """One token-server seat in the cluster map."""

    machine_id: str
    host: str
    port: int


class ClusterMap(NamedTuple):
    """Datasource-pushed leadership assignment (the ``clusterMap``
    converter's output): WHO is the leader this epoch, the ordered
    failover list, and the client membership that sizes the
    degraded-quota share."""

    epoch: int
    servers: Tuple[ClusterServerSpec, ...]  # [0] = leader, rest standbys
    clients: Tuple[str, ...] = ()           # client machine ids (share divisor)
    namespace: str = "default"
    request_timeout_ms: int = 2000

    def leader(self) -> Optional[ClusterServerSpec]:
        return self.servers[0] if self.servers else None

    def server_for(self, machine_id: str) -> Optional[ClusterServerSpec]:
        for s in self.servers:
            if s.machine_id == machine_id:
                return s
        return None


def default_machine_id() -> str:
    """This instance's identity in cluster maps: the config override, or
    ``hostname@pid`` (unique per process, the upstream machineId shape)."""
    import os

    cfg = config.cluster_ha_machine_id()
    if cfg:
        return cfg
    return f"{socket.gethostname()}@{os.getpid()}"


class DegradedQuota:
    """Per-client share admission while no leader is reachable.

    Each flow's share is ``global_threshold / divisor`` where ``divisor``
    is the fleet's client count (from the cluster map, or the
    ``csp.sentinel.cluster.ha.degraded.divisor`` config): with every
    client running the same divisor >= the true client count, the sum of
    all clients' degraded admissions per window is <= the global
    threshold — bounded degradation instead of full-local amnesty
    (docs/SEMANTICS.md "Degraded-quota bound").

    Thresholds come from a callable (the engine's local copy of the
    cluster rules — in the reference deployment model the same rule
    object is pushed everywhere, so the local count IS the global
    threshold) or a static ``{flowId: (threshold, intervalMs)}`` dict.
    Admission reuses :class:`~sentinel_tpu.core.lease.LocalLease` — the
    host-side mirror ring already proven against the device window math.
    """

    def __init__(self, divisor: Optional[int] = None,
                 thresholds: Optional[Dict[int, Tuple[float, int]]] = None,
                 thresholds_fn: Optional[Callable[[], Dict]] = None):
        self.divisor = max(1, int(divisor if divisor is not None
                                  else config.cluster_ha_degraded_divisor()))
        self._static = thresholds
        self._fn = thresholds_fn
        self._lock = threading.Lock()
        self._buckets: Dict[int, tuple] = {}  # fid -> (share, interval, lease)
        self.granted_count = 0
        self.blocked_count = 0

    def thresholds(self) -> Dict[int, Tuple[float, int]]:
        if self._fn is not None:
            return self._fn() or {}
        return self._static or {}

    def acquire(self, flow_id, count: int = 1,
                now_ms: Optional[int] = None) -> Optional[TokenResult]:
        """OK/BLOCKED against this client's share, or None when the flow
        is unknown here (caller degrades to its local fallback)."""
        from sentinel_tpu.cluster.constants import TokenResultStatus
        from sentinel_tpu.core.lease import LocalLease

        try:
            fid = int(flow_id)
        except (TypeError, ValueError):
            return None
        info = self.thresholds().get(fid)
        if info is None:
            return None
        thr, interval_ms = float(info[0]), max(1, int(info[1]))
        if _chaos_reintroduced("degraded-amnesty"):
            # Known-fixed bug, deliberately reintroducible (chaos
            # shrinker proof-of-life — ISSUE 15): the pre-share behavior
            # granted every degraded verdict, voiding the sum-of-shares
            # bound the chaos campaign's invariant checker enforces.
            with self._lock:
                self.granted_count += 1
            return TokenResult(TokenResultStatus.OK)
        share = thr / self.divisor
        now = now_ms if now_ms is not None else time_util.current_time_millis()
        with self._lock:
            cached = self._buckets.get(fid)
            if cached is None or cached[0] != share or cached[1] != interval_ms:
                # One bucket spanning the whole interval: the provable
                # per-window bound needs interval-aligned accounting, not
                # a sliding approximation.
                cached = (share, interval_ms,
                          LocalLease([share], interval_ms, buckets=1))
                self._buckets[fid] = cached
            ok = cached[2].try_acquire(int(count), now)
            if ok:
                self.granted_count += 1
            else:
                self.blocked_count += 1
        return TokenResult(TokenResultStatus.OK if ok
                           else TokenResultStatus.BLOCKED)

    def snapshot(self) -> dict:
        with self._lock:
            return {"divisor": self.divisor,
                    "grantedCount": self.granted_count,
                    "blockedCount": self.blocked_count,
                    "flows": len(self._buckets)}


class FailoverTokenClient:
    """Token client over an ORDERED server list (leader first).

    One inner :class:`~sentinel_tpu.cluster.client.ClusterTokenClient`
    per target, each with its own ``HealthGate`` breaker and a snappy
    reconnect ``RetryPolicy`` (``csp.sentinel.cluster.ha.reconnect.ms``
    base) so a standby promotion lands inside the failover deadline.
    Every request goes to the first CONNECTED target in map order; a
    FAIL (timeout, stale epoch, disconnect) walks to the next. With no
    target connected, requests FAIL (local fallback) for at most
    ``failover.deadline.ms`` after connectivity loss — the reconnectors'
    race window — then the client enters degraded-quota mode and serves
    per-client-share verdicts wire-free until any target reconnects.
    """

    serves_degraded = True  # keeps client_if_active() routing to us

    def __init__(self, targets: List[Tuple[str, int]],
                 namespace: str = "default",
                 request_timeout_s: float = 2.0,
                 failover_deadline_ms: Optional[int] = None,
                 degraded: Optional[DegradedQuota] = None,
                 epoch_fence: Optional[EpochFence] = None,
                 reconnect_interval_s: Optional[float] = None,
                 connect_timeout_s: float = 1.0):
        from sentinel_tpu.cluster.client import ClusterTokenClient

        if not targets:
            raise ValueError("failover client needs at least one target")
        self.namespace = namespace
        self.fence = epoch_fence or EpochFence()
        self.failover_deadline_ms = int(
            failover_deadline_ms if failover_deadline_ms is not None
            else config.cluster_ha_failover_deadline_ms())
        if reconnect_interval_s is None:
            reconnect_interval_s = config.cluster_ha_reconnect_ms() / 1000.0
        self.degraded = degraded or DegradedQuota()
        self._clients = [
            ClusterTokenClient(host, port, namespace,
                               request_timeout_s=request_timeout_s,
                               reconnect_interval_s=reconnect_interval_s,
                               epoch_fence=self.fence,
                               connect_timeout_s=connect_timeout_s)
            for host, port in targets]
        self._lock = threading.Lock()
        self._active_idx = 0
        self.failover_count = 0
        self.last_failover_ms = -1
        # Overload backoff (ISSUE 6): a target that replied OVERLOADED
        # is skipped for its retry-after window instead of being walked
        # into again on every entry — and an overloaded reply is NOT a
        # failure toward the lost->degraded clock (the server is alive,
        # just saturated; hammering it with failover traffic is exactly
        # the collapse amplification this layer exists to prevent).
        self._backoff_until_ms = [0] * len(targets)
        self.overloaded_count = 0
        # Degraded-mode accounting: _lost_at_ms marks total connectivity
        # loss (-1 = connected recently); _degraded_since_ms marks the
        # deadline expiring; degraded_total_ms accumulates closed spells.
        self._lost_at_ms = -1
        self._degraded_since_ms = -1
        self.degraded_total_ms = 0
        self.degraded_entry_count = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FailoverTokenClient":
        for c in self._clients:
            c.start()
        return self

    def stop(self) -> None:
        for c in self._clients:
            c.stop()
        self._note_connected()  # close any open degraded spell

    def is_connected(self) -> bool:
        return any(c.is_connected() for c in self._clients)

    @property
    def health_gate(self):
        """The ACTIVE target's breaker (resilience_stats surface)."""
        return self._clients[self._active_idx].health_gate

    @property
    def targets(self) -> List[str]:
        return [f"{c.host}:{c.port}" for c in self._clients]

    # -- degraded-mode bookkeeping ----------------------------------------

    def _note_connected(self) -> None:
        with self._lock:
            if self._degraded_since_ms >= 0:
                self.degraded_total_ms += max(
                    0, time_util.current_time_millis()
                    - self._degraded_since_ms)
            self._degraded_since_ms = -1
            self._lost_at_ms = -1

    def _degraded_now(self) -> bool:
        """Advance the lost->degraded state machine; True once the
        failover deadline has fully elapsed with no connection."""
        now = time_util.current_time_millis()
        with self._lock:
            if self._degraded_since_ms >= 0:
                return True
            if self._lost_at_ms < 0:
                self._lost_at_ms = now
                return False
            if now - self._lost_at_ms >= self.failover_deadline_ms:
                self._degraded_since_ms = now
                return True
            return False

    def is_degraded(self) -> bool:
        return self._degraded_since_ms >= 0

    def degraded_seconds(self) -> float:
        total = self.degraded_total_ms
        if self._degraded_since_ms >= 0:
            total += max(0, time_util.current_time_millis()
                         - self._degraded_since_ms)
        return total / 1000.0

    def failover_stats(self) -> dict:
        now = time_util.current_time_millis()
        return {
            "failoverCount": self.failover_count,
            "lastFailoverMs": self.last_failover_ms,
            "degraded": self.is_degraded(),
            "degradedEntries": self.degraded_entry_count,
            "degradedSeconds": round(self.degraded_seconds(), 3),
            "activeTarget": self.targets[self._active_idx],
            "targets": self.targets,
            "degradedQuota": self.degraded.snapshot(),
            "overloadedCount": self.overloaded_count,
            "targetsBackedOff": sum(
                1 for t in self._backoff_until_ms if t > now),
        }

    # -- requests ----------------------------------------------------------

    def _note_failover(self, idx: int) -> None:
        with self._lock:
            if idx != self._active_idx:
                self._active_idx = idx
                self.failover_count += 1
                self.last_failover_ms = time_util.current_time_millis()

    def _note_overload(self, idx: int, retry_after_ms: int) -> None:
        backoff = max(int(retry_after_ms),
                      config.overload_client_backoff_ms())
        with self._lock:
            self.overloaded_count += 1
            self._backoff_until_ms[idx] = (
                time_util.current_time_millis() + backoff)

    def _request(self, fn, degraded_fn,
                 timeout_s: Optional[float] = None) -> TokenResult:
        from sentinel_tpu.cluster.constants import TokenResultStatus

        # The caller's timeout is a budget for the WHOLE walk, not per
        # target: each attempt gets only what remains, so one data-path
        # entry never blocks N x its deadline budget when several
        # targets are up but unresponsive during a transition.
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        now_ms = time_util.current_time_millis()
        overload_hint = backed_off = None
        for idx, c in enumerate(self._clients):
            if not c.is_connected():
                continue
            if self._backoff_until_ms[idx] > now_ms:
                # Inside this target's overload-backoff window: skip it
                # without touching the wire (the retry-after contract).
                backed_off = self._backoff_until_ms[idx] - now_ms
                continue
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
            tr = fn(c, remaining)
            if tr.status == TokenResultStatus.OVERLOADED:
                # First-class overload: back this target off for the
                # server's retry-after hint and walk on. NOT a failure
                # toward failover/degraded — the reply itself proves the
                # server is alive.
                self._note_overload(idx, tr.wait_ms)
                overload_hint = tr.wait_ms
                continue
            if tr.status != TokenResultStatus.FAIL:
                self._note_failover(idx)
                self._note_connected()
                return tr
            # FAIL: breaker-open, timeout, garbage, or stale epoch —
            # walk on to the next target in map order.
        if overload_hint is not None or backed_off is not None:
            # Every reachable target is shedding (or still inside its
            # backoff window): report OVERLOADED so the engine degrades
            # this entry to the local lease/fallback path. A fresh
            # OVERLOADED reply resets the lost->degraded clock (the
            # fleet is reachable); a backoff-only round leaves the clock
            # alone — no new evidence either way.
            if overload_hint is not None:
                self._note_connected()
            return TokenResult(
                TokenResultStatus.OVERLOADED,
                wait_ms=int(overload_hint if overload_hint is not None
                            else backed_off))
        # No target produced a verdict. That includes the half-open case
        # (connected to a partitioned leader): a round with zero
        # verdicts advances the lost->degraded clock; any success resets
        # it, so one transient timeout never reaches degraded mode — the
        # full failover deadline must elapse verdict-free first.
        if self._degraded_now():
            self.degraded_entry_count += 1
            result = degraded_fn()
            if result is not None:
                return result
        return TokenResult(TokenResultStatus.FAIL)

    def request_token(self, flow_id, count: int = 1,
                      prioritized: bool = False,
                      timeout_s: Optional[float] = None,
                      gate_neutral: bool = False,
                      trace=None) -> TokenResult:
        return self._request(
            lambda c, t: c.request_token(flow_id, count, prioritized,
                                         timeout_s=t,
                                         gate_neutral=gate_neutral,
                                         trace=trace),
            lambda: self.degraded.acquire(flow_id, count),
            timeout_s=timeout_s)

    def request_param_token(self, flow_id, count, params,
                            timeout_s: Optional[float] = None,
                            gate_neutral: bool = False,
                            trace=None) -> TokenResult:
        # Param-flow degraded verdicts are NOT share-partitioned (per-key
        # global buckets have no local mirror): degraded mode returns
        # None -> FAIL -> the rule's configured local fallback.
        return self._request(
            lambda c, t: c.request_param_token(flow_id, count, params,
                                               timeout_s=t,
                                               gate_neutral=gate_neutral,
                                               trace=trace),
            lambda: None,
            timeout_s=timeout_s)


class ClusterHAManager:
    """Drives one instance's cluster role from datasource-pushed
    :class:`ClusterMap`s (the embedded-mode ``ClusterStateManager``
    pattern): ``apply_map`` flips CLIENT<->SERVER, epoch-fences each
    term, publishes/restores window checkpoints across the handoff, and
    ignores maps older than the one applied (a delayed datasource push
    must not resurrect a deposed leader)."""

    def __init__(self, engine=None, state: Optional[ClusterStateManager] = None,
                 machine_id: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_period_s: Optional[float] = None,
                 server_host: str = "0.0.0.0"):
        self.engine = engine
        self.state = state if state is not None else (
            engine.cluster if engine is not None else ClusterStateManager())
        self.machine_id = machine_id or default_machine_id()
        self.checkpoint_path = (checkpoint_path
                                or config.cluster_ha_checkpoint_path())
        self.checkpoint_period_s = (
            checkpoint_period_s if checkpoint_period_s is not None
            else config.cluster_ha_checkpoint_period_ms() / 1000.0)
        self.server_host = server_host
        self.map: Optional[ClusterMap] = None
        # Sharded assignment (cluster/sharding.py — ISSUE 12): the last
        # ShardMap applied, plus handoff accounting. A manager follows
        # EITHER plain cluster maps or shard maps; apply_map dispatches
        # on the pushed type.
        self.shard_map = None
        self.handoffs = 0
        self.checkpoints_published = 0
        self.rows_restored = 0
        self._lock = threading.RLock()
        self._ckpt_timer = None
        # Failed-transition retry cadence (apply_map): the datasource
        # property never re-fires an unchanged map, so retries are ours.
        self.retry_delay_s = config.cluster_ha_reconnect_ms() / 1000.0
        self._retry_timer = None
        self.state.ha = self
        # Audit-journal back-pointers (ISSUE 14): each map apply links
        # to the previous one, so the journal shows the assignment
        # history as one causal chain per kind.
        self._map_jseq = None
        self._shard_jseq = None

    def _journal(self):
        return getattr(self.engine, "journal", None) \
            if self.engine is not None else None

    # -- datasource wiring -------------------------------------------------

    def watch(self, prop) -> None:
        """Subscribe to a datasource property whose converter is
        ``cluster_map_from_json`` (datasource/converters.py)."""
        from sentinel_tpu.core.property import SimplePropertyListener

        prop.add_listener(SimplePropertyListener(self.apply_map))

    def apply_map(self, cmap: Optional[ClusterMap]) -> None:
        if cmap is None:
            return
        from sentinel_tpu.cluster.sharding import ShardMap

        if isinstance(cmap, ShardMap):
            self.apply_shard_map(cmap)
            return
        from sentinel_tpu.log.record_log import record_log

        with self._lock:
            if self.map is not None and cmap.epoch < self.map.epoch:
                record_log.warn(
                    "ignoring stale cluster map epoch %d (< applied %d)",
                    cmap.epoch, self.map.epoch)
                return
            # The wire is a map source too: responses stamped with a
            # higher epoch prove a newer term exists, so a delayed map
            # below the fence must not promote a leader the whole
            # fleet's fences would reject.
            if cmap.epoch < self.state.fence.highest_seen:
                record_log.warn(
                    "ignoring stale cluster map epoch %d (< observed %d)",
                    cmap.epoch, self.state.fence.highest_seen)
                return
            leader = cmap.leader()
            mine = cmap.server_for(self.machine_id)
            # The apply record lands BEFORE the transition it drives,
            # and the transition runs under causing(seq): the haRoleFlip
            # the transition commits links back to this map — the
            # journal's "why did this seat flip" answer.
            j = self._journal()
            jseq = j.record(
                "clusterMapApply", epoch=int(cmap.epoch),
                leader=leader.machine_id if leader else None,
                servers=[s.machine_id for s in cmap.servers],
                cause_seq=self._map_jseq) if j is not None else None
            try:
                with (journal_causing(jseq) if j is not None
                      else contextlib.nullcontext()):
                    if leader is not None and mine is not None \
                            and mine.machine_id == leader.machine_id:
                        self._become_server(cmap, mine)
                    else:
                        self._become_client(cmap)
                self._map_jseq = jseq
            except Exception as ex:  # noqa: BLE001 — transition must retry
                # Do NOT commit the map: the datasource property caches
                # its value and never re-fires for an unchanged map, so
                # a swallowed transition failure (e.g. EADDRINUSE from a
                # lingering listener) would otherwise strand this seat
                # NOT_STARTED until a human bumps the epoch — in the
                # subsystem built to survive exactly that. Retry on a
                # timer instead; newer maps win via the epoch guards.
                record_log.warn(
                    "cluster map epoch %d transition failed: %r — "
                    "retrying in %.1fs", cmap.epoch, ex, self.retry_delay_s)
                self._schedule_retry(cmap)
                return
            self.map = cmap

    def _schedule_retry(self, cmap: ClusterMap) -> None:
        with self._lock:
            if self._retry_timer is not None:
                # Latest map wins: never leave a newer failed map
                # unretried behind an older pending retry.
                self._retry_timer.cancel()
            t = threading.Timer(self.retry_delay_s, self._retry_apply,
                                args=(cmap,))
            t.daemon = True
            self._retry_timer = t
            t.start()

    def _retry_apply(self, cmap: ClusterMap) -> None:
        with self._lock:
            self._retry_timer = None
        self.apply_map(cmap)

    def transition_pending(self) -> bool:
        """True while a failed map transition awaits its retry timer —
        this seat is MID-HANDOFF and must not be a rebalance donor or
        recipient (the rebalancer's veto input)."""
        with self._lock:
            return self._retry_timer is not None

    # -- role transitions --------------------------------------------------

    def _become_server(self, cmap: ClusterMap, me: ClusterServerSpec) -> None:
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.core import checkpoint as ckpt
        from sentinel_tpu.log.record_log import record_log

        srv = self.state.token_server
        if srv is not None and self.state.mode == CLUSTER_SERVER \
                and srv.epoch == cmap.epoch and not srv.crashed:
            return  # already this term's leader — no churn
        service = DefaultTokenService(rules=self.state.server_rules(),
                                      epoch=cmap.epoch)
        if srv is not None and self.checkpoint_path:
            # In-process re-promotion (same seat, new term — including a
            # crashed server's rebuild): the freshest window state lives
            # in the OLD service, not on disk. Publish it BEFORE the
            # restore below reads the file, or the new term would warm-
            # start from the last periodic snapshot and re-admit every
            # grant made since (the teardown publish inside set_to_server
            # lands only after the restore already ran).
            try:
                ckpt.save_cluster_checkpoint(srv.service, self.checkpoint_path)
                self.checkpoints_published += 1
            except Exception as ex:  # noqa: BLE001 — best-effort pre-drain
                record_log.warn("pre-promotion checkpoint failed: %r", ex)
        if self.checkpoint_path:
            try:
                self.rows_restored += ckpt.restore_cluster_checkpoint(
                    service, self.checkpoint_path)
            except FileNotFoundError:
                pass  # first leader of a fresh cluster: cold start
            except ValueError as ex:
                record_log.warn("cluster checkpoint not restored: %s", ex)
        # Warm the acquire jit BEFORE binding the port: the width-1
        # compile can outlast a client's request timeout (r5 measured),
        # which would burn most of the failover deadline on the very
        # first post-promotion token. A no-rule probe (flow None ->
        # NO_RULE_EXISTS) compiles without consuming any flow's quota.
        try:
            service.request_tokens([(None, 0, False)])
        except Exception as ex:  # noqa: BLE001 — warm-up is best-effort
            record_log.warn("token-service warm-up failed: %r", ex)
        # set_to_server tears the old role down first (on_server_teardown
        # publishes the outgoing leader's final checkpoint).
        self.state.set_to_server(host=self.server_host, port=me.port,
                                 service=service, epoch=cmap.epoch)
        if self.checkpoint_path:
            self._ckpt_timer = ckpt.CheckpointTimer(
                service, self.checkpoint_path,
                period_s=self.checkpoint_period_s,
                save=ckpt.save_cluster_checkpoint).start()

    def _become_client(self, cmap: ClusterMap) -> None:
        # No-churn guard (mirror of _become_server's): a map change that
        # leaves this seat a client of the SAME server list must not
        # tear down the live failover client — dropping its sockets
        # mid-traffic fails in-flight requests fleet-wide and zeroes the
        # failover/degraded counters the exporter publishes as
        # monotonic _total series.
        cur = self.state.token_client
        if (self.state.mode == CLUSTER_CLIENT
                and isinstance(cur, FailoverTokenClient)
                and cur.targets == [f"{s.host}:{s.port}"
                                    for s in cmap.servers]
                and cur.namespace == cmap.namespace):
            # The CURRENT map decides the divisor — falling back to the
            # config default when it lists no clients, exactly as a
            # freshly built client would (behavior must not depend on
            # map-push history).
            cur.degraded.divisor = max(1, len(cmap.clients)
                                       if cmap.clients
                                       else config.cluster_ha_degraded_divisor())
            for inner in cur._clients:  # timeout is read per request
                inner.request_timeout_s = max(cmap.request_timeout_ms,
                                              1) / 1000.0
            self.state.epoch = int(cmap.epoch)
            self.state.fence.observe(cmap.epoch)
            return
        if self.engine is not None:
            thresholds_fn = self.engine.cluster_degraded_thresholds
        else:
            # Engine-less participant (standalone HA seat): degraded
            # shares come from the staged server rules it would serve
            # with as leader — same rule objects, same thresholds.
            thresholds_fn = self.state.server_rules().thresholds
        divisor = len(cmap.clients) if cmap.clients else None
        client = FailoverTokenClient(
            [(s.host, s.port) for s in cmap.servers],
            namespace=cmap.namespace,
            request_timeout_s=max(cmap.request_timeout_ms, 1) / 1000.0,
            degraded=DegradedQuota(divisor=divisor,
                                   thresholds_fn=thresholds_fn),
            epoch_fence=self.state.fence)
        # set_client tears the old role down first (a deposed leader
        # drains: on_server_teardown publishes its final checkpoint).
        self.state.set_client(client)
        self.state.epoch = int(cmap.epoch)
        self.state.fence.observe(cmap.epoch)

    # -- sharded multi-leader assignment (cluster/sharding.py) -------------

    def apply_shard_map(self, smap) -> None:
        """Adopt a :class:`~sentinel_tpu.cluster.sharding.ShardMap`:
        become (or stay) the leader of the slices it assigns this seat —
        publishing handoff checkpoints for slices LOST and warm-starting
        slices GAINED — or route as a sharded client of the leader set.

        Chaos seams: ``cluster.shard.map.split`` (an armed error makes
        this seat sit out the push — the fleet splits across map
        versions, which per-slice fencing + WRONG_SLICE self-healing
        must absorb) and ``cluster.shard.donor.zombie`` (a donor that
        neither publishes nor fences — its stale-epoch replies must be
        fence-rejected fleet-wide)."""
        from sentinel_tpu.cluster.state import SliceEpochFence
        from sentinel_tpu.log.record_log import record_log
        from sentinel_tpu.resilience import faults

        with self._lock:
            cur = self.shard_map
            if cur is not None and smap.version < cur.version:
                record_log.warn(
                    "ignoring stale shard map version %d (< applied %d)",
                    smap.version, cur.version)
                return
            if cur is not None and smap.n_slices != cur.n_slices:
                record_log.warn(
                    "rejecting shard map version %d: ring size %d != "
                    "applied %d (the slice ring is fixed for a cluster's "
                    "lifetime)", smap.version, smap.n_slices, cur.n_slices)
                return
            try:
                faults.fire("cluster.shard.map.split")
            except OSError:
                record_log.warn(
                    "shard map version %d not applied (map.split fault): "
                    "seat stays on version %s", smap.version,
                    cur.version if cur else None)
                return
            # Per-slice terms need a per-slice fence; swap the global
            # fence in before any role runs under this map (duck-typed:
            # EpochFence callers keep working through scope=None).
            if not isinstance(self.state.fence, SliceEpochFence):
                self.state.fence = SliceEpochFence()
            mine = smap.epochs_of(self.machine_id)
            spec = smap.server_for(self.machine_id)
            srv = self.state.token_server
            cur_shard = (srv.service.shard
                         if srv is not None and not srv.crashed
                         and self.state.mode == CLUSTER_SERVER else None)
            if cur_shard is not None and set(cur_shard.epochs) - set(mine):
                # This seat is a DONOR under the new map (losing one or
                # more slices — possibly all of them). Zombie seam: when
                # armed, the donor neither publishes nor fences; it
                # keeps granting the moved slices at their old epochs,
                # and the fleet's per-slice fences must reject those
                # late replies (pinned by the chaos suite).
                try:
                    faults.fire("cluster.shard.donor.zombie")
                except OSError:
                    record_log.warn(
                        "shard map version %d ignored (donor.zombie "
                        "fault): still serving %d deposed slice(s)",
                        smap.version,
                        len(set(cur_shard.epochs) - set(mine)))
                    return
            j = self._journal()
            # An ambient cause (the rebalancer applying under
            # ``causing(applySeq)``) outranks the per-kind back-pointer:
            # the apply record then chains propose -> certify -> apply ->
            # shardMapApply -> haRoleFlip instead of just map-to-map.
            cause = journal_cause()
            jseq = j.record(
                "shardMapApply", version=int(smap.version),
                nSlices=int(smap.n_slices),
                role="server" if (mine and spec is not None) else "client",
                slicesOwned=sorted(int(s) for s in mine),
                sliceEpochs={str(s): int(e) for s, e in sorted(mine.items())},
                cause_seq=cause if cause is not None
                else self._shard_jseq) if j is not None else None
            try:
                with (journal_causing(jseq) if j is not None
                      else contextlib.nullcontext()):
                    if mine and spec is not None:
                        self._become_shard_server(smap, spec, mine)
                    else:
                        self._become_shard_client(smap)
                self._shard_jseq = jseq
            except Exception as ex:  # noqa: BLE001 — transition must retry
                record_log.warn(
                    "shard map version %d transition failed: %r — "
                    "retrying in %.1fs", smap.version, ex,
                    self.retry_delay_s)
                self._schedule_retry(smap)
                return
            self.shard_map = smap
            self.state.epoch = int(max(smap.slice_epoch, default=0))

    def _slice_ckpt_base(self) -> Optional[str]:
        return config.cluster_shard_handoff_path() or self.checkpoint_path

    def _slice_ckpt_path(self, slice_id: int) -> str:
        """The shared per-slice handoff file: donor publishes, recipient
        restores — the slice-granular twin of the PR 5 shared
        checkpoint file."""
        return f"{self._slice_ckpt_base()}.s{int(slice_id):03d}"

    def _publish_slice(self, service, slice_id: int, epoch: int,
                       n_slices: int) -> None:
        from sentinel_tpu.core import checkpoint as ckpt
        from sentinel_tpu.resilience import faults

        if not self._slice_ckpt_base():
            return  # no shared handoff storage configured: cold handoffs
        # Handoff-stall seam (delay mode): a slow NFS / pod eviction
        # stalling the publish — the recipient may warm-start from an
        # OLDER file; the over-admission bound degrades gracefully to
        # grants-since-THAT-publish, never breaks.
        faults.fire("cluster.shard.handoff.stall")
        ckpt.save_cluster_checkpoint(
            service, self._slice_ckpt_path(slice_id),
            slices=(slice_id,), n_slices=n_slices, epoch=epoch)
        self.checkpoints_published += 1

    def _become_shard_server(self, smap, me, mine) -> None:
        """Leader-side map application; ``mine`` is {slice: epoch}.
        (The donor-zombie seam fires in apply_shard_map, before any
        transition; reaching here means the map IS being applied.)"""
        from sentinel_tpu.cluster.sharding import ShardState
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.core import checkpoint as ckpt
        from sentinel_tpu.log.record_log import record_log

        srv = self.state.token_server
        same_seat = (srv is not None and self.state.mode == CLUSTER_SERVER
                     and not srv.crashed and srv.bound_port == me.port)
        old_shard = srv.service.shard if srv is not None else None
        if same_seat and old_shard is not None:
            service = srv.service
            lost = sorted(set(old_shard.epochs) - set(mine))
            gained = sorted(set(mine) - set(old_shard.epochs))
            for sl in lost:
                # Publish BEFORE fencing ourselves out: grants between
                # this publish and set_shard below are the (bounded)
                # handoff over-admission margin.
                try:
                    self._publish_slice(service, sl,
                                        old_shard.epochs.get(sl, 0),
                                        smap.n_slices)
                    self.handoffs += 1
                except Exception as ex:  # noqa: BLE001 — best-effort drain
                    record_log.warn(
                        "slice %d handoff publish failed: %r", sl, ex)
            for sl in gained if self._slice_ckpt_base() else ():
                try:
                    self.rows_restored += ckpt.restore_cluster_checkpoint(
                        service, self._slice_ckpt_path(sl),
                        slices=(sl,), n_slices=smap.n_slices)
                    self.handoffs += 1
                except FileNotFoundError:
                    pass  # no donor publish yet: slice starts cold
                except ValueError as ex:
                    record_log.warn(
                        "slice %d handoff not restored: %s", sl, ex)
            service.set_shard(ShardState(smap.n_slices, smap.version,
                                         dict(mine)))
            for sl, ep in mine.items():
                self.state.fence.observe(ep, sl)
            return
        # Fresh promotion (was a client / NOT_STARTED / crashed / moved
        # port): build a service, warm-start every owned slice, bind.
        service = DefaultTokenService(rules=self.state.server_rules())
        if srv is not None and old_shard is not None:
            # In-process re-promotion: the freshest rows live in the OLD
            # service — publish its slices before restoring (the PR 5
            # same-seat argument, per slice).
            for sl, ep in old_shard.epochs.items():
                try:
                    self._publish_slice(srv.service, sl, ep,
                                        old_shard.n_slices)
                except Exception as ex:  # noqa: BLE001
                    record_log.warn(
                        "pre-promotion slice %d publish failed: %r", sl, ex)
        elif (srv is not None and not srv.crashed
              and self.state.mode == CLUSTER_SERVER):
            # A FLAT (PR 5) leader adopting its first shard map owned
            # the WHOLE key space: publish EVERY ring slice from the
            # live flat service — the slices this seat keeps warm-start
            # below, and the ones handed to other leaders graft on THEIR
            # restore. Skipping this would cold-start every flow
            # mid-window, voiding the grants-since-publish bound for the
            # whole migration. Files carry the flat term (the successor
            # epochs supersede it on their first periodic publish).
            flat_epoch = int(getattr(srv.service, "epoch", 0))
            for sl in range(int(smap.n_slices)):
                try:
                    self._publish_slice(srv.service, sl, flat_epoch,
                                        smap.n_slices)
                except Exception as ex:  # noqa: BLE001
                    record_log.warn(
                        "flat-migration slice %d publish failed: %r",
                        sl, ex)
        service.set_shard(ShardState(smap.n_slices, smap.version,
                                     dict(mine)))
        for sl in sorted(mine) if self._slice_ckpt_base() else ():
            try:
                self.rows_restored += ckpt.restore_cluster_checkpoint(
                    service, self._slice_ckpt_path(sl),
                    slices=(sl,), n_slices=smap.n_slices)
            except FileNotFoundError:
                pass  # cold slice
            except ValueError as ex:
                record_log.warn("slice %d not restored: %s", sl, ex)
        try:
            service.request_tokens([(None, 0, False)])  # pre-bind jit warm
        except Exception as ex:  # noqa: BLE001 — warm-up is best-effort
            record_log.warn("token-service warm-up failed: %r", ex)
        self.state.set_to_server(host=self.server_host, port=me.port,
                                 service=service,
                                 epoch=int(max(mine.values())))
        service.set_shard(ShardState(smap.n_slices, smap.version,
                                     dict(mine)))  # epoch overwritten above
        for sl, ep in mine.items():
            self.state.fence.observe(ep, sl)
        if self._slice_ckpt_base():
            self._ckpt_timer = ckpt.CheckpointTimer(
                service, "<per-slice>", period_s=self.checkpoint_period_s,
                save=self._shard_timer_save).start()
        return

    def _shard_timer_save(self, service, _path) -> None:
        """Periodic publish for a sharded leader: one handoff file per
        OWNED slice, each fenced at its own epoch — the files a
        successor warm-starts from after a crash (grants since the last
        tick = the per-slice over-admission margin)."""
        shard = service.shard
        if shard is None:
            return
        for sl, ep in shard.epochs.items():
            self._publish_slice(service, sl, ep, shard.n_slices)

    def _become_shard_client(self, smap) -> None:
        from sentinel_tpu.cluster.sharding import ShardedTokenClient

        cur = self.state.token_client
        if (self.state.mode == CLUSTER_CLIENT
                and isinstance(cur, ShardedTokenClient)
                and cur.apply_map(smap)):
            # Same client, new map: sockets for unchanged leaders were
            # reused in place (no reconnect storm on a rebalance — the
            # PR 5 same-target pin extended to the per-leader pool).
            return
        if self.engine is not None:
            thresholds_fn = self.engine.cluster_degraded_thresholds
        else:
            thresholds_fn = self.state.server_rules().thresholds
        client = ShardedTokenClient(
            smap, fence=self.state.fence, thresholds_fn=thresholds_fn,
            # Walk spans (ISSUE 14) join the engine's span collector so
            # a sharded client's self-heal/failover routes stitch into
            # the same traces the entry path samples.
            spans=getattr(self.engine, "spans", None)
            if self.engine is not None else None)
        self.state.set_client(client)

    # -- checkpoint plumbing -----------------------------------------------

    def on_server_teardown(self, server) -> None:
        """ClusterStateManager teardown hook: graceful drain publishes
        the outgoing leader's final window checkpoint (a crashed server
        already lost its listener — publishing its last state is still
        correct and only tightens the successor's margin)."""
        if self._ckpt_timer is not None:
            self._ckpt_timer.stop()
            self._ckpt_timer = None
        from sentinel_tpu.log.record_log import record_log

        shard = getattr(server.service, "shard", None)
        if shard is not None:
            # Sharded drain: one final publish per owned slice, each
            # fenced at its own epoch — the successors' warm-start.
            try:
                self._shard_timer_save(server.service, None)
            except Exception as ex:  # noqa: BLE001 — drain is best-effort
                record_log.warn("shard drain checkpoint failed: %r", ex)
            return
        if not self.checkpoint_path:
            return
        from sentinel_tpu.core import checkpoint as ckpt

        try:
            ckpt.save_cluster_checkpoint(server.service, self.checkpoint_path)
            self.checkpoints_published += 1
        except Exception as ex:  # noqa: BLE001 — drain is best-effort
            record_log.warn("drain checkpoint failed: %r", ex)

    def publish_checkpoint(self) -> None:
        """One immediate checkpoint publish (ops / tests): per owned
        slice on a sharded leader, the single shared file otherwise."""
        srv = self.state.token_server
        if srv is None:
            return
        if getattr(srv.service, "shard", None) is not None:
            self._shard_timer_save(srv.service, None)
            return
        if self.checkpoint_path:
            from sentinel_tpu.core import checkpoint as ckpt

            ckpt.save_cluster_checkpoint(srv.service, self.checkpoint_path)
            self.checkpoints_published += 1

    def stats(self) -> dict:
        # Deliberately lock-free: apply_map holds self._lock across a
        # whole promotion (restore I/O + jit warm-up + bind), and the
        # /metrics scrape must not hang on it at exactly the moment
        # operators are watching a failover. Plain attribute reads are
        # atomic; a scrape racing a flip just sees the old values.
        cmap = self.map
        smap = self.shard_map
        return {
            "machineId": self.machine_id,
            "mapEpoch": cmap.epoch if cmap else None,
            "shardMapVersion": smap.version if smap else None,
            "handoffs": self.handoffs,
            "checkpointsPublished": self.checkpoints_published,
            "rowsRestored": self.rows_restored,
        }

    def stop(self) -> None:
        with self._lock:
            if self._retry_timer is not None:
                self._retry_timer.cancel()
                self._retry_timer = None
        self.state.stop()

"""Randomized differential fuzz: cluster token service vs serial oracle.

`DefaultTokenService.request_tokens` claims serial-exact arrival-order
admission (the reference's per-request CAS semantics folded into one
`lax.scan`). This fuzz replays randomized batches — mixed flow ids,
counts, prioritized occupy requests, unknown ids, random time advances
across bucket and window boundaries — against a sequential pure-Python
oracle mirroring the ring geometry (shared bucket count, per-rule
bucket_ms, lazy expected-start reset) and requires identical
(status, extra) for every request: extra is `remaining` for OK and the
time-to-next-bucket for SHOULD_WAIT.

One fixed batch width (padded with an unknown flowId) keeps this at a
single jit specialization.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import constants as CC
from tests.oracle import OracleLeapArray
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.token_service import DefaultTokenService

WIDTH = 32
NOW0 = 1_700_000_000_000
BUCKETS = CC.DEFAULT_SAMPLE_COUNT
P_CH, W_CH = 0, 1  # OracleLeapArray channels: pass, waiting


@pytest.mark.parametrize("seed", [5, 17, 41])
def test_token_service_matches_serial_oracle(seed):
    rng = np.random.default_rng(seed)
    flows = {}
    rules = []
    for i in range(16):
        fid = 1000 + i
        thr = float(rng.integers(0, 20))
        interval = int(rng.choice([500, 1000, 2000]))
        ttype = int(rng.choice([CC.THRESHOLD_GLOBAL, CC.THRESHOLD_AVG_LOCAL]))
        flows[fid] = {"thr": thr, "interval": interval, "ttype": ttype,
                      "ring": OracleLeapArray(interval, BUCKETS, 2)}
        rules.append(st.FlowRule(
            resource=f"clus{i}", count=thr, cluster_mode=True,
            cluster_config={"flowId": fid, "thresholdType": ttype,
                            "windowIntervalMs": interval}))
    mgr = ClusterFlowRuleManager()
    mgr.load_rules("default", rules)
    svc = DefaultTokenService(mgr)
    # Live connections make AVG_LOCAL a real branch: effective threshold
    # = count x max(connected, 1) for AVG_LOCAL rules only.
    n_conns = int(rng.integers(1, 4))
    for _ in range(n_conns):
        svc.connections.connect("default")
    for f in flows.values():
        if f["ttype"] == CC.THRESHOLD_AVG_LOCAL:
            f["thr"] = f["thr"] * max(n_conns, 1)
    fids = sorted(flows)

    now = NOW0
    for step in range(40):
        now += int(rng.integers(0, 300))
        n = int(rng.integers(4, WIDTH + 1))
        batch = []
        for _ in range(n):
            batch.append((fids[int(rng.integers(0, len(fids)))],
                          int(rng.integers(1, 4)),
                          bool(rng.random() < 0.25)))
        batch += [(999, 1, False)] * (WIDTH - n)  # unknown-id padding

        results = svc.request_tokens(batch, now_ms=now)

        # Sequential oracle over the same batch (AVG_LOCAL thresholds
        # already scaled by the registered connection count above).
        for i, (fid, c, prio) in enumerate(batch[:n]):
            f = flows[fid]
            p = f["ring"].total(now, P_CH)
            w = f["ring"].total(now, W_CH)
            scale = 1000.0 / f["interval"]
            used = (p + w) * scale
            bm = f["interval"] // BUCKETS
            if used + c <= f["thr"]:
                want = CC.TokenResultStatus.OK
                want_extra = int(max(f["thr"] - used - c, 0))
                f["ring"].current(now)  # lazy reset
                f["ring"].add(now, P_CH, c)
            elif prio and w + c <= 1.0 * f["thr"]:  # maxOccupyRatio 1.0
                want = CC.TokenResultStatus.SHOULD_WAIT
                want_extra = int(bm - now % bm)
                f["ring"].current(now)
                f["ring"].add(now, W_CH, c)
            else:
                want = CC.TokenResultStatus.BLOCKED  # no quota consumed
                want_extra = 0
            got = results[i]
            assert got.status == want, (
                f"seed {seed} step {step} req {i} ({fid},{c},{prio}): "
                f"device {got.status} != oracle {want}")
            if want == CC.TokenResultStatus.OK:
                assert got.remaining == want_extra, (
                    f"seed {seed} step {step} req {i}: remaining "
                    f"{got.remaining} != {want_extra}")
            elif want == CC.TokenResultStatus.SHOULD_WAIT:
                assert got.wait_ms == want_extra, (
                    f"seed {seed} step {step} req {i}: wait "
                    f"{got.wait_ms} != {want_extra}")
        for r in results[n:]:
            assert r.status == CC.TokenResultStatus.NO_RULE_EXISTS

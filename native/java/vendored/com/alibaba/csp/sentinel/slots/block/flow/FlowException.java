package com.alibaba.csp.sentinel.slots.block.flow;

import com.alibaba.csp.sentinel.slots.block.BlockException;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/block/flow/FlowException.java. */
public class FlowException extends BlockException {

    public FlowException(String ruleLimitApp) {
        super(ruleLimitApp);
    }

    public FlowException(String ruleLimitApp, String message) {
        super(ruleLimitApp, message);
    }
}

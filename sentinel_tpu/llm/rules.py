"""TPS rules: per-(model, tenant) weighted-cost limits, lowered onto flow.

``TpsRule`` limits *tokens* per second for one model (optionally one
tenant): ``tokensPerSecond`` steady-state budget, ``burstTokens`` extra
headroom inside the 1s window, ``maxConcurrentStreams`` an optional cap
on simultaneously-open streaming reservations.

The family adds NO new device machinery.  ``lower_tps_rules`` compiles
each TPS rule into a QPS-grade DEFAULT-behavior :class:`FlowRule` on the
synthetic resource ``llm:{model}`` with ``count = tokensPerSecond +
burstTokens`` — the fused step's mixed-count path debits an N-token
acquire against that window exactly, so token budgets inherit every
existing property: device-exact windows, the token-lease fast path,
cluster mode (a ``clusterConfig.flowId`` forwards verbatim, so remote
enforcement and the HA degraded-quota path cover lowered rules with no
special cases), shadow/canary rollout, and adaptive retuning (a
default-tenant lowered rule satisfies the adaptive loop's tunable
shape).  Lowered rules carry ``derived_from="tps"``; each TPS load
strips previously-derived rules before re-injecting, so the lowering
is idempotent.  An operator flow-rule push REPLACES the whole flow
list — lowered rules vanish until the next TPS load re-lowers (the
documented contract: push TPS rules through the ``tps`` family, not by
hand-editing their lowered form).

Degradation: when the cluster path is lost, ``degraded_tps_quota``
builds the HA :class:`DegradedQuota` over the lowered cluster-mode
rules' thresholds — each client gets threshold/clients tokens per
window, so the sum of tenant shares never exceeds the global budget
(SEMANTICS.md "Degraded-quota bound").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.rule_manager import RuleManager
from sentinel_tpu.models.flow import FlowRule

#: Synthetic-resource namespace the lowering targets. Keeping every
#: lowered rule under one prefix lets telemetry/dashboards group the
#: family and keeps operator resources collision-free.
LLM_RESOURCE_PREFIX = "llm:"

#: ``FlowRule.derived_from`` tag identifying rules this module owns.
DERIVED_TPS = "tps"


def llm_resource(model: str) -> str:
    """The flow resource a model's token window lives on."""
    return LLM_RESOURCE_PREFIX + model


@dataclass
class TpsRule:
    model: str
    tokens_per_second: float
    burst_tokens: float = 0.0
    tenant: str = C.LIMIT_APP_DEFAULT
    max_concurrent_streams: int = 0  # 0 = unbounded
    cluster_mode: bool = False
    cluster_config: Optional[dict] = None
    # Staged rollout tags ride through the lowering: a candidate TPS
    # rule lowers into a candidate flow rule (same shadow-lane story).
    candidate_set: Optional[str] = None
    rollout_stage: Optional[str] = None

    def is_valid(self) -> bool:
        if not self.model or self.tokens_per_second < 0:
            return False
        if self.burst_tokens < 0 or self.max_concurrent_streams < 0:
            return False
        return True


class TpsRuleManager(RuleManager[TpsRule]):
    """Wholesale-swap registry, same lifecycle as every other family."""


def lower_tps_rules(rules: Iterable[TpsRule]) -> List[FlowRule]:
    """Compile TPS rules onto the flow machinery (see module docstring)."""
    lowered: List[FlowRule] = []
    for r in rules:
        if not r.is_valid():
            continue
        lowered.append(FlowRule(
            resource=llm_resource(r.model),
            count=float(r.tokens_per_second) + float(r.burst_tokens),
            grade=C.FLOW_GRADE_QPS,
            limit_app=r.tenant or C.LIMIT_APP_DEFAULT,
            strategy=C.FLOW_STRATEGY_DIRECT,
            control_behavior=C.CONTROL_BEHAVIOR_DEFAULT,
            cluster_mode=r.cluster_mode,
            cluster_config=r.cluster_config,
            candidate_set=r.candidate_set,
            rollout_stage=r.rollout_stage,
            derived_from=DERIVED_TPS,
        ))
    return lowered


def max_streams_by_resource(rules: Iterable[TpsRule]) -> Dict[str, int]:
    """resource -> effective ``maxConcurrentStreams`` (tightest positive
    cap across that model's rules; models with no positive cap absent)."""
    caps: Dict[str, int] = {}
    for r in rules:
        if not r.is_valid() or r.max_concurrent_streams <= 0:
            continue
        res = llm_resource(r.model)
        cur = caps.get(res)
        caps[res] = r.max_concurrent_streams if cur is None \
            else min(cur, r.max_concurrent_streams)
    return caps


def degraded_tps_quota(rules: Iterable[TpsRule], clients: int):
    """Tenant-fair degraded shares for cluster-mode TPS rules.

    Reuses the HA share math verbatim: each of ``clients`` admitters
    gets ``threshold / clients`` tokens per window for every lowered
    cluster-mode rule carrying a ``flowId``, so the fleet-wide sum of
    shares is ≤ the global token budget even while partitioned
    (SEMANTICS.md "Degraded-quota bound" — the proof transfers because
    the lowering maps token budgets onto the exact threshold shape the
    proof quantifies over)."""
    from sentinel_tpu.cluster.ha import DegradedQuota
    from sentinel_tpu.cluster.rules import cluster_thresholds

    lowered = [r for r in lower_tps_rules(rules) if r.cluster_mode]
    return DegradedQuota(divisor=max(1, int(clients)),
                         thresholds=cluster_thresholds(lowered))

"""Envoy RLS surface tests: rule conversion, ShouldRateLimit semantics, and
a real gRPC round-trip over the runtime-built proto messages.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.envoy_rls import (
    EnvoyRlsRule,
    EnvoyRlsRuleManager,
    KeyValueResource,
    ResourceDescriptor,
    SentinelEnvoyRlsService,
    descriptor_flow_id,
    to_cluster_flow_rules,
)
from sentinel_tpu.envoy_rls import proto


def _rls_rule(domain="web", key="path", value="/api", count=3):
    return EnvoyRlsRule(domain, [
        ResourceDescriptor([KeyValueResource(key, value)], count)])


def test_rule_conversion_generates_cluster_rules():
    rules = to_cluster_flow_rules(_rls_rule())
    assert len(rules) == 1
    r = rules[0]
    assert r.cluster_mode and r.count == 3
    assert r.resource == "web|path:/api"
    assert r.cluster_config["flowId"] == descriptor_flow_id(
        "web", [("path", "/api")])
    # flowId is stable and descriptor-sensitive.
    assert descriptor_flow_id("web", [("path", "/api")]) == \
        descriptor_flow_id("web", [("path", "/api")])
    assert descriptor_flow_id("web", [("path", "/other")]) != \
        descriptor_flow_id("web", [("path", "/api")])


@pytest.fixture()
def rls_service(frozen_time):
    svc = SentinelEnvoyRlsService()
    svc.rules.load_rules([_rls_rule(count=3)])
    return svc


def test_should_rate_limit_enforces_quota(rls_service, frozen_time):
    codes = []
    for _ in range(5):
        overall, statuses = rls_service.should_rate_limit(
            "web", [[("path", "/api")]])
        codes.append(overall)
    assert codes.count(proto.CODE_OK) == 3
    assert codes.count(proto.CODE_OVER_LIMIT) == 2
    frozen_time.advance_time(1100)
    overall, _ = rls_service.should_rate_limit("web", [[("path", "/api")]])
    assert overall == proto.CODE_OK


def test_unknown_descriptor_passes(rls_service):
    overall, statuses = rls_service.should_rate_limit(
        "web", [[("header", "x")]])
    assert overall == proto.CODE_OK


def test_mixed_descriptors_over_limit_wins(rls_service, frozen_time):
    descriptors = [[("path", "/api")], [("header", "x")]]
    for _ in range(3):
        rls_service.should_rate_limit("web", [[("path", "/api")]])
    overall, statuses = rls_service.should_rate_limit("web", descriptors)
    assert overall == proto.CODE_OVER_LIMIT
    assert statuses[0][0] == proto.CODE_OVER_LIMIT
    assert statuses[1][0] == proto.CODE_OK


def test_hits_addend(rls_service, frozen_time):
    overall, _ = rls_service.should_rate_limit(
        "web", [[("path", "/api")]], hits_addend=3)
    assert overall == proto.CODE_OK
    overall, _ = rls_service.should_rate_limit(
        "web", [[("path", "/api")]], hits_addend=1)
    assert overall == proto.CODE_OVER_LIMIT


def test_rule_reload_clears_old_domains(frozen_time):
    mgr = EnvoyRlsRuleManager()
    mgr.load_rules([_rls_rule(domain="a"), _rls_rule(domain="b")])
    assert set(mgr.cluster_rules.namespaces()) >= {"a", "b"}
    mgr.load_rules([_rls_rule(domain="a")])
    assert mgr.cluster_rules.get_rules("b") == []


def test_proto_messages_round_trip():
    req = proto.RateLimitRequest()
    req.domain = "web"
    d = req.descriptors.add()
    e = d.entries.add()
    e.key, e.value = "path", "/api"
    req.hits_addend = 2
    raw = req.SerializeToString()
    back = proto.RateLimitRequest.FromString(raw)
    assert back.domain == "web"
    assert back.descriptors[0].entries[0].value == "/api"
    assert back.hits_addend == 2


def test_proto_messages_round_trip_v3():
    req = proto.RateLimitRequestV3()
    req.domain = "web"
    d = req.descriptors.add()
    e = d.entries.add()
    e.key, e.value = "path", "/api"
    req.hits_addend = 2
    back = proto.RateLimitRequestV3.FromString(req.SerializeToString())
    assert back.domain == "web"
    assert back.descriptors[0].entries[0].value == "/api"
    assert back.hits_addend == 2
    assert back.DESCRIPTOR.full_name == \
        "envoy.service.ratelimit.v3.RateLimitRequest"


def test_v2_v3_wire_compatible():
    """The schemas are shape-identical, so v2 bytes parse as v3 and
    vice versa — exactly the migration property Envoy relied on when it
    renamed the packages."""
    req = proto.RateLimitRequest()
    req.domain = "web"
    d = req.descriptors.add()
    e = d.entries.add()
    e.key, e.value = "k", "v"
    req.hits_addend = 7
    as_v3 = proto.RateLimitRequestV3.FromString(req.SerializeToString())
    assert as_v3.domain == "web" and as_v3.hits_addend == 7
    assert as_v3.descriptors[0].entries[0].key == "k"

    resp = proto.RateLimitResponseV3()
    resp.overall_code = proto.CODE_OVER_LIMIT
    s = resp.statuses.add()
    s.code = proto.CODE_OVER_LIMIT
    s.limit_remaining = 0
    as_v2 = proto.RateLimitResponse.FromString(resp.SerializeToString())
    assert as_v2.overall_code == proto.CODE_OVER_LIMIT
    assert as_v2.statuses[0].code == proto.CODE_OVER_LIMIT


def test_grpc_round_trip_v3(frozen_time):
    """current Envoy's service path: /envoy.service.ratelimit.v3.
    RateLimitService/ShouldRateLimit — served alongside v2 from the
    SAME server and token windows (a v2 and a v3 client drain one
    quota)."""
    grpc = pytest.importorskip("grpc")
    svc = SentinelEnvoyRlsService()
    svc.rules.load_rules([_rls_rule(count=2)])
    server = svc.serve_grpc("127.0.0.1:0")
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{server.bound_port}")
        call_v3 = channel.unary_unary(
            f"/{proto.SERVICE_NAME_V3}/{proto.METHOD_NAME}",
            request_serializer=proto.RateLimitRequestV3.SerializeToString,
            response_deserializer=proto.RateLimitResponseV3.FromString,
        )
        call_v2 = channel.unary_unary(
            f"/{proto.SERVICE_NAME}/{proto.METHOD_NAME}",
            request_serializer=proto.RateLimitRequest.SerializeToString,
            response_deserializer=proto.RateLimitResponse.FromString,
        )
        req3 = proto.RateLimitRequestV3()
        req3.domain = "web"
        d = req3.descriptors.add()
        e = d.entries.add()
        e.key, e.value = "path", "/api"
        req2 = proto.RateLimitRequest.FromString(req3.SerializeToString())
        # one v3 + one v2 acquire exhaust the 2-token quota; the next v3
        # call is over limit — both versions share the windows
        assert call_v3(req3, timeout=5).overall_code == proto.CODE_OK
        assert call_v2(req2, timeout=5).overall_code == proto.CODE_OK
        r = call_v3(req3, timeout=5)
        assert r.overall_code == proto.CODE_OVER_LIMIT
        assert r.statuses[0].code == proto.CODE_OVER_LIMIT
        channel.close()
    finally:
        server.stop(0)


def test_grpc_round_trip(frozen_time):
    grpc = pytest.importorskip("grpc")
    svc = SentinelEnvoyRlsService()
    svc.rules.load_rules([_rls_rule(count=2)])
    server = svc.serve_grpc("127.0.0.1:0")
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{server.bound_port}")
        call = channel.unary_unary(
            f"/{proto.SERVICE_NAME}/{proto.METHOD_NAME}",
            request_serializer=proto.RateLimitRequest.SerializeToString,
            response_deserializer=proto.RateLimitResponse.FromString,
        )
        req = proto.RateLimitRequest()
        req.domain = "web"
        d = req.descriptors.add()
        e = d.entries.add()
        e.key, e.value = "path", "/api"
        codes = [call(req, timeout=5).overall_code for _ in range(4)]
        assert codes.count(proto.CODE_OK) == 2
        assert codes.count(proto.CODE_OVER_LIMIT) == 2
        channel.close()
    finally:
        server.stop(0)

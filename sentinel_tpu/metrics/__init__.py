"""Metric log pipeline (reference: ``core:node/metric/`` — SURVEY.md §2.1
"Metric log pipeline", §3.5): per-second aggregation of every resource to a
rotating log + index, and range reads for the ops plane.
"""

from sentinel_tpu.metrics.metric_node import MetricNode
from sentinel_tpu.metrics.profiling import StepTimer
from sentinel_tpu.metrics.profiling import trace as profile_trace
from sentinel_tpu.metrics.searcher import MetricSearcher
from sentinel_tpu.metrics.timer import MetricTimerListener
from sentinel_tpu.metrics.writer import MetricWriter

__all__ = ["MetricNode", "MetricSearcher", "MetricTimerListener",
           "MetricWriter", "StepTimer", "profile_trace"]

"""Flask extension (reference: the per-framework convenience modules of
``sentinel-adapter/`` — e.g. ``sentinel-spring-webmvc-adapter``'s
config-object registration — SURVEY.md §2.5).

Flask is WSGI, so the enforcement IS ``SentinelWSGIMiddleware``; this
extension only supplies the idiomatic ``init_app`` registration and
callback plumbing::

    sentinel = SentinelFlask(url_cleaner=clean, origin_parser=parse)
    sentinel.init_app(app)          # or SentinelFlask(app=app, ...)

Duck-typed: ``app`` needs only a ``wsgi_app`` attribute, so tests (and
any WSGI framework with the same convention, e.g. Bottle via ``wsgi``)
run without Flask installed.
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware


class SentinelFlask:
    def __init__(self, app=None,
                 url_cleaner: Optional[Callable[[str], str]] = None,
                 origin_parser: Optional[Callable[[dict], str]] = None,
                 block_handler: Optional[Callable] = None,
                 total_resource: Optional[str] = None):
        self.url_cleaner = url_cleaner
        self.origin_parser = origin_parser
        self.block_handler = block_handler
        self.total_resource = total_resource
        if app is not None:
            self.init_app(app)

    def init_app(self, app) -> None:
        """Wrap ``app.wsgi_app`` (the Flask extension convention).

        Idempotent: the app-factory pattern often calls both
        ``SentinelFlask(app=app)`` and ``init_app(app)``; a second wrap
        would double-count every request (two entries per resource)."""
        if isinstance(app.wsgi_app, SentinelWSGIMiddleware):
            return
        app.wsgi_app = SentinelWSGIMiddleware(
            app.wsgi_app,
            url_cleaner=self.url_cleaner,
            origin_parser=self.origin_parser,
            block_handler=self.block_handler,
            total_resource=self.total_resource,
        )

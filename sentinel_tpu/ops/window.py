"""Sliding-window statistics as tensor programs.

This module is the TPU-native replacement for the reference's sliding-window
engine (``core:slots/statistic/base/LeapArray.java`` + ``WindowWrap`` +
``MetricBucket`` + ``ArrayMetric`` — SURVEY.md §2.1 "Sliding-window engine").

Reference semantics being reproduced:
  * a ring of B buckets, each covering ``bucket_ms``; bucket for time t is
    slot ``(t // bucket_ms) % B`` with windowStart ``t - t % bucket_ms``;
  * a bucket is *deprecated* when its stored windowStart is older than the
    most recent occurrence of its slot; deprecated buckets are lazily reset
    (``LeapArray.currentWindow`` CAS / ``resetWindowTo``) and skipped by
    reads (``values()`` / ``isWindowDeprecated``).

TPU-native design: instead of per-node rings with CAS, ALL node rows share
one ``[rows, B, E]`` tensor. Because every row uses the same clock, the ring
geometry is row-independent: ``starts`` is a single ``int64[B]`` vector.
Rotation normalizes state so that every bucket holds the most recent window
of its slot (zeroing stale ones in a single masked ``where``), making every
subsequent read a plain sum — branchless, batched, and fused by XLA. The
full-tensor write only happens when a bucket boundary was actually crossed
(``lax.cond``), i.e. at most once per ``bucket_ms`` rather than per request.

A second variant, :class:`RowWindow`, gives each row its own bucket length —
needed for degrade-rule breakers and param-flow rules whose ``statIntervalMs``
/ ``durationInSec`` vary per rule (reference keeps a private LeapArray per
circuit breaker).

Time is an explicit ``now_ms`` argument everywhere: device kernels cannot
call clocks, and this also fixes the reference's untestable static
``TimeUtil`` (SURVEY.md §4 takeaways).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from sentinel_tpu.core.constants import NUM_EVENTS

# A large sentinel for MIN_RT empty buckets (reference uses maxRt default).
MIN_RT_EMPTY = jnp.int32(2**31 - 1)


def oob(rows: jax.Array, n: int) -> jax.Array:
    """Map negative row ids to an out-of-bounds index.

    JAX wraps negative indices *before* ``mode="drop"/"fill"`` applies, so a
    raw -1 would silently hit the last row. Every scatter/gather below must
    route through this.
    """
    return jnp.where(rows < 0, n, rows)


def varying_zeros(like: jax.Array, shape, dtype) -> jax.Array:
    """All-zero array DERIVED from ``like``, not a literal constant.

    Accumulators that flow through ``lax.cond`` gates whose taken branch
    depends on (device-sharded) batch data must type as "varying" under
    shard_map's varying-axes rules — a literal ``jnp.zeros`` is
    unvarying and makes the cond branches disagree. Deriving the zeros
    from batch data is free elementwise algebra outside shard_map and
    carries the varying marking inside it. Use this for every
    cond-gated accumulator seed (flow sweep, degrade feed, ...).
    """
    # [:1].sum(), not [0]: a width-0 batch (empty pipeline flush) must
    # trace — indexing would raise at trace time and the engine's
    # dispatch-error handler would then drop the whole device state.
    z = like.ravel()[:1].sum() * 0
    if dtype in (jnp.bool_, bool):
        return jnp.zeros(shape, bool) | (z != 0)
    return jnp.zeros(shape, dtype) + z.astype(dtype)


class WindowSpec(NamedTuple):
    """Static geometry of a shared-clock window."""

    interval_ms: int
    buckets: int

    @property
    def bucket_ms(self) -> int:
        return self.interval_ms // self.buckets


class Window(NamedTuple):
    """Device state of one shared-clock sliding window over all node rows.

    counts:  int32[B, NUM_EVENTS, rows] additive event counters
    min_rt:  int32[B, rows]             per-bucket minimum RT (ms)
    starts:  int64[B]                   windowStart of each slot (shared)

    Layout note (TPU-critical): the ROW axis is minor. TPU tiling pads the
    minor dimension to 128 lanes, so a row-major ``[R, B, E]`` layout with
    E=6 minor would physically occupy ~21x its logical size and every
    rotate/commit would pay that bandwidth (measured: ~3ms per touch of the
    minute window at R=16k). With rows minor the tensors are dense.
    """

    counts: jax.Array
    min_rt: jax.Array
    starts: jax.Array

    @property
    def num_rows(self) -> int:
        return self.counts.shape[2]


def make_window(rows: int, spec: WindowSpec) -> Window:
    return Window(
        counts=jnp.zeros((spec.buckets, NUM_EVENTS, rows), jnp.int32),
        min_rt=jnp.full((spec.buckets, rows), MIN_RT_EMPTY, jnp.int32),
        # -bucket_ms * B: strictly older than any real window start, so the
        # first rotation resets everything.
        starts=jnp.full((spec.buckets,), -spec.interval_ms, jnp.int64),
    )


def expected_starts(now_ms: jax.Array, spec: WindowSpec) -> jax.Array:
    """windowStart of the most recent occurrence of each slot at ``now_ms``.

    Slot b's latest window ending at-or-before now started at
    ``cur_start - ((cur_idx - b) % B) * bucket_ms``.
    """
    bucket_ms = jnp.int64(spec.bucket_ms)
    now_ms = now_ms.astype(jnp.int64)
    cur_start = now_ms - now_ms % bucket_ms
    cur_idx = (now_ms // bucket_ms) % spec.buckets
    slots = jnp.arange(spec.buckets, dtype=jnp.int64)
    offset = jnp.mod(cur_idx - slots, spec.buckets)
    return cur_start - offset * bucket_ms


def rotate(win: Window, now_ms: jax.Array, spec: WindowSpec) -> Window:
    """Normalize: zero every deprecated bucket, stamp fresh starts.

    Equivalent to running ``LeapArray.currentWindow(now)``'s lazy reset for
    every slot of every row at once. After this, plain sums over the bucket
    axis equal the reference's ``values()`` aggregation.

    Unconditionally branchless: with the rows-minor layout the masked write
    is one dense sweep (~bandwidth of the tensor), and avoiding ``lax.cond``
    keeps the step efficient inside ``scan``/``vmap`` where cond lowers to
    executing both branches anyway.
    """
    exp = expected_starts(now_ms, spec)
    keep = win.starts == exp  # bool[B]
    counts = jnp.where(keep[:, None, None], win.counts, 0)
    min_rt = jnp.where(keep[:, None], win.min_rt, MIN_RT_EMPTY)
    return Window(counts, min_rt, exp)


def rotate_current(win: Window, now_ms: jax.Array, spec: WindowSpec) -> Window:
    """Cheap rotation for the WRITE path: freshen only the current bucket.

    Zeroes + restamps the bucket ``now`` falls in when it is stale, leaving
    older buckets' stamps untouched — a full :func:`rotate` (or a read-side
    staleness mask against ``expected_starts``) later still sees exactly
    which buckets are deprecated. Cost is one ``[E, rows]`` slice instead of
    the whole ``[B, E, rows]`` tensor; at 60 buckets that is the difference
    between touching 0.4MB and 24MB per step.
    """
    idx = current_index(now_ms, spec)
    now = now_ms.astype(jnp.int64)
    cur_start = now - now % spec.bucket_ms
    fresh = win.starts[idx] == cur_start
    counts = win.counts.at[idx].set(
        jnp.where(fresh, win.counts[idx], 0))
    min_rt = win.min_rt.at[idx].set(
        jnp.where(fresh, win.min_rt[idx], MIN_RT_EMPTY))
    return Window(counts, min_rt, win.starts.at[idx].set(cur_start))


def staleness_mask(win: Window, now_ms: jax.Array, spec: WindowSpec) -> jax.Array:
    """bool[B]: True where the stored bucket is fresh at ``now``.

    Read-side companion of :func:`rotate_current` — reads over a partially
    rotated window multiply by this mask instead of paying a full rotate.
    """
    return win.starts == expected_starts(now_ms, spec)


def current_index(now_ms: jax.Array, spec: WindowSpec) -> jax.Array:
    return ((now_ms.astype(jnp.int64) // spec.bucket_ms) % spec.buckets).astype(jnp.int32)


def add_events(
    win: Window,
    now_ms: jax.Array,
    rows: jax.Array,  # int32[N] node-row ids; negative => dropped
    events: jax.Array,  # int32[N] MetricEvent index
    values: jax.Array,  # int32[N] amounts
    spec: WindowSpec,
) -> Window:
    """Scatter-add a batch of (row, event, value) into the current bucket.

    The window must already be rotated to ``now_ms``. Rows < 0 are dropped
    (used for masked/missing origin rows).
    """
    idx = current_index(now_ms, spec)
    rows = oob(rows, win.counts.shape[2])
    bucket_idx = jnp.full_like(rows, idx)
    counts = win.counts.at[bucket_idx, events, rows].add(
        values, mode="drop", indices_are_sorted=False, unique_indices=False
    )
    return win._replace(counts=counts)


def add_min_rt(win: Window, now_ms: jax.Array, rows: jax.Array, rt: jax.Array, spec: WindowSpec) -> Window:
    idx = current_index(now_ms, spec)
    rows = oob(rows, win.min_rt.shape[1])
    bucket_idx = jnp.full_like(rows, idx)
    min_rt = win.min_rt.at[bucket_idx, rows].min(rt.astype(jnp.int32), mode="drop")
    return win._replace(min_rt=min_rt)


def row_totals(win: Window, rows: jax.Array) -> jax.Array:
    """Sum of each event over all (fresh) buckets for the given rows.

    Returns int32[N, NUM_EVENTS]. Caller must have rotated first.
    Negative rows yield zeros (mode="fill" with 0 fill).
    """
    totals = win.counts.sum(axis=0)  # [E, R] — cheap: B is tiny
    gathered = totals.at[:, oob(rows, totals.shape[1])].get(
        mode="fill", fill_value=0
    )  # [E, N]
    return gathered.T


def row_min_rt(win: Window, rows: jax.Array) -> jax.Array:
    gathered = win.min_rt.at[:, oob(rows, win.min_rt.shape[1])].get(
        mode="fill", fill_value=MIN_RT_EMPTY
    )  # [B, N]
    return gathered.min(axis=0)


def all_totals(win: Window) -> jax.Array:
    """[rows, NUM_EVENTS] totals over the full window (for metric log dump)."""
    return win.counts.sum(axis=0).T


# ---------------------------------------------------------------------------
# Per-row-clock window: each row has its own bucket_ms (degrade breakers,
# param-flow rules). Geometry: starts int64[rows, B]; channel axis C is
# caller-defined (e.g. total/error/slow for breakers).
# ---------------------------------------------------------------------------


class RowWindow(NamedTuple):
    counts: jax.Array  # int32[rows, B, C]
    starts: jax.Array  # int64[rows, B]
    bucket_ms: jax.Array  # int64[rows] (0 => row unused)


def make_row_window(rows: int, buckets: int, channels: int, bucket_ms) -> RowWindow:
    bucket_ms = jnp.asarray(bucket_ms, jnp.int64)
    if bucket_ms.ndim == 0:
        bucket_ms = jnp.full((rows,), bucket_ms, jnp.int64)
    return RowWindow(
        counts=jnp.zeros((rows, buckets, channels), jnp.int32),
        starts=jnp.full((rows, buckets), jnp.int64(-(1 << 40))),
        bucket_ms=bucket_ms,
    )


def row_expected_starts(rw: RowWindow, now_ms: jax.Array) -> jax.Array:
    buckets = rw.starts.shape[1]
    bm = jnp.maximum(rw.bucket_ms, 1)[:, None]  # [rows, 1]
    now = now_ms.astype(jnp.int64)
    cur_start = now - now % bm
    cur_idx = (now // bm) % buckets
    slots = jnp.arange(buckets, dtype=jnp.int64)[None, :]
    offset = jnp.mod(cur_idx - slots, buckets)
    return cur_start - offset * bm


def row_rotate(rw: RowWindow, now_ms: jax.Array) -> RowWindow:
    exp = row_expected_starts(rw, now_ms)
    keep = rw.starts == exp
    counts = jnp.where(keep[:, :, None], rw.counts, 0)
    return RowWindow(counts, exp, rw.bucket_ms)


def row_window_add(rw: RowWindow, now_ms: jax.Array, rows: jax.Array, channel: jax.Array, values: jax.Array) -> RowWindow:
    """Scatter-add into each row's current bucket. Must be rotated."""
    buckets = rw.starts.shape[1]
    rows = oob(rows, rw.counts.shape[0])
    bm = jnp.maximum(rw.bucket_ms.at[rows].get(mode="fill", fill_value=1), 1)
    idx = ((now_ms.astype(jnp.int64) // bm) % buckets).astype(jnp.int32)
    counts = rw.counts.at[rows, idx, channel].add(values, mode="drop")
    return rw._replace(counts=counts)


def row_window_totals(rw: RowWindow, rows: jax.Array) -> jax.Array:
    """int32[N, C] full-window totals for given rows (rotated state)."""
    gathered = rw.counts.at[oob(rows, rw.counts.shape[0])].get(
        mode="fill", fill_value=0
    )
    return gathered.sum(axis=1)

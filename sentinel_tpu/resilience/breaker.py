"""Host-side circuit breaker: the repo's own CLOSED/OPEN/HALF_OPEN
semantics (``models/degrade.py``) dogfooded onto its remote clients.

The device breaker is a vectorized per-rule state machine; remote
touchpoints (one token client, one heartbeat target) need the same
three-state contract as a tiny lock-guarded host object instead:

* CLOSED passes and counts consecutive failures; ``failure_threshold``
  consecutive failures trip OPEN.
* OPEN rejects without touching the wire until ``open_ms`` elapses, then
  the FIRST caller through becomes the HALF_OPEN probe (same
  first-arrival-wins stance as the device machine's segmented probe
  flag).
* HALF_OPEN admits at most ``half_open_probes`` in-flight probes; one
  success closes the breaker (stats reset), one failure re-opens it
  with a fresh retry window.

Time comes from ``utils/time_util`` so tests drive transitions with the
frozen clock. State numbering matches ``models/degrade.py``
(CLOSED=0 / OPEN=1 / HALF_OPEN=2) so ops dashboards read one legend.
"""

from __future__ import annotations

import threading

from sentinel_tpu.utils import time_util

STATE_CLOSED = 0
STATE_OPEN = 1
STATE_HALF_OPEN = 2

_STATE_NAMES = {STATE_CLOSED: "CLOSED", STATE_OPEN: "OPEN",
                STATE_HALF_OPEN: "HALF_OPEN"}


class HealthGate:
    """Client-side breaker guarding one remote dependency."""

    def __init__(self, failure_threshold: int = 3, open_ms: int = 5_000,
                 half_open_probes: int = 1):
        if failure_threshold <= 0 or open_ms < 0 or half_open_probes <= 0:
            raise ValueError(
                f"invalid gate: threshold={failure_threshold} "
                f"open_ms={open_ms} probes={half_open_probes}")
        self.failure_threshold = int(failure_threshold)
        self.open_ms = int(open_ms)
        self.half_open_probes = int(half_open_probes)
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._consecutive_failures = 0
        self._next_retry_ms = 0
        self._probes_in_flight = 0
        # Ops counters (monotonic for the gate's lifetime).
        self.open_count = 0
        self.rejected_count = 0
        self._state_since_ms = time_util.current_time_millis()

    # -- queries ----------------------------------------------------------

    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self._state]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": _STATE_NAMES[self._state],
                "consecutiveFailures": self._consecutive_failures,
                "openCount": self.open_count,
                "rejectedCount": self.rejected_count,
                "stateSinceMs": self._state_since_ms,
            }

    # -- transitions ------------------------------------------------------

    def allow(self) -> bool:
        """May a call touch the wire right now? OPEN past its window
        flips to HALF_OPEN and admits the caller as the probe."""
        now = time_util.current_time_millis()
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_OPEN:
                if now >= self._next_retry_ms:
                    self._set_state(STATE_HALF_OPEN, now)
                    self._probes_in_flight = 1
                    return True
                self.rejected_count += 1
                return False
            # HALF_OPEN: bounded concurrent probes.
            if self._probes_in_flight < self.half_open_probes:
                self._probes_in_flight += 1
                return True
            self.rejected_count += 1
            return False

    def record_success(self) -> None:
        now = time_util.current_time_millis()
        with self._lock:
            self._consecutive_failures = 0
            if self._state != STATE_CLOSED:
                self._set_state(STATE_CLOSED, now)
            self._probes_in_flight = 0

    def record_failure(self) -> None:
        now = time_util.current_time_millis()
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                self._trip(now)  # failed probe: re-open, fresh window
                return
            self._consecutive_failures += 1
            if (self._state == STATE_CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._trip(now)

    def _trip(self, now: int) -> None:
        self._set_state(STATE_OPEN, now)
        self._next_retry_ms = now + self.open_ms
        self._probes_in_flight = 0
        self._consecutive_failures = 0
        self.open_count += 1

    def _set_state(self, state: int, now: int) -> None:
        self._state = state
        self._state_since_ms = now

    @classmethod
    def from_config(cls) -> "HealthGate":
        """Thresholds from ``csp.sentinel.resilience.breaker.*``."""
        from sentinel_tpu.core.config import (
            DEFAULT_RESILIENCE_BREAKER_FAILURES,
            DEFAULT_RESILIENCE_BREAKER_OPEN_MS,
            DEFAULT_RESILIENCE_BREAKER_PROBES,
            RESILIENCE_BREAKER_FAILURES,
            RESILIENCE_BREAKER_OPEN_MS,
            RESILIENCE_BREAKER_PROBES,
            config,
        )

        try:
            return cls(
                failure_threshold=config.get_int(
                    RESILIENCE_BREAKER_FAILURES,
                    DEFAULT_RESILIENCE_BREAKER_FAILURES),
                open_ms=config.get_int(
                    RESILIENCE_BREAKER_OPEN_MS,
                    DEFAULT_RESILIENCE_BREAKER_OPEN_MS),
                half_open_probes=config.get_int(
                    RESILIENCE_BREAKER_PROBES,
                    DEFAULT_RESILIENCE_BREAKER_PROBES),
            )
        except ValueError as ex:
            # Config typo -> warn and run with defaults, never a
            # client-startup crash.
            from sentinel_tpu.log.record_log import record_log

            record_log.warn("invalid resilience breaker config (%s); "
                            "using defaults", ex)
            return cls(
                failure_threshold=DEFAULT_RESILIENCE_BREAKER_FAILURES,
                open_ms=DEFAULT_RESILIENCE_BREAKER_OPEN_MS,
                half_open_probes=DEFAULT_RESILIENCE_BREAKER_PROBES)

"""Async-stream adapter (reference: ``sentinel-reactor-adapter``'s
``SentinelReactorTransformer`` / ``SentinelReactorSubscriber`` —
SURVEY.md §2.5).

The reactor adapter guards a *subscription*: the entry happens when the
subscriber subscribes (an ``AsyncEntry`` around the whole stream, not one
per element), a rejection surfaces as ``onError(BlockException)``, the
entry exits on terminate (complete | error | cancel), and stream errors
feed exception metrics. Python's twin of a ``Flux`` is the async
iterator, and the twin of "subscribe time" is the first ``__anext__``
pull — so :func:`guard_aiter` wraps any async iterable and defers
admission to the first pull, and :func:`sentinel_stream` decorates async
generator functions wholesale.
"""

from __future__ import annotations

import asyncio
import functools
from typing import AsyncIterable, AsyncIterator, Callable, Optional

from sentinel_tpu.adapters.aio import entry_async
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.exceptions import BlockException


async def guard_aiter(resource: str, source: AsyncIterable,
                      entry_type: int = C.EntryType.OUT,
                      count: int = 1, args=()) -> AsyncIterator:
    """Guard an async iterable as ONE entry spanning the whole stream.

    Admission runs at the first pull (= subscribe time): a rejected
    stream raises ``BlockException`` out of the first ``__anext__``, so
    the consumer's except-clause is the ``onError`` hook. Business
    errors raised by the source are traced (exception metrics + breaker
    food), cancellation/abandonment is not (it exits the entry but feeds
    no error, like a reactor ``cancel()``).
    """
    handle = await entry_async(resource, entry_type, count, args)
    try:
        async for item in source:
            yield item
    except BaseException as ex:
        if not BlockException.is_block_exception(ex) and not isinstance(
                ex, (asyncio.CancelledError, GeneratorExit)):
            handle.trace(ex)
        raise
    finally:
        # Sync exit FIRST: it cannot be interrupted, so the concurrency
        # slot is released even if the awaited cleanup below is itself
        # cancelled (see adapters/aio.py on cancellation-proof exits).
        handle.exit()
        # Then propagate the cancel upstream (the reactor adapter cancels
        # its upstream subscription): aclose the source NOW so its finally
        # blocks run at abandonment time, not at GC. Awaiting inside an
        # async generator's GeneratorExit path is legal while not yielding.
        aclose = getattr(source, "aclose", None)
        if aclose is not None:
            await aclose()


def sentinel_stream(value: Optional[str] = None,
                    entry_type: int = C.EntryType.OUT,
                    args_from: Optional[Callable] = None):
    """Decorator form for async generator functions: the stream analog of
    ``@sentinel_coroutine`` (no handler routing — stream consumers handle
    ``BlockException`` where they iterate, as reactor subscribers do in
    ``onError``)."""

    def deco(fn):
        resource = value or f"{fn.__module__}:{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*fargs, **kwargs):
            params = args_from(*fargs, **kwargs) if args_from else ()
            return guard_aiter(resource, fn(*fargs, **kwargs),
                               entry_type, args=params)

        wrapper.__sentinel_resource__ = resource
        return wrapper

    return deco

"""Core constants for sentinel-tpu.

Mirrors the semantic constants of the reference framework
(`core:Constants.java`, `core:slots/statistic/MetricEvent.java`,
`core:slots/block/RuleConstant.java`, `core:EntryType.java` — see SURVEY.md
§2.1; reference mount was empty, paths are upstream-layout citations), but the
*representation* is TPU-first: events are indices into the last axis of one
``[rows, buckets, events]`` stats tensor instead of a ``LongAdder[]`` per
node.
"""

from __future__ import annotations

import enum


class MetricEvent(enum.IntEnum):
    """Index into the event axis of the stats tensor.

    Reference: ``MetricEvent`` (PASS, BLOCK, EXCEPTION, SUCCESS, RT,
    OCCUPIED_PASS). RT is a *sum* of response times (ms); average RT =
    RT / SUCCESS. MIN_RT lives in a separate tensor because it is a min,
    not a sum.
    """

    PASS = 0
    BLOCK = 1
    EXCEPTION = 2
    SUCCESS = 3
    RT = 4
    OCCUPIED_PASS = 5


NUM_EVENTS = len(MetricEvent)


class EntryType(enum.IntEnum):
    """Traffic direction. Only IN traffic is guarded by system rules."""

    IN = 0
    OUT = 1


class ResourceType(enum.IntEnum):
    """Classification of a resource (reference: ``ResourceTypeConstants``)."""

    COMMON = 0
    COMMON_WEB = 1
    COMMON_RPC = 2
    COMMON_API_GATEWAY = 3
    COMMON_DB_SQL = 4


class BlockReason(enum.IntEnum):
    """Decision codes returned from the device step.

    0 means pass; nonzero maps 1:1 onto the reference's BlockException
    subclasses. WAIT means "pass after sleeping wait_ms" (rate-limiter
    pacing / cluster SHOULD_WAIT / priority occupy-future-window).
    """

    PASS = 0
    FLOW = 1
    DEGRADE = 2
    SYSTEM = 3
    AUTHORITY = 4
    PARAM_FLOW = 5
    WAIT = 6
    CUSTOM = 7  # SPI-registered device checker (core/spi.py)


# ---------------------------------------------------------------------------
# Rule constants (reference: RuleConstant.java)
# ---------------------------------------------------------------------------

FLOW_GRADE_THREAD = 0
FLOW_GRADE_QPS = 1

FLOW_STRATEGY_DIRECT = 0
FLOW_STRATEGY_RELATE = 1
FLOW_STRATEGY_CHAIN = 2

CONTROL_BEHAVIOR_DEFAULT = 0
CONTROL_BEHAVIOR_WARM_UP = 1
CONTROL_BEHAVIOR_RATE_LIMITER = 2
CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER = 3

DEGRADE_GRADE_RT = 0
DEGRADE_GRADE_EXCEPTION_RATIO = 1
DEGRADE_GRADE_EXCEPTION_COUNT = 2

DEGRADE_DEFAULT_SLOW_RATIO_THRESHOLD = 1.0
DEGRADE_DEFAULT_MIN_REQUEST_AMOUNT = 5
DEGRADE_DEFAULT_STAT_INTERVAL_MS = 1000

AUTHORITY_WHITE = 0
AUTHORITY_BLACK = 1

PARAM_FLOW_GRADE_THREAD = 0
PARAM_FLOW_GRADE_QPS = 1

SYSTEM_RULE_NOT_SET = -1.0

COLD_FACTOR = 3  # warm-up controller cold factor (Guava SmoothWarmingUp)

LIMIT_APP_DEFAULT = "default"
LIMIT_APP_OTHER = "other"

# Encoded limit-origin ids in the flow-rule tensor.
ORIGIN_ID_DEFAULT = -1
ORIGIN_ID_OTHER = -2

# Circuit breaker states (reference 1.8: CircuitBreaker.State).
BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2

# ---------------------------------------------------------------------------
# Well-known context / node names (reference: Constants.java, ContextUtil)
# ---------------------------------------------------------------------------

ROOT_NODE_NAME = "machine-root"
ENTRY_NODE_NAME = "__entry_node__"  # Constants.ENTRY_NODE aggregate of all IN
CONTEXT_DEFAULT_NAME = "sentinel_default_context"

MAX_CONTEXT_NAME_SIZE = 2000
MAX_SLOT_CHAIN_SIZE = 6000  # reference CtSph cap; we cap registry rows instead

DEFAULT_MAX_RT_MS = 4900  # csp.sentinel.statistic.max.rt default

# Prioritized entries may wait at most this long for the next window bucket
# (reference: OccupyTimeoutProperty default, capped at one sample bucket).
DEFAULT_OCCUPY_TIMEOUT_MS = 500

# Per-request acquire counts ride bf16 matmul operands on device
# (ops/segment.py), exact only up to 256; the API rejects larger counts.
MAX_ACQUIRE_COUNT = 256

# ---------------------------------------------------------------------------
# Window geometry: two windows per node row, matching the reference's
# ArrayMetric pair in StatisticNode (1s/2-bucket "second" window for
# instantaneous QPS + 60s/60-bucket "minute" window for the metric log).
# ---------------------------------------------------------------------------

SECOND_WINDOW_MS = 1000
SECOND_BUCKETS = 2  # -> 500ms buckets (SampleCountProperty default 2)
MINUTE_WINDOW_MS = 60_000
MINUTE_BUCKETS = 60  # -> 1s buckets

"""Dynamic rule datasources (reference: ``sentinel-datasource-extension`` —
SURVEY.md §2.2): pull/push rule configuration into the property system.

``ReadableDataSource`` reads an external source, converts it with a
``Converter``, and pushes the result into its ``SentinelProperty`` — to which
a rule manager listens. ``WritableDataSource`` persists rules pushed from the
ops plane (``setRules`` command handler).
"""

from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    AutoRefreshDataSource,
    Converter,
    FileRefreshableDataSource,
    FileWritableDataSource,
    ReadableDataSource,
    WritableDataSource,
    bind,
)
from sentinel_tpu.datasource.push import (
    BrokerDataSource,
    BrokerWritableDataSource,
    InProcessBroker,
    PollingKVDataSource,
    PushDataSource,
)
from sentinel_tpu.datasource.http import (
    HttpRefreshableDataSource,
    MiniConfigHTTPServer,
)
from sentinel_tpu.datasource.redis import (
    MiniRedisServer,
    RedisDataSource,
    RedisWritableDataSource,
)
from sentinel_tpu.datasource.nacos import (
    MiniNacosServer,
    NacosDataSource,
    NacosWritableDataSource,
)
from sentinel_tpu.datasource.consul import (
    ConsulDataSource,
    ConsulWritableDataSource,
    MiniConsulServer,
)
from sentinel_tpu.datasource.converters import (
    authority_rules_from_json,
    authority_rules_to_json,
    degrade_rules_from_json,
    degrade_rules_to_json,
    flow_rules_from_json,
    flow_rules_to_json,
    param_rules_from_json,
    param_rules_to_json,
    system_rules_from_json,
    system_rules_to_json,
)

__all__ = [
    "AbstractDataSource", "AutoRefreshDataSource", "Converter",
    "BrokerDataSource", "BrokerWritableDataSource", "InProcessBroker",
    "PollingKVDataSource", "PushDataSource",
    "FileRefreshableDataSource", "FileWritableDataSource",
    "HttpRefreshableDataSource", "MiniConfigHTTPServer",
    "MiniRedisServer", "RedisDataSource", "RedisWritableDataSource",
    "MiniNacosServer", "NacosDataSource", "NacosWritableDataSource",
    "ConsulDataSource", "ConsulWritableDataSource", "MiniConsulServer",
    "ReadableDataSource", "WritableDataSource", "bind",
    "authority_rules_from_json", "authority_rules_to_json",
    "degrade_rules_from_json", "degrade_rules_to_json",
    "flow_rules_from_json", "flow_rules_to_json",
    "param_rules_from_json", "param_rules_to_json",
    "system_rules_from_json", "system_rules_to_json",
]

"""Pipelined-admission tests: micro-batched steps must preserve the serial
semantics of the synchronous path under concurrency.
"""

import threading

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C


@pytest.fixture()
def piped(engine, frozen_time):
    engine.start_pipeline(linger_s=0.0005)
    yield engine
    engine.stop_pipeline()


def test_qps_quota_exact_under_pipeline(piped, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="p", count=10)])
    passed = blocked = 0
    for _ in range(16):
        h = st.entry_ok("p")
        if h:
            passed += 1
            h.exit()
        else:
            blocked += 1
    assert passed == 10 and blocked == 6


def test_concurrent_callers_share_quota_exactly(piped, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="conc", count=25)])
    results = []
    lock = threading.Lock()

    def worker(n):
        local = 0
        for _ in range(n):
            h = st.entry_ok("conc")
            if h:
                local += 1
                h.exit()
        with lock:
            results.append(local)

    threads = [threading.Thread(target=worker, args=(10,)) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 25  # 80 attempts, quota 25, no overshoot


def test_exit_before_entry_order_for_thread_grade(piped, frozen_time):
    st.load_flow_rules([
        st.FlowRule(resource="tg", count=1, grade=C.FLOW_GRADE_THREAD)])
    for _ in range(5):
        h = st.entry_ok("tg")
        assert h is not None, "exit must land before the next entry"
        h.exit()


def test_pipeline_batches_concurrent_submissions(piped, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="b", count=1000)])
    barrier = threading.Barrier(16)

    def worker():
        barrier.wait()
        for _ in range(5):
            h = st.entry_ok("b")
            if h:
                h.exit()

    threads = [threading.Thread(target=worker) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pipe = piped._pipeline
    # Some cycles must have carried more than one entry.
    assert pipe.batched > pipe.cycles
    assert pipe.batched == 16 * 5


def test_stop_pipeline_restores_sync_path(engine, frozen_time):
    engine.start_pipeline()
    st.load_flow_rules([st.FlowRule(resource="s", count=2)])
    assert st.entry_ok("s") is not None
    engine.stop_pipeline()
    assert st.entry_ok("s") is not None
    assert st.entry_ok("s") is None  # quota shared across modes


def test_fail_open_is_counted_and_logged(piped, frozen_time, caplog):
    """A pipeline cycle error passes entries UNGUARDED — that outage must be
    observable: fail_open_count increments and a warning is logged."""
    import logging

    st.load_flow_rules([st.FlowRule(resource="fo", count=0)])  # blocks all
    orig = piped._run_entry_batch
    piped._run_entry_batch = lambda batch: (_ for _ in ()).throw(RuntimeError("boom"))
    try:
        with caplog.at_level(logging.WARNING, logger="sentinel_tpu"):
            with st.entry("fo"):  # passes unguarded despite the count=0 rule
                pass
    finally:
        piped._run_entry_batch = orig
    assert piped.fail_open_count == 1
    assert any("UNGUARDED" in r.message for r in caplog.records)

package com.alibaba.csp.sentinel.slots.block.authority;

import com.alibaba.csp.sentinel.slots.block.BlockException;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/block/authority/AuthorityException.java. */
public class AuthorityException extends BlockException {

    public AuthorityException(String ruleLimitApp) {
        super(ruleLimitApp);
    }

    public AuthorityException(String ruleLimitApp, String message) {
        super(ruleLimitApp, message);
    }
}

package com.alibaba.csp.sentinel.slots.logger;

import com.alibaba.csp.sentinel.context.Context;
import com.alibaba.csp.sentinel.node.DefaultNode;
import com.alibaba.csp.sentinel.slotchain.AbstractLinkedProcessorSlot;
import com.alibaba.csp.sentinel.slotchain.ResourceWrapper;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/logger/LogSlot.java. */
public class LogSlot extends AbstractLinkedProcessorSlot<DefaultNode> {

    @Override
    public void entry(Context context, ResourceWrapper resourceWrapper,
                      DefaultNode obj, int count, boolean prioritized,
                      Object... args) throws Throwable {
        fireEntry(context, resourceWrapper, obj, count, prioritized, args);
    }

    @Override
    public void exit(Context context, ResourceWrapper resourceWrapper,
                     int count, Object... args) {
        fireExit(context, resourceWrapper, count, args);
    }
}

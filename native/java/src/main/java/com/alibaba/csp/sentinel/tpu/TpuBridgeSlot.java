package com.alibaba.csp.sentinel.tpu;

import com.alibaba.csp.sentinel.EntryType;
import com.alibaba.csp.sentinel.cluster.ClusterConstants;
import com.alibaba.csp.sentinel.cluster.client.config.ClusterClientConfigManager;
import com.alibaba.csp.sentinel.context.Context;
import com.alibaba.csp.sentinel.log.RecordLog;
import com.alibaba.csp.sentinel.node.DefaultNode;
import com.alibaba.csp.sentinel.slotchain.AbstractLinkedProcessorSlot;
import com.alibaba.csp.sentinel.slotchain.ResourceWrapper;
import com.alibaba.csp.sentinel.slots.block.BlockException;
import com.alibaba.csp.sentinel.slots.block.authority.AuthorityException;
import com.alibaba.csp.sentinel.slots.block.degrade.DegradeException;
import com.alibaba.csp.sentinel.slots.block.flow.FlowException;
import com.alibaba.csp.sentinel.slots.block.flow.param.ParamFlowException;
import com.alibaba.csp.sentinel.slots.block.system.SystemBlockException;
import com.sun.jna.Pointer;
import com.sun.jna.ptr.IntByReference;
import com.sun.jna.ptr.LongByReference;

import java.util.ArrayDeque;
import java.util.Deque;

/**
 * The M4 rule-check forwarding slot (SURVEY.md §7 M4: "SPI-registered
 * slot that forwards StatisticSlot/rule checks to the backend"):
 * replaces the local FlowSlot/DegradeSlot/SystemSlot/AuthoritySlot/
 * ParamFlowSlot tail of the chain with ONE remote MSG_ENTRY check
 * against the sentinel-tpu backend, which runs its full fused slot
 * chain AND commits the StatisticSlot 4-row fan-out there. Exit
 * forwards the RT/success/thread-count release via MSG_EXIT.
 *
 * <p>Reference twins: {@code core:slotchain/ProcessorSlot.java} (the
 * SPI this implements), {@code core:slots/statistic/StatisticSlot.java}
 * (whose commit-inversion the backend performs),
 * {@code core:slots/block/*} (the exception mapping below).
 *
 * <p>Failure semantics: transport failure or a backend FAIL status
 * fails OPEN (fireEntry proceeds locally) — the stance of the
 * reference's {@code fallbackToLocalOrPass} and of the backend's own
 * DeviceDispatchError fail-open (core/engine.py). A BLOCKED status
 * re-raises the exact BlockException subclass the backend's BlockReason
 * code names, so blockHandler/fallback dispatch in user code is
 * unchanged.
 *
 * <p>Entry ids ride a per-thread stack: the sync entry model nests
 * strictly per thread (CtEntry enforces it), so exit order matches.
 * Async entries ({@code context.isAsync()}) are NOT forwarded — they
 * fire through locally (documented limitation; the async context
 * detaches from the thread).
 *
 * <p>NOTE (sandbox provenance): written against the vendored 1.8 SPI
 * surface in {@code native/java/vendored}; re-check against the fork
 * before first compile (BUILD.md).
 */
public class TpuBridgeSlot extends AbstractLinkedProcessorSlot<DefaultNode> {

    /** BlockReason codes (backend core/constants.py BlockReason). */
    static final int REASON_FLOW = 1;
    static final int REASON_DEGRADE = 2;
    static final int REASON_SYSTEM = 3;
    static final int REASON_AUTHORITY = 4;
    static final int REASON_PARAM_FLOW = 5;

    private static final long RECONNECT_BACKOFF_MS = 2000;

    // Shared multi-in-flight handle (the shim demuxes by xid); guarded
    // by the class monitor for connect/drop only — requests race freely.
    private static volatile Pointer handle;
    private static long lastConnectFailMs;

    private static final ThreadLocal<Deque<Long>> ENTRY_IDS =
        ThreadLocal.withInitial(ArrayDeque::new);

    private static synchronized Pointer connectedHandle() {
        if (handle != null) {
            return handle;
        }
        if (System.currentTimeMillis() - lastConnectFailMs < RECONNECT_BACKOFF_MS) {
            return null;
        }
        String host = System.getProperty("csp.sentinel.tpu.host",
            ClusterClientConfigManager.getServerHost());
        int port = Integer.getInteger("csp.sentinel.tpu.port",
            ClusterClientConfigManager.getServerPort());
        if (host == null || port <= 0) {
            return null;
        }
        Pointer fresh = SentinelTpuShim.INSTANCE.st_client_connect(
            host, port, ClusterConstants.DEFAULT_CLUSTER_NAMESPACE,
            ClusterClientConfigManager.getRequestTimeout());
        if (fresh == null) {
            lastConnectFailMs = System.currentTimeMillis();
            return null;
        }
        handle = fresh;
        RecordLog.info("[TpuBridgeSlot] connected to {}:{}", host, port);
        return handle;
    }

    private static synchronized void dropConnection() {
        if (handle != null) {
            SentinelTpuShim.INSTANCE.st_client_close(handle);
            handle = null;
            lastConnectFailMs = System.currentTimeMillis();
        }
    }

    @Override
    public void entry(Context context, ResourceWrapper resourceWrapper,
                      DefaultNode node, int count, boolean prioritized,
                      Object... args) throws Throwable {
        Pointer h = context.isAsync() ? null : connectedHandle();
        if (h == null) {
            // fail open: no backend -> behave like an unruled resource
            ENTRY_IDS.get().push(0L);
            fireEntry(context, resourceWrapper, node, count, prioritized, args);
            return;
        }
        SentinelTpuShim.StParam[] arr = marshalParams(args);
        LongByReference outId = new LongByReference();
        IntByReference outReason = new IntByReference();
        // Wire entry_type matches the backend's EntryType enum: IN=0,
        // OUT=1 (core/constants.py — note the inversion vs. a naive
        // boolean encoding).
        int status = SentinelTpuShim.INSTANCE.st_remote_entry(
            h, resourceWrapper.getName(),
            context.getOrigin() == null ? "" : context.getOrigin(), count,
            resourceWrapper.getEntryType() == EntryType.IN ? 0 : 1,
            prioritized ? 1 : 0, arr, args == null ? 0 : args.length,
            outId, outReason);
        if (status == -1) {
            dropConnection();  // transport death: reconnect next entry
            ENTRY_IDS.get().push(0L);
            fireEntry(context, resourceWrapper, node, count, prioritized, args);
            return;
        }
        if (status == 1) {  // BLOCKED: re-raise the typed exception
            // Push a sentinel FIRST: the framework still runs the chain's
            // exit for a blocked entry (CtSph catches the BlockException
            // and calls e.exit()), and that exit must pop THIS entry's
            // slot — not the enclosing entry's live id.
            ENTRY_IDS.get().push(0L);
            throw exceptionFor(outReason.getValue(), resourceWrapper.getName(),
                               context.getOrigin());
        }
        ENTRY_IDS.get().push(outId.getValue());
        fireEntry(context, resourceWrapper, node, count, prioritized, args);
    }

    @Override
    public void exit(Context context, ResourceWrapper resourceWrapper,
                     int count, Object... args) {
        Deque<Long> stack = ENTRY_IDS.get();
        Long entryId = stack.isEmpty() ? null : stack.pop();
        if (entryId != null && entryId != 0L) {
            Pointer h = handle;  // volatile read; no connect on exit path
            if (h != null) {
                boolean error = context.getCurEntry() != null
                    && context.getCurEntry().getError() != null;
                int rc = SentinelTpuShim.INSTANCE.st_remote_exit(
                    h, entryId, error ? 1 : 0, count);
                if (rc == -1) {
                    dropConnection();
                }
            }
            // else: connection already died; the backend's disconnect
            // drain released this entry server-side.
        }
        fireExit(context, resourceWrapper, count, args);
    }

    static BlockException exceptionFor(int reason, String resource,
                                       String origin) {
        String app = origin == null ? "" : origin;
        switch (reason) {
            case REASON_DEGRADE:
                return new DegradeException(app, resource);
            case REASON_SYSTEM:
                return new SystemBlockException(resource, "tpu-backend");
            case REASON_AUTHORITY:
                return new AuthorityException(app, resource);
            case REASON_PARAM_FLOW:
                return new ParamFlowException(resource, "tpu-backend");
            case REASON_FLOW:
            default:
                return new FlowException(app, resource);
        }
    }

    static SentinelTpuShim.StParam[] marshalParams(Object[] args) {
        int n = args == null ? 0 : args.length;
        SentinelTpuShim.StParam[] arr =
            (SentinelTpuShim.StParam[]) new SentinelTpuShim.StParam()
                .toArray(Math.max(n, 1));
        for (int k = 0; k < n; ++k) {
            Object p = args[k];
            SentinelTpuShim.StParam sp = arr[k];
            if (p instanceof Boolean) {
                sp.tag = 2;
                sp.i = ((Boolean) p) ? 1 : 0;
            } else if (p instanceof Integer || p instanceof Long
                       || p instanceof Short || p instanceof Byte) {
                sp.tag = 0;
                sp.i = ((Number) p).longValue();
            } else if (p instanceof Double || p instanceof Float) {
                sp.tag = 3;
                sp.d = ((Number) p).doubleValue();
            } else {
                sp.tag = 1;
                sp.s = String.valueOf(p);
            }
        }
        return arr;
    }
}

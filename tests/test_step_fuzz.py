"""Randomized differential fuzz: fused step vs a serial oracle.

SURVEY.md §4/§5 (the race-detector analog): the device step must agree
with a sequential pure-Python re-implementation of the reference
semantics on randomized mixed workloads. Scope is the serially-exact
regime — one rule per family per resource, flow and degrade on
disjoint resources (their cross-family prefix interplay is the
documented bounded delta), distinct non-colliding param values — where
the prefix scheme is documented to equal serial execution, so any
divergence is a bug, not an approximation. MIXED per-entry acquire
counts are covered too (``test_fuzz_mixed_acquire_counts`` — exact
since r5's survivor-fixpoint loop in check_flow), with the
rate-limiter's bounded mixed-count delta pinned separately
(``test_fuzz_rate_limiter_mixed_counts_bounded``, SEMANTICS.md #7) and
the warm-up controller fuzzed under randomized bursts
(``test_fuzz_warmup_random_traffic``).

The rule mix: flow QPS / THREAD / rate-limiter (exact (reason, wait_us)
agreement) / origin-limited QPS / warm-up; authority white+black lists;
param QPS / THREAD; exception-count circuit breakers (probe-at-entry,
feed-at-exit with bad-wins batch votes, calendar-tumbling stat
windows); randomized exits carrying error flags and acquire counts.
Already caught: the multi-token rate-limiter idle-grace fidelity bug,
the zero-width batch trace crash, the undocumented flow→degrade prefix
delta (r4), and the unbounded mixed-count over-admission the fixpoint
loop now prevents (r5: 30 tokens admitted against a 9-token rule).

The pod-parallel twin lives in test_pod_fuzz.py (staleness-envelope
assertions over the real shard_mapped step on the 8-device CPU mesh).

One fixed batch width (padding with invalid rows) keeps each scenario
at two jit specializations.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import (
    ExitBatch,
    make_entry_batch_np,
    make_exit_batch_np,
)
from sentinel_tpu.core.batch import EntryBatch
from sentinel_tpu.utils.param_hash import hash_param
from tests.oracle import OracleRateLimiter

WIDTH = 32
NOW0 = 1_700_000_000_000


class OracleWindow:
    """1s/2-bucket pass window (lazy reset), matching SPEC_1S."""

    def __init__(self):
        self.starts = [-1, -1]
        self.counts = [0, 0]

    def total(self, now):
        idx = (now // 500) % 2
        ws = now - now % 500
        t = 0
        for b in range(2):
            expect = ws if b == idx else ws - 500
            if self.starts[b] == expect:
                t += self.counts[b]
        return t

    def add(self, now, c=1):
        idx = (now // 500) % 2
        ws = now - now % 500
        if self.starts[idx] != ws:
            self.starts[idx] = ws
            self.counts[idx] = 0
        self.counts[idx] += c


class Oracle:
    """Sequential reference semantics over the fuzz rule set."""

    def __init__(self, spec):
        self.spec = spec          # per-resource dict of rules
        self.win = {r: OracleWindow() for r in spec}
        self.owin = {}            # (resource, origin) -> OracleWindow
        self.gauge = {r: 0 for r in spec}
        self.param = {}           # (resource, value) -> [tokens, filled]
        self.pgauge = {}          # (resource, value) -> concurrency
        self.rl = {r: OracleRateLimiter(s["flow"][1], s["flow"][2])
                   for r, s in spec.items()
                   if s.get("flow") and s["flow"][0] == "rl"}
        # Breaker state per degrade-ruled resource. The stat window is a
        # single calendar-aligned tumbling bucket (BREAKER_BUCKETS=1):
        # totals zero lazily whenever now crosses a stat-interval
        # boundary, mirrored here via win_start.
        self.brk = {r: {"state": "CLOSED", "retry": 0, "total": 0,
                        "err": 0, "win_start": None}
                    for r, s in spec.items() if s.get("degrade")}

    def admit(self, res, origin, value, now, c=1):
        s = self.spec[res]
        # Chain order: authority -> param -> flow (system off).
        auth = s.get("authority")
        if auth is not None:
            allow, white = auth
            inside = origin in allow
            if (white and not inside) or ((not white) and inside):
                return C.BlockReason.AUTHORITY, 0
        prule = s.get("param")
        if prule is not None and value is not None:
            pgrade, pcount = prule
            key = (res, value)
            if pgrade == "thread":
                # Per-value concurrency gauge (1 per ENTRY, like the
                # reference — acquireCount moves tokens, not gauges);
                # exits release.
                if self.pgauge.get(key, 0) + 1 > pcount:
                    return C.BlockReason.PARAM_FLOW, 0
                self.pgauge[key] = self.pgauge.get(key, 0) + 1
            else:
                # Reference token bucket: elapsed-based refill against
                # the LAST fill stamp (not calendar windows); an owner
                # touch writes the refreshed level back even when
                # blocked.
                state = self.param.get(key)
                if state is None:
                    if pcount < c:
                        return C.BlockReason.PARAM_FLOW, 0
                    self.param[key] = [pcount - c, now]
                else:
                    tokens, filled = state
                    windows = (now - filled) // 1000
                    avail = min(tokens + windows * pcount, pcount)
                    if windows >= 1:
                        state[1] = now
                    state[0] = avail
                    if avail < c:
                        return C.BlockReason.PARAM_FLOW, 0
                    state[0] = avail - c
        wait_us = 0
        frule = s.get("flow")
        if frule is not None:
            if frule[0] == "rl":
                ok, wait_us = self.rl[res].try_pass(now, acquire=c)
                if not ok:
                    return C.BlockReason.FLOW, 0
            elif frule[0] == C.FLOW_GRADE_QPS:
                if self.win[res].total(now) + c > frule[1]:
                    # A param admit above already consumed a token; the
                    # serial reference does the same (rate-limiter heads
                    # and param buckets move before later slots reject).
                    return C.BlockReason.FLOW, 0
            elif frule[0] == "qps_origin":
                # Applies only to the named origin, admitting against
                # that origin's own statistics node.
                _, count, lim = frule
                if origin == lim:
                    ow = self.owin.setdefault(
                        (res, origin), OracleWindow())
                    if ow.total(now) + c > count:
                        return C.BlockReason.FLOW, 0
            else:  # THREAD
                if self.gauge[res] + 1 > frule[1]:
                    return C.BlockReason.FLOW, 0
        if s.get("degrade"):
            b = self.brk[res]
            if b["state"] == "OPEN":
                if now >= b["retry"]:
                    b["state"] = "HALF_OPEN"  # probe admitted
                else:
                    return C.BlockReason.DEGRADE, 0
            elif b["state"] == "HALF_OPEN":
                return C.BlockReason.DEGRADE, 0
        self.win[res].add(now, c)
        if frule is not None and frule[0] == "qps_origin" and origin == frule[2]:
            self.owin.setdefault((res, origin), OracleWindow()).add(now, c)
        self.gauge[res] += 1
        return C.BlockReason.PASS, wait_us

    def exit_batch(self, completions, now):
        """Device exit-batch semantics: feed all windows, then apply
        HALF_OPEN votes (bad wins within a batch) and trip checks once
        on the post-batch totals."""
        votes = {}
        for res, value, error, c in completions:
            self.gauge[res] -= 1
            prule = self.spec[res].get("param")
            if (prule is not None and prule[0] == "thread"
                    and value is not None):
                self.pgauge[(res, value)] -= 1
            d = self.spec[res].get("degrade")
            if d:
                b = self.brk[res]
                stat_ms = d[3]
                ws = now - now % stat_ms
                if b["win_start"] != ws:  # lazy calendar roll
                    b["win_start"] = ws
                    b["total"] = b["err"] = 0
                b["total"] += c
                b["err"] += c if error else 0
                if b["state"] == "HALF_OPEN":
                    votes.setdefault(res, []).append(error)
        for res, s in self.spec.items():
            d = s.get("degrade")
            if not d:
                continue
            thr, min_req, window_ms, _stat_ms = d
            b = self.brk[res]
            if b["state"] == "HALF_OPEN" and res in votes:
                if any(votes[res]):          # bad wins
                    b["state"] = "OPEN"
                    b["retry"] = now + window_ms
                else:
                    b["state"] = "CLOSED"
                    b["total"] = b["err"] = 0  # resetStat on close
            elif b["state"] == "CLOSED":
                if b["total"] >= min_req and b["err"] > thr:
                    b["state"] = "OPEN"
                    b["retry"] = now + window_ms


def _pick_param_values(rng):
    """Distinct values whose table slots don't collide (the fuzz scope
    is the exact-ownership regime)."""
    vals, slots = [], set()
    while len(vals) < 4:
        v = f"v{int(rng.integers(1, 10_000))}"
        slot = int(np.uint32(hash_param(v)) % 2048)
        if slot not in slots:
            slots.add(slot)
            vals.append(v)
    return vals


@pytest.mark.parametrize("seed,steps", [
    (11, 40),
    # Redundant 40-step seeds ride the slow tier (ISSUE 11 + ISSUE 16
    # tier-1 wall-time trims): each costs ~14s and exercises the same
    # regimes as the tier-1 seed; the full sweep still runs with
    # -m slow.
    pytest.param(23, 40, marks=pytest.mark.slow),
    pytest.param(37, 40, marks=pytest.mark.slow),
    pytest.param(59, 40, marks=pytest.mark.slow),
    pytest.param(101, 40, marks=pytest.mark.slow),
    pytest.param(137, 40, marks=pytest.mark.slow),
    # One long soak: many breaker retry cycles, stat-window rolls, and
    # QPS-window turnovers against a single compile.
    (7, 150),
])
def test_fuzz_step_matches_serial_oracle(engine, frozen_time, seed, steps):
    rng = np.random.default_rng(seed)
    resources = [f"res{i}" for i in range(12)]
    origins = ["appA", "appB", "appC"]

    spec = {}
    flow_rules, auth_rules, param_rules, degrade_rules = [], [], [], []
    for r in resources:
        s = {}
        roll = rng.random()
        if roll < 0.4:
            count = int(rng.integers(0, 8))
            s["flow"] = (C.FLOW_GRADE_QPS, count)
            flow_rules.append(st.FlowRule(resource=r, count=count))
        elif roll < 0.6:
            count = int(rng.integers(1, 4))
            s["flow"] = (C.FLOW_GRADE_THREAD, count)
            flow_rules.append(st.FlowRule(resource=r, count=count,
                                          grade=C.FLOW_GRADE_THREAD))
        elif roll < 0.75:
            count = int(rng.integers(2, 30))
            mq = int(rng.integers(0, 800))
            s["flow"] = ("rl", count, mq)
            flow_rules.append(st.FlowRule(
                resource=r, count=count,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=mq))
        elif roll < 0.85:
            count = int(rng.integers(0, 6))
            lim = origins[int(rng.integers(0, len(origins)))]
            s["flow"] = ("qps_origin", count, lim)
            flow_rules.append(st.FlowRule(resource=r, count=count,
                                          limit_app=lim))
        if rng.random() < 0.3:
            allow = set(rng.choice(origins,
                                   size=int(rng.integers(1, 3)),
                                   replace=False).tolist())
            white = bool(rng.random() < 0.5)
            s["authority"] = (allow, white)
            auth_rules.append(st.AuthorityRule(
                r, ",".join(sorted(allow)),
                C.AUTHORITY_WHITE if white else C.AUTHORITY_BLACK))
        if rng.random() < 0.4:
            pcount = int(rng.integers(1, 5))
            if rng.random() < 0.35:
                s["param"] = ("thread", pcount)
                param_rules.append(st.ParamFlowRule(
                    r, param_idx=0, count=pcount,
                    grade=C.PARAM_FLOW_GRADE_THREAD))
            else:
                s["param"] = ("qps", pcount)
                param_rules.append(st.ParamFlowRule(r, param_idx=0,
                                                    count=pcount))
        if "flow" not in s and rng.random() < 0.4:
            # Exception-count breaker; the oracle mirrors the device's
            # single calendar-aligned tumbling stat bucket (lazy roll at
            # now - now % stat_interval). Degrade-ruled resources carry
            # no flow rule here: within one batch, flow's prefix counts
            # entries the (later) degrade slot blocks — the documented
            # bounded micro-batch delta (SEMANTICS.md), outside this
            # fuzz's serial-exact scope.
            dthr = int(rng.integers(1, 4))
            dmin = int(rng.integers(1, 3))
            dstat = int(rng.choice([2000, 5000, 30000]))
            s["degrade"] = (dthr, dmin, 1000, dstat)
            degrade_rules.append(st.DegradeRule(
                resource=r, grade=C.DEGRADE_GRADE_EXCEPTION_COUNT,
                count=dthr, time_window=1, min_request_amount=dmin,
                stat_interval_ms=dstat))
        spec[r] = s

    st.load_flow_rules(flow_rules)
    st.load_authority_rules(auth_rules)
    st.load_param_flow_rules(param_rules)
    st.load_degrade_rules(degrade_rules)
    engine._ensure_compiled()

    reg = engine.registry
    values = {r: _pick_param_values(rng) for r in resources
              if spec[r].get("param") is not None}
    oracle = Oracle(spec)
    now = NOW0
    open_handles = []   # (resource,) admitted, not yet exited

    for step in range(steps):
        now += int(rng.integers(0, 800))
        frozen_time.freeze_time(now)
        n = int(rng.integers(4, WIDTH + 1))
        # Uniform acquire count per batch: equal counts keep the
        # two-pass prefixes serially exact (mixed counts are the
        # documented approximation regime).
        c = int(rng.integers(1, 4))
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1  # padding rows: invalid
        meta = []
        for i in range(n):
            r = resources[int(rng.integers(0, len(resources)))]
            origin = origins[int(rng.integers(0, len(origins)))]
            v = None
            if spec[r].get("param") is not None and rng.random() < 0.8:
                v = values[r][int(rng.integers(0, 4))]
            buf["cluster_row"][i] = reg.cluster_row(r)
            buf["origin_row"][i] = reg.origin_row(r, origin)
            buf["origin_id"][i] = reg.origin_id(origin)
            buf["origin_named"][i] = True
            buf["dn_row"][i] = -1
            buf["count"][i] = c
            if v is not None:
                buf["param_hash"][i, 0] = np.uint32(hash_param(v))
                buf["param_present"][i, 0] = True
            meta.append((r, origin, v))

        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        reasons = np.asarray(dec.reason)[:n]

        waits = np.asarray(dec.wait_us)[:n]
        oracle_out = [oracle.admit(r, o, v, now, c) for r, o, v in meta]
        want = np.asarray([w[0] for w in oracle_out])
        want_wait = np.asarray([w[1] for w in oracle_out], np.int64)
        assert (reasons == want).all(), (
            f"seed {seed} step {step}: device {reasons.tolist()} "
            f"!= oracle {want.tolist()} for {meta}")
        assert (waits == want_wait).all(), (
            f"seed {seed} step {step}: device waits {waits.tolist()} "
            f"!= oracle {want_wait.tolist()} for {meta}")

        open_handles += [(m[0], m[2], c) for m, rr in zip(meta, reasons)
                         if rr == C.BlockReason.PASS]

        # Exit a random subset of open handles (releases THREAD gauges).
        rng.shuffle(open_handles)
        n_exit = int(rng.integers(0, len(open_handles) + 1))
        if n_exit:
            closing, open_handles = (open_handles[:n_exit],
                                     open_handles[n_exit:])
            xbuf = make_exit_batch_np(WIDTH)
            xbuf["cluster_row"][:] = -1
            completions = []
            for i, (r, v, hc) in enumerate(closing[:WIDTH]):
                err = bool(rng.random() < 0.3)
                xbuf["cluster_row"][i] = reg.cluster_row(r)
                xbuf["dn_row"][i] = -1
                xbuf["count"][i] = hc
                xbuf["rt_ms"][i] = int(rng.integers(1, 50))
                xbuf["success"][i] = not err
                xbuf["error"][i] = err
                if v is not None:
                    xbuf["param_hash"][i, 0] = np.uint32(hash_param(v))
                    xbuf["param_present"][i, 0] = True
                completions.append((r, v, err, hc))
            oracle.exit_batch(completions, now)
            open_handles += closing[WIDTH:]
            engine.complete_batch(
                ExitBatch(**{k: np.asarray(a) for k, a in xbuf.items()}),
                now_ms=now)


@pytest.mark.parametrize("seed,steps", [
    (13, 50),
    # Redundant 50-step seed slow-tier'd (ISSUE 17 tier-1 wall-time
    # trim): ~19s for the same mixed-count fixpoint regimes as (13, 50);
    # (83, 80) stays quick for the longer window-roll soak.
    pytest.param(47, 50, marks=pytest.mark.slow),
    (83, 80),
])
def test_fuzz_mixed_acquire_counts(engine, frozen_time, seed, steps):
    """Per-ENTRY random acquire counts (1-3) — the regime the original
    fuzz excluded. Round 5 made the flow sweep serially exact here via
    the survivor-fixpoint loop (models/flow.py check_flow): before that,
    a mixed batch could admit 30 tokens against a 9-token rule (pass 2's
    prefixes never saw its own admissions). Families stay on DISJOINT
    resources (flow vs param vs degrade) — cross-family prefix interplay
    is the separately-documented bounded delta; rate-limiter rules are
    excluded (their mixed-count delta is pinned by
    test_fuzz_rate_limiter_mixed_counts_bounded below)."""
    rng = np.random.default_rng(seed)
    resources = [f"res{i}" for i in range(10)]
    origins = ["appA", "appB", "appC"]

    spec = {}
    flow_rules, auth_rules, param_rules = [], [], []
    for r in resources:
        s = {}
        roll = rng.random()
        if roll < 0.35:
            count = int(rng.integers(0, 10))
            s["flow"] = (C.FLOW_GRADE_QPS, count)
            flow_rules.append(st.FlowRule(resource=r, count=count))
        elif roll < 0.5:
            count = int(rng.integers(1, 4))
            s["flow"] = (C.FLOW_GRADE_THREAD, count)
            flow_rules.append(st.FlowRule(resource=r, count=count,
                                          grade=C.FLOW_GRADE_THREAD))
        elif roll < 0.65:
            count = int(rng.integers(0, 6))
            lim = origins[int(rng.integers(0, len(origins)))]
            s["flow"] = ("qps_origin", count, lim)
            flow_rules.append(st.FlowRule(resource=r, count=count,
                                          limit_app=lim))
        elif roll < 0.9:
            pcount = int(rng.integers(1, 6))
            s["param"] = ("qps", pcount)
            param_rules.append(st.ParamFlowRule(r, param_idx=0,
                                                count=pcount))
        if rng.random() < 0.3 and "param" not in s:
            allow = set(rng.choice(origins, size=int(rng.integers(1, 3)),
                                   replace=False).tolist())
            white = bool(rng.random() < 0.5)
            s["authority"] = (allow, white)
            auth_rules.append(st.AuthorityRule(
                r, ",".join(sorted(allow)),
                C.AUTHORITY_WHITE if white else C.AUTHORITY_BLACK))
        spec[r] = s

    st.load_flow_rules(flow_rules)
    st.load_authority_rules(auth_rules)
    st.load_param_flow_rules(param_rules)
    engine._ensure_compiled()

    reg = engine.registry
    values = {r: _pick_param_values(rng) for r in resources
              if spec[r].get("param") is not None}
    oracle = Oracle(spec)
    now = NOW0
    open_handles = []

    for step in range(steps):
        now += int(rng.integers(0, 800))
        frozen_time.freeze_time(now)
        n = int(rng.integers(4, WIDTH + 1))
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1
        meta = []
        for i in range(n):
            r = resources[int(rng.integers(0, len(resources)))]
            origin = origins[int(rng.integers(0, len(origins)))]
            c = int(rng.integers(1, 4))  # MIXED: per entry
            v = None
            if spec[r].get("param") is not None and rng.random() < 0.8:
                v = values[r][int(rng.integers(0, 4))]
            buf["cluster_row"][i] = reg.cluster_row(r)
            buf["origin_row"][i] = reg.origin_row(r, origin)
            buf["origin_id"][i] = reg.origin_id(origin)
            buf["origin_named"][i] = True
            buf["dn_row"][i] = -1
            buf["count"][i] = c
            if v is not None:
                buf["param_hash"][i, 0] = np.uint32(hash_param(v))
                buf["param_present"][i, 0] = True
            meta.append((r, origin, v, c))

        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        reasons = np.asarray(dec.reason)[:n]
        want = np.asarray(
            [oracle.admit(r, o, v, now, c)[0] for r, o, v, c in meta])
        assert (reasons == want).all(), (
            f"seed {seed} step {step}: device {reasons.tolist()} "
            f"!= oracle {want.tolist()} for {meta}")

        open_handles += [(m[0], m[2], m[3]) for m, rr in zip(meta, reasons)
                         if rr == C.BlockReason.PASS]
        rng.shuffle(open_handles)
        n_exit = int(rng.integers(0, len(open_handles) + 1))
        if n_exit:
            closing, open_handles = (open_handles[:n_exit][:WIDTH],
                                     open_handles[n_exit:])
            xbuf = make_exit_batch_np(WIDTH)
            xbuf["cluster_row"][:] = -1
            completions = []
            for i, (r, v, hc) in enumerate(closing):
                xbuf["cluster_row"][i] = reg.cluster_row(r)
                xbuf["dn_row"][i] = -1
                xbuf["count"][i] = hc
                xbuf["rt_ms"][i] = int(rng.integers(1, 50))
                xbuf["success"][i] = True
                if v is not None:
                    xbuf["param_hash"][i, 0] = np.uint32(hash_param(v))
                    xbuf["param_present"][i, 0] = True
                completions.append((r, v, False, hc))
            oracle.exit_batch(completions, now)
            engine.complete_batch(
                ExitBatch(**{k: np.asarray(a) for k, a in xbuf.items()}),
                now_ms=now)


@pytest.mark.parametrize("seed", [7, 41])
def test_fuzz_param_hot_key_mixed_counts(engine, frozen_time, seed):
    """Mixed acquire counts concentrated on ONE hot param value — the
    density the general mixed-count fuzz's value spread masked (r5:
    before the param sweep adopted the survivor fixpoint, a mixed batch
    on one value admitted 32 tokens against a 9-token bucket)."""
    rng = np.random.default_rng(seed)
    pcount = int(rng.integers(3, 12))
    st.load_param_flow_rules([
        st.ParamFlowRule("hotres", param_idx=0, count=pcount)])
    engine._ensure_compiled()
    reg = engine.registry
    oracle = Oracle({"hotres": {"param": ("qps", pcount)}})
    values = _pick_param_values(rng)
    now = NOW0
    for step in range(40):
        now += int(rng.integers(0, 1500))
        frozen_time.freeze_time(now)
        n = int(rng.integers(4, WIDTH + 1))
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1
        meta = []
        for i in range(n):
            c = int(rng.integers(1, 4))
            # 70% of traffic on values[0]: heavy same-key density
            v = values[0] if rng.random() < 0.7 else \
                values[int(rng.integers(1, 4))]
            buf["cluster_row"][i] = reg.cluster_row("hotres")
            buf["dn_row"][i] = -1
            buf["count"][i] = c
            buf["param_hash"][i, 0] = np.uint32(hash_param(v))
            buf["param_present"][i, 0] = True
            meta.append((v, c))
        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        reasons = np.asarray(dec.reason)[:n]
        want = np.asarray(
            [oracle.admit("hotres", "", v, now, c)[0] for v, c in meta])
        assert (reasons == want).all(), (
            f"seed {seed} step {step}: device {reasons.tolist()} "
            f"!= oracle {want.tolist()} for {meta}")


@pytest.mark.parametrize("seed,interval_ms,buckets", [
    (21, 2000, 4), (87, 500, 5), (133, 3000, 2),
])
def test_fuzz_qps_under_retuned_geometry(engine, frozen_time, seed,
                                         interval_ms, buckets):
    """QPS admission fuzz under NON-DEFAULT instant-window geometry
    (engine.set_window_geometry — the reference's IntervalProperty/
    SampleCountProperty): the default-geometry fuzz never exercises the
    generalized bucket math, so a rotation bug specific to e.g. odd
    bucket counts or multi-second intervals would hide. Oracle:
    OracleLeapArray at the SAME geometry. Threshold semantics scale by
    1000/interval (window_sum × 1000/interval + count ≤ thr)."""
    from tests.oracle import OracleLeapArray

    engine.set_window_geometry(interval_ms, buckets)
    rng = np.random.default_rng(seed)
    resources = [f"g{i}" for i in range(5)]
    thr = {r: int(rng.integers(1, 12)) for r in resources}
    st.load_flow_rules([st.FlowRule(resource=r, count=thr[r])
                        for r in resources])
    engine._ensure_compiled()
    reg = engine.registry
    oracles = {r: OracleLeapArray(interval_ms, buckets, 1)
               for r in resources}
    now = NOW0
    for step in range(40):
        now += int(rng.integers(0, int(interval_ms * 1.2)))
        frozen_time.freeze_time(now)
        n = int(rng.integers(3, WIDTH + 1))
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1
        meta = []
        for i in range(n):
            r = resources[int(rng.integers(0, len(resources)))]
            buf["cluster_row"][i] = reg.cluster_row(r)
            buf["dn_row"][i] = -1
            buf["count"][i] = 1
            meta.append(r)
        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        reasons = np.asarray(dec.reason)[:n]
        want = []
        for r in meta:
            o = oracles[r]
            used = o.total(now, 0) * (1000.0 / interval_ms)
            if used + 1 <= thr[r]:
                want.append(int(C.BlockReason.PASS))
                o.add(now, 0, 1)
            else:
                want.append(int(C.BlockReason.FLOW))
        assert (reasons == np.asarray(want)).all(), (
            f"seed {seed} geo {interval_ms}/{buckets} step {step}: "
            f"device {reasons.tolist()} != oracle {want} for {meta}")


@pytest.mark.parametrize("seed", [9, 53])
def test_fuzz_system_rule_mixed_counts(engine, frozen_time, seed):
    """System-rule QPS cap under mixed acquire counts, system-ONLY (the
    cross-family prefix interaction is the documented delta; alone, the
    global IN prefix must be serially exact — it had the same truncated
    second-pass defect as flow/param before adopting the fixpoint, r5)."""
    rng = np.random.default_rng(seed)
    qps = int(rng.integers(4, 15))
    st.load_system_rules([st.SystemRule(qps=qps)])
    engine._ensure_compiled()
    reg = engine.registry
    now = NOW0
    for step in range(30):
        now += 3000  # fresh second: the global budget resets to qps
        frozen_time.freeze_time(now)
        n = int(rng.integers(4, 24))
        counts = [int(rng.integers(1, 4)) for _ in range(n)]
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1
        for i, c in enumerate(counts):
            buf["cluster_row"][i] = reg.cluster_row(f"sys{i % 5}",
                                                    C.EntryType.IN)
            buf["dn_row"][i] = -1
            buf["count"][i] = c
            buf["entry_in"][i] = True
        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        reasons = np.asarray(dec.reason)[:n]
        used = 0
        want = []
        for c in counts:  # serial greedy against the global budget
            if used + c <= qps:
                want.append(int(C.BlockReason.PASS))
                used += c
            else:
                want.append(int(C.BlockReason.SYSTEM))
        assert (reasons == np.asarray(want)).all(), (
            f"seed {seed} step {step}: device {reasons.tolist()} "
            f"!= oracle {want} for counts {counts}")


@pytest.mark.parametrize("seed", [3, 19, 71])
def test_fuzz_rate_limiter_mixed_counts_bounded(engine, frozen_time, seed):
    """Rate-limiter rules under MIXED acquire counts: the batch advance
    clamps the bucket head per-rule with the batch's max admitted count
    (models/flow.py ``rl_cmax``) while the serial reference clamps per
    request — after an idle gap the head can sit up to
    ``(c_max - c_min) * cost`` early, worth at most (c_max - c_min)
    extra tokens of later admission per idle-gap batch (r4 advisory,
    pinned here). Assert the cumulative divergence obeys that envelope
    and never exceeds it."""
    rng = np.random.default_rng(seed)
    count, mq = 20, 500  # 20 QPS -> cost 50ms; queue up to 500ms
    st.load_flow_rules([st.FlowRule(
        resource="rl", count=count,
        control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
        max_queueing_time_ms=mq)])
    engine._ensure_compiled()
    reg = engine.registry
    oracle = OracleRateLimiter(count, mq)
    now = NOW0
    c_lo, c_hi = 1, 3
    dev_total = orc_total = 0
    idle_gap_batches = 0
    for step in range(60):
        gap = int(rng.choice([0, 30, 200, 2000]))
        if gap >= 1000:
            idle_gap_batches += 1  # full-drain idle: the clamp regime
        now += gap
        frozen_time.freeze_time(now)
        n = int(rng.integers(2, 12))
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1
        counts = [int(rng.integers(c_lo, c_hi + 1)) for _ in range(n)]
        for i, c in enumerate(counts):
            buf["cluster_row"][i] = reg.cluster_row("rl")
            buf["dn_row"][i] = -1
            buf["count"][i] = c
        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        reasons = np.asarray(dec.reason)[:n]
        dev_total += sum(c for c, r in zip(counts, reasons) if r == 0)
        for c in counts:
            ok, _w = oracle.try_pass(now, acquire=c)
            orc_total += c if ok else 0
    # Envelope: every idle-gap mixed batch may leave the head early by
    # at most (c_hi - c_lo) tokens; the device never under-admits by
    # more than one acquire's worth of rounding.
    bound = (c_hi - c_lo) * max(idle_gap_batches, 1) + c_hi
    assert abs(dev_total - orc_total) <= bound, (
        seed, dev_total, orc_total, idle_gap_batches)


class OracleWarmUpWindowed:
    """Serial WarmUpController against the fuzz's OracleWindow (1s/2
    buckets — matching SPEC_1S), supporting arbitrary timestamps.

    Sync/threshold arithmetic runs in float32, mirroring the device
    (compile_flow_rules stores wt/mt/slope as float32 and _sync_warmup /
    the warm admission are float32 throughout). This is load-bearing:
    warm-up has positive feedback across seconds (an admission flipped
    at a float boundary changes the prev-bucket pass count, which
    changes the next sync's stored tokens, which keeps the thresholds
    diverged), so a float64 oracle can drift from the device by far
    more than the per-flip ±1 — seed 31 below accumulated -11 over 50
    steps. In float32 the oracle IS the device decision-for-decision;
    the tolerances below only absorb batch-internal ordering."""

    F = np.float32

    def __init__(self, count: float, warm_up_sec: int):
        cold = C.COLD_FACTOR
        # Constants exactly as compiled: float64 host math, then the
        # float32 cast the rule tensors apply.
        wt64 = warm_up_sec * count / (cold - 1)
        mt64 = wt64 + 2.0 * warm_up_sec * count / (1 + cold)
        self.count = self.F(count)
        self.wt = self.F(wt64)
        self.mt = self.F(mt64)
        self.slope = self.F((cold - 1.0) / count / max(mt64 - wt64, 1e-9))
        self.stored = self.F(0.0)
        self.last_filled = 0
        self.win = OracleWindow()

    def _prev_bucket_pass(self, now_ms):
        idx = ((now_ms // 500) - 1) % 2
        ws = (now_ms - now_ms % 500) - 500
        if self.win.starts[idx] == ws:
            return self.F(self.win.counts[idx])
        return self.F(0.0)

    def sync(self, now_ms):
        F = self.F
        cold = C.COLD_FACTOR
        now_sec = now_ms // 1000 * 1000
        if now_sec <= self.last_filled:
            return
        prev_pass = self._prev_bucket_pass(now_ms)
        stored = self.stored
        elapsed_s = F(now_sec - self.last_filled) / F(1000.0)
        refill = stored + elapsed_s * self.count
        below = stored < self.wt
        above = stored > self.wt
        if below or (above and prev_pass < self.count / F(cold)):
            stored = refill
        stored = min(stored, self.mt)
        stored = max(F(stored - prev_pass), F(0.0))
        self.stored = stored
        self.last_filled = now_sec

    def threshold(self):
        F = self.F
        if self.stored >= self.wt:
            return F(1.0) / (F(self.stored - self.wt) * self.slope
                             + F(1.0) / self.count)
        return self.count

    def try_acquire(self, now_ms):
        self.sync(now_ms)
        if self.win.total(now_ms) + 1 <= self.threshold():
            self.win.add(now_ms, 1)
            return True
        return False


@pytest.mark.parametrize("seed,count,wp", [
    (5, 40, 4),
    # The heaviest geometry rides the slow tier (ISSUE 16 tier-1
    # wall-time trim, ~13s); the two light geometries stay tier-1.
    pytest.param(31, 60, 8, marks=pytest.mark.slow),
    (67, 25, 3),
])
def test_fuzz_warmup_random_traffic(engine, frozen_time, seed, count, wp):
    """Warm-up controller under RANDOMIZED traffic (the r4 fuzz gap):
    random burst widths and inter-batch gaps instead of the fixed
    per-second trace of test_warmup_oracle.py. Per-batch admitted counts
    must track the serial oracle within the float32-boundary tolerance,
    and cumulative drift stays small (each boundary rounding is worth at
    most one entry, and thresholds re-sync every second)."""
    rng = np.random.default_rng(seed)
    st.load_flow_rules([st.FlowRule(
        resource="warm", count=count,
        control_behavior=C.CONTROL_BEHAVIOR_WARM_UP, warm_up_period_sec=wp)])
    engine._ensure_compiled()
    reg = engine.registry
    oracle = OracleWarmUpWindowed(count, wp)
    now = NOW0
    dev_cum = orc_cum = 0
    checked = 0
    for step in range(50):
        now += int(rng.integers(50, 1500))
        frozen_time.freeze_time(now)
        n = int(rng.integers(1, WIDTH + 1))
        buf = make_entry_batch_np(WIDTH)
        buf["cluster_row"][:] = -1
        for i in range(n):
            buf["cluster_row"][i] = reg.cluster_row("warm")
            buf["dn_row"][i] = -1
            buf["count"][i] = 1
        dec = engine.check_batch(
            EntryBatch(**{k: np.asarray(a) for k, a in buf.items()}),
            now_ms=now)
        adm_e = int((np.asarray(dec.reason)[:n] == C.BlockReason.PASS).sum())
        adm_o = sum(oracle.try_acquire(now) for _ in range(n))
        dev_cum += adm_e
        orc_cum += adm_o
        checked += 1
        # each batch may differ by 1 at a float32 admission boundary,
        # and one boundary flip feeds at most ±1 into the next second's
        # prev-bucket sync — drift tracks sqrt-ish, pin it linearly at 2
        assert abs(adm_e - adm_o) <= 2, (
            f"seed {seed} step {step}: device {adm_e} oracle {adm_o}")
    assert abs(dev_cum - orc_cum) <= max(4, checked // 10), (
        seed, dev_cum, orc_cum)


def test_width_zero_batches_trace_and_preserve_state(engine, frozen_time):
    """A zero-width entry/exit flush (empty pipeline buffer) must trace
    and be a no-op — W.varying_zeros indexes like.ravel()[:1], because a
    [0]-index would raise at trace time and the dispatch-error handler
    would then drop the whole device state."""
    st.load_flow_rules([st.FlowRule(resource="api", count=5)])
    st.load_degrade_rules([st.DegradeRule(resource="api", grade=2, count=3,
                                          time_window=1)])
    h = st.entry_ok("api")
    assert h is not None
    h.exit()
    before = engine._state
    assert before is not None
    ebuf = make_entry_batch_np(0)
    dec = engine.check_batch(
        EntryBatch(**{k: np.asarray(a) for k, a in ebuf.items()}))
    assert np.asarray(dec.reason).shape == (0,)
    xbuf = make_exit_batch_np(0)
    engine.complete_batch(
        ExitBatch(**{k: np.asarray(a) for k, a in xbuf.items()}))
    assert engine._state is not None  # no dispatch error, state kept
    assert st.entry_ok("api") is not None

"""Hard safety envelope for autonomous rule actuation.

"Designing Scalable Rate Limiting Systems" (PAPERS.md) warns that
adaptive limiters without bounded actuation oscillate; this module is
the bound. Every invariant lives here, first-class and separately
testable, so the controller/policy layer (``controller.py``) can be
swapped for a learned model without re-litigating safety:

* **Floor/ceiling clamps** — a proposed threshold never leaves the
  target's ``[floor, ceiling]`` band, whatever the policy says.
* **Bounded step size** — one actuation moves a threshold by at most
  ``step_pct`` of its current value (with a 1.0 absolute minimum so
  small integer-ish thresholds can still move at all).
* **Per-resource cooldown** — after a promoted change, the resource is
  untouchable for ``cooldown_ms``: the new setting's effect must show
  up in the flight recorder before it may be re-judged.
* **Hysteresis (no flapping across the target)** — a proposal that
  REVERSES the direction of the previous promoted change is rejected
  for ``flip_cooldown_ms`` (2x the plain cooldown by default): one
  boundary-straddling sense can never ping-pong a threshold.
* **Global freeze** (:class:`FreezeGate`) — stale or faulted telemetry,
  a manual ops freeze, or the post-abort backoff window turn the whole
  loop read-only: a controller must never actuate on senses it cannot
  trust, and never re-propose into the blast crater of an abort.

The envelope never talks to the engine or the rollout manager — it is
pure host arithmetic over explicit inputs, which is what makes the
invariants testable in isolation (tests/test_adaptive.py drives every
clause without a device).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

# EnvelopeDecision.reason values (stable strings — the decision log and
# the ops command surface them verbatim).
REASON_OK = "ok"
REASON_FLOOR = "floor"
REASON_CEILING = "ceiling"
REASON_STEP = "step"
REASON_COOLDOWN = "cooldown"
REASON_FLIP = "hysteresis"
REASON_NOOP = "no-op"

# FreezeGate reasons, in precedence order (manual beats everything:
# an operator's freeze must not be re-labelled by a coincident fault).
FREEZE_MANUAL = "manual"
FREEZE_DISABLED = "recorder-disabled"
FREEZE_STALE = "telemetry-stale"
FREEZE_FAULTED = "telemetry-faulted"
FREEZE_BACKOFF = "abort-backoff"


@dataclass(frozen=True)
class EnvelopeDecision:
    """Outcome of one :meth:`SafetyEnvelope.admit` call.

    ``allowed`` — the (possibly clamped) proposal may proceed;
    ``value`` — the threshold to actually stage (== ``current`` when
    rejected); ``clamped`` — a clamp changed the policy's ask;
    ``reason`` — which clause decided (one of the REASON_* constants).
    """

    allowed: bool
    value: float
    clamped: bool
    reason: str


class SafetyEnvelope:
    """Clamp + cooldown + hysteresis state for one adaptive loop."""

    def __init__(self, step_pct: float, cooldown_ms: int,
                 flip_cooldown_ms: Optional[int] = None):
        self.step_pct = float(step_pct)
        self.cooldown_ms = int(cooldown_ms)
        # Direction flips wait out a longer window than same-direction
        # refinement: crossing the target is where oscillation lives.
        self.flip_cooldown_ms = (int(flip_cooldown_ms)
                                 if flip_cooldown_ms is not None
                                 else 2 * int(cooldown_ms))
        self._lock = threading.Lock()
        # resource -> (last promoted actuation ms, direction +1/-1)
        self._last: Dict[str, Tuple[int, int]] = {}

    def admit(self, resource: str, current: float, proposed: float,
              floor: float, ceiling: float, now_ms: int) -> EnvelopeDecision:
        """Run one proposal through every clause. Order matters and is
        part of the contract: cooldown/hysteresis (is actuation allowed
        AT ALL right now?) before clamps (how far may it go?), so a
        rejected resource never reports a misleading clamp reason."""
        with self._lock:
            last = self._last.get(resource)
        direction = 1 if proposed > current else -1
        if last is not None:
            last_ms, last_dir = last
            if now_ms - last_ms < self.cooldown_ms:
                return EnvelopeDecision(False, current, False, REASON_COOLDOWN)
            if direction != last_dir \
                    and now_ms - last_ms < self.flip_cooldown_ms:
                return EnvelopeDecision(False, current, False, REASON_FLIP)
        if not floor <= current <= ceiling:
            # The LIVE value sits outside the band (an operator put it
            # there — e.g. an emergency clamp below the target's floor).
            # Admitting anything would either invert the ask's direction
            # (a congestion DECREASE clamped up to the floor is a limit
            # INCREASE) or stage a value the band forbids; both are
            # wrong, so the envelope refuses until the operator
            # reconciles the rule with the target (docs/OPERATIONS.md
            # "How to pin a resource static").
            return EnvelopeDecision(
                False, current, True,
                REASON_FLOOR if current < floor else REASON_CEILING)
        value, clamped, reason = proposed, False, REASON_OK
        # Bounded step first, band second: the band is the HARD invariant
        # (a floor/ceiling is never exceeded even when the step allows it).
        max_step = max(abs(current) * self.step_pct, 1.0)
        if abs(value - current) > max_step:
            value = current + max_step * direction
            clamped, reason = True, REASON_STEP
        if value < floor:
            value, clamped, reason = floor, True, REASON_FLOOR
        elif value > ceiling:
            value, clamped, reason = ceiling, True, REASON_CEILING
        if value == current:
            # Fully clamped back to where we already are (pinned at a
            # band edge, typically): not an actuation.
            return EnvelopeDecision(False, current, True, REASON_NOOP)
        return EnvelopeDecision(True, value, clamped, reason)

    def record_actuation(self, resource: str, current: float,
                         promoted: float, now_ms: int) -> None:
        """Stamp a PROMOTED change (cooldown + flip guard input).
        Proposals that die in shadow/canary don't stamp — the post-abort
        backoff (FreezeGate) covers that quiet period instead."""
        direction = 1 if promoted > current else -1
        with self._lock:
            self._last[resource] = (int(now_ms), direction)

    def cooldown_state(self, now_ms: int) -> Dict[str, Dict]:
        """Ops view: per-resource cooldown remaining."""
        with self._lock:
            items = dict(self._last)
        out = {}
        for res, (last_ms, direction) in items.items():
            remaining = max(0, self.cooldown_ms - (now_ms - last_ms))
            if remaining > 0:
                out[res] = {"remainingMs": remaining,
                            "direction": direction}
        return out

    def reset(self) -> None:
        with self._lock:
            self._last.clear()


@dataclass(frozen=True)
class FreezeState:
    frozen: bool
    reason: Optional[str]  # FREEZE_* constant, None when thawed


class FreezeGate:
    """Global actuation freeze: pure predicate over explicit inputs.

    The loop feeds it what it observed this tick; the gate only decides.
    Keeping it stateless (beyond nothing at all) means every clause is a
    one-line truth-table test.
    """

    def __init__(self, stale_after_ms: int):
        self.stale_after_ms = int(stale_after_ms)

    def evaluate(self, now_ms: int, *,
                 manual_frozen: bool,
                 recorder_enabled: bool,
                 last_second_ms: int,
                 fault_delta: int,
                 backoff_until_ms: int) -> FreezeState:
        """Precedence: manual > recorder-disabled > stale > faulted >
        backoff. ``last_second_ms`` is the newest COMPLETE second the
        flight recorder spilled (<= 0 means none yet — stale by
        definition); ``fault_delta`` counts fail-open / cluster-fallback
        events since the previous tick (any > 0 means the telemetry this
        tick judged may be missing the traffic that mattered most)."""
        if manual_frozen:
            return FreezeState(True, FREEZE_MANUAL)
        if not recorder_enabled:
            return FreezeState(True, FREEZE_DISABLED)
        if last_second_ms <= 0 \
                or now_ms - last_second_ms > self.stale_after_ms:
            return FreezeState(True, FREEZE_STALE)
        if fault_delta > 0:
            return FreezeState(True, FREEZE_FAULTED)
        if now_ms < backoff_until_ms:
            return FreezeState(True, FREEZE_BACKOFF)
        return FreezeState(False, None)

"""Shadow-rule evaluation & staged rollout (shadow → canary → promote).

The tensor design makes "what would this candidate ruleset have
blocked?" nearly free: a candidate set is just extra vectorized rule
rows evaluated in the same fused device step (``ops/step.py`` shadow
lanes), so operators can stage a rule edit against live traffic before
it rejects a single request — then enforce it for a deterministic
hash-selected canary slice, and finally promote it through the same
rule-manager path every datasource push takes (or let the block-rate
guardrail auto-abort it).

Import surface: :mod:`~sentinel_tpu.rollout.canary` (pure assignment
math, importable from device code) is re-exported here;
:class:`~sentinel_tpu.rollout.manager.RolloutManager` must be imported
from its module directly — ``manager`` pulls in the device step, and
the device step pulls in ``canary``, so re-exporting the manager here
would make that import a cycle.
"""

from sentinel_tpu.rollout.canary import (  # noqa: F401
    CANARY_BPS_MAX,
    canary_bucket,
    canary_hash,
    in_canary,
)

__all__ = ["CANARY_BPS_MAX", "canary_bucket", "canary_hash", "in_canary"]

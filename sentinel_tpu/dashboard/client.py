"""HTTP client to each engine's command port.

Reference: ``dashboard:client/SentinelApiClient.java`` — the dashboard
talks to every registered instance's command center (default :8719) to
fetch/push rules, scrape metrics, and drive cluster mode. Thin, synchronous
``urllib`` here (callers poll from worker threads).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

DEFAULT_TIMEOUT_S = 3.0


class ApiError(RuntimeError):
    pass


class SentinelApiClient:
    def __init__(self, timeout_s: float = DEFAULT_TIMEOUT_S):
        self.timeout_s = timeout_s

    # -- raw command transport --------------------------------------------

    def _url(self, ip: str, port: int, cmd: str, params: Optional[Dict] = None) -> str:
        qs = f"?{urllib.parse.urlencode(params)}" if params else ""
        return f"http://{ip}:{port}/{cmd}{qs}"

    def get(self, ip: str, port: int, cmd: str,
            params: Optional[Dict] = None) -> str:
        try:
            with urllib.request.urlopen(
                    self._url(ip, port, cmd, params), timeout=self.timeout_s) as r:
                return r.read().decode("utf-8")
        except (urllib.error.URLError, OSError) as ex:
            raise ApiError(f"GET {cmd} on {ip}:{port} failed: {ex}") from ex

    def post(self, ip: str, port: int, cmd: str,
             params: Optional[Dict] = None, body: str = "") -> str:
        req = urllib.request.Request(
            self._url(ip, port, cmd, params), data=body.encode("utf-8"),
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.read().decode("utf-8")
        except urllib.error.HTTPError as ex:
            raise ApiError(
                f"POST {cmd} on {ip}:{port}: {ex.read().decode(errors='replace')}"
            ) from ex
        except (urllib.error.URLError, OSError) as ex:
            raise ApiError(f"POST {cmd} on {ip}:{port} failed: {ex}") from ex

    # -- typed surface (mirrors SentinelApiClient methods) ----------------

    def fetch_rules(self, ip: str, port: int, rule_type: str) -> List[Dict]:
        return json.loads(self.get(ip, port, "getRules", {"type": rule_type}))

    def set_rules(self, ip: str, port: int, rule_type: str,
                  rules: List[Dict]) -> None:
        out = self.post(ip, port, "setRules", {"type": rule_type},
                        body=f"data={urllib.parse.quote(json.dumps(rules))}")
        if out != "success":
            raise ApiError(f"setRules rejected: {out}")

    def fetch_gateway_rules(self, ip: str, port: int) -> List[Dict]:
        return json.loads(self.get(ip, port, "gateway/getRules"))

    def set_gateway_rules(self, ip: str, port: int,
                          rules: List[Dict]) -> None:
        out = self.post(ip, port, "gateway/updateRules", {},
                        body=f"data={urllib.parse.quote(json.dumps(rules))}")
        if out != "success":
            raise ApiError(f"gateway/updateRules rejected: {out}")

    def fetch_api_definitions(self, ip: str, port: int) -> List[Dict]:
        return json.loads(self.get(ip, port, "gateway/getApiDefinitions"))

    def set_api_definitions(self, ip: str, port: int,
                            defs: List[Dict]) -> None:
        out = self.post(ip, port, "gateway/updateApiDefinitions", {},
                        body=f"data={urllib.parse.quote(json.dumps(defs))}")
        if out != "success":
            raise ApiError(f"gateway/updateApiDefinitions rejected: {out}")

    def fetch_metric(self, ip: str, port: int, start_ms: int, end_ms: int,
                     max_lines: int = 6000) -> str:
        return self.get(ip, port, "metric", {
            "startTime": start_ms, "endTime": end_ms, "maxLines": max_lines})

    def fetch_cluster_node(self, ip: str, port: int) -> List[Dict]:
        return json.loads(self.get(ip, port, "clusterNode"))

    def fetch_cluster_mode(self, ip: str, port: int) -> Dict:
        return json.loads(self.get(ip, port, "getClusterMode"))

    # -- staged rollout (sentinel_tpu/rollout/) ---------------------------

    def fetch_rollout(self, ip: str, port: int, op: str = "status") -> Dict:
        """``rollout`` read ops: status / diff."""
        return json.loads(self.get(ip, port, "rollout", {"op": op}))

    def fetch_telemetry(self, ip: str, port: int) -> Dict:
        """``telemetry`` snapshot (attribution / RT percentiles / timers)."""
        return json.loads(self.get(ip, port, "telemetry"))

    def fetch_traces(self, ip: str, port: int,
                     limit: Optional[int] = None,
                     offset: Optional[int] = None) -> Dict:
        """Sampled decision traces (``traces`` command), drained first."""
        params = {"drain": "true"}
        if limit is not None:
            params["limit"] = limit
        if offset is not None:
            params["offset"] = offset
        return json.loads(self.get(ip, port, "traces", params))

    def fetch_timeseries(self, ip: str, port: int,
                         since_ms: Optional[int] = None,
                         resource: Optional[str] = None,
                         limit: Optional[int] = None) -> Dict:
        """Flight-recorder per-second windows (``timeseries`` command);
        ``since_ms`` is the SSE pump's cursor (strictly-after filter)."""
        params: Dict = {}
        if since_ms is not None:
            params["sinceMs"] = since_ms
        if resource is not None:
            params["resource"] = resource
        if limit is not None:
            params["limit"] = limit
        return json.loads(self.get(ip, port, "timeseries", params))

    def fetch_alerts(self, ip: str, port: int,
                     since_seq: Optional[int] = None,
                     limit: Optional[int] = None) -> Dict:
        """SLO/anomaly alerts (``alerts`` command): active set + the
        seq-numbered transition log after ``since_seq`` (the SSE pump's
        cursor)."""
        params: Dict = {}
        if since_seq is not None:
            params["sinceSeq"] = since_seq
        if limit is not None:
            params["limit"] = limit
        return json.loads(self.get(ip, port, "alerts", params))

    def fetch_adaptive(self, ip: str, port: int, op: str = "status",
                       since_seq: Optional[int] = None,
                       limit: Optional[int] = None) -> Dict:
        """Adaptive-loop state (``adaptive`` command): status (default)
        or the seq-cursored decision log (``op="history"``)."""
        params: Dict = {"op": op}
        if since_seq is not None:
            params["sinceSeq"] = since_seq
        if limit is not None:
            params["limit"] = limit
        return json.loads(self.get(ip, port, "adaptive", params))

    def fetch_sim(self, ip: str, port: int, op: str = "report") -> Dict:
        """Simulator state (``sim`` command): the last policy-lab
        report (per-policy objective vectors) or the scenario catalog."""
        return json.loads(self.get(ip, port, "sim", {"op": op}))

    def fetch_fleet(self, ip: str, port: int, op: str = "status",
                    params: Optional[Dict] = None) -> Dict:
        """Fleet federation state (``fleet`` command): per-leader
        staleness/skew/health (op=status) or the exact federated
        per-second series (op=series)."""
        return json.loads(self.get(ip, port, "fleet",
                                   {"op": op, **(params or {})}))

    def fetch_rebalance(self, ip: str, port: int, op: str = "status",
                        params: Optional[Dict] = None) -> Dict:
        """Shard rebalancer state (``rebalance`` command): freeze
        state, counters and plan history (op=status) or the
        slice-granular load fold + skew (op=sense)."""
        return json.loads(self.get(ip, port, "rebalance",
                                   {"op": op, **(params or {})}))

    def fetch_waterfall(self, ip: str, port: int,
                        params: Optional[Dict] = None) -> Dict:
        """Wire-to-device latency waterfall (``waterfall`` command,
        op=status): per-stage cumulative budget, RTT reconciliation,
        exemplars and the regression sentry's alert state."""
        return json.loads(self.get(ip, port, "waterfall",
                                   {"op": "status", **(params or {})}))

    def fetch_population(self, ip: str, port: int, op: str = "status",
                         params: Optional[Dict] = None) -> Dict:
        """Namespace telescope (``population`` command): cardinality +
        top-k + churn (op=status), admission-readiness projection
        (op=report, budget=), the budget-ladder curve (op=curve), or
        the fleet-merged view (op=fleet)."""
        return json.loads(self.get(ip, port, "population",
                                   {"op": op, **(params or {})}))

    def fetch_journal(self, ip: str, port: int,
                      params: Optional[Dict] = None) -> Dict:
        """Audit-journal tail (``journal`` command): seq-cursored
        control-plane records (sinceSeq/limit/kind)."""
        return json.loads(self.get(ip, port, "journal", params or {}))

    def fetch_why(self, ip: str, port: int,
                  params: Optional[Dict] = None) -> Dict:
        """Forensic ``why`` join for one (resource, stampMs)."""
        return json.loads(self.get(ip, port, "why", params or {}))

    def fetch_explain(self, ip: str, port: int,
                      resource: Optional[str] = None,
                      index: int = 0) -> Dict:
        """``explain`` join: sampled trace × flight-recorder second."""
        params: Dict = {"index": index}
        if resource is not None:
            params["resource"] = resource
        return json.loads(self.get(ip, port, "explain", params))

    def rollout_command(self, ip: str, port: int, params: Dict,
                        body: str = "") -> Dict:
        """``rollout`` mutating ops (load/stage/promote/abort/tick)."""
        out = self.post(ip, port, "rollout", params, body=body)
        try:
            return json.loads(out)
        except ValueError as ex:
            raise ApiError(f"rollout command rejected: {out}") from ex

    def set_cluster_mode(self, ip: str, port: int, mode: int) -> None:
        out = self.post(ip, port, "setClusterMode", {"mode": mode})
        if out != "success":
            raise ApiError(f"setClusterMode rejected: {out}")

    def modify_cluster_client_config(self, ip: str, port: int,
                                     server_host: str, server_port: int) -> None:
        self.post(ip, port, "cluster/client/modifyConfig",
                  body=json.dumps({"serverHost": server_host,
                                   "serverPort": server_port}))

    def modify_cluster_server_config(self, ip: str, port: int,
                                     token_port: int) -> None:
        self.post(ip, port, "cluster/server/modifyTransportConfig",
                  {"port": token_port})

    def fetch_cluster_server_config(self, ip: str, port: int) -> Dict:
        return json.loads(self.get(ip, port, "cluster/server/fetchConfig"))

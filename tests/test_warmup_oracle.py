"""Warm-up controller fidelity vs a serial Guava-SmoothWarmingUp oracle.

The WarmUpController's slope math (coldFactor 3, warning zone, 1 Hz token
sync against the previous bucket's pass count) is the subtlest numerics in
the flow family. This test drives the SAME traffic trace through a pure-
Python serial oracle (built on the OracleLeapArray window replica) and the
vectorized device path, and requires per-second admitted counts to agree
within float32 rounding — covering the cold throttle, the warm-up ramp,
and the fully-warm plateau.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
from tests.oracle import PASS, OracleLeapArray

COLD = C.COLD_FACTOR
NOW0 = 1_700_000_000_000


class OracleWarmUp:
    """Serial WarmUpController (the documented reference algorithm)."""

    def __init__(self, count: float, warm_up_sec: int):
        self.count = count
        self.wt = warm_up_sec * count / (COLD - 1)
        self.mt = self.wt + 2.0 * warm_up_sec * count / (1 + COLD)
        self.slope = (COLD - 1.0) / count / (self.mt - self.wt)
        self.stored = 0.0
        self.last_filled = 0  # epoch 0: first sync refills to maxToken
        self.window = OracleLeapArray(C.SECOND_WINDOW_MS, C.SECOND_BUCKETS, 6)

    def _sync(self, now_ms: int) -> None:
        now_sec = now_ms // 1000 * 1000
        if now_sec <= self.last_filled:
            return
        prev_pass = float(self.window.previous_bucket(now_ms, PASS))
        stored = self.stored
        refill = stored + (now_sec - self.last_filled) / 1000.0 * self.count
        below = stored < self.wt
        above = stored > self.wt
        if below or (above and prev_pass < self.count / COLD):
            stored = refill
        stored = min(stored, self.mt)
        stored = max(stored - prev_pass, 0.0)
        self.stored = stored
        self.last_filled = now_sec

    def threshold(self) -> float:
        if self.stored >= self.wt:
            return 1.0 / ((self.stored - self.wt) * self.slope
                          + 1.0 / self.count)
        return self.count

    def try_acquire(self, now_ms: int) -> bool:
        self._sync(now_ms)
        used = self.window.total(now_ms, PASS)
        if used + 1 <= self.threshold():
            self.window.add(now_ms, PASS, 1)
            return True
        return False


def test_warmup_curve_matches_serial_oracle(engine, frozen_time):
    count, wp, offered = 60, 6, 80  # one 80-wide burst per second
    st.load_flow_rules([st.FlowRule(
        resource="curve", count=count,
        control_behavior=C.CONTROL_BEHAVIOR_WARM_UP, warm_up_period_sec=wp)])
    row = engine.registry.cluster_row("curve")
    engine._ensure_compiled()
    oracle = OracleWarmUp(count, wp)

    buf = make_entry_batch_np(offered)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})

    probe_buf = make_entry_batch_np(1)
    probe_buf["cluster_row"][:] = -1  # no candidates: sync-only step
    probe = EntryBatch(**{k: jnp.asarray(v) for k, v in probe_buf.items()})

    # Traffic concentrated at N.6 each second, with a no-op probe at N.2:
    # the probe's sync reads the PREVIOUS second's full bucket (upstream
    # semantics: previousWindowPass is a bucket count compared against the
    # per-second count/coldFactor — evenly spread traffic never drains the
    # bucket, which is the reference's own cold-trap; concentrated bursts
    # do, and the ramp appears).
    per_sec_engine, per_sec_oracle = [], []
    for sec in range(20):
        t_probe = NOW0 + sec * 1000 + 200
        engine.check_batch(probe, now_ms=t_probe)
        oracle._sync(t_probe)
        ts = NOW0 + sec * 1000 + 600
        dec = engine.check_batch(batch, now_ms=ts)
        adm_e = int((np.asarray(dec.reason) == C.BlockReason.PASS).sum())
        adm_o = sum(oracle.try_acquire(ts) for _ in range(offered))
        per_sec_engine.append(adm_e)
        per_sec_oracle.append(adm_o)

    # per-second agreement within float32-vs-float64 rounding at the
    # admission boundary — the fidelity claim
    for sec, (e, o) in enumerate(zip(per_sec_engine, per_sec_oracle)):
        assert abs(e - o) <= 1, (sec, per_sec_engine, per_sec_oracle)
    # and the curve has the right SHAPE: cold throttle near count/COLD,
    # then a ramp well above it once the stored tokens drain
    assert per_sec_engine[1] == pytest.approx(count / COLD, abs=3)
    assert per_sec_engine[-1] >= count * 0.8
    assert per_sec_engine[-1] > per_sec_engine[1] * 2

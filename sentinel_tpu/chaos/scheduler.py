"""Seedable fault-schedule generation.

A schedule is a PURE FUNCTION of ``(campaign_seed, episode_index)``:
:func:`episode_seed` derives a stable per-episode seed (sha256, no
``hash()`` — process-stable), and :class:`FaultScheduler` draws the
schedule from a ``random.Random`` over that seed while simulating the
plan's cluster state (who is crashed, who owns how many slices) with
the SAME rules the mesh executes, so every generated action is valid
at its scheduled second.

Every action is self-contained — a ``rebalance`` carries the FULL new
assignment and the moved slices' epochs, a ``crash`` on an already-dead
seat is a no-op — so ANY subset of a schedule is executable, which is
exactly what the delta-debugging shrinker needs.
"""

from __future__ import annotations

import hashlib
import random
from typing import Dict, List

from sentinel_tpu.chaos.mesh import DEFAULT_FLOWS, initial_assignment

# Every kind the mesh can execute; docs/OPERATIONS.md "Chaos campaign"
# documents the catalogue.
ACTION_KINDS = (
    "conn.drop", "conn.stall", "halfopen", "stale.epoch", "link.down",
    "crash", "rebalance", "publish", "torn.publish", "ckpt.crash",
    "journal.full", "journal.restart", "flap", "map.split", "zombie",
    "router.stale", "skew", "overload",
)

# Skew draws: bounded to less than one window so a leader's timebase
# stays monotone against the 1s driver cadence (one skew per leader per
# episode; the window-keyed invariant checkers absorb the boundary
# shifts).
_SKEWS = (-400, 300, 700, 900)


def episode_seed(campaign_seed: int, episode_index: int) -> int:
    digest = hashlib.sha256(
        f"{int(campaign_seed)}:{int(episode_index)}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class FaultScheduler:
    def __init__(self, leaders=("A", "B", "C"), flows=None, n_slices: int = 8,
                 seconds: int = 12, max_faults: int = 6):
        self.leaders = tuple(leaders)
        self.flows = dict(flows) if flows else dict(DEFAULT_FLOWS)
        self.n_slices = int(n_slices)
        self.seconds = int(seconds)
        self.max_faults = max(1, int(max_faults))

    def schedule(self, campaign_seed: int, episode_index: int) -> List[dict]:
        if self.seconds <= 1:
            # A 1-second episode drives only sec 0 and faults fire from
            # sec 1 — an honestly EMPTY schedule, never actions the
            # episode loop can silently skip.
            return []
        rng = random.Random(episode_seed(campaign_seed, episode_index))
        assignment = initial_assignment(self.leaders, self.flows,
                                        self.n_slices)
        crashed: set = set()
        skewed: set = set()
        epochs: Dict[int, int] = {sl: 1 for sl in range(self.n_slices)}
        version = 1
        n = rng.randint(1, self.max_faults)
        # Draw the firing seconds first and plan IN TIME ORDER, so the
        # plan's simulated cluster state matches execution order.
        ats = sorted(rng.randrange(1, max(2, self.seconds - 1))
                     for _ in range(n))
        actions: List[dict] = []
        for at in ats:
            choices = ["conn.drop", "conn.stall", "halfopen", "stale.epoch",
                       "link.down", "publish", "torn.publish", "ckpt.crash",
                       "journal.full", "journal.restart", "flap",
                       "map.split", "zombie", "router.stale", "skew",
                       "overload"]
            alive = [m for m in self.leaders if m not in crashed]
            if len(alive) > 1:
                choices.append("crash")
            rebal_from = [m for m in self.leaders
                          if (m in crashed and assignment.get(m))
                          or (m not in crashed
                              and len(assignment.get(m, ())) >= 2)]
            if rebal_from and len(alive) >= (1 if crashed else 2):
                choices.append("rebalance")
            kind = rng.choice(choices)
            if kind == "skew":
                fresh = [m for m in self.leaders if m not in skewed]
                if not fresh:
                    kind = "publish"
            if kind == "rebalance":
                frm = rng.choice(sorted(rebal_from))
                to_cands = [m for m in alive if m != frm]
                if not to_cands:
                    kind = "publish"
            if kind == "crash":
                victim = rng.choice(sorted(alive))
                crashed.add(victim)
                actions.append({"at": at, "kind": "crash",
                                "leader": victim})
            elif kind == "rebalance":
                to = rng.choice(sorted(to_cands))
                moved = (list(assignment[frm]) if frm in crashed
                         else [max(assignment[frm])])
                version += 1
                for sl in moved:
                    epochs[sl] = version
                assignment[to] = sorted(set(assignment.get(to, [])) |
                                        set(moved))
                assignment[frm] = sorted(set(assignment.get(frm, [])) -
                                         set(moved))
                actions.append({
                    "at": at, "kind": "rebalance", "frm": frm, "to": to,
                    "assignment": {m: list(s)
                                   for m, s in assignment.items()},
                    "epochs": {int(sl): version for sl in moved},
                    "version": version})
            elif kind == "skew":
                mid = rng.choice(sorted(fresh))
                skewed.add(mid)
                actions.append({"at": at, "kind": "skew", "leader": mid,
                                "ms": rng.choice(_SKEWS)})
            elif kind == "link.down":
                mid = rng.choice(sorted(alive)) if alive else self.leaders[0]
                actions.append({"at": at, "kind": "link.down",
                                "leader": mid,
                                "secs": rng.randint(1, 3)})
            elif kind in ("conn.drop", "conn.stall", "halfopen",
                          "stale.epoch"):
                mid = rng.choice(sorted(alive)) if alive else self.leaders[0]
                actions.append({"at": at, "kind": kind, "leader": mid,
                                "times": rng.randint(1, 4)})
            elif kind == "overload":
                mid = rng.choice(sorted(alive)) if alive else self.leaders[0]
                actions.append({"at": at, "kind": "overload", "leader": mid,
                                "qps": rng.choice((1, 2, 5))})
            elif kind in ("publish", "journal.restart"):
                mid = rng.choice(sorted(alive)) if alive else self.leaders[0]
                actions.append({"at": at, "kind": kind, "leader": mid})
            elif kind == "journal.full":
                actions.append({"at": at, "kind": kind,
                                "times": rng.randint(1, 3)})
            elif kind == "flap":
                mid = rng.choice(sorted(self.leaders))
                actions.append({"at": at, "kind": kind, "leader": mid,
                                "times": 1})
            elif kind == "map.split":
                actions.append({"at": at, "kind": kind,
                                "after": rng.randrange(len(self.leaders))})
            else:  # torn.publish / ckpt.crash / zombie / router.stale
                actions.append({"at": at, "kind": kind})
        return actions

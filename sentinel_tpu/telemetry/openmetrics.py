"""Minimal OpenMetrics text renderer (no client-library dependency).

Emits the exposition format Prometheus scrapes and the OpenMetrics 1.0
parser accepts: ``# TYPE`` / ``# HELP`` metadata per family, samples with
escaped labels, histogram ``_bucket``/``_count``/``_sum`` series with a
``+Inf`` bucket, and the mandatory ``# EOF`` trailer. Families render in
registration order; within a family, samples in emission order — stable
output for diffing and for the round-trip test
(tests/test_telemetry.py parses the endpoint with
``prometheus_client.openmetrics.parser``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")

# Escaping per the OpenMetrics 1.0 ABNF: label VALUES escape backslash,
# double-quote and newline; HELP text escapes only backslash and newline
# (a quote is legal there verbatim — escaping it produces the invalid
# sequence ``\"`` strict parsers reject).
_LABEL_ESCAPES = {"\\": "\\\\", "\"": "\\\"", "\n": "\\n"}
_HELP_ESCAPES = {"\\": "\\\\", "\n": "\\n"}


def _escape_label(v: str) -> str:
    return "".join(_LABEL_ESCAPES.get(ch, ch) for ch in str(v))


def _escape_help(v: str) -> str:
    return "".join(_HELP_ESCAPES.get(ch, ch) for ch in str(v))


def _fmt_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


class OpenMetricsBuilder:
    """Accumulate metric families, then :meth:`render` the exposition."""

    def __init__(self):
        self._lines: List[str] = []

    def family(self, name: str, mtype: str, help_text: str) -> None:
        """Start a family. ``mtype``: gauge | counter | histogram | info."""
        self._lines.append(f"# TYPE {name} {mtype}")
        if help_text:
            self._lines.append(f"# HELP {name} {_escape_help(help_text)}")

    def sample(self, name: str, labels: Optional[Dict[str, str]],
               value) -> None:
        self._lines.append(f"{name}{_labels(labels)} {_fmt_value(value)}")

    def counter(self, name: str, help_text: str, value,
                labels: Optional[Dict[str, str]] = None) -> None:
        """One-sample counter family (cumulative; ``_total`` suffix)."""
        self.family(name, "counter", help_text)
        self.sample(name + "_total", labels, value)

    def histogram(self, name: str, labels: Dict[str, str],
                  edges: Sequence[float], bucket_counts: Sequence[float],
                  total_sum: float,
                  exemplars: Optional[Dict[int, Tuple[Dict[str, str],
                                                      float,
                                                      Optional[float]]]]
                  = None) -> None:
        """Histogram samples for ONE label set of an already-declared
        family: per-bucket counts (same indexing as ``edges`` plus one
        overflow) render as cumulative ``le`` buckets + ``+Inf`` +
        ``_count`` / ``_sum``.

        ``exemplars`` (OpenMetrics 1.0): bucket index -> (labelset,
        observed value, optional unix timestamp in SECONDS); renders as
        the ``# {trace_id="..."} value ts`` suffix on that bucket line —
        the waterfall's latency-bucket -> stitched-trace join."""
        cum = 0.0
        for b, (edge, cnt) in enumerate(zip(edges, bucket_counts)):
            cum += float(cnt)
            self._bucket_line(name, {**labels, "le": _fmt_value(edge)},
                              cum, exemplars.get(b) if exemplars else None)
        cum += float(bucket_counts[len(edges)]) \
            if len(bucket_counts) > len(edges) else 0.0
        self._bucket_line(name, {**labels, "le": "+Inf"}, cum,
                          exemplars.get(len(edges)) if exemplars else None)
        self.sample(name + "_count", labels, cum)
        self.sample(name + "_sum", labels, total_sum)

    def _bucket_line(self, name: str, labels: Dict[str, str], value,
                     exemplar) -> None:
        line = f"{name}_bucket{_labels(labels)} {_fmt_value(value)}"
        if exemplar is not None:
            ex_labels, ex_value, ex_ts = exemplar
            line += f" # {_labels(ex_labels)} {_fmt_value(ex_value)}"
            if ex_ts is not None:
                line += f" {_fmt_value(ex_ts)}"
        self._lines.append(line)

    def render(self) -> str:
        return "\n".join(self._lines + ["# EOF", ""])


def parse_families(text: str) -> Dict[str, List[Tuple[str, Dict, float]]]:
    """Tiny exposition parser: family name -> [(sample_name, labels,
    value)]. Dependency-free fallback used by tests/tools when the
    prometheus_client OpenMetrics parser is unavailable; NOT a validator.
    """
    out: Dict[str, List[Tuple[str, Dict, float]]] = {}
    family = None
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            family = line.split()[2]
            out.setdefault(family, [])
            continue
        if not line or line.startswith("#"):
            continue
        # Strip any exemplar suffix (``... # {labels} value ts``) —
        # this fallback reads sample values, not exemplars.
        line = line.split(" # ", 1)[0]
        head, _, val = line.rpartition(" ")
        labels: Dict[str, str] = {}
        name = head
        if "{" in head:
            name, _, rest = head.partition("{")
            for part in rest.rstrip("}").split(","):
                if "=" in part:
                    k, _, v = part.partition("=")
                    labels[k] = v.strip('"')
        key = family if family and name.startswith(family) else name
        out.setdefault(key, []).append((name, labels, float(val)))
    return out

"""SLO engine (sentinel_tpu/slo/): burn-rate + EWMA/z-score math pinned
bit-exactly against a numpy oracle over randomized series, end-to-end
alert propagation (recorder second -> breach -> `alerts` command +
webhook + SSE frame), SSE Last-Event-ID resume, the rollout SLO-abort
gate, health scoring, the continuous step-duration histogram, and the
zero-per-step-device-work A/B guard.

The load-bearing property is DIFFERENTIAL (the timeseries-oracle
stance): every burn rate, EWMA mean/variance, z-score, and firing
decision the manager produces must EXACTLY equal a brute-force numpy
reimplementation run over the same series.
"""

import json
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import converters as CV
from sentinel_tpu.slo.manager import SloManager
from sentinel_tpu.slo.objectives import BurnWindow, SloObjective
from sentinel_tpu.slo.webhook import AlertWebhook
from sentinel_tpu.telemetry.attribution import (
    NUM_RT_BUCKETS,
    RT_BUCKET_EDGES_MS,
)
from sentinel_tpu.utils import time_util

BASE_MS = 1_700_000_000_000
_EDGES = np.asarray(RT_BUCKET_EDGES_MS, np.int64)


def _http(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read().decode())


# ---------------------------------------------------------------------------
# numpy oracle: the brute-force reimplementation of every formula
# ---------------------------------------------------------------------------

def _oracle_bad_total(obj, cell):
    if obj.sli == "availability":
        bad = int(cell.get("block", 0))
        return bad, bad + int(cell.get("pass", 0))
    buckets = np.asarray(cell.get("rtBuckets") or [0] * NUM_RT_BUCKETS,
                         np.int64)
    total = int(buckets.sum())
    edge = int(_EDGES[np.searchsorted(_EDGES, obj.latency_ms)]) \
        if obj.latency_ms <= int(_EDGES[-1]) else int(_EDGES[-1])
    good = int(buckets[: int(np.sum(_EDGES <= edge))].sum())
    return total - good, total


def _oracle_burn(series, end_ms, window_s, budget):
    """series: np.int64[N, 3] of (stamp, bad, total)."""
    if series.size == 0:
        return 0.0, 0, 0
    m = (series[:, 0] >= end_ms - window_s * 1000) & (series[:, 0] < end_ms)
    bad = int(series[m, 1].sum())
    total = int(series[m, 2].sum())
    burn = (bad / float(total)) / budget if total > 0 else 0.0
    return burn, bad, total


def _oracle_quantile(buckets, q):
    """numpy reimplementation of attribution.histogram_quantile (same
    float64 operation order, so results are bit-identical)."""
    total = float(sum(int(b) for b in buckets))
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for b in range(len(buckets)):
        prev = cum
        cum += float(int(buckets[b]))
        if cum >= target and buckets[b] > 0:
            if b >= len(RT_BUCKET_EDGES_MS):
                return float(RT_BUCKET_EDGES_MS[-1])
            lo = 0.0 if b == 0 else float(RT_BUCKET_EDGES_MS[b - 1])
            hi = float(RT_BUCKET_EDGES_MS[b])
            return lo + (hi - lo) * (target - prev) / float(int(buckets[b]))
    return float(RT_BUCKET_EDGES_MS[-1])


class _OracleEwma:
    """The West-recursion EWMA, reimplemented on numpy float64."""

    def __init__(self, alpha, zthr, warmup):
        self.alpha = np.float64(alpha)
        self.zthr = np.float64(zthr)
        self.warmup = warmup
        self.mean = np.float64(0.0)
        self.var = np.float64(0.0)
        self.n = 0
        self.z = np.float64(0.0)

    def update(self, x):
        x = np.float64(x)
        if self.n >= self.warmup and self.var > 0.0:
            self.z = (x - self.mean) / np.sqrt(self.var)
        else:
            self.z = np.float64(0.0)
        breached = bool(self.z >= self.zthr)
        diff = x - self.mean
        incr = self.alpha * diff
        self.mean = self.mean + incr
        self.var = (np.float64(1.0) - self.alpha) * (self.var + diff * incr)
        self.n += 1
        return breached


def _rand_buckets(rng, n):
    buckets = np.zeros(NUM_RT_BUCKETS, np.int64)
    for _ in range(n):
        rt = int(rng.integers(1, 5000))
        buckets[int(np.sum(rt > _EDGES))] += 1
    return buckets


@pytest.mark.parametrize("seed", [3, 11, 42])
def test_burn_and_ewma_match_numpy_oracle(seed):
    """Every evaluated second of a randomized gappy series: burn rates,
    firing decisions, active-alert sets, EWMA mean/var/z, and anomaly
    state all EXACTLY equal the oracle (availability + latency SLIs,
    calm/storm phases, a deterministic anomaly spike)."""
    rng = np.random.default_rng(seed)
    slo = SloManager()
    avail = SloObjective(resource="api", objective=0.95, min_events=5,
                         windows=(BurnWindow(30, 5, 3.0, "page"),
                                  BurnWindow(120, 30, 1.5, "ticket")))
    lat = SloObjective(resource="api", sli="latency", objective=0.9,
                       latency_ms=8, min_events=5, name="api-rt",
                       windows=(BurnWindow(20, 4, 2.0, "page"),))
    slo.load_objectives([avail, lat])
    objs = {"api:availability": avail, "api-rt": lat}
    series = {k: [] for k in objs}           # oracle (stamp, bad, total)
    ewma = {}                                 # oracle baselines for "free"
    anomaly_active = {}                       # oracle anomaly alert state
    fired_burn = fired_anomaly = 0

    stamp = BASE_MS
    for k in range(400):
        stamp += 1000 * int(rng.integers(1, 3))  # idle gaps are implicit
        storm = 150 <= k < 200
        cells = {}
        total = int(rng.integers(0, 30))
        if total:
            block = int(rng.binomial(total, 0.4 if storm else 0.02))
            cells["api"] = {
                "pass": total - block, "block": block,
                "rtBuckets": _rand_buckets(
                    rng, int(rng.integers(0, 20))).tolist(),
            }
        ftotal = 30 if k == 350 else int(rng.integers(5, 40))
        fblock = ftotal if k == 350 else int(rng.binomial(ftotal, 0.05))
        cells["free"] = {
            "pass": ftotal - fblock, "block": fblock,
            "rtBuckets": _rand_buckets(
                rng, int(rng.integers(1, 15))).tolist(),
        }
        slo.ingest(stamp, cells)
        end = stamp + 1000
        slo.evaluate(end)

        # -- oracle bookkeeping --------------------------------------------
        for key, obj in objs.items():
            cell = cells.get(obj.resource)
            if cell:
                bad, tot = _oracle_bad_total(obj, cell)
                if tot > 0 or bad > 0:
                    series[key].append((stamp, bad, tot))
        for sig, x, events in (
            ("blockRate",
             np.float64(fblock) / np.float64(ftotal), ftotal),
            ("rtP99Ms",
             _oracle_quantile(cells["free"]["rtBuckets"], 0.99),
             int(sum(cells["free"]["rtBuckets"]))),
        ):
            if events <= 0:
                continue
            bl = ewma.setdefault(sig, _OracleEwma(
                slo.baseline_alpha, slo.baseline_zscore,
                slo.baseline_warmup))
            breach = bl.update(x) and events >= slo.baseline_min_events
            was = anomaly_active.get(sig, False)
            anomaly_active[sig] = breach
            if breach and not was:
                fired_anomaly += 1

        # -- differential assertions ---------------------------------------
        status = slo.status()
        oracle_firing = set()
        for key, obj in objs.items():
            arr = (np.asarray(series[key], np.int64)
                   if series[key] else np.zeros((0, 3), np.int64))
            got_rules = status["burn"][key]["rules"]
            for i, w in enumerate(obj.windows):
                burn_l, _bad, tot_l = _oracle_burn(
                    arr, end, w.long_s, obj.budget)
                burn_s, _, _ = _oracle_burn(arr, end, w.short_s, obj.budget)
                firing = (tot_l >= obj.min_events
                          and burn_l >= w.burn and burn_s >= w.burn)
                got = got_rules[i]
                assert got["burnLong"] == burn_l, (k, key, i)
                assert got["burnShort"] == burn_s, (k, key, i)
                assert got["totalLong"] == tot_l, (k, key, i)
                assert got["firing"] == firing, (k, key, i)
                if firing:
                    oracle_firing.add((key, w.long_s, w.short_s))
                    fired_burn += 1
        got_active = {(a["objective"], a["windowLongS"], a["windowShortS"])
                      for a in slo.alerts_snapshot()["active"]
                      if a["kind"] == "burn_rate"}
        assert got_active == oracle_firing, k
        got_anomaly = {a["signal"]
                       for a in slo.alerts_snapshot()["active"]
                       if a["kind"] == "anomaly"}
        assert got_anomaly == {s for s, on in anomaly_active.items()
                               if on}, k
        for sig, bl in ewma.items():
            got_bl = slo._baselines["free"][sig]
            assert got_bl.mean == float(bl.mean), (k, sig)
            assert got_bl.var == float(bl.var), (k, sig)
            assert got_bl.last_z == float(bl.z), (k, sig)

    # the run must actually exercise both alert machineries
    assert fired_burn > 0, "storm phase never fired a burn alert"
    assert fired_anomaly > 0, "spike second never fired an anomaly"


def test_engine_burn_matches_recorder_oracle(engine):
    """Through the REAL pipeline: a randomized device stream's recorded
    seconds (the flight recorder spill) drive the same burn numbers the
    oracle computes from the served `timeseries` view."""
    from tests.test_timeseries import _run_randomized_stream

    obj = SloObjective(resource="tsA", objective=0.9, min_events=1,
                       windows=(BurnWindow(10, 3, 1.0, "page"),))
    engine.slo.load_objectives([obj])
    oracle, end_now = _run_randomized_stream(engine, seed=23)
    final_now = end_now + 2500
    view = engine.timeseries_view(now_ms=final_now)  # spills + evaluates
    end = final_now - final_now % 1000
    arr = np.asarray(
        [(s["timestamp"],
          s["resources"]["tsA"]["block"],
          s["resources"]["tsA"]["pass"] + s["resources"]["tsA"]["block"])
         for s in view["seconds"] if "tsA" in s["resources"]], np.int64)
    burn_l, _, tot_l = _oracle_burn(arr, end, 10, obj.budget)
    burn_s, _, _ = _oracle_burn(arr, end, 3, obj.budget)
    got = engine.slo.status()["burn"]["tsA:availability"]["rules"][0]
    assert got["burnLong"] == burn_l
    assert got["burnShort"] == burn_s
    assert got["totalLong"] == tot_l


# ---------------------------------------------------------------------------
# end-to-end: breach -> alerts command + /metrics + webhook + SSE frame
# ---------------------------------------------------------------------------

class _Hook(BaseHTTPRequestHandler):
    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(n))
        code = self.server.responses.pop(0) if self.server.responses else 200
        if 200 <= code < 300:
            self.server.received.append(body)
        self.send_response(code)
        self.end_headers()

    def log_message(self, fmt, *args):
        pass


def _hook_server(responses=None):
    srv = HTTPServer(("127.0.0.1", 0), _Hook)
    srv.received = []
    srv.responses = list(responses or [])
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _drive_breach(engine, resource="drill", seconds=6, per_sec=6):
    """Flow-limit a resource to 1 QPS, drive per_sec entries/s for
    `seconds` seconds, refresh judgement past the last complete second.
    Returns the stream-end clock."""
    from tests.test_telemetry import _batch

    st.load_flow_rules([st.FlowRule(resource=resource, count=1)])
    now = BASE_MS
    for _ in range(seconds):
        engine.check_batch(_batch(engine, [(resource, "", None)] * per_sec),
                           now_ms=now)
        now += 1000
    time_util.freeze_time(now)  # wall-clock readers see the stream end
    engine.slo_refresh(now_ms=now)
    return now


def test_alert_fires_end_to_end(engine):
    """One induced breach propagates everywhere: the `alerts` command
    (over HTTP), the OpenMetrics families, and the webhook (with a
    failed first attempt retried)."""
    from sentinel_tpu.transport.command_center import CommandCenter

    hook = _hook_server(responses=[503, 200])  # first attempt fails
    engine.slo.webhook = AlertWebhook(
        urls=[f"http://127.0.0.1:{hook.server_port}/hook"],
        timeout_ms=2000, retries=2)
    engine.slo.load_objectives([SloObjective(
        resource="drill", objective=0.9, min_events=1,
        windows=(BurnWindow(10, 2, 2.0, "page"),))])
    _drive_breach(engine)
    center = CommandCenter(engine, port=0).start()
    try:
        base = f"http://127.0.0.1:{center.bound_port}"
        out = _http(f"{base}/alerts")
        assert len(out["active"]) == 1
        alert = out["active"][0]
        assert alert["kind"] == "burn_rate" and alert["severity"] == "page"
        assert alert["resource"] == "drill"
        assert out["events"][-1]["type"] == "fired"
        assert out["health"]["resources"]["drill"] == 60
        # sinceSeq cursor: strictly-after
        assert _http(f"{base}/alerts?sinceSeq={out['nextSeq']}")["events"] \
            == []
        # resource filter
        assert _http(f"{base}/alerts?resource=nope")["active"] == []
        # /metrics families
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert 'sentinel_tpu_alert_active{severity="page"} 1' in text
        assert 'sentinel_tpu_slo_health_score{resource="drill"} 60' in text
        assert "sentinel_tpu_slo_burn_rate{" in text
        # `slo` command status view
        status = _http(f"{base}/slo")
        assert status["activeAlerts"] == 1
        rule = status["burn"]["drill:availability"]["rules"][0]
        assert rule["firing"] is True
        # webhook delivered after the 503 retry
        deadline = time.time() + 5
        while not hook.received and time.time() < deadline:
            time.sleep(0.02)
        assert hook.received, "webhook never delivered"
        ev = hook.received[0]
        assert ev["type"] == "fired"
        assert ev["alert"]["resource"] == "drill"
        deadline = time.time() + 5
        while engine.slo.webhook.stats()["delivered"] < 1 \
                and time.time() < deadline:
            time.sleep(0.02)  # counter lands after the response round-trip
        assert engine.slo.webhook.stats()["delivered"] == 1
    finally:
        center.stop()
        hook.shutdown()


def _read_sse(url, headers=None):
    """(event, data, id) frames until the server closes the stream."""
    req = urllib.request.Request(url, headers=headers or {})
    frames = []
    with urllib.request.urlopen(req, timeout=10) as r:
        event = eid = None
        for raw in r:
            line = raw.decode("utf-8").rstrip("\n")
            if line.startswith("id: "):
                eid = line[len("id: "):]
            elif line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: ") and event is not None:
                frames.append((event, json.loads(line[len("data: "):]), eid))
                event = None
    return frames


@pytest.fixture()
def dash(monkeypatch):
    from sentinel_tpu.dashboard.server import DashboardServer

    # heartbeats must register a dialable address, not the container IP
    monkeypatch.setenv("CSP_SENTINEL_HEARTBEAT_CLIENT_IP", "127.0.0.1")
    d = DashboardServer(port=0).start(fetch=False)
    d.stream_interval_s = 0.05
    yield d
    d.stop()


def _register(engine, dash):
    from sentinel_tpu.transport.command_center import CommandCenter
    from sentinel_tpu.transport.heartbeat import HeartbeatSender

    center = CommandCenter(engine, port=0).start()
    HeartbeatSender(dashboards=[f"127.0.0.1:{dash.bound_port}"],
                    api_port=center.bound_port).send_once()
    app = _http(f"http://127.0.0.1:{dash.bound_port}/app/names.json")["data"][0]
    return center, app


def test_alert_reaches_dashboard_sse_and_alerts_json(engine, dash):
    """The SSE stream carries the breach as an `event: alert` frame
    beside the second frames, and /alerts.json proxies the machine's
    alert store."""
    engine.slo.load_objectives([SloObjective(
        resource="drill", objective=0.9, min_events=1,
        windows=(BurnWindow(10, 2, 2.0, "page"),))])
    _drive_breach(engine)
    center, app = _register(engine, dash)
    try:
        base = f"http://127.0.0.1:{dash.bound_port}"
        out = _http(f"{base}/alerts.json?app={app}")["data"]
        assert out["active"][0]["resource"] == "drill"
        assert out["health"]["instance"] == 60
        # 6 complete seconds + 1 fired-alert transition = 7 data frames
        frames = _read_sse(f"{base}/telemetry/stream?app={app}&maxEvents=7")
        kinds = [e for e, _, _ in frames]
        assert kinds.count("second") == 6
        assert kinds.count("alert") == 1
        alert_frame = next(d for e, d, _ in frames if e == "alert")
        assert alert_frame["type"] == "fired"
        assert alert_frame["alert"]["resource"] == "drill"
        # every data frame carries a resumable compound id
        assert all(eid and ":" in eid for _, _, eid in frames)
    finally:
        center.stop()


def test_sse_last_event_id_resumes_missed_seconds(engine, dash):
    """A reconnecting consumer replays the complete seconds (and alert
    transitions) it missed from the bounded history instead of losing
    them: the second stream starts strictly after the presented id and
    serves everything retained since."""
    from tests.test_telemetry import _batch

    st.load_flow_rules([st.FlowRule(resource="sse", count=2)])
    now = BASE_MS
    for _ in range(5):
        engine.check_batch(_batch(engine, [("sse", "", None)] * 4),
                           now_ms=now)
        now += 1000
    time_util.freeze_time(now)
    engine.slo_refresh(now_ms=now)
    center, app = _register(engine, dash)
    try:
        base = f"http://127.0.0.1:{dash.bound_port}"
        first = _read_sse(f"{base}/telemetry/stream?app={app}&maxEvents=2")
        assert [e for e, _, _ in first] == ["second", "second"]
        assert [d["timestamp"] for _, d, _ in first] == \
            [BASE_MS, BASE_MS + 1000]
        last_id = first[-1][2]
        # reconnect presenting the last id: the remaining 3 seconds
        # replay, nothing repeats, nothing is skipped
        resumed = _read_sse(f"{base}/telemetry/stream?app={app}&maxEvents=3",
                            headers={"Last-Event-ID": last_id})
        assert [d["timestamp"] for _, d, _ in resumed] == \
            [BASE_MS + 2000, BASE_MS + 3000, BASE_MS + 4000]
        # a garbage id degrades to a fresh stream, not an error
        fresh = _read_sse(f"{base}/telemetry/stream?app={app}&maxEvents=1",
                          headers={"Last-Event-ID": "bogus"})
        assert fresh[0][1]["timestamp"] == BASE_MS
    finally:
        center.stop()


# ---------------------------------------------------------------------------
# rollout gate, health, config plumbing, step-duration histogram, A/B
# ---------------------------------------------------------------------------

def test_slo_breach_aborts_rollout(engine):
    """An active page-severity burn alert on a resource the candidate
    touches aborts the rollout on the next guardrail tick — no streak;
    the kill switch disables the gate."""
    engine.slo.load_objectives([SloObjective(
        resource="drill", objective=0.9, min_events=1,
        windows=(BurnWindow(10, 2, 2.0, "page"),))])
    cand_rules = {"flow": [{"resource": "drill", "count": 50}]}
    engine.rollout.load_candidate("cand", cand_rules, stage="shadow")
    now = _drive_breach(engine)
    out = engine.rollout.tick(now_ms=now)
    assert out["status"] == "aborted"
    assert out["sloBreaches"][0]["resource"] == "drill"
    assert engine.rollout.active_name is None
    ended = engine.rollout._sets["cand"]
    assert ended.stage == "aborted" and "slo:" in ended.ended_reason
    # an untouched resource does not abort the candidate
    engine.rollout.load_candidate("other", {"flow": [
        {"resource": "unrelated", "count": 5}]}, stage="shadow")
    out = engine.rollout.tick(now_ms=now)
    assert out.get("status") != "aborted"
    engine.rollout.abort("other")
    # kill switch off: breach is reported by `alerts` but never aborts
    engine.slo.rollout_abort_enabled = False
    engine.rollout.load_candidate("cand2", cand_rules, stage="shadow")
    out = engine.rollout.tick(now_ms=now)
    assert out.get("status") != "aborted"
    assert engine.rollout.active_name == "cand2"


def test_health_scores_compose():
    """Deterministic score math: page -40, ticket -20, anomaly -15 per
    active alert, instance = worst resource minus the capped shed
    penalty."""
    slo = SloManager()
    with slo._lock:
        slo._transition("p", True, 0, {
            "key": "p", "kind": "burn_rate", "severity": "page",
            "resource": "a"})
        slo._transition("t", True, 0, {
            "key": "t", "kind": "burn_rate", "severity": "ticket",
            "resource": "a"})
        slo._transition("z", True, 0, {
            "key": "z", "kind": "anomaly", "severity": "anomaly",
            "resource": "b", "signal": "blockRate"})
    h = slo.health_scores()
    assert h["resources"] == {"a": 40, "b": 85}
    assert h["instance"] == 40
    slo.shed_rate = 0.25
    h = slo.health_scores()
    assert h["shedPenalty"] == 25 and h["instance"] == 15
    slo.shed_rate = 0.9  # penalty caps at 50
    assert slo.health_scores()["shedPenalty"] == 50
    # resolving the page alert restores its weight
    with slo._lock:
        slo._transition("p", False, 1, {})
    slo.shed_rate = 0.0
    assert slo.health_scores()["resources"]["a"] == 80
    snap = slo.alerts_snapshot()
    assert snap["counters"] == {"fired": 3, "resolved": 1}
    assert [e["type"] for e in snap["events"]] == \
        ["fired", "fired", "fired", "resolved"]


def test_batcher_exposes_shed_rate():
    """The overload batcher's shed-rate (ISSUE 7): cumulative shed
    fraction + the admitted-requests counter the SLO health delta
    consumes."""
    from sentinel_tpu.cluster.server import _Batcher
    from sentinel_tpu.cluster.token_service import DefaultTokenService

    b = _Batcher(DefaultTokenService(), linger_s=0.001, max_batch=64,
                 max_queue_groups=2, watermark_pct=100, deadline_ms=1000)
    assert b.shed_rate() == 0.0
    b.submit_many([object()] * 3)   # admitted (queued, never drained)
    b.submit_many([object()] * 2)
    b.submit_many([object()] * 5)   # queue full (maxsize 2): shed
    stats = b.overload_stats()
    assert stats["admittedRequests"] == 5
    assert stats["shedRequests"] == 5
    assert b.shed_rate() == 0.5
    assert stats["shedRate"] == 0.5


def test_slo_converter_roundtrip_and_validation():
    objs = CV.slo_objectives_from_json(json.dumps([
        {"resource": "a", "objective": 0.999},
        {"resource": "a", "sli": "latency", "objective": 0.99,
         "latencyMs": 5, "name": "a-rt",
         "windows": [{"longSeconds": 30, "shortSeconds": 5,
                      "burnRate": 2, "severity": "ticket"}]},
    ]))
    assert objs[0].windows[0].long_s == 60  # defaults applied
    assert objs[0].windows[1].severity == "ticket"
    d = CV.slo_objective_to_dict(objs[1])
    assert d["latencyMs"] == 5
    assert d["effectiveLatencyMs"] == 8  # snapped UP to the bucket edge
    # round trip is stable
    again = CV.slo_objectives_from_json(
        CV.slo_objectives_to_json(objs))
    assert again == objs
    for bad in (
        [{"resource": "", "objective": 0.9}],                 # no resource
        [{"resource": "r", "objective": 1.0}],                # no budget
        [{"resource": "r", "sli": "weird"}],                  # unknown SLI
        [{"resource": "r", "windows": []}],                   # no windows
        [{"resource": "r", "windows": [                       # short > long
            {"longSeconds": 5, "shortSeconds": 9, "burnRate": 1}]}],
        [{"resource": "r", "windows": [                       # bad severity
            {"longSeconds": 9, "shortSeconds": 5, "burnRate": 1,
             "severity": "nope"}]}],
        {"resource": "r"},                                    # not a list
    ):
        with pytest.raises(ValueError):
            CV.slo_objectives_from_json(json.dumps(bad))
    # duplicate keys rejected at load
    slo = SloManager()
    with pytest.raises(ValueError):
        slo.load_objectives(CV.slo_objectives_from_json(json.dumps(
            [{"resource": "r"}, {"resource": "r"}])))


def test_slo_command_set_get_roundtrip(engine):
    from sentinel_tpu.transport.command_center import CommandCenter

    center = CommandCenter(engine, port=0).start()
    try:
        base = f"http://127.0.0.1:{center.bound_port}"
        payload = json.dumps([{"resource": "cmd", "objective": 0.95}])
        out = _http(f"{base}/slo?op=set&data=" +
                    urllib.parse.quote(payload))
        assert out == {"loaded": 1}
        got = _http(f"{base}/slo?op=get")
        assert got[0]["resource"] == "cmd"
        assert got[0]["objective"] == 0.95
        status = _http(f"{base}/slo")
        assert len(status["objectives"]) == 1
    finally:
        center.stop()


def test_step_duration_histogram_is_continuous(engine):
    """The cumulative step-duration histogram: counts every sampled
    sync step, renders as an OpenMetrics histogram, and survives a
    profile reset (monotone — SLO burn math may rate() it)."""
    from tests.test_telemetry import _batch

    engine.step_timer.sync_every = 1  # sample every dispatch
    for k in range(4):
        engine._run_entry_batch(_batch(engine, [("sd", "", None)]))
    hist = engine.step_timer.duration_histogram()
    assert hist["entry"]["count"] == 4
    assert sum(hist["entry"]["buckets"]) == 4
    assert hist["entry"]["sumMs"] > 0
    # renders beside (not instead of) the rolling quantile gauges
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    text = render_engine_metrics(engine)
    assert 'sentinel_tpu_step_duration_ms_bucket{kind="entry",le="+Inf"}' \
        in text
    assert 'sentinel_tpu_step_duration_ms_count{kind="entry"} 4' in text
    # a profile reset clears the rolling rings but NOT the histogram
    engine.step_timer.snapshot(reset=True)
    assert engine.step_timer.duration_histogram()["entry"]["count"] == 4


def test_slo_evaluation_adds_no_device_work():
    """A/B guard: the same stream with and without objectives dispatches
    the SAME number of device programs — judgement is host arithmetic
    riding the once-per-second fold."""
    from tests.test_telemetry import _batch

    def run(with_objectives):
        from sentinel_tpu.core.context import replace_context

        replace_context(None)
        eng = st.reset(capacity=256)
        if with_objectives:
            eng.slo.load_objectives([SloObjective(
                resource="ab", objective=0.9, min_events=1,
                windows=(BurnWindow(10, 2, 2.0, "page"),))])
        st.load_flow_rules([st.FlowRule(resource="ab", count=2)])
        now = BASE_MS
        for _ in range(5):
            time_util.freeze_time(now)  # device + refresh share the clock
            eng._run_entry_batch(_batch(eng, [("ab", "", None)] * 4))
            eng.slo_refresh(now_ms=now)  # judge every second
            now += 1000
        time_util.freeze_time(now)
        eng.slo_refresh(now_ms=now)  # complete the final second
        dispatches = {k: v["dispatches"]
                      for k, v in eng.step_timer.snapshot().items()}
        fired = eng.slo.alerts_snapshot()["counters"]["fired"]
        return dispatches, fired

    time_util.freeze_time(BASE_MS)
    try:
        base_dispatches, base_fired = run(False)
        slo_dispatches, slo_fired = run(True)
    finally:
        time_util.unfreeze_time()
        st.reset(capacity=512)
    assert base_fired == 0
    assert slo_fired > 0, "the A/B run never exercised evaluation"
    assert slo_dispatches == base_dispatches


def test_recording_disabled_slo_still_safe():
    """With the flight recorder off (timeseries.seconds=0) the SLO
    engine sees nothing and every surface stays empty — never an
    error."""
    from sentinel_tpu.core.config import config

    config.set("csp.sentinel.telemetry.timeseries.seconds", "0")
    try:
        from sentinel_tpu.core.context import replace_context

        replace_context(None)
        eng = st.reset(capacity=256)
        eng.slo.load_objectives([SloObjective(resource="x")])
        st.load_flow_rules([st.FlowRule(resource="x", count=1)])
        from tests.test_telemetry import _batch

        eng.check_batch(_batch(eng, [("x", "", None)] * 4), now_ms=BASE_MS)
        eng.slo_refresh(now_ms=BASE_MS + 5000)
        snap = eng.slo.alerts_snapshot()
        assert snap["active"] == [] and snap["events"] == []
        assert eng.slo.status()["burn"]["x:availability"]["rules"][0][
            "totalLong"] == 0
    finally:
        config.set("csp.sentinel.telemetry.timeseries.seconds",
                   str(128))
        st.reset(capacity=512)


def test_webhook_bounded_queue_drops_oldest():
    from sentinel_tpu.slo.webhook import QUEUE_CAPACITY

    wh = AlertWebhook(urls=["http://127.0.0.1:1/nothing"], retries=0,
                      timeout_ms=50)
    # pin a never-started worker stand-in so the queue actually fills
    wh._thread = threading.Thread(target=lambda: None)
    for i in range(QUEUE_CAPACITY + 5):
        wh.submit({"seq": i})
    assert wh.stats()["queued"] == QUEUE_CAPACITY
    assert wh.stats()["dropped"] == 5

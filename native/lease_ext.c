/* sentinel_lease_ext — CPython extension for the token-lease admission
 * ring (the native twin of core/lease.py's LocalLease).
 *
 * Why an extension and not ctypes: the leased entry path budget is a few
 * µs per op and a ctypes trampoline costs ~2-4µs — measured to ERASE the
 * win (r5). A PyMethodDef call is ~0.1-0.2µs, so the ring's rotate/sum
 * arithmetic drops from ~3µs of interpreted Python to ~0.3µs total.
 *
 * Thread-safety: all methods run WITH the GIL held (no
 * Py_BEGIN_ALLOW_THREADS) — the GIL itself serializes the ring, exactly
 * like the Python fallback's threading.Lock but with a critical section
 * three orders of magnitude shorter. No internal mutex is needed or
 * taken; if a future caller wants to release the GIL here, it must add
 * one.
 *
 * Semantics are bucket-for-bucket identical to the Python ring
 * (device-exact DEFAULT admission: window_sum * 1000/interval + count
 * <= every threshold); tests/test_lease.py runs its exactness suite
 * against whichever backend is active, and test_native.py compares the
 * two directly.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    PyObject_HEAD
    int64_t interval_ms;
    int64_t bucket_ms;
    int buckets;
    int nthresholds;
    double *thresholds;
    int64_t *starts;
    int64_t *counts;
} LeaseObject;

static void
Lease_dealloc(LeaseObject *self)
{
    PyMem_Free(self->thresholds);
    PyMem_Free(self->starts);
    PyMem_Free(self->counts);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static int
Lease_init(LeaseObject *self, PyObject *args, PyObject *kwds)
{
    PyObject *thresholds;
    long long interval_ms;
    int buckets;
    static char *kwlist[] = {"thresholds", "interval_ms", "buckets", NULL};

    if (!PyArg_ParseTupleAndKeywords(args, kwds, "OLi", kwlist,
                                     &thresholds, &interval_ms, &buckets))
        return -1;
    if (interval_ms <= 0 || buckets <= 0 || interval_ms % buckets != 0) {
        PyErr_SetString(PyExc_ValueError, "bad ring geometry");
        return -1;
    }
    PyObject *seq = PySequence_Fast(thresholds, "thresholds not a sequence");
    if (seq == NULL)
        return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    self->interval_ms = interval_ms;
    self->buckets = buckets;
    self->bucket_ms = interval_ms / buckets;
    self->nthresholds = (int)n;
    self->thresholds = PyMem_Malloc(sizeof(double) * (size_t)(n > 0 ? n : 1));
    self->starts = PyMem_Malloc(sizeof(int64_t) * (size_t)buckets);
    self->counts = PyMem_Malloc(sizeof(int64_t) * (size_t)buckets);
    if (!self->thresholds || !self->starts || !self->counts) {
        Py_DECREF(seq);
        PyErr_NoMemory();
        return -1;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        double v = PyFloat_AsDouble(PySequence_Fast_GET_ITEM(seq, i));
        if (v == -1.0 && PyErr_Occurred()) {
            Py_DECREF(seq);
            return -1;
        }
        self->thresholds[i] = v;
    }
    Py_DECREF(seq);
    for (int b = 0; b < buckets; b++) {
        self->starts[b] = -1;
        self->counts[b] = 0;
    }
    return 0;
}

/* Lazy bucket reset; returns the current index. Mirrors the Python
 * _rotate fast path: if the current bucket's start is right, the whole
 * ring is right. */
static inline int
rotate(LeaseObject *self, int64_t now_ms)
{
    int idx = (int)((now_ms / self->bucket_ms) % self->buckets);
    int64_t cur_start = now_ms - now_ms % self->bucket_ms;
    if (self->starts[idx] == cur_start)
        return idx;
    for (int b = 0; b < self->buckets; b++) {
        int64_t off = ((idx - b) % self->buckets + self->buckets)
                      % self->buckets;
        int64_t expected = cur_start - off * self->bucket_ms;
        if (self->starts[b] != expected) {
            self->starts[b] = expected;
            self->counts[b] = 0;
        }
    }
    return idx;
}

static inline double
used_qps(LeaseObject *self)
{
    int64_t total = 0;
    for (int b = 0; b < self->buckets; b++)
        total += self->counts[b];
    return (double)total * (1000.0 / (double)self->interval_ms);
}

static PyObject *
Lease_try_acquire(LeaseObject *self, PyObject *args)
{
    int count;
    long long now_ms;
    if (!PyArg_ParseTuple(args, "iL", &count, &now_ms))
        return NULL;
    int idx = rotate(self, now_ms);
    double used = used_qps(self);
    for (int i = 0; i < self->nthresholds; i++) {
        if (used + count > self->thresholds[i])
            Py_RETURN_FALSE;
    }
    self->counts[idx] += count;
    Py_RETURN_TRUE;
}

static PyObject *
Lease_add(LeaseObject *self, PyObject *args)
{
    int count;
    long long now_ms;
    if (!PyArg_ParseTuple(args, "iL", &count, &now_ms))
        return NULL;
    self->counts[rotate(self, now_ms)] += count;
    Py_RETURN_NONE;
}

static PyObject *
Lease_usage(LeaseObject *self, PyObject *args)
{
    long long now_ms;
    if (!PyArg_ParseTuple(args, "L", &now_ms))
        return NULL;
    rotate(self, now_ms);
    return PyFloat_FromDouble(used_qps(self));
}

static PyObject *
Lease_snapshot(LeaseObject *self, PyObject *Py_UNUSED(ignored))
{
    PyObject *starts = PyList_New(self->buckets);
    PyObject *counts = PyList_New(self->buckets);
    if (!starts || !counts) {
        Py_XDECREF(starts);
        Py_XDECREF(counts);
        return NULL;
    }
    for (int b = 0; b < self->buckets; b++) {
        PyList_SET_ITEM(starts, b, PyLong_FromLongLong(self->starts[b]));
        PyList_SET_ITEM(counts, b, PyLong_FromLongLong(self->counts[b]));
    }
    return Py_BuildValue("(NN)", starts, counts);
}

static PyObject *
Lease_seed(LeaseObject *self, PyObject *args)
{
    PyObject *starts, *counts;
    if (!PyArg_ParseTuple(args, "OO", &starts, &counts))
        return NULL;
    PyObject *s = PySequence_Fast(starts, "starts not a sequence");
    if (!s)
        return NULL;
    PyObject *c = PySequence_Fast(counts, "counts not a sequence");
    if (!c) {
        Py_DECREF(s);
        return NULL;
    }
    if (PySequence_Fast_GET_SIZE(s) != self->buckets ||
        PySequence_Fast_GET_SIZE(c) != self->buckets) {
        /* geometry mismatch: drop, like the Python ring */
        Py_DECREF(s);
        Py_DECREF(c);
        Py_RETURN_NONE;
    }
    for (int b = 0; b < self->buckets; b++) {
        long long sv = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(s, b));
        long long cv = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(c, b));
        if (PyErr_Occurred()) {
            Py_DECREF(s);
            Py_DECREF(c);
            return NULL;
        }
        self->starts[b] = sv;
        self->counts[b] = cv;
    }
    Py_DECREF(s);
    Py_DECREF(c);
    Py_RETURN_NONE;
}

static PyMethodDef Lease_methods[] = {
    {"try_acquire", (PyCFunction)Lease_try_acquire, METH_VARARGS,
     "try_acquire(count, now_ms) -> bool: device-exact DEFAULT admission"},
    {"add", (PyCFunction)Lease_add, METH_VARARGS,
     "add(count, now_ms): record a device-decided pass"},
    {"usage", (PyCFunction)Lease_usage, METH_VARARGS,
     "usage(now_ms) -> float: current window QPS"},
    {"snapshot", (PyCFunction)Lease_snapshot, METH_NOARGS,
     "snapshot() -> (starts, counts)"},
    {"seed", (PyCFunction)Lease_seed, METH_VARARGS,
     "seed(starts, counts): adopt a window wholesale"},
    {NULL, NULL, 0, NULL}
};

static PyTypeObject LeaseType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "sentinel_lease_ext.LeaseRing",
    .tp_basicsize = sizeof(LeaseObject),
    .tp_dealloc = (destructor)Lease_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "Native token-lease admission ring",
    .tp_methods = Lease_methods,
    .tp_init = (initproc)Lease_init,
    .tp_new = PyType_GenericNew,
};

static PyModuleDef lease_module = {
    PyModuleDef_HEAD_INIT,
    .m_name = "sentinel_lease_ext",
    .m_doc = "Native token-lease admission ring (see core/lease.py)",
    .m_size = -1,
};

PyMODINIT_FUNC
PyInit_sentinel_lease_ext(void)
{
    if (PyType_Ready(&LeaseType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&lease_module);
    if (m == NULL)
        return NULL;
    Py_INCREF(&LeaseType);
    if (PyModule_AddObject(m, "LeaseRing", (PyObject *)&LeaseType) < 0) {
        Py_DECREF(&LeaseType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}

"""Shared fixture builder for pallas_bisect.py's entry-step rungs: the
same rule/batch shape as bench.py's throughput section, scaled by
``width`` (the r4 panic config is width=8192 / 16 steps / donated)."""

from __future__ import annotations

import numpy as np


def build_step_fixture(width: int, n_resources: int = 64):
    import jax.numpy as jnp

    from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.models import authority as A
    from sentinel_tpu.models import degrade as D
    from sentinel_tpu.models import flow as F
    from sentinel_tpu.models import param_flow as P
    from sentinel_tpu.models import system as Y
    from sentinel_tpu.ops import step as S

    now0 = 1_700_000_000_000
    capacity = max(256, 4 * n_resources)
    reg = NodeRegistry(capacity)
    flow_rules = [F.FlowRule(resource=f"res{i}", count=1e9)
                  for i in range(0, n_resources, 10)]
    degrade_rules = [D.DegradeRule(resource=f"res{i}", count=100,
                                   grade=i % 3, time_window=10)
                     for i in range(0, n_resources, 20)]
    param_rules = [P.ParamFlowRule(f"res{i}", param_idx=0, count=1e9)
                   for i in range(0, n_resources, 40)]
    ctx = "sentinel_default_context"
    ent = reg.entrance_row(ctx)
    c_rows = np.asarray([reg.cluster_row(f"res{i}")
                         for i in range(n_resources)])
    d_rows = np.asarray([reg.default_row(ctx, f"res{i}", ent)
                         for i in range(n_resources)])
    ft, _ = F.compile_flow_rules(flow_rules, reg, capacity)
    dt, di = D.compile_degrade_rules(degrade_rules, reg, capacity)
    pt = P.compile_param_rules(param_rules, reg, capacity)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, capacity),
        system=Y.compile_system_rules([Y.SystemRule(qps=1e12)]),
        param=pt,
    )
    state = S.make_state(capacity, ft.num_rules, now0,
                         degrade=D.make_degrade_state(dt, di),
                         param=P.make_param_state(pt.num_rules))
    rng = np.random.default_rng(0)
    buf = make_entry_batch_np(width)
    pick = rng.integers(0, n_resources, size=width)
    buf["cluster_row"][:] = c_rows[pick]
    buf["dn_row"][:] = d_rows[pick]
    buf["count"][:] = 1
    buf["param_hash"][:, 0] = rng.integers(1, 1 << 31, size=width)
    buf["param_present"][:, 0] = True
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    return state, pack, batch, now0

package com.alibaba.csp.sentinel.slots.statistic;

import com.alibaba.csp.sentinel.context.Context;
import com.alibaba.csp.sentinel.node.DefaultNode;
import com.alibaba.csp.sentinel.slotchain.AbstractLinkedProcessorSlot;
import com.alibaba.csp.sentinel.slotchain.ResourceWrapper;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/statistic/StatisticSlot.java — the real class records
 * pass/block/RT into local nodes; in the bridged chain it stays for
 * local observability while the backend owns authoritative stats. */
public class StatisticSlot extends AbstractLinkedProcessorSlot<DefaultNode> {

    @Override
    public void entry(Context context, ResourceWrapper resourceWrapper,
                      DefaultNode node, int count, boolean prioritized,
                      Object... args) throws Throwable {
        fireEntry(context, resourceWrapper, node, count, prioritized, args);
    }

    @Override
    public void exit(Context context, ResourceWrapper resourceWrapper,
                     int count, Object... args) {
        fireExit(context, resourceWrapper, count, args);
    }
}

"""Authority + system rule tests.

Modeled on the reference's checker unit tests
(``AuthorityRuleCheckerTest``, ``SystemSlotTest`` — SURVEY.md §4): load
rules programmatically, spin real entries, assert pass/block.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import context as ctx


def _enter_with_origin(origin, resource="authRes", **kw):
    ctx.replace_context(None)
    ctx.enter("test_ctx", origin)
    return st.entry(resource, **kw)


class TestAuthority:
    def test_white_list_allows_listed(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "appA,appB", st.constants.AUTHORITY_WHITE)
        ])
        with _enter_with_origin("appA"):
            pass
        ctx.replace_context(None)

    def test_white_list_blocks_unlisted(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "appA,appB", st.constants.AUTHORITY_WHITE)
        ])
        with pytest.raises(st.AuthorityException):
            _enter_with_origin("appC")
        ctx.replace_context(None)

    def test_black_list_blocks_listed(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "badApp", st.constants.AUTHORITY_BLACK)
        ])
        with pytest.raises(st.AuthorityException):
            _enter_with_origin("badApp")
        ctx.replace_context(None)

    def test_black_list_allows_unlisted(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "badApp", st.constants.AUTHORITY_BLACK)
        ])
        with _enter_with_origin("goodApp"):
            pass
        ctx.replace_context(None)

    def test_empty_origin_always_passes(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "appA", st.constants.AUTHORITY_WHITE)
        ])
        with st.entry("authRes"):
            pass

    def test_other_resources_unaffected(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "appA", st.constants.AUTHORITY_WHITE)
        ])
        with _enter_with_origin("appC", resource="freeRes"):
            pass
        ctx.replace_context(None)

    def test_block_counts_recorded(self, engine):
        st.load_authority_rules([
            st.AuthorityRule("authRes", "appA", st.constants.AUTHORITY_WHITE)
        ])
        for _ in range(3):
            with pytest.raises(st.AuthorityException):
                _enter_with_origin("appC")
            ctx.replace_context(None)
        snap = engine.node_snapshot()
        assert snap["authRes"]["blockQps"] == 3


class TestSystem:
    def test_qps_cap_blocks_inbound(self, engine):
        st.load_system_rules([st.SystemRule(qps=3)])
        for _ in range(3):
            with st.entry("inRes", entry_type=st.EntryType.IN):
                pass
        with pytest.raises(st.SystemBlockException):
            st.entry("inRes2", entry_type=st.EntryType.IN)

    def test_outbound_not_guarded(self, engine):
        st.load_system_rules([st.SystemRule(qps=1)])
        for _ in range(5):
            with st.entry("outRes"):
                pass

    def test_thread_cap(self, engine):
        # Reference semantics: checkSystem blocks when the PRE-increment
        # gauge exceeds maxThread (strict >), so cap 2 admits a 3rd
        # concurrent inbound entry and rejects the 4th.
        st.load_system_rules([st.SystemRule(max_thread=2)])
        e1 = st.entry("a", entry_type=st.EntryType.IN)
        e2 = st.entry("b", entry_type=st.EntryType.IN)
        e3 = st.entry("c", entry_type=st.EntryType.IN)
        with pytest.raises(st.SystemBlockException):
            st.entry("d", entry_type=st.EntryType.IN)
        e3.exit()
        # Capacity freed: admits again.
        e4 = st.entry("e", entry_type=st.EntryType.IN)
        e4.exit()
        e2.exit()
        e1.exit()

    def test_avg_rt_cap(self, engine, frozen_time):
        st.load_system_rules([st.SystemRule(avg_rt=50)])
        e = st.entry("slow", entry_type=st.EntryType.IN)
        frozen_time.advance_time(200)  # 200ms RT >> 50ms cap
        e.exit()
        with pytest.raises(st.SystemBlockException):
            st.entry("slow", entry_type=st.EntryType.IN)

    def test_qps_window_rolls_over(self, engine, frozen_time):
        st.load_system_rules([st.SystemRule(qps=2)])
        for _ in range(2):
            with st.entry("roll", entry_type=st.EntryType.IN):
                pass
        with pytest.raises(st.SystemBlockException):
            st.entry("roll", entry_type=st.EntryType.IN)
        frozen_time.advance_time(1100)
        with st.entry("roll", entry_type=st.EntryType.IN):
            pass

    def test_load_rule_uses_host_signal_and_bbr(self, engine):
        # Threshold -1 load never triggers; a 0.0 threshold with a real
        # load sample > 0 triggers the BBR branch. With no completed
        # requests the capacity estimate is 0 so >1 concurrent inbound
        # entries get rejected.
        st.load_system_rules([st.SystemRule(highest_system_load=0.0)])
        engine.system_status._sample()
        engine._signals_refreshed_ms = 0  # force the fold-in
        # BBR (like the thread cap) tests the PRE-increment gauge with a
        # strict > 1, so two live entries must exist before a block.
        e1 = st.entry("bbr", entry_type=st.EntryType.IN)
        e2 = st.entry("bbr2", entry_type=st.EntryType.IN)
        if engine.system_status.snapshot()[0] > 0:
            with pytest.raises(st.SystemBlockException):
                st.entry("bbr3", entry_type=st.EntryType.IN)
        e2.exit()
        e1.exit()

    def test_effective_threshold_is_min(self, engine):
        st.load_system_rules([st.SystemRule(qps=100), st.SystemRule(qps=2)])
        for _ in range(2):
            with st.entry("m", entry_type=st.EntryType.IN):
                pass
        with pytest.raises(st.SystemBlockException):
            st.entry("m", entry_type=st.EntryType.IN)

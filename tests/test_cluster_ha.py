"""Cluster token-server HA suite (ISSUE 5 tentpole): embedded-mode
CLIENT<->SERVER flipping from datasource-pushed cluster maps, epoch-fenced
leadership, ordered-list client failover with degraded-quota mode, and
state-preserving (checkpoint warm-start) recovery.

Determinism stance matches test_chaos.py: everything host-side runs on
the frozen ``utils/time_util`` clock (window accounting, degraded-mode
state machines, epoch fences), so quota math across a failover is exact;
the socket scenarios necessarily use real time for connect/reconnect
waits. Long wall-clock partition drills are marked ``slow`` and stay out
of tier-1.
"""

from __future__ import annotations

import json
import socket
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.cluster.ha import (
    ClusterHAManager,
    ClusterMap,
    ClusterServerSpec,
    DegradedQuota,
    FailoverTokenClient,
    default_machine_id,
)
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.state import (
    CLUSTER_CLIENT,
    CLUSTER_SERVER,
    ClusterStateManager,
    EpochFence,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.core import checkpoint as ckpt
from sentinel_tpu.datasource.converters import (
    cluster_map_from_json,
    cluster_map_to_dict,
)
from sentinel_tpu.resilience import FaultInjector, HealthGate
from sentinel_tpu.utils import time_util

pytestmark = pytest.mark.chaos

SEED = 1234


@pytest.fixture()
def injector():
    with FaultInjector(seed=SEED) as inj:
        yield inj


def _rule(flow_id, count, **cc):
    return st.FlowRule(
        resource=f"res-{flow_id}", count=count, cluster_mode=True,
        cluster_config={"flowId": flow_id, "thresholdType": THRESHOLD_GLOBAL,
                        **cc})


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _ok_with_retry(request, timeout_s: float = 15.0):
    """First OK (rides out the token service's cold-jit compile on a
    loaded CI box); returns (result, wall seconds to first OK)."""
    t0 = time.monotonic()
    r = request()
    while r.status != TokenResultStatus.OK \
            and time.monotonic() - t0 < timeout_s:
        time.sleep(0.05)
        r = request()
    return r, time.monotonic() - t0


# -- epoch fence (frozen clock, no sockets) -----------------------------------


def test_epoch_fence_monotonic_observe_and_mint():
    f = EpochFence()
    assert f.observe(3) and f.highest_seen == 3
    assert f.observe(3)              # equal epoch: same leader, fine
    assert not f.observe(2)          # stale: rejected AND counted
    assert f.stale_rejected_count == 1
    assert f.highest_seen == 3       # a stale observation never lowers it
    assert f.mint() == 4             # mint is strictly above everything seen
    assert f.mint() == 5


def test_manual_server_flip_epoch_semantics(frozen_time):
    """Pre-HA manual flips keep epoch 0 (wire format byte-identical);
    once an instance has seen an HA epoch, a manual re-flip mints ABOVE
    it — this process can never restart a term it already observed."""
    mgr = ClusterStateManager()
    srv = mgr.set_to_server(host="127.0.0.1", port=0)
    assert srv.epoch == 0 and mgr.epoch == 0      # legacy wire format
    mgr.set_to_server(host="127.0.0.1", port=0, epoch=7)
    assert mgr.token_server.epoch == 7
    srv3 = mgr.set_to_server(host="127.0.0.1", port=0)   # manual, no epoch
    assert srv3.epoch == 8                         # minted above 7
    mgr.stop()


def test_ha_stats_plain_deployment_zeroes(frozen_time):
    """Non-HA deployments get the same ops shape with zeroed counters —
    the resilience command never KeyErrors on a plain instance."""
    stats = ClusterStateManager().ha_stats()
    assert stats["roleName"] == "NOT_STARTED" and stats["role"] == -1
    assert stats["epoch"] == 0 and stats["failoverCount"] == 0
    assert stats["degraded"] is False and stats["staleEpochRejected"] == 0


# -- epoch TLV codec ----------------------------------------------------------


def test_epoch_tlv_round_trip_and_tag_scanning():
    entity = codec.encode_flow_response(5, 0)
    base = len(entity)
    # span TLV first (PR 4 wire layout), epoch appended AFTER it
    entity = codec.append_trace_tlv(entity, codec.encode_span_info(
        "00f067aa0ba902b7", 1700000000000, 250))
    entity = codec.append_epoch_tlv(entity, codec.encode_epoch_value(9))
    assert codec.read_epoch_tlv(entity, base) == 9        # scans past span
    assert codec.read_trace_tlv(entity, base) is not None  # span still reads
    # absent / garbled runs are None, never an exception
    assert codec.read_epoch_tlv(codec.encode_flow_response(5, 0), base) is None
    assert codec.read_epoch_tlv(entity[:-3], base) is None  # truncated TLV
    # a wrong-size epoch payload is ignored (future-proofing, not a crash)
    bad = codec.append_tlv(codec.encode_flow_response(5, 0),
                           codec.TLV_EPOCH, b"\x01")
    assert codec.read_epoch_tlv(bad, base) is None


# -- cluster map converter ----------------------------------------------------


def test_cluster_map_converter_valid_and_leader_reorder():
    m = cluster_map_from_json(json.dumps({
        "epoch": 3, "namespace": "nsX",
        "servers": [{"machineId": "a", "host": "10.0.0.1", "port": 18730},
                    {"machineId": "b", "host": "10.0.0.2", "port": 18731}],
        "clients": ["c", "d"], "leader": "b", "requestTimeoutMs": 1500}))
    assert isinstance(m, ClusterMap) and m.epoch == 3
    assert m.leader().machine_id == "b"            # leader field reorders
    assert [s.machine_id for s in m.servers] == ["b", "a"]
    assert m.clients == ("c", "d") and m.namespace == "nsX"
    assert m.request_timeout_ms == 1500
    assert m.server_for("a").port == 18730 and m.server_for("zz") is None
    # round-trip through the writer shape
    again = cluster_map_from_json(cluster_map_to_dict(m))
    assert again.epoch == m.epoch and again.servers == m.servers


def test_cluster_map_converter_rejects_malformed():
    good_server = {"machineId": "a", "host": "h", "port": 1}
    for bad in (
        [1, 2],                                          # not an object
        {"epoch": "x", "servers": [good_server]},        # non-int epoch
        {"epoch": 1},                                    # no servers
        {"epoch": 1, "servers": []},                     # empty servers
        {"epoch": 1, "servers": [{"machineId": "a"}]},   # no host/port
        {"epoch": 1, "servers": [{**good_server, "port": "nope"}]},
        {"epoch": 1, "servers": [good_server], "leader": "ghost"},
        # a bare string would iterate character-wise into a silently
        # wrong degraded-quota divisor
        {"epoch": 1, "servers": [good_server], "clients": "node-c"},
    ):
        with pytest.raises(ValueError):
            cluster_map_from_json(json.dumps(bad))


# -- degraded quota (frozen clock) --------------------------------------------


def test_degraded_quota_share_bound_sum_leq_global(frozen_time):
    """The SEMANTICS.md bound: N clients, divisor N — each admits at most
    T/N per interval-aligned window, so the fleet total is <= T."""
    T, N = 12.0, 4
    clients = [DegradedQuota(divisor=N, thresholds={7: (T, 1000)})
               for _ in range(N)]
    total = 0
    for q in clients:
        grants = sum(1 for _ in range(10)
                     if q.acquire(7).status == TokenResultStatus.OK)
        assert grants == int(T / N)       # exactly the share, then BLOCKED
        total += grants
    assert total <= T
    frozen_time.advance_time(1100)        # window rolls: shares refill
    assert clients[0].acquire(7).status == TokenResultStatus.OK
    snap = clients[0].snapshot()
    assert snap["divisor"] == N and snap["grantedCount"] == 4
    assert snap["blockedCount"] == 10 - 3 and snap["flows"] == 1


def test_degraded_quota_unknown_flow_and_live_thresholds(frozen_time):
    seen = {}
    q = DegradedQuota(divisor=2, thresholds_fn=lambda: seen)
    assert q.acquire(9) is None           # unknown flow -> caller falls back
    assert q.acquire("junk") is None
    seen[9] = (4.0, 1000)                 # rule push lands mid-degraded
    assert q.acquire(9).status == TokenResultStatus.OK
    assert q.acquire(9).status == TokenResultStatus.OK   # share = 4/2
    assert q.acquire(9).status == TokenResultStatus.BLOCKED


# -- wire fencing over TCP ----------------------------------------------------


@pytest.fixture()
def epoch_server(frozen_time):
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [_rule(500, 1000)])
    server = ClusterTokenServer(
        DefaultTokenService(rules, epoch=5), host="127.0.0.1", port=0).start()
    yield server
    server.stop()


def test_fenced_client_accepts_current_epoch(epoch_server):
    fence = EpochFence()
    client = ClusterTokenClient("127.0.0.1", epoch_server.bound_port,
                                epoch_fence=fence, health_gate=None).start()
    try:
        assert _wait(client.is_connected)
        r, _ = _ok_with_retry(lambda: client.request_token(500))
        assert r.status == TokenResultStatus.OK
        assert fence.highest_seen == 5     # epoch TLV observed
    finally:
        client.stop()


def test_stale_epoch_replay_rejected(epoch_server, injector):
    """Acceptance pin: a deposed leader's reply (epoch below the fence's
    high-water mark) is rejected as FAIL — split-brain cannot
    double-grant. The ``cluster.ha.stale.epoch`` seam replays epoch 4
    against a client that has already observed epoch 5."""
    fence = EpochFence()
    client = ClusterTokenClient("127.0.0.1", epoch_server.bound_port,
                                epoch_fence=fence, health_gate=None).start()
    try:
        assert _wait(client.is_connected)
        r, _ = _ok_with_retry(lambda: client.request_token(500))
        assert r.status == TokenResultStatus.OK and fence.highest_seen == 5
        injector.arm("cluster.ha.stale.epoch", "garbage", times=1,
                     garbage=codec.encode_epoch_value(4))
        assert client.request_token(500).status == TokenResultStatus.FAIL
        assert fence.stale_rejected_count == 1
        assert fence.highest_seen == 5
        # healed: the next (correctly stamped) response serves again
        assert client.request_token(500).status == TokenResultStatus.OK
    finally:
        client.stop()


def test_epoch_zero_keeps_pre_ha_wire_format(frozen_time):
    """epoch 0 (every pre-HA deployment) stamps nothing: a fenced client
    sees no TLV and its fence never advances — byte-identical wire."""
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [_rule(501, 1000)])
    server = ClusterTokenServer(
        DefaultTokenService(rules), host="127.0.0.1", port=0).start()
    fence = EpochFence()
    client = ClusterTokenClient("127.0.0.1", server.bound_port,
                                epoch_fence=fence, health_gate=None).start()
    try:
        assert _wait(client.is_connected)
        r, _ = _ok_with_retry(lambda: client.request_token(501))
        assert r.status == TokenResultStatus.OK
        assert fence.highest_seen == 0
    finally:
        client.stop()
        server.stop()


def test_halfopen_swallowed_reply_times_out_not_hangs(epoch_server, injector):
    """The half-open seam: the server eats one reply with the connection
    left up. The client must FAIL within its request timeout (and keep
    the connection serviceable), never hang on the dead response."""
    client = ClusterTokenClient("127.0.0.1", epoch_server.bound_port,
                                request_timeout_s=0.4,
                                health_gate=None).start()
    try:
        assert _wait(client.is_connected)
        r, _ = _ok_with_retry(lambda: client.request_token(500))
        assert r.status == TokenResultStatus.OK
        injector.arm("cluster.ha.halfopen", "garbage", times=1, garbage=b"")
        t0 = time.monotonic()
        assert client.request_token(500).status == TokenResultStatus.FAIL
        assert time.monotonic() - t0 < 2.0
        assert client.is_connected()       # half-open, not disconnected
        assert client.request_token(500).status == TokenResultStatus.OK
    finally:
        client.stop()


# -- failover client ----------------------------------------------------------


def test_failover_client_walks_to_standby(frozen_time):
    """Leader dies -> the next verdict comes from the second target in
    map order; failover_count and the active target record the walk."""
    rules_a = ClusterFlowRuleManager()
    rules_a.load_rules("default", [_rule(600, 1000)])
    rules_b = ClusterFlowRuleManager()
    rules_b.load_rules("default", [_rule(600, 1000)])
    a = ClusterTokenServer(DefaultTokenService(rules_a, epoch=1),
                           host="127.0.0.1", port=0).start()
    b = ClusterTokenServer(DefaultTokenService(rules_b, epoch=1),
                           host="127.0.0.1", port=0).start()
    fc = FailoverTokenClient(
        [("127.0.0.1", a.bound_port), ("127.0.0.1", b.bound_port)],
        request_timeout_s=2.0, reconnect_interval_s=0.05,
        failover_deadline_ms=60_000).start()
    try:
        assert _wait(fc.is_connected)
        r, _ = _ok_with_retry(lambda: fc.request_token(600))
        assert r.status == TokenResultStatus.OK and fc.failover_count == 0
        # warm B's jit through the fence-shared wire path is not possible
        # pre-failover (A answers first); warm its service directly so
        # the post-failover request is not measuring a compile.
        b.service.request_tokens([(None, 0, False)])
        a.stop()
        r, _ = _ok_with_retry(lambda: fc.request_token(600))
        assert r.status == TokenResultStatus.OK
        assert fc.failover_count == 1
        assert fc.failover_stats()["activeTarget"].endswith(str(b.bound_port))
        assert not fc.is_degraded()        # a standby answered: no spell
    finally:
        fc.stop()
        a.stop()
        b.stop()


def test_degraded_mode_after_deadline_and_recovery(frozen_time):
    """No target reachable: FAIL until the failover deadline elapses
    verdict-free, then per-client-share verdicts (wire-free); the first
    real verdict after reconnect closes the spell and the accounting
    (entries, seconds) survives in failover_stats."""
    port = _free_port()
    fc = FailoverTokenClient(
        [("127.0.0.1", port)], request_timeout_s=0.3,
        reconnect_interval_s=0.05, failover_deadline_ms=1000,
        degraded=DegradedQuota(divisor=2, thresholds={7: (10.0, 1000)}))
    fc.start()
    try:
        # inside the deadline: FAIL (engine local fallback), not degraded
        assert fc.request_token(7).status == TokenResultStatus.FAIL
        assert not fc.is_degraded()
        frozen_time.advance_time(1001)
        got = [fc.request_token(7).status for _ in range(7)]
        assert got.count(TokenResultStatus.OK) == 5        # share 10/2
        assert got.count(TokenResultStatus.BLOCKED) == 2
        assert fc.is_degraded() and fc.degraded_entry_count == 7
        # param tokens have no local bucket mirror: degraded -> FAIL
        assert fc.request_param_token(7, 1, ["k"]).status == \
            TokenResultStatus.FAIL
        # flows with no threshold here -> FAIL (local fallback), counted
        assert fc.request_token(999).status == TokenResultStatus.FAIL
        frozen_time.advance_time(2500)                     # spell runs on

        # recovery: a server appears on the dead target's port
        rules = ClusterFlowRuleManager()
        rules.load_rules("default", [_rule(7, 1000)])
        server = ClusterTokenServer(DefaultTokenService(rules, epoch=2),
                                    host="127.0.0.1", port=port).start()
        try:
            assert _wait(fc.is_connected)
            r, _ = _ok_with_retry(lambda: fc.request_token(7))
            assert r.status == TokenResultStatus.OK
            assert not fc.is_degraded()
            stats = fc.failover_stats()
            # spell opened when the deadline elapsed (t0+1001) and closed
            # at the first real verdict (t0+3501): exactly 2.5 frozen s
            assert stats["degradedSeconds"] == pytest.approx(2.5)
            assert stats["degradedQuota"]["grantedCount"] == 5
        finally:
            server.stop()
    finally:
        fc.stop()


def test_failover_walk_shares_one_timeout_budget():
    """The caller's timeout bounds the WHOLE walk: with several
    connected-but-unresponsive targets, one data-path entry must never
    block N x its deadline budget — later targets get only the
    remaining slice, and a spent budget stops the walk."""

    from sentinel_tpu.cluster.token_service import TokenResult

    class _Stub:
        def __init__(self, fail_for_s=0.0):
            self.fail_for_s = fail_for_s
            self.seen_timeouts = []

        def is_connected(self):
            return True

        def request_token(self, *a, timeout_s=None, **k):
            self.seen_timeouts.append(timeout_s)
            if self.fail_for_s:
                time.sleep(self.fail_for_s)
                return TokenResult(TokenResultStatus.FAIL)
            return TokenResult(TokenResultStatus.OK)

    fc = FailoverTokenClient([("127.0.0.1", 1), ("127.0.0.1", 2)],
                             failover_deadline_ms=60_000)
    slow, fast = _Stub(fail_for_s=0.05), _Stub()
    fc._clients = [slow, fast]

    r = fc.request_token(5, timeout_s=0.2)
    assert r.status == TokenResultStatus.OK
    assert slow.seen_timeouts[0] == pytest.approx(0.2, abs=0.01)
    assert 0 < fast.seen_timeouts[0] <= 0.16        # only the remainder

    slow.seen_timeouts.clear()
    fast.seen_timeouts.clear()
    assert fc.request_token(5, timeout_s=0.03).status \
        == TokenResultStatus.FAIL                   # budget died mid-walk
    assert fast.seen_timeouts == []                 # second target skipped

    # no caller budget: every target keeps its own configured timeout
    slow.seen_timeouts.clear()
    fc.request_token(5)
    assert slow.seen_timeouts == [None]


# -- HA manager: map-driven flips, drain, warm start --------------------------


def _two_seat_setup(ck_path, rule):
    """Two engine-less HA seats sharing a checkpoint path + rule set."""
    seats = {}
    for mid in ("A", "B"):
        state = ClusterStateManager()
        state.server_rules().load_rules("default", [rule])
        seats[mid] = ClusterHAManager(
            state=state, machine_id=mid, checkpoint_path=ck_path,
            checkpoint_period_s=3600.0, server_host="127.0.0.1")
    return seats


def test_apply_map_graceful_flip_preserves_windows(frozen_time, tmp_path):
    """Graceful leadership handoff: the deposed leader's drain checkpoint
    hands the successor its windows, so TOTAL admissions across the flip
    never exceed the global threshold (margin 0 for a graceful drain)."""
    ck_path = str(tmp_path / "ha.npz")
    seats = _two_seat_setup(ck_path, _rule(700, 6))
    pa, pb = _free_port(), _free_port()
    servers = (ClusterServerSpec("A", "127.0.0.1", pa),
               ClusterServerSpec("B", "127.0.0.1", pb))
    m1 = ClusterMap(epoch=1, servers=servers, clients=("X",))
    m2 = ClusterMap(epoch=2, servers=servers[::-1], clients=("X",))
    fc = FailoverTokenClient([("127.0.0.1", pa), ("127.0.0.1", pb)],
                             request_timeout_s=2.0, reconnect_interval_s=0.05,
                             failover_deadline_ms=60_000).start()
    try:
        seats["A"].apply_map(m1)
        seats["B"].apply_map(m1)
        assert seats["A"].state.mode == CLUSTER_SERVER
        assert seats["B"].state.mode == CLUSTER_CLIENT
        assert seats["A"].state.token_server.epoch == 1

        assert _wait(fc.is_connected)
        r, _ = _ok_with_retry(lambda: fc.request_token(700))
        assert r.status == TokenResultStatus.OK
        pre = 1 + sum(1 for _ in range(4)
                      if fc.request_token(700).status == TokenResultStatus.OK)
        assert pre == 5                                    # 1 left of 6

        # graceful flip: deposed leader drains FIRST (publishes), then
        # the successor warm-starts from the drained checkpoint.
        seats["A"].apply_map(m2)
        assert seats["A"].state.mode == CLUSTER_CLIENT
        assert seats["A"].checkpoints_published >= 1
        seats["B"].apply_map(m2)
        assert seats["B"].state.mode == CLUSTER_SERVER
        assert seats["B"].state.token_server.epoch == 2
        assert seats["B"].rows_restored == 1

        r, _ = _ok_with_retry(lambda: fc.request_token(700))
        assert r.status == TokenResultStatus.OK            # the 6th token
        post_block = [fc.request_token(700).status for _ in range(3)]
        assert post_block.count(TokenResultStatus.BLOCKED) == 3
        assert fc.failover_count == 1
        assert fc.fence.highest_seen == 2
        stats = seats["B"].state.ha_stats()
        assert stats["roleName"] == "SERVER" and stats["epoch"] == 2
        assert stats["modeFlips"] >= 2
    finally:
        fc.stop()
        seats["A"].stop()
        seats["B"].stop()


def test_stale_map_ignored(frozen_time, tmp_path):
    """A delayed datasource push (epoch below the applied map) must not
    resurrect a deposed leader."""
    seats = _two_seat_setup(str(tmp_path / "ha.npz"), _rule(710, 5))
    pa, pb = _free_port(), _free_port()
    servers = (ClusterServerSpec("A", "127.0.0.1", pa),
               ClusterServerSpec("B", "127.0.0.1", pb))
    try:
        seats["A"].apply_map(ClusterMap(epoch=2, servers=servers[::-1]))
        assert seats["A"].state.mode == CLUSTER_CLIENT     # B leads
        seats["A"].apply_map(ClusterMap(epoch=1, servers=servers))
        assert seats["A"].state.mode == CLUSTER_CLIENT     # stale: ignored
        assert seats["A"].map.epoch == 2
    finally:
        seats["A"].stop()
        seats["B"].stop()


def test_in_process_repromotion_preserves_unpublished_grants(frozen_time,
                                                             tmp_path):
    """Same seat re-promoted for a new term (e.g. a standby reorder):
    the freshest window state lives in the OLD in-process service, so
    _become_server must publish it BEFORE restoring — warm-starting
    from the last periodic snapshot would re-admit every grant made
    since it (here: ALL of them, the periodic timer never fired)."""
    T = 6
    seats = _two_seat_setup(str(tmp_path / "reprom.npz"), _rule(730, T))
    servers = (ClusterServerSpec("A", "127.0.0.1", _free_port()),
               ClusterServerSpec("B", "127.0.0.1", _free_port()))
    try:
        seats["A"].apply_map(ClusterMap(epoch=1, servers=servers))
        svc = seats["A"].state.token_server.service
        for _ in range(4):
            assert svc.request_token(730).status == TokenResultStatus.OK

        seats["A"].apply_map(ClusterMap(epoch=2, servers=servers))
        assert seats["A"].state.token_server.epoch == 2
        assert seats["A"].rows_restored == 1
        svc2 = seats["A"].state.token_server.service
        got = [svc2.request_token(730).status for _ in range(3)]
        assert got == [TokenResultStatus.OK, TokenResultStatus.OK,
                       TokenResultStatus.BLOCKED]       # 4 carried + 2 = T
    finally:
        seats["A"].stop()
        seats["B"].stop()


def test_same_target_map_change_reuses_live_client(frozen_time, tmp_path):
    """A map change that leaves this seat a client of the SAME server
    list must not tear down the live failover client: sockets stay up,
    the monotonic failover/degraded counters survive, and only the
    epoch/fence/divisor advance. A real topology change still rebuilds."""
    seats = _two_seat_setup(str(tmp_path / "ha.npz"), _rule(740, 5))
    servers = (ClusterServerSpec("A", "127.0.0.1", _free_port()),
               ClusterServerSpec("B", "127.0.0.1", _free_port()))
    try:
        seats["B"].apply_map(ClusterMap(epoch=1, servers=servers,
                                        clients=("X",)))
        cur = seats["B"].state.token_client
        cur.failover_count = 3                      # accumulated history
        seats["B"].apply_map(ClusterMap(epoch=2, servers=servers,
                                        clients=("X", "Y"),
                                        request_timeout_ms=5000))
        assert seats["B"].state.token_client is cur             # no churn
        assert cur.failover_count == 3              # counters not zeroed
        assert cur.degraded.divisor == 2            # membership tracked
        assert all(c.request_timeout_s == 5.0       # timeout applied live
                   for c in cur._clients)
        assert seats["B"].state.epoch == 2
        assert seats["B"].state.fence.highest_seen == 2

        # clients list CLEARED: divisor falls back to the config default
        # (1), exactly as a freshly built client would — no map-history
        # dependence
        seats["B"].apply_map(ClusterMap(epoch=3, servers=servers))
        assert seats["B"].state.token_client is cur
        assert cur.degraded.divisor == 1

        seats["B"].apply_map(ClusterMap(epoch=4, servers=servers[:1]))
        assert seats["B"].state.token_client is not cur         # rebuilt
    finally:
        seats["A"].stop()
        seats["B"].stop()


def test_failed_promotion_retries_until_port_frees(frozen_time, tmp_path):
    """A transition failure (EADDRINUSE from a lingering listener) must
    NOT commit the map: the datasource property never re-fires an
    unchanged value, so without the manager's own retry timer the seat
    would sit NOT_STARTED forever — no leader, whole fleet degraded —
    until a human bumps the epoch."""
    seats = _two_seat_setup(str(tmp_path / "ha.npz"), _rule(760, 5))
    port = _free_port()
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", port))
    blocker.listen(1)
    servers = (ClusterServerSpec("A", "127.0.0.1", port),)
    try:
        seats["A"].retry_delay_s = 0.05
        seats["A"].apply_map(ClusterMap(epoch=1, servers=servers))
        assert seats["A"].state.mode != CLUSTER_SERVER
        assert seats["A"].map is None            # NOT committed: retried
        blocker.close()
        assert _wait(lambda: seats["A"].state.mode == CLUSTER_SERVER, 10.0)
        assert seats["A"].map is not None and seats["A"].map.epoch == 1
        assert seats["A"].state.token_server.epoch == 1
    finally:
        blocker.close()
        seats["A"].stop()
        seats["B"].stop()


def test_map_below_wire_observed_epoch_ignored(frozen_time, tmp_path):
    """The wire is a map source too: once an epoch-5-stamped response
    has been observed, a delayed epoch-4 map must not promote a leader
    the whole fleet's fences would reject (and must not trip the
    stale-epoch split-brain alarm doing so)."""
    seats = _two_seat_setup(str(tmp_path / "ha.npz"), _rule(750, 5))
    servers = (ClusterServerSpec("A", "127.0.0.1", _free_port()),)
    try:
        seats["A"].state.fence.observe(5)
        seats["A"].apply_map(ClusterMap(epoch=4, servers=servers))
        assert seats["A"].state.mode != CLUSTER_SERVER
        assert seats["A"].map is None                   # never applied
        assert seats["A"].state.fence.stale_rejected_count == 0

        seats["A"].apply_map(ClusterMap(epoch=5, servers=servers))
        assert seats["A"].state.mode == CLUSTER_SERVER  # current term: ok
        assert seats["A"].state.token_server.epoch == 5
    finally:
        seats["A"].stop()


def test_leader_crash_failover_acceptance(frozen_time, tmp_path, injector):
    """THE acceptance scenario: traffic flowing, leader killed via the
    ``cluster.ha.leader.crash`` fault point (hard kill — no drain), a
    standby is promoted and serves within the configured failover
    deadline, and total admissions across the handoff exceed the global
    threshold by EXACTLY the grants made since the last checkpoint
    publish (the asserted bound)."""
    T = 10
    ck_path = str(tmp_path / "crash.npz")
    seats = _two_seat_setup(ck_path, _rule(720, T))
    pa, pb = _free_port(), _free_port()
    servers = (ClusterServerSpec("A", "127.0.0.1", pa),
               ClusterServerSpec("B", "127.0.0.1", pb))
    failover_deadline_ms = 20_000   # generous: includes the promotion jit
    fc = FailoverTokenClient(
        [("127.0.0.1", pa), ("127.0.0.1", pb)],
        request_timeout_s=0.5, reconnect_interval_s=0.05,
        failover_deadline_ms=failover_deadline_ms).start()
    try:
        seats["A"].apply_map(ClusterMap(epoch=1, servers=servers,
                                        clients=("X",)))
        assert _wait(fc.is_connected)
        r, _ = _ok_with_retry(lambda: fc.request_token(720))
        assert r.status == TokenResultStatus.OK

        # 3 more grants, then the leader publishes its periodic checkpoint
        for _ in range(3):
            assert fc.request_token(720).status == TokenResultStatus.OK
        seats["A"].publish_checkpoint()
        checkpointed = 4
        # ... and 2 grants AFTER the publish: the allowed over-admission
        margin = 2
        for _ in range(margin):
            assert fc.request_token(720).status == TokenResultStatus.OK
        pre_crash = checkpointed + margin

        # kill the leader mid-traffic: the next drained batch dies, no
        # drain checkpoint is published
        injector.arm("cluster.ha.leader.crash", "error", times=1)
        assert fc.request_token(720).status == TokenResultStatus.FAIL
        assert _wait(lambda: seats["A"].state.token_server.crashed, 5.0)
        published_before = seats["A"].checkpoints_published

        # the map controller promotes the standby (epoch 2)
        t_promote = time.monotonic()
        seats["B"].apply_map(ClusterMap(epoch=2, servers=servers[::-1],
                                        clients=("X",)))
        assert seats["B"].state.mode == CLUSTER_SERVER
        assert seats["B"].rows_restored == 1               # warm start
        r, _ = _ok_with_retry(lambda: fc.request_token(720))
        elapsed_ms = (time.monotonic() - t_promote) * 1000
        assert r.status == TokenResultStatus.OK, "standby never served"
        assert elapsed_ms < failover_deadline_ms, (
            f"failover took {elapsed_ms:.0f}ms "
            f"(deadline {failover_deadline_ms}ms)")
        assert fc.failover_count == 1
        assert seats["A"].checkpoints_published == published_before

        # bounded over-admission: the successor restored the checkpoint,
        # so it grants exactly T - checkpointed more — total across the
        # handoff is T + margin, NOT T + a fresh window
        post = 1
        while fc.request_token(720).status == TokenResultStatus.OK:
            post += 1
            assert post <= T, "over-admission unbounded"
        assert post == T - checkpointed
        assert pre_crash + post == T + margin
        # and the epoch fence carried the new term
        assert fc.fence.highest_seen == 2
        stats = seats["B"].state.ha_stats()
        assert stats["epoch"] == 2 and stats["manager"]["rowsRestored"] == 1
    finally:
        fc.stop()
        seats["A"].stop()
        seats["B"].stop()


# -- standalone HA participant (python -m sentinel_tpu.cluster) ---------------


def test_standalone_ha_participant_file_map_flip(tmp_path, frozen_time):
    from sentinel_tpu.cluster.__main__ import StandaloneHAParticipant

    port = _free_port()
    map_path = tmp_path / "map.json"
    rules_path = tmp_path / "rules.json"
    rules_path.write_text(json.dumps({
        "default": [{"resource": "r", "count": 4, "clusterMode": True,
                     "clusterConfig": {"flowId": 800, "thresholdType": 1}}]}))
    map_path.write_text(json.dumps({
        "epoch": 1,
        "servers": [{"machineId": "A", "host": "127.0.0.1", "port": port},
                    {"machineId": "B", "host": "127.0.0.1",
                     "port": _free_port()}]}))
    part = StandaloneHAParticipant(
        map_path=str(map_path), machine_id="A", rules_path=str(rules_path),
        checkpoint_path=str(tmp_path / "ck.npz"), refresh_ms=3_600_000,
        host="127.0.0.1")
    part.start()
    try:
        stats = part.state.ha_stats()
        assert stats["roleName"] == "SERVER" and stats["epoch"] == 1
        client = ClusterTokenClient("127.0.0.1", port,
                                    health_gate=None).start()
        try:
            assert _wait(client.is_connected)
            r, _ = _ok_with_retry(lambda: client.request_token(800))
            assert r.status == TokenResultStatus.OK        # rules staged
        finally:
            client.stop()

        # the map file demotes this seat; the poll applies it
        map_path.write_text(json.dumps({
            "epoch": 2, "leader": "B",
            "servers": [{"machineId": "A", "host": "127.0.0.1", "port": port},
                        {"machineId": "B", "host": "127.0.0.1",
                         "port": _free_port()}]}))
        part.refresh()
        stats = part.state.ha_stats()
        assert stats["roleName"] == "CLIENT" and stats["epoch"] == 2
    finally:
        part.stop()


def test_default_machine_id_shape():
    import os

    assert default_machine_id().endswith(f"@{os.getpid()}")


# -- ops surfaces: resilience_stats, command, /metrics gauges -----------------


def test_resilience_stats_and_exporter_carry_ha_block(engine, frozen_time):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    st.load_flow_rules([st.FlowRule(
        resource="shared", count=50, cluster_mode=True,
        cluster_config={"flowId": 900, "thresholdType": THRESHOLD_GLOBAL,
                        "windowIntervalMs": 2000})])
    # the degraded-share base tracks the LOCAL copies of cluster rules
    assert engine.cluster_degraded_thresholds() == {900: (50.0, 2000)}

    ha = engine.resilience_stats()["clusterHA"]
    assert ha["roleName"] == "NOT_STARTED" and ha["failoverCount"] == 0
    text = render_engine_metrics(engine)
    assert "sentinel_tpu_cluster_ha_role -1" in text
    assert "sentinel_tpu_cluster_ha_epoch 0" in text
    assert "sentinel_tpu_cluster_ha_failovers_total 0" in text
    assert "sentinel_tpu_cluster_ha_stale_epoch_rejected_total 0" in text
    assert "sentinel_tpu_cluster_ha_degraded 0" in text
    assert "sentinel_tpu_cluster_ha_degraded_seconds_total 0" in text


def test_get_cluster_mode_command_includes_ha(engine, frozen_time):
    import urllib.request

    from sentinel_tpu.transport.command_center import CommandCenter

    center = CommandCenter(engine, port=0)
    center.start()
    try:
        url = f"http://127.0.0.1:{center.bound_port}/getClusterMode"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = json.loads(r.read().decode())
        assert body["ha"]["roleName"] == "NOT_STARTED"
        assert body["ha"]["epoch"] == 0
        url = f"http://127.0.0.1:{center.bound_port}/resilience"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = json.loads(r.read().decode())
        assert "clusterHA" in body and body["clusterHA"]["degraded"] is False
    finally:
        center.stop()


# -- heartbeat under leader churn (satellite) ---------------------------------


def test_heartbeat_last_success_monotonic_across_failover(frozen_time,
                                                          injector):
    """``last_success_ms`` is exported through the resilience probe
    registry and must be monotonic: rotating to a dashboard behind a
    skewed clock (or a frozen test clock) must never move it backwards —
    scrape-side 'age since success' math would go negative."""
    from sentinel_tpu.resilience import (
        RetryPolicy,
        health_snapshot,
        register_probe,
    )
    from sentinel_tpu.transport.heartbeat import HeartbeatSender

    class Beat(HeartbeatSender):
        def _post(self, req) -> bool:
            return True

    hb = Beat(dashboards=["d1:80", "d2:80"], interval_ms=100, api_port=1,
              retry_policy=RetryPolicy(base_ms=100, max_ms=1600,
                                       multiplier=2.0, jitter="none"))
    assert hb.send_once()
    t0 = time_util.current_time_millis()
    assert hb.last_success_ms == t0

    # failover to the second dashboard while the observed clock runs
    # BACKWARDS (skewed host): success must not regress the stamp
    injector.arm("heartbeat.post", "error", times=1)
    assert not hb.send_once()              # d1 fails -> rotate to d2
    assert hb._idx == 1
    frozen_time.freeze_time(t0 - 5_000)
    assert hb.send_once()                  # d2 succeeds, clock skewed back
    assert hb.last_success_ms == t0        # monotonic: unchanged
    frozen_time.freeze_time(t0 + 1_000)
    assert hb.send_once()
    assert hb.last_success_ms == t0 + 1_000

    # exported: the probe registry serves the same stamp
    probe_off = register_probe("heartbeat", hb.health)
    try:
        snap = health_snapshot()
        assert snap["heartbeat"]["lastSuccessMs"] == t0 + 1_000
    finally:
        probe_off()


def test_heartbeat_full_rotation_backoff_resets_on_success(frozen_time,
                                                           injector):
    """Leader-churn cadence: repeated full rotations back off, ONE
    success restores the healthy cadence and zeroes the failure count —
    a promoted dashboard does not inherit the backoff."""
    from sentinel_tpu.resilience import RetryPolicy
    from sentinel_tpu.transport.heartbeat import HeartbeatSender

    class Beat(HeartbeatSender):
        def _post(self, req) -> bool:
            return True

    hb = Beat(dashboards=["d1:80", "d2:80"], interval_ms=100, api_port=1,
              retry_policy=RetryPolicy(base_ms=100, max_ms=1600,
                                       multiplier=2.0, jitter="none"))
    injector.arm("heartbeat.post", "error", times=8)
    waits = [hb._next_wait_ms(hb.send_once()) for _ in range(8)]
    assert waits == [100, 100, 100, 200, 100, 400, 100, 800]
    assert hb.consecutive_failures == 8
    assert hb._next_wait_ms(hb.send_once()) == 100     # success: cadence back
    assert hb.consecutive_failures == 0
    # the backoff SESSION reset too: a fresh outage starts at base again
    injector.arm("heartbeat.post", "error", times=4)
    waits = [hb._next_wait_ms(hb.send_once()) for _ in range(4)]
    assert waits == [100, 100, 100, 200]


# -- extended partition drill (slow: excluded from tier-1) --------------------


@pytest.mark.slow
def test_extended_partition_multiple_degraded_spells():
    """Real-clock drill: two full lost->degraded->recovered spells, with
    the cumulative degraded_seconds accounting surviving both."""
    time_util.unfreeze_time()
    port = _free_port()
    fc = FailoverTokenClient(
        [("127.0.0.1", port)], request_timeout_s=0.2,
        reconnect_interval_s=0.05, failover_deadline_ms=300,
        degraded=DegradedQuota(divisor=1, thresholds={7: (1000.0, 1000)}))
    fc.start()
    try:
        spells = 0
        for _ in range(2):
            deadline = time.monotonic() + 10
            while not fc.is_degraded() and time.monotonic() < deadline:
                fc.request_token(7)
                time.sleep(0.05)
            assert fc.is_degraded()
            assert fc.request_token(7).status == TokenResultStatus.OK
            spells += 1

            rules = ClusterFlowRuleManager()
            rules.load_rules("default", [_rule(7, 1000)])
            svc = DefaultTokenService(rules, epoch=spells)
            svc.request_tokens([(None, 0, False)])  # pre-warm the jit: a
            # cold compile outlasts the 0.2s request timeout, and a FAILed
            # wire request would be answered by the degraded share —
            # masking the spell-close this test asserts
            server = ClusterTokenServer(svc, host="127.0.0.1",
                                        port=port).start()
            try:
                assert _wait(fc.is_connected, 10.0)
                # a WIRE verdict (not a degraded-share one) closes the
                # spell; loop until it lands
                assert _wait(
                    lambda: fc.request_token(7).status ==
                    TokenResultStatus.OK and not fc.is_degraded(), 10.0)
            finally:
                server.stop()
            # the stopped server's handler socket lives in its handler
            # thread: force the client-side drop (the next partition)
            fc._clients[0]._drop_connection()
            assert _wait(lambda: not fc.is_connected(), 10.0)
        assert fc.degraded_entry_count >= 2
        assert fc.degraded_seconds() > 0.0
    finally:
        fc.stop()

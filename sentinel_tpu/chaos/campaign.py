"""The chaos campaign: seed-replayable episodes, continuous invariant
checking, auto-shrinking counterexamples, forensic bundles.

An episode is a pure function of ``(campaign_seed, episode_index)``:
fresh mesh in a throwaway workdir, the scheduled faults of
``FaultScheduler.schedule``, a fixed per-second workload, the
``SimClock`` program-advanced timebase injected into every
timing-sensitive component (never the process clock — a campaign can
run beside a live engine), and a seeded ``FaultInjector`` whose
per-point RNG streams cannot interfere.
Re-running any episode from its seed reproduces the fault firing
sequence and the verdict stream BIT-IDENTICALLY (sha256 oracles in
tests/test_chaos_campaign.py and the BENCH_14 ``chaos_campaign`` phase).

A violation triggers :func:`~sentinel_tpu.chaos.shrink.ddmin` over the
episode's schedule and comes back as a forensic bundle: the violation,
the minimal still-failing schedule, and each seat's audit-journal join
(tail + causeSeq chain + the shard map in force at the violation
second) — a committed-artifact repro, not a flaky log line.
"""

from __future__ import annotations

import shutil
import tempfile
import time
from typing import Dict, List, NamedTuple, Optional

from sentinel_tpu import chaos as _pkg
from sentinel_tpu.chaos.invariants import History, check_all
from sentinel_tpu.chaos.mesh import DEFAULT_FLOWS, ChaosMesh
from sentinel_tpu.chaos.scheduler import FaultScheduler, episode_seed
from sentinel_tpu.chaos.shrink import ddmin
from sentinel_tpu.core.config import config
from sentinel_tpu.resilience import FaultInjector
from sentinel_tpu.simulator.clock import SimClock


class EpisodeResult(NamedTuple):
    index: int
    seed: int
    schedule: List[dict]
    verdict_sha256: str
    fault_sha256: str
    violations: List
    ops: int
    grants: int
    fault_log: List[tuple]
    journals: Dict[str, dict]
    first_violation_sec: Optional[int]

    def to_dict(self) -> dict:
        return {
            "episode": self.index, "episodeSeed": self.seed,
            "schedule": self.schedule,
            "verdictSha256": self.verdict_sha256,
            "faultSha256": self.fault_sha256,
            "violations": [v.to_dict() for v in self.violations],
            "ops": self.ops, "grants": self.grants,
            "firstViolationSec": self.first_violation_sec,
        }


def _sha(lines) -> str:
    import hashlib

    h = hashlib.sha256()
    for line in lines:
        h.update(line.encode("utf-8"))
        h.update(b"\n")
    return h.hexdigest()


class ChaosCampaign:
    """N seed-replayable episodes over the full seam set."""

    def __init__(self, campaign_seed: int = 0, episodes: Optional[int] = None,
                 seconds: Optional[int] = None, per_second: int = 3,
                 max_faults: Optional[int] = None,
                 leaders=("A", "B", "C"), n_slices: int = 8,
                 flows: Optional[Dict[int, float]] = None,
                 regressions=(), shrink: bool = True,
                 stop_on_violation: bool = True,
                 shrink_max_runs: int = 64):
        self.campaign_seed = int(campaign_seed)
        self.episodes = int(episodes if episodes is not None
                            else config.chaos_episodes())
        self.seconds = int(seconds if seconds is not None
                           else config.chaos_seconds_per_episode())
        self.per_second = max(1, int(per_second))
        self.max_faults = int(max_faults if max_faults is not None
                              else config.chaos_max_faults())
        self.leaders = tuple(leaders)
        self.n_slices = int(n_slices)
        self.flows = dict(flows) if flows else dict(DEFAULT_FLOWS)
        self.regressions = tuple(regressions)
        self.shrink = bool(shrink)
        self.stop_on_violation = bool(stop_on_violation)
        self.shrink_max_runs = int(shrink_max_runs)
        self.epoch_ms = config.chaos_epoch_ms()
        self.scheduler = FaultScheduler(
            leaders=self.leaders, flows=self.flows, n_slices=self.n_slices,
            seconds=self.seconds, max_faults=self.max_faults)

    # -- one episode -------------------------------------------------------

    def episode_schedule(self, index: int) -> List[dict]:
        return self.scheduler.schedule(self.campaign_seed, index)

    def run_episode(self, index: int,
                    schedule: Optional[List[dict]] = None) -> EpisodeResult:
        sched = (self.episode_schedule(index) if schedule is None
                 else list(schedule))
        seed = episode_seed(self.campaign_seed, index)
        workdir = tempfile.mkdtemp(prefix="sentinel-chaos-")
        clock = SimClock(self.epoch_ms)
        history = History()
        mesh = None
        violations: List = []
        first_violation_sec: Optional[int] = None
        journals: Dict[str, dict] = {}
        try:
            # scope_thread: the whole fault surface fires on THIS driver
            # thread — a live host engine's own threads can neither eat
            # the schedule's fault budget (replay drift) nor suffer its
            # faults (blast-radius bleed).
            with FaultInjector(seed=seed, scope_thread=True) as injector:
                mesh = ChaosMesh(clock, history, workdir,
                                 leaders=self.leaders,
                                 n_slices=self.n_slices, flows=self.flows)
                by_sec: Dict[int, List[dict]] = {}
                for act in sched:
                    by_sec.setdefault(int(act["at"]), []).append(act)
                restores: Dict[int, List[str]] = {}
                flow_order = sorted(self.flows)
                for sec in range(self.seconds):
                    for mid in restores.pop(sec, ()):
                        mesh.link_up[mid] = True
                        mesh.log_fault("link.up", mid, sec=sec)
                    for act in by_sec.get(sec, ()):
                        up_at = mesh.apply_action(act, injector, sec)
                        if up_at is not None:
                            restores.setdefault(min(up_at, self.seconds),
                                                []).append(act["leader"])
                    for fid in flow_order:
                        for _ in range(self.per_second):
                            mesh.request(fid, sec)
                    violations = check_all(history, mesh.thresholds,
                                           mesh.divisor)
                    if violations:
                        first_violation_sec = sec
                        break
                    clock.advance(1000)
                mesh.collect_journals()
                if not violations:
                    violations = check_all(history, mesh.thresholds,
                                           mesh.divisor)
                    if violations and first_violation_sec is None:
                        first_violation_sec = self.seconds - 1
                stamp = self.epoch_ms + 1000 * (first_violation_sec or 0)
                journals = mesh.journal_snapshot(stamp)
                fault_log = list(mesh.fault_log)
                verdict_sha = _sha(
                    f"{ev['op']}:{ev['flow']}:{ev['status']}:{ev['by']}"
                    f":{ev.get('wire')}"
                    for ev in history.of("verdict"))
                fault_sha = _sha(repr(entry) for entry in fault_log)
                ops = len(history.of("offered"))
                grants = len(history.of("grant"))
        finally:
            if mesh is not None:
                mesh.stop()
            shutil.rmtree(workdir, ignore_errors=True)
        return EpisodeResult(index, seed, sched, verdict_sha, fault_sha,
                             violations, ops, grants, fault_log, journals,
                             first_violation_sec)

    # -- shrinking + forensics ---------------------------------------------

    def shrink_episode(self, index: int, schedule: List[dict]):
        """ddmin the schedule to a minimal still-failing subset; returns
        ``(minimal_schedule, final_result, runs)``."""
        def predicate(subset) -> bool:
            return bool(self.run_episode(index, schedule=subset).violations)

        minimal, runs = ddmin(predicate, schedule,
                              max_runs=self.shrink_max_runs)
        final = self.run_episode(index, schedule=minimal)
        return minimal, final, runs

    def shrink_and_bundle(self, index: int,
                          result: Optional[EpisodeResult] = None):
        """The public repro surface (campaign loop AND the `chaos
        op=shrink` ops command): replay episode ``index`` (or take the
        caller's just-run ``result``), ddmin its schedule if it
        violates, and return ``(forensic_bundle, shrink_runs)`` —
        ``(None, 0)`` for a clean episode."""
        if result is None:
            result = self.run_episode(index)
        if not result.violations:
            return None, 0
        minimal, final, runs = self.shrink_episode(index, result.schedule)
        return self._bundle(result, minimal, final, runs), runs

    def _bundle(self, result: EpisodeResult, minimal: List[dict],
                final: EpisodeResult, runs: int) -> dict:
        return {
            "campaignSeed": self.campaign_seed,
            "episode": result.index,
            "episodeSeed": result.seed,
            "violations": [v.to_dict() for v in result.violations],
            "schedule": result.schedule,
            "minimalSchedule": minimal,
            "minimalViolations": [v.to_dict() for v in final.violations],
            "shrinkSteps": runs,
            "verdictSha256": result.verdict_sha256,
            "faultSha256": result.fault_sha256,
            "firstViolationSec": result.first_violation_sec,
            # The PR 13 forensic join: each seat's journal tail, the
            # causeSeq walk from its newest record, and the shard map
            # in force at the violation second.
            "journal": result.journals,
        }

    # -- the campaign ------------------------------------------------------

    def run(self) -> dict:
        import contextlib

        from sentinel_tpu.chaos.regressions import reintroduce

        t0 = time.perf_counter()
        results: List[EpisodeResult] = []
        bundles: List[dict] = []
        shrink_steps = 0
        with contextlib.ExitStack() as stack:
            for name in self.regressions:
                stack.enter_context(reintroduce(name))
            for i in range(self.episodes):
                res = self.run_episode(i)
                results.append(res)
                _pkg._count(episodes=1, faultsFired=len(res.fault_log),
                            violations=len(res.violations))
                if res.violations:
                    if self.shrink:
                        bundle, runs = self.shrink_and_bundle(i, result=res)
                        shrink_steps += runs
                        _pkg._count(shrinkSteps=runs)
                        bundles.append(bundle)
                    else:
                        bundles.append(self._bundle(res, res.schedule,
                                                    res, 0))
                    if self.stop_on_violation:
                        break
        wall = max(time.perf_counter() - t0, 1e-9)
        report = {
            "campaignSeed": self.campaign_seed,
            "episodesPlanned": self.episodes,
            "episodesRun": len(results),
            "secondsPerEpisode": self.seconds,
            "perSecond": self.per_second,
            "maxFaults": self.max_faults,
            "regressions": list(self.regressions),
            "ops": sum(r.ops for r in results),
            "grants": sum(r.grants for r in results),
            "faultsFired": sum(len(r.fault_log) for r in results),
            "violations": sum(len(r.violations) for r in results),
            "shrinkSteps": shrink_steps,
            "bundles": bundles,
            "wallSeconds": round(wall, 3),
            "episodesPerSec": round(len(results) / wall, 3),
            "firstEpisode": results[0].to_dict() if results else None,
            "verdictSha256": _sha(r.verdict_sha256 for r in results),
            "faultSha256": _sha(r.fault_sha256 for r in results),
        }
        _pkg._set_last_report(report)
        return report

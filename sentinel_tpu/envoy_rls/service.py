"""The RLS service implementation (reference:
``SentinelEnvoyRlsServiceImpl.java``): each request descriptor resolves to
its generated cluster rule's flowId and acquires tokens from the token
service; any over-limit descriptor makes the overall answer OVER_LIMIT.

``SentinelEnvoyRlsService`` is transport-agnostic (plain Python call);
``serve_grpc`` wraps it in a real gRPC server via a generic handler when
grpcio is present.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from sentinel_tpu.cluster.constants import TokenResultStatus
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.envoy_rls.rule import EnvoyRlsRuleManager, descriptor_flow_id


class SentinelEnvoyRlsService:
    def __init__(self, rule_manager: Optional[EnvoyRlsRuleManager] = None,
                 token_service: Optional[DefaultTokenService] = None):
        self.rules = rule_manager or EnvoyRlsRuleManager()
        self.token_service = token_service or DefaultTokenService(
            self.rules.cluster_rules)

    def should_rate_limit(
        self,
        domain: str,
        descriptors: Sequence[Sequence[Tuple[str, str]]],
        hits_addend: int = 1,
    ) -> Tuple[int, List[Tuple[int, int]]]:
        """-> (overall_code, [(code, limit_remaining)] per descriptor).

        Codes are the RLS proto's: 1 = OK, 2 = OVER_LIMIT. Descriptors with
        no matching rule pass (reference behavior: unknown descriptor = OK).
        """
        from sentinel_tpu.envoy_rls import proto

        hits = max(1, int(hits_addend))
        statuses: List[Tuple[int, int]] = []
        overall = proto.CODE_OK
        requests = [(descriptor_flow_id(domain, list(entries)), hits, False)
                    for entries in descriptors]
        results = self.token_service.request_tokens(requests)
        for result in results:
            if result.status == TokenResultStatus.OK:
                statuses.append((proto.CODE_OK, result.remaining))
            elif result.status == TokenResultStatus.NO_RULE_EXISTS:
                statuses.append((proto.CODE_OK, 0))
            else:
                statuses.append((proto.CODE_OVER_LIMIT, 0))
                overall = proto.CODE_OVER_LIMIT
        return overall, statuses

    # -- gRPC transport ----------------------------------------------------

    def _grpc_body(self, request, response_cls):
        descriptors = [
            [(e.key, e.value) for e in d.entries] for d in request.descriptors
        ]
        overall, statuses = self.should_rate_limit(
            request.domain, descriptors, request.hits_addend or 1)
        resp = response_cls()
        resp.overall_code = overall
        for code, remaining in statuses:
            s = resp.statuses.add()
            s.code = code
            s.limit_remaining = remaining
        return resp

    def grpc_should_rate_limit(self, request, context=None):
        """v2 gRPC method body over the dynamic proto messages."""
        from sentinel_tpu.envoy_rls import proto

        return self._grpc_body(request, proto.RateLimitResponse)

    def grpc_should_rate_limit_v3(self, request, context=None):
        """v3 twin (``envoy.service.ratelimit.v3`` — what current Envoy
        speaks); identical semantics, renamed packages."""
        from sentinel_tpu.envoy_rls import proto

        return self._grpc_body(request, proto.RateLimitResponseV3)

    def serve_grpc(self, address: str = "0.0.0.0:10245", max_workers: int = 8):
        """Start a gRPC server exposing RateLimitService under BOTH the
        v2 service name (the reference's surface) and the v3 one
        (current Envoy's); returns it."""
        import concurrent.futures

        import grpc

        from sentinel_tpu.envoy_rls import proto

        v2_handler = grpc.method_handlers_generic_handler(
            proto.SERVICE_NAME,
            {
                proto.METHOD_NAME: grpc.unary_unary_rpc_method_handler(
                    self.grpc_should_rate_limit,
                    request_deserializer=proto.RateLimitRequest.FromString,
                    response_serializer=proto.RateLimitResponse.SerializeToString,
                )
            },
        )
        v3_handler = grpc.method_handlers_generic_handler(
            proto.SERVICE_NAME_V3,
            {
                proto.METHOD_NAME: grpc.unary_unary_rpc_method_handler(
                    self.grpc_should_rate_limit_v3,
                    request_deserializer=proto.RateLimitRequestV3.FromString,
                    response_serializer=(
                        proto.RateLimitResponseV3.SerializeToString),
                )
            },
        )
        server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=max_workers))
        server.add_generic_rpc_handlers((v2_handler, v3_handler))
        port = server.add_insecure_port(address)
        server.start()
        server.bound_port = port
        return server

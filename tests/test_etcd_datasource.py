"""etcd v3 gRPC datasource tests (SURVEY.md §2.2:
``sentinel-datasource-etcd``): the real etcd3 wire protocol (runtime-
built ``etcdserverpb``/``mvccpb`` messages over grpcio) — initial Range,
Watch-stream pushes, revision-replay recovery across a server restart,
writable Put, and bad-payload resilience.
"""

import json
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import bind
from sentinel_tpu.datasource.converters import (
    flow_rules_from_json,
    flow_rules_to_json,
)
from sentinel_tpu.datasource.etcd import (
    EtcdDataSource,
    EtcdWritableDataSource,
    MiniEtcdServer,
)


def _wait_for(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _rules_json(*resources, count=5.0) -> str:
    return json.dumps([{"resource": r, "count": count} for r in resources])


def _resources(prop):
    return {r.resource for r in (prop.value or [])}


@pytest.fixture()
def etcd():
    s = MiniEtcdServer().start()
    yield s
    s.stop()


def _source(server, **kw) -> EtcdDataSource:
    kw.setdefault("reconnect_backoff_ms", (20, 100))
    return EtcdDataSource(server.endpoint, "/sentinel/flow-rules",
                          flow_rules_from_json, **kw)


def test_etcd_initial_load_and_watch_push(etcd):
    etcd.put("/sentinel/flow-rules", _rules_json("api:a"))
    src = _source(etcd).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"api:a"})
        etcd.put("/sentinel/flow-rules", _rules_json("api:a", "api:b"))
        assert _wait_for(
            lambda: _resources(src.property) == {"api:a", "api:b"})
    finally:
        src.close()


def test_etcd_absent_key_then_first_put(etcd):
    src = _source(etcd).start()
    try:
        assert src.property.value is None
        etcd.put("/sentinel/flow-rules", _rules_json("late"))
        assert _wait_for(lambda: _resources(src.property) == {"late"})
    finally:
        src.close()


def test_etcd_writable_put_roundtrip(etcd):
    writer = EtcdWritableDataSource(etcd.endpoint, "/sentinel/flow-rules",
                                    flow_rules_to_json)
    src = _source(etcd).start()
    try:
        writer.write([st.FlowRule(resource="via-writer", count=9.0)])
        assert _wait_for(lambda: _resources(src.property) == {"via-writer"})
    finally:
        src.close()


def test_etcd_bad_payload_keeps_last_good(etcd):
    etcd.put("/sentinel/flow-rules", _rules_json("good"))
    src = _source(etcd).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"good"})
        etcd.put("/sentinel/flow-rules", "{not json]")
        time.sleep(0.3)
        assert _resources(src.property) == {"good"}
        etcd.put("/sentinel/flow-rules", _rules_json("recovered"))
        assert _wait_for(lambda: _resources(src.property) == {"recovered"})
    finally:
        src.close()


def test_etcd_reconnect_replays_update_missed_during_outage(etcd):
    etcd.put("/sentinel/flow-rules", _rules_json("v1"))
    src = _source(etcd).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"v1"})
        etcd.stop()
        assert _wait_for(lambda: src.reconnect_count > 0)
        # Put lands in the (surviving) store while the server is down...
        with etcd._lock:
            etcd._revision += 1
            etcd._kv[b"/sentinel/flow-rules"] = (
                _rules_json("v2").encode("utf-8"), 1, etcd._revision, 2)
        etcd.start()
        # ...and the reconnected watch's start_revision triggers replay.
        assert _wait_for(lambda: _resources(src.property) == {"v2"},
                         timeout_s=8.0)
    finally:
        src.close()


def test_etcd_watch_is_event_driven_not_polled(etcd):
    etcd.put("/sentinel/flow-rules", _rules_json("idle"))
    src = _source(etcd).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"idle"})
        assert _wait_for(lambda: etcd.watch_count >= 1)
        watches_before = etcd.watch_count
        time.sleep(0.5)
        # One long-lived stream, not a reconnect-per-poll loop.
        assert etcd.watch_count == watches_before
        assert src.reconnect_count == 0
    finally:
        src.close()


def test_etcd_bind_to_engine(etcd):
    eng = st.reset(capacity=64)
    try:
        src = _source(etcd).start()
        bind(src, st.load_flow_rules)
        etcd.put("/sentinel/flow-rules", _rules_json("bound", count=0.0))
        try:
            def blocked():
                try:
                    with st.entry("bound"):
                        pass
                    return False
                except st.BlockException:
                    return True

            # Generous bound: the fresh engine's first entry() compiles
            # (tens of seconds on a contended 1-core box); _wait_for
            # returns the moment the push is enforced.
            assert _wait_for(blocked, timeout_s=90.0)
        finally:
            src.close()
    finally:
        eng.close()


def test_etcd_wire_messages_roundtrip():
    """The runtime-built messages serialize/parse like real etcd3 ones."""
    from sentinel_tpu.datasource.etcd import (
        KeyValue, PutRequest, RangeResponse, WatchRequest, WatchResponse)

    kv = KeyValue(key=b"k", value=b"v", mod_revision=7, version=2)
    data = kv.SerializeToString()
    back = KeyValue.FromString(data)
    assert back.key == b"k" and back.mod_revision == 7

    wr = WatchRequest()
    wr.create_request.key = b"/sentinel/flow-rules"
    wr.create_request.start_revision = 42
    parsed = WatchRequest.FromString(wr.SerializeToString())
    assert parsed.HasField("create_request")
    assert parsed.create_request.start_revision == 42

    resp = WatchResponse()
    resp.header.revision = 9
    ev = resp.events.add()
    ev.kv.key = b"k"
    ev.kv.value = b"v2"
    parsed2 = WatchResponse.FromString(resp.SerializeToString())
    assert parsed2.events[0].kv.value == b"v2"

    assert PutRequest(key=b"a", value=b"b").SerializeToString()
    assert RangeResponse.FromString(b"") is not None

"""Embedded HTTP command center (reference: ``sentinel-transport-common``'s
``CommandHandler``/``@CommandMapping`` SPI + ``sentinel-transport-simple-http``'s
``SimpleHttpCommandCenter`` — SURVEY.md §2.3).

One handler per command name, dispatched on the URL path
(``GET /version``, ``GET /getRules?type=flow``, ``POST /setRules``, ...).
Responses are the reference's plain-text/JSON bodies so dashboard and curl
tooling transfer. The server is a stdlib ``ThreadingHTTPServer`` on the
configured ``csp.sentinel.api.port`` (default 8719).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from sentinel_tpu.core.config import config


@dataclass
class CommandRequest:
    """Reference: ``CommandRequest`` — parameters + optional body.

    ``engine`` / ``center`` are injected by the dispatching command center so
    handlers act on *that* server's engine (several centers can coexist, and
    a center built without an explicit engine follows the live default one).
    """

    parameters: Dict[str, str] = field(default_factory=dict)
    body: str = ""
    engine: object = None
    center: object = None

    def get_param(self, name: str, default: Optional[str] = None) -> Optional[str]:
        v = self.parameters.get(name)
        return v if v not in (None, "") else default


@dataclass
class CommandResponse:
    """Reference: ``CommandResponse`` — success flag + result string.

    ``content_type`` lets non-JSON commands (the OpenMetrics ``metrics``
    exposition) declare their media type; the default matches the
    reference's plain-text bodies.
    """

    success: bool
    result: str
    content_type: str = "text/plain; charset=utf-8"

    @classmethod
    def of_success(cls, result) -> "CommandResponse":
        if not isinstance(result, str):
            result = json.dumps(result)
        return cls(True, result)

    @classmethod
    def of_failure(cls, message: str) -> "CommandResponse":
        return cls(False, message)


Handler = Callable[[CommandRequest], CommandResponse]

_registry: Dict[str, Handler] = {}
_descriptions: Dict[str, str] = {}


def command_mapping(name: str, desc: str = ""):
    """Register a handler under a command name (``@CommandMapping`` analog)."""

    def deco(fn: Handler) -> Handler:
        _registry[name] = fn
        _descriptions[name] = desc
        return fn

    return deco


def get_handler(name: str) -> Optional[Handler]:
    return _registry.get(name)


def registered_commands() -> Dict[str, str]:
    return dict(_descriptions)


def dispatch_command(center, path: str, body: str):
    """Shared request->handler dispatch: ``(status_code, text, ctype)``.

    Used by both transports (threaded simple-http here, the event-loop
    center in ``aio_command_center.py``) so command semantics cannot
    drift between them."""
    plain = "text/plain; charset=utf-8"
    parsed = urllib.parse.urlparse(path)
    name = parsed.path.strip("/")
    params = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
    # Reference simple-http also accepts form-encoded bodies as params.
    if body and "=" in body and not body.lstrip().startswith(("[", "{")):
        for k, v in urllib.parse.parse_qs(body).items():
            params.setdefault(k, v[0])
        body = ""
    handler = get_handler(name)
    if handler is None:
        return 400, f"Unknown command `{name}`", plain
    try:
        resp = handler(CommandRequest(parameters=params, body=body,
                                      engine=center.engine, center=center))
    except Exception as ex:
        return 500, f"command error: {ex!r}", plain
    return (200 if resp.success else 400), resp.result, resp.content_type


class _HttpHandler(BaseHTTPRequestHandler):
    server_version = "sentinel-tpu"

    def log_message(self, fmt, *args):  # quiet; ops logs go to record_log
        pass

    def _dispatch(self, body: str):
        code, text, ctype = dispatch_command(self.server.command_center,
                                             self.path, body)
        self._reply(code, text, ctype)

    def _reply(self, code: int, text: str,
               ctype: str = "text/plain; charset=utf-8"):
        data = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        self._dispatch("")

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length).decode("utf-8") if length else ""
        self._dispatch(body)


class CommandCenter:
    """The embedded command server (``SimpleHttpCommandCenter`` analog).

    Binds ``csp.sentinel.api.host``, defaulting to 127.0.0.1: the command
    plane is unauthenticated (``setRules``/``setSwitch`` can disable all
    protection), so exposing it beyond loopback is an explicit operator
    decision via config, not a default. Without an explicit ``engine`` the
    center follows the process-default engine, surviving
    ``sentinel_tpu.reset()``.
    """

    def __init__(self, engine=None, port: Optional[int] = None,
                 host: Optional[str] = None):
        # Importing handlers registers the default command set (SPI analog).
        from sentinel_tpu.transport import handlers as _h  # noqa: F401

        self._engine = engine
        self.host = host or config.get("csp.sentinel.api.host") or "127.0.0.1"
        self.port = port if port is not None else config.api_port()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def engine(self):
        if self._engine is not None:
            return self._engine
        import sentinel_tpu

        return sentinel_tpu.get_engine()

    @property
    def bound_port(self) -> int:
        return self._server.server_address[1] if self._server else self.port

    def start(self) -> "CommandCenter":
        self._server = ThreadingHTTPServer((self.host, self.port), _HttpHandler)
        self._server.command_center = self
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="sentinel-command-center", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

"""The token client (reference: ``cluster-client:DefaultClusterTokenClient``
+ ``netty/NettyTransportClient`` + ``TokenClientPromiseHolder`` — SURVEY.md
§2.4): one TCP connection, xid-correlated request/response futures, request
timeouts, backoff reconnect, and a namespace PING on connect.

Resilience (sentinel_tpu/resilience/): reconnects follow a seedable
``RetryPolicy`` instead of a fixed cadence, and a ``HealthGate`` breaker
guards the request path — a connected-but-degraded server (slow, hung,
partitioned) trips the gate after consecutive timeouts and token requests
fail fast (no wire touch) until the gate's probe succeeds.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
from typing import Dict, Optional, Sequence, Tuple

from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.constants import (
    MSG_FLEET,
    MSG_FLOW,
    MSG_PARAM_FLOW,
    MSG_PING,
    TokenResultStatus,
)
from sentinel_tpu.cluster.token_service import TokenResult
from sentinel_tpu.resilience import HealthGate, RetryPolicy, faults


class _GarbageFrame(Exception):
    """Undecodable frame on the wire: the stream is desynced; treated as
    a connection loss (internal to the read loop)."""


class _Gather:
    """Shared completion latch for one pipelined batch (ISSUE 11): every
    xid of the batch registers THIS object in ``_pending`` instead of
    its own ``threading.Event`` — ``set()`` counts a response down and
    wakes the waiter once, when the LAST response (or drop) lands. One
    wakeup per batch, not per request; duck-types the per-request Event
    for the read loop and ``_drop_connection``, which only call set()."""

    __slots__ = ("_event", "_remaining", "_lock")

    def __init__(self, n: int):
        self._event = threading.Event()
        self._remaining = n
        self._lock = threading.Lock()

    def set(self) -> None:
        with self._lock:
            self._remaining -= 1
            if self._remaining > 0:
                return
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


_CONFIG_GATE = object()  # default marker: build the HealthGate from config


class ClusterTokenClient:
    def __init__(self, host: str, port: int, namespace: str = "default",
                 request_timeout_s: float = 2.0,
                 reconnect_interval_s: float = 2.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 health_gate=_CONFIG_GATE,
                 epoch_fence=None,
                 connect_timeout_s: float = 3.0,
                 fence_scope_fn=None):
        self.host = host
        self.port = port
        self.namespace = namespace
        self.request_timeout_s = request_timeout_s
        self.reconnect_interval_s = reconnect_interval_s
        self.connect_timeout_s = connect_timeout_s
        # Leadership-epoch fence (cluster/ha.py): responses stamped with
        # an epoch BELOW the highest this fence has observed are from a
        # deposed leader — rejected as FAIL so split-brain can never
        # double-grant quota. None (default) disables fencing.
        self.epoch_fence = epoch_fence
        # Sharded fencing (cluster/sharding.py): maps a request's
        # flowId to the fence SCOPE its response is judged under (the
        # flow's hash slice, via the shared ``sharding.slice_of``
        # helper) — per-slice leadership terms are independent, so one
        # slice's epoch must never gate another's. None (default)
        # keeps the single global fence lane.
        self.fence_scope_fn = fence_scope_fn
        # Backoff schedule for the reconnect loop: first delay is exactly
        # ``reconnect_interval_s`` (legacy cadence), repeated failures
        # back off with decorrelated jitter instead of hammering a dead
        # or recovering server every 2s forever.
        self.retry_policy = retry_policy or RetryPolicy.from_config(
            "cluster.client", base_ms=int(reconnect_interval_s * 1000),
            max_ms=60_000)
        # ``health_gate=None`` disables the breaker (raw client); the
        # default builds one from csp.sentinel.resilience.breaker.*.
        self.health_gate: Optional[HealthGate] = (
            HealthGate.from_config() if health_gate is _CONFIG_GATE
            else health_gate)
        self._xid = itertools.count(1)
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()  # serialize frame writes
        self._sock: Optional[socket.socket] = None
        self._pending: Dict[int, Tuple[threading.Event, dict]] = {}
        self._reader: Optional[threading.Thread] = None
        self._reconnector: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- connection management --------------------------------------------

    def start(self) -> "ClusterTokenClient":
        self._stop.clear()
        try:
            self._connect()
        except OSError:
            pass  # reconnector keeps trying
        self._reconnector = threading.Thread(
            target=self._reconnect_loop, name="sentinel-token-reconnect",
            daemon=True)
        self._reconnector.start()
        return self

    def _connect(self) -> None:
        # Dial OUTSIDE the lock: a blackholed server must not stall
        # is_connected() readers (the entry() fallback path) for the
        # connect timeout.
        with self._lock:
            if self._sock is not None:
                return
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout_s)
        # Bounded I/O timeout, derived from the request timeout (was
        # ``settimeout(None)``): with an unbounded socket, a server that
        # stops READING mid-reply leaves ``sendall`` parked forever
        # holding ``_send_lock`` — every later request on this client
        # hangs behind it with no path to the reconnector. Bounded, the
        # stalled write raises and drops the connection like any other
        # wire failure. The read side treats a timeout as an idle tick
        # (no traffic != failure — see ``_read_loop``), so a quiet but
        # healthy connection is never torn down by this.
        sock.settimeout(self._io_timeout_s())
        with self._lock:
            if self._sock is not None:  # raced with another connect
                sock.close()
                return
            self._sock = sock
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,),
            name="sentinel-token-reader", daemon=True)
        self._reader.start()
        # Register the namespace (reference: PingRequest on channel active).
        self._call(MSG_PING, codec.encode_ping(self.namespace))

    def _reconnect_loop(self):
        session = self.retry_policy.session()
        delay_s = session.next_delay_ms() / 1000.0
        while not self._stop.wait(delay_s):
            if self.is_connected():
                session.reset()
                delay_s = session.next_delay_ms() / 1000.0
                continue
            try:
                self._connect()
                session.reset()
            except OSError:
                pass
            delay_s = session.next_delay_ms() / 1000.0

    def _io_timeout_s(self) -> float:
        """Socket send/recv bound: twice the request timeout (a write
        that cannot progress for 2x the longest any caller would wait on
        its reply is a dead peer, not a slow one), floored so a
        pathologically small request timeout can't busy-spin the
        reader."""
        return max(self.request_timeout_s * 2, 0.2)

    def is_connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    def _drop_connection(self):
        with self._lock:
            sock, self._sock = self._sock, None
            pending = list(self._pending.values())
            self._pending.clear()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for done, box in pending:
            done.set()  # fail fast: box stays empty -> FAIL

    def _read_loop(self, sock: socket.socket):
        reader = codec.FrameReader()
        try:
            while True:
                try:
                    data = sock.recv(65536)
                except socket.timeout:
                    # Idle tick on the bounded-I/O socket: no traffic
                    # for the timeout window is normal on a quiet
                    # connection — only a real error drops it.
                    continue
                if not data:
                    break
                for body in reader.feed(data):
                    try:
                        resp = codec.decode_response(body)
                    except (ValueError, struct.error, IndexError):
                        # Garbage frame: the length-prefixed stream is
                        # desynced beyond repair — drop the connection
                        # (pending requests fail fast, the reconnector
                        # dials fresh) instead of letting the decode
                        # error kill this thread with the socket open
                        # and every future request left to time out.
                        raise _GarbageFrame()
                    with self._lock:
                        entry = self._pending.pop(resp.xid, None)
                    if entry is not None:
                        entry[1]["resp"] = resp
                        entry[0].set()
        except (OSError, _GarbageFrame):
            pass
        finally:
            self._drop_connection()

    def stop(self) -> None:
        self._stop.set()
        self._drop_connection()
        if self._reconnector is not None:
            self._reconnector.join(timeout=1.0)
            self._reconnector = None

    # -- requests ----------------------------------------------------------

    def _call(self, msg_type: int, entity: bytes,
              timeout_s: Optional[float] = None) -> Optional[codec.Response]:
        xid = next(self._xid)
        done = threading.Event()
        box: dict = {}
        with self._lock:
            sock = self._sock
            if sock is None:
                return None
            self._pending[xid] = (done, box)
        try:
            raw = codec.encode_request(xid, msg_type, entity)
        except (ValueError, struct.error):  # oversized frame: fail this call
            with self._lock:
                self._pending.pop(xid, None)
            return None
        try:
            faults.fire("cluster.client.send")
            with self._send_lock:  # frames must not interleave on the wire
                sock.sendall(raw)
        except OSError:
            self._drop_connection()
            return None
        wait_s = self.request_timeout_s if timeout_s is None \
            else min(timeout_s, self.request_timeout_s)
        if not done.wait(wait_s):
            with self._lock:
                self._pending.pop(xid, None)
            return None
        return box.get("resp")

    def _gated_call(self, msg_type: int, entity: bytes,
                    timeout_s: Optional[float] = None,
                    gate_neutral: bool = False) -> Optional[codec.Response]:
        """`_call` behind the health gate: an OPEN breaker fails fast
        without touching the wire; outcomes feed the gate.

        ``gate_neutral``: a failed call does NOT count against the
        breaker. Deadline-budgeted callers set it when the remaining
        budget is so small that a HEALTHY server could miss it — a miss
        against a starved deadline says nothing about server health, and
        counting it would spuriously trip the gate under load."""
        gate = self.health_gate
        if gate is not None and not gate.allow():
            return None
        resp = self._call(msg_type, entity, timeout_s)
        if gate is not None:
            if resp is not None:
                gate.record_success()
            elif not gate_neutral:
                gate.record_failure()
        return resp

    @staticmethod
    def _read_server_span(entity: bytes, offset: int):
        """Server-side span info TLV from a response entity, or None."""
        tlv = codec.read_trace_tlv(entity, offset)
        if not tlv:
            return None
        info = codec.decode_span_info(tlv)
        if info is None:
            return None
        return {"spanId": info[0], "startMs": info[1], "durationUs": info[2]}

    def request_token(self, flow_id: int, count: int = 1,
                      prioritized: bool = False,
                      timeout_s: Optional[float] = None,
                      gate_neutral: bool = False,
                      trace=None) -> TokenResult:
        """One acquire; FAIL on disconnect/timeout/open-breaker — immediate
        (no wire wait) when disconnected or the gate is OPEN; callers
        decide fallback. ``timeout_s`` tightens (never widens) the
        configured request timeout, for deadline-budgeted callers;
        ``gate_neutral`` keeps a starved-deadline miss out of the
        breaker's failure count. ``trace`` (telemetry/spans.py
        TraceContext) rides the wire as a trailing TLV old servers
        ignore; a new server ships its token-service span back in
        ``TokenResult.server_span``."""
        entity = codec.encode_flow_request(flow_id, count, prioritized)
        if trace is not None:
            entity = codec.append_trace_tlv(entity, trace.traceparent())
        resp = self._gated_call(MSG_FLOW, entity, timeout_s, gate_neutral)
        return self._flow_result(resp, traced=trace is not None,
                                 scope=self._scope_for(flow_id))

    def _scope_for(self, flow_id):
        """The fence scope (hash slice) a flow's responses are judged
        under, or None on un-sharded clients."""
        if self.fence_scope_fn is None:
            return None
        return self.fence_scope_fn(flow_id)

    def _flow_result(self, resp: Optional[codec.Response],
                     traced: bool = False, scope=None) -> TokenResult:
        """Decode one FLOW response (epoch fence, OVERLOADED retry-after,
        span TLV) — shared by the per-request and pipelined paths."""
        if resp is None:
            return TokenResult(TokenResultStatus.FAIL)
        if resp.status == TokenResultStatus.WRONG_SLICE:
            # Out-of-slice (cluster/sharding.py): not a verdict and not
            # fenced (the replying leader holds no term for the slice).
            # waitMs mirrors the map-version TLV; prefer the TLV.
            _, wait_ms = codec.decode_flow_response(resp.entity)
            ver = codec.read_map_version_tlv(resp.entity,
                                             codec.FLOW_RESP_SIZE)
            return TokenResult(resp.status,
                               wait_ms=int(ver if ver is not None
                                           else wait_ms))
        if self._epoch_stale(resp.entity, codec.FLOW_RESP_SIZE, scope):
            return TokenResult(TokenResultStatus.FAIL)
        remaining, wait_ms = codec.decode_flow_response(resp.entity)
        span = (self._read_server_span(resp.entity, codec.FLOW_RESP_SIZE)
                if traced else None)
        if resp.status in (TokenResultStatus.SHOULD_WAIT,
                           TokenResultStatus.OVERLOADED):
            # OVERLOADED is a shed, not a verdict: waitMs carries the
            # server's retry-after hint. It reaches the caller as-is —
            # the failover client backs the target off, the engine
            # degrades the entry to its local lease/fallback path.
            return TokenResult(resp.status, wait_ms=wait_ms,
                               server_span=span)
        return TokenResult(resp.status, remaining=remaining,
                           server_span=span)

    def request_tokens_pipelined(self, requests: Sequence[Tuple],
                                 timeout_s: Optional[float] = None,
                                 gate_neutral: bool = False):
        """Batched acquires with >1 request in flight on ONE socket
        (ISSUE 11): every request gets its own xid, all frames go out as
        ONE coalesced write, and responses are matched back by xid in
        any arrival order — the old path serialized send+wait per call,
        so a single connection could never keep the server's coalescing
        collector fed. Requests are ``(flow_id, count, prioritized)``
        tuples; returns one TokenResult per request, in request order.

        Semantics are per-request identical to :meth:`request_token`
        (epoch fencing, OVERLOADED retry-after, FAIL on drop/timeout);
        the health gate is consulted once for the batch and fed one
        outcome: success if any response arrived, failure (unless
        ``gate_neutral``) if none did."""
        n = len(requests)
        if n == 0:
            return []
        gate = self.health_gate
        if gate is not None and not gate.allow():
            return [TokenResult(TokenResultStatus.FAIL)] * n
        gather = _Gather(n)
        xids = []
        frames = []
        boxes = []
        scopes = [self._scope_for(r[0]) for r in requests]
        with self._lock:
            sock = self._sock
            if sock is None:
                return [TokenResult(TokenResultStatus.FAIL)] * n
            for flow_id, count, prioritized in requests:
                xid = next(self._xid)
                box: dict = {}
                try:
                    frames.append(codec.encode_request(
                        xid, MSG_FLOW, codec.encode_flow_request(
                            flow_id, count, prioritized)))
                except (ValueError, struct.error):
                    # Oversized/garbage request: pre-resolved FAIL slot,
                    # never registered — the gather shrinks accordingly.
                    gather.set()
                    boxes.append(None)
                    xids.append(None)
                    continue
                self._pending[xid] = (gather, box)
                xids.append(xid)
                boxes.append(box)
        try:
            faults.fire("cluster.client.send")
            with self._send_lock:  # frames must not interleave on the wire
                sock.sendall(b"".join(frames))
        except OSError:
            self._drop_connection()  # sets the gather for every pending xid
        wait_s = self.request_timeout_s if timeout_s is None \
            else min(timeout_s, self.request_timeout_s)
        gather.wait(wait_s)
        with self._lock:
            for xid in xids:
                if xid is not None:
                    self._pending.pop(xid, None)
        out = [self._flow_result(box.get("resp"), scope=scopes[k])
               if box is not None
               else TokenResult(TokenResultStatus.FAIL)
               for k, box in enumerate(boxes)]
        if gate is not None:
            if any(b is not None and "resp" in b for b in boxes):
                gate.record_success()
            elif not gate_neutral:
                gate.record_failure()
        return out

    def request_fleet_telemetry(self, since_ms: int = 0,
                                max_seconds: int = 16,
                                timeout_s: Optional[float] = None
                                ) -> Optional[dict]:
        """Pull one fleetTelemetry page (ISSUE 14): the leader's
        complete seconds strictly after ``since_ms``, its instance
        health, and shard ownership, as a decoded dict (plus
        ``wireEpoch`` when the reply carried the epoch TLV). None on
        disconnect/timeout/garbled payload; ``{"unsupported": True}``
        when the server predates the command (BAD_REQUEST).

        Deliberately NOT behind the health gate: a telemetry scrape
        failing must never trip the breaker the TOKEN path relies on —
        the read plane reports staleness, it doesn't fail admission."""
        resp = self._call(
            MSG_FLEET, codec.encode_fleet_request(since_ms, max_seconds),
            timeout_s)
        if resp is None:
            return None
        if resp.status == TokenResultStatus.BAD_REQUEST:
            return {"unsupported": True}
        if resp.status != TokenResultStatus.OK:
            return None
        payload, end = codec.decode_json_entity(resp.entity)
        if payload is None:
            return None
        epoch = codec.read_epoch_tlv(resp.entity, end)
        if epoch is not None:
            # Reported, never fenced: telemetry is read-only — a stale
            # leader's page is still true history, and rejecting it
            # would inflate the fence's stale counter with reads.
            payload["wireEpoch"] = epoch
        return payload

    def request_population_page(self, timeout_s: Optional[float] = None
                                ) -> Optional[dict]:
        """Pull this leader's namespace-telescope page (ISSUE 19) —
        the ``MSG_FLEET`` message with the ``max_seconds == -1``
        sentinel. None on disconnect/timeout/garbled payload;
        ``{"unsupported": True}`` when the server predates the message
        entirely (BAD_REQUEST) OR answered with a plain seconds page
        (a pre-telescope fleet server that ignored the sentinel).

        Same stance as :meth:`request_fleet_telemetry`: NOT behind the
        health gate — a telescope scrape failing must never trip the
        breaker the token path relies on."""
        resp = self._call(
            MSG_FLEET, codec.encode_fleet_request(0, -1), timeout_s)
        if resp is None:
            return None
        if resp.status == TokenResultStatus.BAD_REQUEST:
            return {"unsupported": True}
        if resp.status != TokenResultStatus.OK:
            return None
        payload, end = codec.decode_json_entity(resp.entity)
        if payload is None:
            return None
        if "population" not in payload:
            return {"unsupported": True}
        epoch = codec.read_epoch_tlv(resp.entity, end)
        if epoch is not None:
            payload["wireEpoch"] = epoch
        page = payload.get("population")
        if page:
            page["leader"] = payload.get("leader")
            page["nowMs"] = payload.get("nowMs")
        return page or {"unsupported": True}

    def request_param_token(self, flow_id: int, count: int, params: Sequence,
                            timeout_s: Optional[float] = None,
                            gate_neutral: bool = False,
                            trace=None) -> TokenResult:
        entity = codec.encode_param_flow_request(flow_id, count, params)
        if trace is not None:
            entity = codec.append_trace_tlv(entity, trace.traceparent())
        resp = self._gated_call(MSG_PARAM_FLOW, entity, timeout_s,
                                gate_neutral)
        if resp is None:
            return TokenResult(TokenResultStatus.FAIL)
        if resp.status == TokenResultStatus.WRONG_SLICE:
            # Param responses carry the shard-map version ONLY in the
            # TLV (no waitMs field in the entity).
            ver = codec.read_map_version_tlv(resp.entity, 0)
            return TokenResult(resp.status,
                               wait_ms=int(ver) if ver is not None else 0)
        if self._epoch_stale(resp.entity, 0, self._scope_for(flow_id)):
            return TokenResult(TokenResultStatus.FAIL)
        span = (self._read_server_span(resp.entity, 0)
                if trace is not None else None)
        return TokenResult(resp.status, server_span=span)

    def _epoch_stale(self, entity: bytes, offset: int, scope=None) -> bool:
        """True when the response's epoch TLV is below the fence's
        high-water mark: a deposed leader replied, and honoring its
        grant could double-spend quota the new leader is also granting.
        ``scope`` keys the fence lane (the flow's hash slice on sharded
        clients — per-slice terms are independent); unstamped responses
        (pre-HA servers) pass through unfenced."""
        fence = self.epoch_fence
        if fence is None:
            return False
        epoch = codec.read_epoch_tlv(entity, offset)
        if epoch is None:
            return False
        return not fence.observe(epoch, scope)

"""Datasource framework (reference: ``sentinel-datasource-extension``:
``ReadableDataSource`` / ``WritableDataSource`` / ``AbstractDataSource`` /
``AutoRefreshDataSource`` / ``FileRefreshableDataSource`` /
``FileWritableDataSource`` / ``Converter`` — SURVEY.md §2.2, §3.2).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Generic, Optional, TypeVar

from sentinel_tpu.core.property import (
    DynamicSentinelProperty,
    SentinelProperty,
    SimplePropertyListener,
)
from sentinel_tpu.resilience import RetryPolicy, faults, register_probe
from sentinel_tpu.utils import time_util

S = TypeVar("S")
T = TypeVar("T")


def _log_warn(msg: str, *args) -> None:
    from sentinel_tpu.log.record_log import record_log

    record_log.warn(msg, *args)

# Reference: ``Converter<S, T>`` — a single ``convert`` method, so a plain
# callable is the Python-native shape.
Converter = Callable[[S], T]


class ReadableDataSource(Generic[S, T]):
    """Reference: ``ReadableDataSource<S, T>``."""

    def load_config(self) -> Optional[T]:
        raise NotImplementedError

    def read_source(self) -> S:
        raise NotImplementedError

    @property
    def property(self) -> SentinelProperty[T]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class WritableDataSource(Generic[T]):
    """Reference: ``WritableDataSource<T>``."""

    def write(self, value: T) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class AbstractDataSource(ReadableDataSource[S, T]):
    """Holds the converter + a ``DynamicSentinelProperty`` fan-out point."""

    def __init__(self, converter: Converter):
        if converter is None:
            raise ValueError("converter can't be None")
        self.converter = converter
        self._property: DynamicSentinelProperty[T] = DynamicSentinelProperty()

    def load_config(self) -> Optional[T]:
        faults.fire("datasource.read")
        return self.converter(self.read_source())

    @property
    def property(self) -> SentinelProperty[T]:
        return self._property


class AutoRefreshDataSource(AbstractDataSource[S, T]):
    """Poll loop (reference default 3s): re-read, convert, push on change.

    ``is_modified`` lets subclasses cheaply skip unchanged sources (the
    file impl checks mtime, mirroring the reference).

    Resilience: consecutive refresh failures back off on a seedable
    ``RetryPolicy`` (base = the poll cadence) instead of log-and-retry at
    fixed cadence against a down source; ``last_success_ms`` exposes the
    age of the last good poll (also published to the resilience
    health-probe registry while the loop runs — last good rules keep
    enforcing during an outage, and this is how ops sees how stale
    they are).
    """

    def __init__(self, converter: Converter, recommend_refresh_ms: int = 3000,
                 retry_policy: Optional[RetryPolicy] = None):
        super().__init__(converter)
        self.refresh_ms = recommend_refresh_ms
        self.retry_policy = retry_policy or RetryPolicy.from_config(
            "datasource", base_ms=max(1, recommend_refresh_ms),
            max_ms=max(60_000, recommend_refresh_ms * 20))
        self._retry_session = self.retry_policy.session()
        self._last_success_ms = -1
        self._last_check_ms = -1
        self.consecutive_failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._probe_off: Optional[Callable[[], None]] = None

    @property
    def last_success_ms(self) -> int:
        """Clock time of the last successful READ (-1: never). A source
        that simply hasn't changed keeps this at the last real read —
        watch ``last_check_ms``/``consecutiveFailures`` for liveness."""
        return self._last_success_ms

    @property
    def last_check_ms(self) -> int:
        """Clock time of the last error-free poll, including polls
        skipped as unmodified (-1: never)."""
        return self._last_check_ms

    def health(self) -> dict:
        return {"lastSuccessMs": self._last_success_ms,
                "lastCheckMs": self._last_check_ms,
                "consecutiveFailures": self.consecutive_failures,
                "refreshMs": self.refresh_ms}

    def start(self, initial_load: bool = True) -> "AutoRefreshDataSource":
        """``initial_load=False`` skips the (error-swallowing) first read —
        for callers that already loaded, validated, and pushed the initial
        value themselves and must not race a second read."""
        if initial_load:
            self.first_load()
        self._probe_off = register_probe(
            f"datasource.{type(self).__name__}.{id(self):x}", self.health)
        self._thread = threading.Thread(
            target=self._run, name="sentinel-datasource-auto-refresh", daemon=True
        )
        self._thread.start()
        return self

    def _acting(self):
        """Provenance context for the audit journal (ISSUE 14): every
        rule/objective/target load this source pushes records
        ``datasource:<ClassName>`` as its actor."""
        from sentinel_tpu.telemetry.journal import acting

        return acting(f"datasource:{type(self).__name__}")

    def first_load(self) -> None:
        try:
            value = self.load_config()
            if value is not None:
                with self._acting():
                    self._property.update_value(value)
            self._note_success()
        except Exception as ex:
            _log_warn("datasource initial load failed: %r", ex)

    def is_modified(self) -> bool:
        return True

    def refresh(self, force: bool = False) -> bool:
        """One poll iteration (exposed for deterministic tests); ``force``
        skips the is_modified gate (coarse-mtime filesystems can miss a
        same-tick rewrite). Returns whether the source was actually READ
        (False = skipped as unmodified)."""
        if not force and not self.is_modified():
            return False
        # Flap seam (resilience/faults.py "datasource.flap" — ISSUE 15):
        # the SOURCE is healthy but the path to it flapped this cycle —
        # the poll fails transiently and catches up on a later cadence
        # tick (distinct from datasource.read, which models the read
        # itself failing inside the connector).
        faults.fire("datasource.flap")
        value = self.load_config()
        if value is not None:
            with self._acting():
                self._property.update_value(value)
        return True

    def _note_success(self) -> None:
        now = time_util.current_time_millis()
        self._last_success_ms = now
        self._last_check_ms = now
        self.consecutive_failures = 0
        self._retry_session.reset()

    def _poll_once(self) -> int:
        """One poll; returns the wait before the next one. Successful
        reads keep the configured cadence; consecutive failures back
        off. Polls skipped by ``is_modified`` leave ``last_success_ms``
        (last real read) and the failure counter alone — a deleted file
        also reads as "unmodified" — but refresh ``last_check_ms``: an
        unchanged-for-hours source is healthy, not stale."""
        try:
            did_read = self.refresh()
        except Exception as ex:  # poll loop survives, with a trace
            self.consecutive_failures += 1
            delay_ms = max(self.refresh_ms,
                           self._retry_session.next_delay_ms())
            _log_warn("datasource refresh failed (%d consecutive, "
                      "next poll in %dms): %r",
                      self.consecutive_failures, delay_ms, ex)
            return delay_ms
        if did_read:
            self._note_success()
        else:
            self._last_check_ms = time_util.current_time_millis()
        return self.refresh_ms

    def _run(self):
        wait_ms = self.refresh_ms
        while not self._stop.wait(wait_ms / 1000.0):
            wait_ms = self._poll_once()

    def close(self) -> None:
        self._stop.set()
        if self._probe_off is not None:
            self._probe_off()
            self._probe_off = None
        if self._thread is not None:
            self._thread.join(timeout=1.0)


class FileRefreshableDataSource(AutoRefreshDataSource[str, T]):
    """Reference: ``FileRefreshableDataSource`` — mtime-polled file source."""

    def __init__(self, file_path: str, converter: Converter,
                 recommend_refresh_ms: int = 3000, charset: str = "utf-8",
                 retry_policy: Optional[RetryPolicy] = None):
        super().__init__(converter, recommend_refresh_ms,
                         retry_policy=retry_policy)
        self.file_path = os.path.abspath(file_path)
        self.charset = charset
        self._last_mtime = -1.0

    def read_source(self) -> str:
        with open(self.file_path, "r", encoding=self.charset) as f:
            return f.read()

    def is_modified(self) -> bool:
        try:
            mtime = os.stat(self.file_path).st_mtime
        except OSError:
            return False
        if mtime != self._last_mtime:
            self._last_mtime = mtime
            return True
        return False

    def first_load(self) -> None:
        try:
            self._last_mtime = os.stat(self.file_path).st_mtime
        except OSError:
            pass
        super().first_load()


class FileWritableDataSource(WritableDataSource[T]):
    """Reference: ``FileWritableDataSource`` — serialize + atomic rewrite."""

    def __init__(self, file_path: str, encoder: Converter, charset: str = "utf-8"):
        self.file_path = os.path.abspath(file_path)
        self.encoder = encoder
        self.charset = charset
        self._lock = threading.Lock()

    def write(self, value: T) -> None:
        text = self.encoder(value)
        with self._lock:
            tmp = self.file_path + ".tmp"
            with open(tmp, "w", encoding=self.charset) as f:
                f.write(text)
            os.replace(tmp, self.file_path)


class ContentDedupPollMixin:
    """``load_config`` for poll connectors whose only change signal is
    the document bytes (Eureka metadata, Spring Cloud Config — neither
    API has a usable change index): ``read_source() -> None`` (absent
    key/instance) or unchanged content pushes nothing and keeps the last
    good rules; ``_applied`` commits only after the converter succeeds,
    so a bad payload can't poison the dedup cache.
    """

    _applied: Optional[str] = None

    def load_config(self):
        faults.fire("datasource.read")
        raw = self.read_source()
        if raw is None or raw == self._applied:
            return None
        value = self.converter(raw)
        if value is not None:
            self._applied = raw
        return value


class ReconnectingWatchMixin:
    """Scaffolding shared by the push connectors (Redis / Nacos / Consul /
    etcd): a daemon watch thread that runs ``_watch_round()`` forever,
    turning any exception in ``_watch_exceptions`` into an exponential-
    backoff reconnect. One implementation so the stop-guard/backoff
    discipline can't drift between connectors.

    Contract for subclasses:
      - call ``_init_watch(reconnect_backoff_ms)`` in ``__init__``,
        ``_start_watching()`` in ``start()``, ``_join_watch()`` in
        ``close()``;
      - implement ``_watch_round()``: ONE connect/park/read cycle; raise
        one of ``_watch_exceptions`` on any failure; call ``_healthy()``
        once the round proves the server is back (resets the backoff);
        return normally when ``self._stop`` is set;
      - override ``_interrupt_watch()`` if a parked round needs an
        explicit kick (e.g. socket shutdown) to notice ``close()``.
    """

    _watch_exceptions: tuple = (OSError, ConnectionError, ValueError)
    _watch_thread_name = "sentinel-datasource-watch"

    def _init_watch(self, reconnect_backoff_ms) -> None:
        self.backoff_min_ms, self.backoff_max_ms = reconnect_backoff_ms
        self._backoff_ms = self.backoff_min_ms
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.reconnect_count = 0  # ops visibility + test hook

    def _start_watching(self) -> None:
        self._thread = threading.Thread(
            target=self._watch_forever, name=self._watch_thread_name,
            daemon=True)
        self._thread.start()

    def _healthy(self) -> None:
        self._backoff_ms = self.backoff_min_ms

    def _watch_round(self) -> None:
        raise NotImplementedError

    def _interrupt_watch(self) -> None:
        pass

    def _watch_forever(self) -> None:
        while not self._stop.is_set():
            try:
                self._watch_round()
            except self._watch_exceptions as ex:
                if self._stop.is_set():
                    break
                self.reconnect_count += 1
                _log_warn("%s lost (%r); retry in %dms",
                          self._watch_thread_name, ex, self._backoff_ms)
                self._stop.wait(self._backoff_ms / 1000.0)
                self._backoff_ms = min(self._backoff_ms * 2,
                                       self.backoff_max_ms)

    def _join_watch(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        self._interrupt_watch()
        if self._thread is not None:
            # The thread may be parked inside a long poll; it is a daemon
            # and the stop guards discard any post-close push, so an
            # impatient join is safe.
            self._thread.join(timeout=timeout_s)
            self._thread = None


def bind(source: ReadableDataSource, load_rules: Callable) -> None:
    """Attach a datasource to a rule loader (``register2Property`` analog).

    ``load_rules`` is e.g. ``sentinel_tpu.load_flow_rules`` or a manager's
    ``load_rules`` bound method; every push re-loads the family wholesale
    (§3.2 swap semantics).
    """
    source.property.add_listener(SimplePropertyListener(load_rules))

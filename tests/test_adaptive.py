"""Closed-loop adaptive limiting (sentinel_tpu/adaptive/): envelope
invariants in isolation, the AIMD policy, converters, the end-to-end
closed loop (propose -> shadow -> canary -> promote restoring the SLO
target within bounded steps), the mirror test (guardrail breach ->
auto-abort restores last-known-good verdict-for-verdict, zero direct
rule mutations), chaos coverage under FaultInjector (stale telemetry,
token-server death mid-loop, SLO page mid-canary), the no-oscillation
property under a step-load change, the ops command, the exporter
families, and the zero-per-step-device-work A/B guard.

Every engine test runs on a frozen clock: the loop's cadence, soaks,
cooldowns, and the guardrail windows are all driven explicitly, so the
suite is deterministic and the "bounded steps" claims are exact."""

import json

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.adaptive.controller import (
    AdaptiveTarget,
    AimdPolicy,
    ResourceSense,
)
from sentinel_tpu.adaptive.envelope import (
    FreezeGate,
    SafetyEnvelope,
)
from sentinel_tpu.core import constants as C
from sentinel_tpu.datasource import converters as CV
from sentinel_tpu.utils import time_util

BASE_MS = 1_700_000_000_000


@pytest.fixture()
def engine(frozen_time):
    """Fresh engine with drill-speed adaptive knobs: 2s cadence/soaks,
    4s cooldown, x2 steps — every stage transition fits in a few driven
    seconds. Config restores to defaults on teardown."""
    from sentinel_tpu.core.config import config
    from sentinel_tpu.core.context import replace_context

    for k, v in {
        "csp.sentinel.adaptive.interval.seconds": "2",
        "csp.sentinel.adaptive.shadow.seconds": "2",
        "csp.sentinel.adaptive.canary.seconds": "2",
        "csp.sentinel.adaptive.cooldown.seconds": "4",
        "csp.sentinel.adaptive.abort.backoff.seconds": "30",
        "csp.sentinel.adaptive.step.pct": "1.0",
        "csp.sentinel.adaptive.increase.pct": "1.0",
        "csp.sentinel.adaptive.freeze.stale.seconds": "5",
    }.items():
        config.set(k, v)
    replace_context(None)
    eng = st.reset(capacity=512)
    eng.rollout.min_window_entries = 8
    yield eng
    replace_context(None)
    config.reset_for_tests()
    st.reset(capacity=512)


def _spy_load_rules(eng):
    """Record every live flow-rule application with its call stack; the
    zero-direct-mutation assertions read it."""
    import traceback

    calls = []
    orig = eng.flow_rules.load_rules

    def spy(rules):
        calls.append("".join(traceback.format_stack()))
        return orig(rules)

    eng.flow_rules.load_rules = spy
    return calls


def _drive(eng, resource, per_sec, seconds, now, rt_ms=None):
    """Drive per_sec-entry batches for N seconds from `now`; optionally
    complete each second's passed entries with the given RT. Returns
    the stream-end clock (frozen there)."""
    from tests.test_telemetry import _batch, _exit_batch

    for _ in range(seconds):
        time_util.freeze_time(now)
        dec = eng.check_batch(
            _batch(eng, [(resource, "", None)] * per_sec), now_ms=now)
        if rt_ms is not None:
            passed = int((np.asarray(dec.reason)
                          == C.BlockReason.PASS).sum())
            if passed:
                eng.complete_batch(
                    _exit_batch(eng, [(resource, "", None)] * passed,
                                [rt_ms] * passed),
                    now_ms=now + 900)
        now += 1000
    time_util.freeze_time(now)
    return now


def _tick(eng, now):
    time_util.freeze_time(now)
    return eng.adaptive.tick(now_ms=now, force=True)


def _count_of(eng, resource):
    return [r.count for r in eng.flow_rules.get_rules()
            if r.resource == resource][0]


# ---------------------------------------------------------------------------
# envelope invariants, in isolation (no engine, no device)
# ---------------------------------------------------------------------------

def test_envelope_band_and_step_clamps():
    env = SafetyEnvelope(step_pct=0.25, cooldown_ms=0)
    # Step clamp: 100 -> 200 asked, 25% max step -> 125, clamped.
    d = env.admit("r", 100.0, 200.0, floor=1.0, ceiling=1000.0, now_ms=0)
    assert d.allowed and d.clamped and d.value == 125.0 and d.reason == "step"
    # Band beats step: ceiling 110 wins over the 125 the step allows.
    d = env.admit("r", 100.0, 200.0, floor=1.0, ceiling=110.0, now_ms=0)
    assert d.allowed and d.clamped and d.value == 110.0
    assert d.reason == "ceiling"
    # Floor clamp on a decrease.
    d = env.admit("r", 100.0, 10.0, floor=90.0, ceiling=1000.0, now_ms=0)
    assert d.allowed and d.value == 90.0 and d.reason == "floor"
    # Small thresholds keep an absolute minimum step of 1.0.
    d = env.admit("r", 2.0, 10.0, floor=1.0, ceiling=100.0, now_ms=0)
    assert d.value == 3.0  # 2 + max(2*0.25, 1.0)
    # Fully pinned at the band edge: not an actuation.
    d = env.admit("r", 110.0, 200.0, floor=1.0, ceiling=110.0, now_ms=0)
    assert not d.allowed and d.clamped and d.reason == "no-op"
    assert d.value == 110.0
    # LIVE value outside the band (operator emergency clamp below the
    # floor): NOTHING is admitted — clamping a congestion DECREASE up
    # to the floor would invert it into a 50x limit increase.
    d = env.admit("r", 1.0, 0.7, floor=50.0, ceiling=1000.0, now_ms=0)
    assert not d.allowed and d.clamped and d.reason == "floor"
    assert d.value == 1.0
    d = env.admit("r", 1.0, 2.0, floor=50.0, ceiling=1000.0, now_ms=0)
    assert not d.allowed and d.reason == "floor"  # increases too
    d = env.admit("r", 2000.0, 2500.0, floor=50.0, ceiling=1000.0, now_ms=0)
    assert not d.allowed and d.reason == "ceiling"


def test_envelope_cooldown_and_flip_hysteresis():
    env = SafetyEnvelope(step_pct=1.0, cooldown_ms=10_000)
    env.record_actuation("r", 100.0, 150.0, now_ms=0)  # direction +1
    # Inside the cooldown: any proposal is rejected.
    d = env.admit("r", 150.0, 200.0, 1.0, 1000.0, now_ms=5_000)
    assert not d.allowed and d.reason == "cooldown"
    # Past the cooldown, same direction proceeds...
    d = env.admit("r", 150.0, 200.0, 1.0, 1000.0, now_ms=12_000)
    assert d.allowed
    # ...but a direction FLIP waits out 2x the cooldown.
    d = env.admit("r", 150.0, 100.0, 1.0, 1000.0, now_ms=12_000)
    assert not d.allowed and d.reason == "hysteresis"
    d = env.admit("r", 150.0, 100.0, 1.0, 1000.0, now_ms=21_000)
    assert d.allowed
    # Other resources are unaffected throughout.
    assert env.admit("q", 10.0, 12.0, 1.0, 100.0, now_ms=1).allowed
    # Ops view reports remaining cooldown.
    assert "r" in env.cooldown_state(now_ms=4_000)
    assert env.cooldown_state(now_ms=60_000) == {}


def test_freeze_gate_truth_table():
    gate = FreezeGate(stale_after_ms=5_000)

    def ev(**kw):
        base = dict(manual_frozen=False, recorder_enabled=True,
                    last_second_ms=99_000, fault_delta=0,
                    backoff_until_ms=0)
        base.update(kw)
        return gate.evaluate(100_000, **base)

    assert not ev().frozen
    assert ev(manual_frozen=True).reason == "manual"
    assert ev(recorder_enabled=False).reason == "recorder-disabled"
    assert ev(last_second_ms=90_000).reason == "telemetry-stale"
    assert ev(last_second_ms=0).reason == "telemetry-stale"
    assert ev(fault_delta=1).reason == "telemetry-faulted"
    assert ev(backoff_until_ms=100_001).reason == "abort-backoff"
    # Precedence: manual wins over every other cause.
    assert ev(manual_frozen=True, last_second_ms=0,
              fault_delta=5).reason == "manual"
    # Boundary: exactly stale_after old is NOT stale; backoff expiry is
    # exclusive (now == until -> thawed).
    assert not ev(last_second_ms=95_000).frozen
    assert not ev(backoff_until_ms=100_000).frozen


def test_aimd_policy_increase_decrease_deadband():
    pol = AimdPolicy(increase_pct=0.5, decrease_pct=0.3, hysteresis_pct=0.1)
    target = AdaptiveTarget(resource="r", max_block_rate=0.10,
                            rt_p99_ms=100.0, min_entries=10)

    def sense(block_rate, rt=50.0, entries=100, completions=50):
        blocked = int(entries * block_rate)
        return ResourceSense(
            resource="r", seconds=2, passed=entries - blocked,
            blocked=blocked, completions=completions,
            block_rate=block_rate, rt_p99_ms=rt)

    # Blocking above target + band with healthy RT -> increase.
    assert pol.propose(sense(0.30), target, 100.0) == 150.0
    # Inside the deadband (0.10 + 0.01): no proposal either direction.
    assert pol.propose(sense(0.105), target, 100.0) is None
    assert pol.propose(sense(0.0), target, 100.0) is None
    # RT breach -> multiplicative decrease, even while block rate says
    # increase (congestion wins).
    assert pol.propose(sense(0.30, rt=200.0), target, 100.0) == 70.0
    # RT inside ITS deadband (100 * 1.1) does not trigger decrease.
    assert pol.propose(sense(0.0, rt=105.0), target, 100.0) is None
    # Quiet windows don't vote.
    assert pol.propose(sense(0.5, entries=5), target, 100.0) is None
    # No RT target -> RT never votes.
    avail_only = AdaptiveTarget(resource="r", max_block_rate=0.10)
    assert pol.propose(sense(0.0, rt=9_999.0), avail_only, 100.0) is None


def test_adaptive_target_converter_roundtrip_and_validation():
    t = CV.adaptive_target_from_dict({
        "resource": "getUser", "maxBlockRate": 0.05, "rtP99Ms": 250,
        "floor": 50, "ceiling": 5000, "minEntries": 16})
    d = CV.adaptive_target_to_dict(t)
    assert CV.adaptive_target_from_dict(d) == t
    assert json.loads(CV.adaptive_targets_to_json([t]))[0] == d
    # Defaults fill absent fields.
    t2 = CV.adaptive_target_from_dict({"resource": "x"})
    assert t2.max_block_rate == 0.05 and t2.floor == 1.0
    for bad in (
        {"resource": ""},                                # no resource
        {"resource": "x", "maxBlockRate": 1.5},          # rate >= 1
        {"resource": "x", "floor": 0},                   # floor <= 0
        {"resource": "x", "floor": 10, "ceiling": 5},    # inverted band
        {"resource": "x", "rtP99Ms": -1},                # negative RT
        {"resource": "x", "minEntries": -1},
        "not-a-dict",
    ):
        with pytest.raises(ValueError):
            CV.adaptive_target_from_dict(bad)
    with pytest.raises(ValueError):  # duplicate resources reject at load
        from sentinel_tpu.adaptive.controller import AdaptiveController
        AdaptiveController(AimdPolicy(0.1, 0.3, 0.1)).load_targets(
            [AdaptiveTarget(resource="x"), AdaptiveTarget(resource="x")])


# ---------------------------------------------------------------------------
# end-to-end closed loop (the acceptance differential)
# ---------------------------------------------------------------------------

def test_e2e_closed_loop_restores_target_within_bounded_steps(engine):
    """Scripted load shift: demand 16/s against a count=4 QPS rule
    (block rate 0.75). The loop must propose, shadow, canary, and
    promote retuned rule sets until the block rate is back at/below the
    0.05 target — and every live-rule write must pass through
    RolloutManager.promote (zero direct mutations)."""
    eng = engine
    calls = _spy_load_rules(eng)
    st.load_flow_rules([st.FlowRule(resource="ad", count=4)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="ad", max_block_rate=0.05, floor=1.0, ceiling=64.0,
        min_entries=8)])
    eng.adaptive.enable()
    now = BASE_MS
    promotions_seen = []
    # 40 driven seconds is far more than 2 full rollout cycles need;
    # the loop must converge well inside it.
    for _ in range(40):
        now = _drive(eng, "ad", 16, 1, now)
        _tick(eng, now)
        if eng.adaptive.promotion_count > len(promotions_seen):
            promotions_seen.append(_count_of(eng, "ad"))
        sense = eng.adaptive.status()["senses"].get("ad")
        if promotions_seen and sense and sense["blockRate"] <= 0.05 \
                and eng.adaptive.status()["inflight"] is None:
            break
    # Converged: 4 -> 8 -> 16 admits the full 16/s demand.
    assert promotions_seen == [8.0, 16.0]
    sense = eng.adaptive.status()["senses"]["ad"]
    assert sense["blockRate"] <= 0.05
    assert _count_of(eng, "ad") == 16.0
    # The decision log tells the whole story in order.
    kinds = [e["kind"] for e in eng.adaptive.history()["events"]]
    assert kinds.count("propose") >= 2
    assert kinds.count("canary") >= 2
    assert kinds.count("promote") == 2
    # Every live-rule application came from RolloutManager.promote.
    assert len(calls) >= 3  # initial load + 2 promotions
    for stack in calls[1:]:
        assert "rollout/manager.py" in stack and "in promote" in stack, \
            "live rules written outside RolloutManager.promote"
    # target_delta gauge went to <= 0 (no work left).
    assert eng.adaptive.target_deltas()["ad"] <= 0.0


def test_mirror_guardrail_abort_restores_last_known_good(engine):
    """The mirror differential: an RT-target-driven DECREASE candidate
    blocks more than live, breaches the block-rate-delta guardrail, and
    auto-aborts — live verdicts must equal the retained last-known-good
    rule set verdict-for-verdict, with zero non-rollout rule writes."""
    eng = engine
    calls = _spy_load_rules(eng)
    eng.rollout.abort_windows = 2
    st.load_flow_rules([st.FlowRule(resource="mir", count=8)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="mir", max_block_rate=0.5, rt_p99_ms=1.0,
        floor=1.0, ceiling=64.0, min_entries=8)])
    eng.adaptive.enable()
    lkg = eng.adaptive.last_known_good()
    assert lkg["flow"] == eng.flow_rules.get_rules()
    # Demand 8/s passes fully on live (count=8) but RT p99 ~ 50ms
    # breaches the absurd 1ms target -> the policy proposes 8 -> 5.6.
    now = _drive(eng, "mir", 8, 3, BASE_MS, rt_ms=50)
    out = _tick(eng, now)
    assert out["status"] == "proposed"
    name = out["candidate"]
    # Shadow ticks: baseline, then two breached windows -> auto-abort.
    statuses = []
    for _ in range(4):
        now = _drive(eng, "mir", 8, 1, now, rt_ms=50)
        statuses.append(_tick(eng, now)["status"])
        if statuses[-1] == "aborted":
            break
    assert "aborted" in statuses
    cand = eng.rollout.candidate(name)
    assert cand.stage == "aborted" and "guardrail" in cand.ended_reason
    # Books: abort counted, backoff armed, LKG verified intact.
    assert eng.adaptive.abort_count == 1
    abort_ev = [e for e in eng.adaptive.history()["events"]
                if e["kind"] == "abort"][0]
    assert abort_ev["lkgIntact"] is True
    # Live rules ARE the last-known-good set, field for field...
    assert eng.flow_rules.get_rules() == lkg["flow"]
    # ...and verdict-for-verdict: 12 demand against the restored
    # count=8 admits exactly 8 (the LKG threshold, not the candidate's).
    from tests.test_telemetry import _batch

    dec = eng.check_batch(_batch(eng, [("mir", "", None)] * 12),
                          now_ms=now)
    reasons = np.asarray(dec.reason)
    assert int((reasons == C.BlockReason.PASS).sum()) == 8
    assert int((reasons == C.BlockReason.FLOW).sum()) == 4
    # Zero direct mutations: only the initial load touched the managers.
    assert len(calls) == 1
    # Backoff: the unchanged RT breach proposes NOTHING for 30s.
    now = _drive(eng, "mir", 8, 1, now, rt_ms=50)
    out = _tick(eng, now)
    assert out == {"status": "frozen", "reason": "abort-backoff",
                   "timestamp": now}
    assert eng.adaptive.proposal_count == 1


# ---------------------------------------------------------------------------
# chaos: the loop freezes rather than actuates on bad senses
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_stale_telemetry_freezes_and_aborts_inflight(engine):
    """Blackholed/stale telemetry mid-rollout: the loop must freeze AND
    tear its in-flight candidate down through the rollout manager —
    promoting on senses nobody refreshed would be blind actuation."""
    eng = engine
    st.load_flow_rules([st.FlowRule(resource="stale", count=4)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="stale", max_block_rate=0.05, floor=1.0, ceiling=64.0,
        min_entries=8)])
    eng.adaptive.enable()
    now = _drive(eng, "stale", 16, 3, BASE_MS)
    out = _tick(eng, now)
    assert out["status"] == "proposed"
    name = out["candidate"]
    # The stream stops: 10 silent seconds > freeze.stale.seconds=5.
    now += 10_000
    out = _tick(eng, now)
    assert out["status"] == "frozen"
    assert out["reason"] == "telemetry-stale"
    cand = eng.rollout.candidate(name)
    assert cand.stage == "aborted"
    assert "telemetry-stale" in cand.ended_reason
    assert eng.rollout.active_set() is None
    # Frozen means READ-ONLY: repeated ticks propose nothing.
    out = _tick(eng, now + 2_000)
    assert out["status"] == "frozen"
    assert eng.adaptive.proposal_count == 1
    # Traffic resumes -> fresh seconds -> the loop thaws (backoff from
    # the freeze-abort still applies first — also a freeze state).
    kinds = [e["kind"] for e in eng.adaptive.history()["events"]]
    assert "freeze" in kinds and "abort" in kinds


@pytest.mark.chaos
def test_token_server_death_mid_loop_freezes_on_fault_channel(engine):
    """FaultInjector kills the token-server wire mid-loop: entries
    degrade to local fallback (counted on the engine's fault channels),
    and the NEXT tick freezes — the recorded series is missing exactly
    the traffic that misbehaved, so it must not actuate."""
    from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, \
        TokenResultStatus
    from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
    from sentinel_tpu.cluster.server import ClusterTokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.resilience import FaultInjector

    eng = engine
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="shared", count=1000.0, cluster_mode=True,
        cluster_config={"flowId": 910, "thresholdType": THRESHOLD_GLOBAL,
                        "fallbackToLocalWhenFail": True})])
    service = DefaultTokenService(rules=rules)
    server = ClusterTokenServer(service=service, host="127.0.0.1").start()
    try:
        st.load_flow_rules([st.FlowRule(
            resource="shared", count=100.0, cluster_mode=True,
            cluster_config={"flowId": 910,
                            "thresholdType": THRESHOLD_GLOBAL,
                            "fallbackToLocalWhenFail": True})])
        eng.adaptive.load_targets([AdaptiveTarget(
            resource="shared", max_block_rate=0.5, min_entries=4)])
        eng.adaptive.enable()
        eng.cluster.set_to_client("127.0.0.1", server.bound_port,
                                  request_timeout_s=2.0)
        import time as _time

        deadline = _time.monotonic() + 5
        while eng.cluster.client_if_active() is None \
                and _time.monotonic() < deadline:
            _time.sleep(0.01)
        client = eng.cluster.token_client
        # Warm the token service jit so the healthy phase is healthy.
        deadline = _time.monotonic() + 10
        while client.request_token(910).status != TokenResultStatus.OK \
                and _time.monotonic() < deadline:
            _time.sleep(0.05)
        # Healthy phase: entries pass remotely, the loop is thawed.
        now = BASE_MS
        for _ in range(3):
            time_util.freeze_time(now)
            with eng.entry("shared"):
                pass
            now += 1000
        time_util.freeze_time(now)
        out = _tick(eng, now)
        assert out["status"] != "frozen", out
        fallbacks0 = eng.cluster_fallback_count
        # Token-server death: every subsequent frame write raises.
        with FaultInjector(seed=7) as inj:
            inj.arm("cluster.client.send", "error")
            for _ in range(3):
                time_util.freeze_time(now)
                try:
                    with eng.entry("shared"):
                        pass
                except Exception:  # noqa: BLE001 — local verdict may block
                    pass
                now += 1000
        assert eng.cluster_fallback_count > fallbacks0
        time_util.freeze_time(now)
        out = _tick(eng, now)
        assert out["status"] == "frozen"
        assert out["reason"] == "telemetry-faulted"
        assert eng.adaptive.proposal_count == 0
    finally:
        server.stop()


@pytest.mark.chaos
@pytest.mark.slow  # ~30s of shadow/canary compiles; the rollout-level
# SLO abort is tier-1 in test_slo.py::test_slo_breach_aborts_rollout and
# the loop's abort bookkeeping is tier-1 in the mirror test above
def test_slo_page_mid_canary_aborts_and_backs_off(engine):
    """An SLO page firing while the adaptive candidate is enforcing its
    canary slice: the rollout SLO gate aborts IMMEDIATELY (no streak),
    the loop books the abort and enters backoff."""
    from sentinel_tpu.slo.objectives import BurnWindow, SloObjective

    eng = engine
    st.load_flow_rules([st.FlowRule(resource="pg", count=4)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="pg", max_block_rate=0.05, floor=1.0, ceiling=64.0,
        min_entries=8)])
    eng.adaptive.enable()
    now = _drive(eng, "pg", 16, 3, BASE_MS)
    assert _tick(eng, now)["status"] == "proposed"
    name = eng.adaptive.status()["inflight"]["candidate"]
    # Soak shadow to canary (two healthy windows + the 2s soak).
    for _ in range(4):
        now = _drive(eng, "pg", 16, 1, now)
        out = _tick(eng, now)
        if out["status"] == "canary":
            break
    assert eng.rollout.candidate(name).stage == "canary"
    # NOW the page arrives: objective loaded mid-flight, the sustained
    # blocking burns its budget instantly (min_events=1, burn 2x).
    eng.slo.load_objectives([SloObjective(
        resource="pg", objective=0.9, min_events=1,
        windows=(BurnWindow(10, 2, 2.0, "page"),))])
    now = _drive(eng, "pg", 16, 2, now)
    out = _tick(eng, now)
    assert out["status"] == "aborted"
    cand = eng.rollout.candidate(name)
    assert cand.stage == "aborted" and "slo:" in cand.ended_reason
    assert eng.adaptive.abort_count == 1
    assert eng.adaptive.promotion_count == 0
    # Live rules never moved; backoff holds.
    assert _count_of(eng, "pg") == 4.0
    assert _tick(eng, now + 1000)["reason"] == "abort-backoff"


@pytest.mark.slow  # ~30s (a full promote cycle + pinned steady-state);
# the no-flap invariants are tier-1 at unit level (envelope cooldown /
# flip-hysteresis / deadband tests above)
def test_no_oscillation_across_target_under_step_load(engine):
    """Step-load change with a binding ceiling: the loop walks the
    threshold UP to the ceiling and then goes quiet — no direction flip,
    no candidate churn at the band edge (one transition-logged reject),
    however long the over-target blocking persists."""
    eng = engine
    st.load_flow_rules([st.FlowRule(resource="osc", count=32)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="osc", max_block_rate=0.05, floor=1.0, ceiling=40.0,
        min_entries=8)])
    eng.adaptive.enable()
    # Phase 1: demand 16/s under a 32 limit — inside the deadband,
    # nothing proposed.
    now = _drive(eng, "osc", 16, 3, BASE_MS)
    assert _tick(eng, now)["status"] == "steady"
    # Phase 2: step to 48/s (3 batches of 16 per second).
    from tests.test_telemetry import _batch

    def burst(now):
        time_util.freeze_time(now)
        for _ in range(3):
            eng.check_batch(_batch(eng, [("osc", "", None)] * 16),
                            now_ms=now)
        return now + 1000

    directions = []
    last = _count_of(eng, "osc")
    for _ in range(30):
        now = burst(now)
        time_util.freeze_time(now)
        _tick(eng, now)
        cur = _count_of(eng, "osc")
        if cur != last:
            directions.append(1 if cur > last else -1)
            last = cur
    # Walked up to the ceiling, never down: monotone, no flapping.
    assert directions and all(d == 1 for d in directions)
    assert _count_of(eng, "osc") == 40.0
    # Pinned at the ceiling: proposals stopped (clamped no-ops), and
    # the reject is logged ONCE, not once per tick.
    rejects = [e for e in eng.adaptive.history()["events"]
               if e["kind"] == "reject" and e.get("reason") == "no-op"]
    assert len(rejects) == 1
    assert eng.adaptive.clamp_count >= 1
    st_now = eng.adaptive.status()
    assert st_now["inflight"] is None
    # Still honest about the residual gap: delta stays positive.
    assert eng.adaptive.target_deltas()["osc"] > 0


def test_active_alert_gates_proposals(engine):
    """Any active alert on a resource (a page here) vetoes proposals
    touching it — a proposal has no canary blast shield yet."""
    from sentinel_tpu.slo.objectives import BurnWindow, SloObjective

    eng = engine
    st.load_flow_rules([st.FlowRule(resource="al", count=4)])
    eng.slo.load_objectives([SloObjective(
        resource="al", objective=0.9, min_events=1,
        windows=(BurnWindow(10, 2, 2.0, "page"),))])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="al", max_block_rate=0.05, floor=1.0, ceiling=64.0,
        min_entries=8)])
    eng.adaptive.enable()
    now = _drive(eng, "al", 16, 4, BASE_MS)
    eng.slo_refresh(now_ms=now)
    assert eng.slo.active_alerts_on({"al"}), "breach never paged"
    out = _tick(eng, now)
    assert out["status"] == "steady"  # desire existed but was vetoed
    assert eng.adaptive.proposal_count == 0
    rejects = [e for e in eng.adaptive.history()["events"]
               if e["kind"] == "reject"]
    assert rejects and rejects[0]["reason"] == "alert-active"


def test_operator_candidate_wins_and_disable_aborts(engine):
    """A human-staged rollout holds the device: the loop skips instead
    of fighting it. And disable() tears the loop's own candidate down
    through the rollout manager."""
    eng = engine
    st.load_flow_rules([st.FlowRule(resource="op", count=4)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="op", max_block_rate=0.05, floor=1.0, ceiling=64.0,
        min_entries=8)])
    eng.adaptive.enable()
    eng.rollout.load_candidate(
        "human-v1", {"flow": [{"resource": "other", "count": 5}]})
    now = _drive(eng, "op", 16, 3, BASE_MS)
    out = _tick(eng, now)
    assert out["status"] == "skipped"
    assert eng.rollout.active_name == "human-v1"
    eng.rollout.abort("human-v1")
    # Now the loop proposes; disable aborts its in-flight candidate.
    now = _drive(eng, "op", 16, 2, now)
    out = _tick(eng, now)
    assert out["status"] == "proposed"
    name = out["candidate"]
    eng.adaptive.disable()
    cand = eng.rollout.candidate(name)
    assert cand.stage == "aborted" and "disabled" in cand.ended_reason
    assert _tick(eng, now + 1000) == {"status": "disabled"}


# ---------------------------------------------------------------------------
# surfaces: ops command, exporter, resilience_stats, A/B device guard
# ---------------------------------------------------------------------------

def test_adaptive_ops_command_roundtrip(engine):
    from sentinel_tpu.transport.command_center import CommandRequest
    from sentinel_tpu.transport.handlers import cmd_adaptive

    eng = engine

    def run(params, body=""):
        resp = cmd_adaptive(CommandRequest(parameters=params, body=body,
                                           engine=eng))
        assert resp.success, resp.result
        return json.loads(resp.result) if resp.result else None

    assert run({"op": "enable"}) == {"enabled": True}
    out = run({"op": "set"}, body=json.dumps([
        {"resource": "cmd", "maxBlockRate": 0.1, "floor": 2,
         "ceiling": 20}]))
    assert out == {"loaded": 1}
    got = run({"op": "get"})
    assert got[0]["resource"] == "cmd" and got[0]["floor"] == 2.0
    status = run({"op": "status"})
    assert status["enabled"] and not status["frozen"]
    assert status["targets"][0]["resource"] == "cmd"
    assert run({"op": "freeze", "reason": "drill"}) == {"frozen": True}
    assert run({"op": "status"})["frozen"] is True
    assert run({"op": "status"})["freezeReason"] == "manual"
    assert run({"op": "tick"})["status"] == "frozen"
    assert run({"op": "unfreeze"}) == {"frozen": False}
    hist = run({"op": "history"})
    kinds = [e["kind"] for e in hist["events"]]
    assert "enabled" in kinds and "freeze" in kinds and "unfreeze" in kinds
    # sinceSeq cursor is strictly-after; limit=0 returns cursor only.
    assert run({"op": "history", "sinceSeq": str(hist["nextSeq"])})[
        "events"] == []
    assert run({"op": "history", "limit": "0"})["events"] == []
    assert run({"op": "disable"}) == {"enabled": False}
    bad = cmd_adaptive(CommandRequest(parameters={"op": "nope"},
                                      engine=eng))
    assert not bad.success
    bad = cmd_adaptive(CommandRequest(parameters={"op": "set"},
                                      body="[{\"resource\": \"\"}]",
                                      engine=eng))
    assert not bad.success


def test_exporter_renders_adaptive_families(engine):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    eng = engine
    st.load_flow_rules([st.FlowRule(resource="mx", count=4)])
    eng.adaptive.load_targets([AdaptiveTarget(
        resource="mx", max_block_rate=0.05, floor=1.0, ceiling=64.0,
        min_entries=8)])
    eng.adaptive.enable()
    now = _drive(eng, "mx", 16, 3, BASE_MS)
    _tick(eng, now)
    text = render_engine_metrics(eng)
    assert "sentinel_tpu_adaptive_enabled 1" in text
    assert "sentinel_tpu_adaptive_frozen 0" in text
    assert "sentinel_tpu_adaptive_proposals_total 1" in text
    assert "sentinel_tpu_adaptive_promotions_total 0" in text
    assert "sentinel_tpu_adaptive_aborts_total 0" in text
    assert 'sentinel_tpu_adaptive_target_delta{resource="mx"}' in text
    # resilience_stats carries the same compact slice.
    ad = eng.resilience_stats()["adaptive"]
    assert ad["enabled"] and ad["proposals"] == 1
    assert ad["inflightCandidate"] == "adaptive-1"


def test_adaptive_loop_adds_no_device_work():
    """A/B guard (the bench phase's tier-1 twin): the same driven
    stream with the loop enabled-but-steady dispatches the SAME device
    programs as with it disabled — sensing is host arithmetic riding
    the once-per-second fold."""
    from tests.test_telemetry import _batch

    def run(with_adaptive):
        from sentinel_tpu.core.config import config
        from sentinel_tpu.core.context import replace_context

        config.set("csp.sentinel.adaptive.interval.seconds", "1")
        replace_context(None)
        eng = st.reset(capacity=256)
        st.load_flow_rules([st.FlowRule(resource="ab", count=64)])
        if with_adaptive:
            eng.adaptive.load_targets([AdaptiveTarget(
                resource="ab", max_block_rate=0.5, min_entries=8)])
            eng.adaptive.enable()
        now = BASE_MS
        for _ in range(5):
            time_util.freeze_time(now)
            eng._run_entry_batch(_batch(eng, [("ab", "", None)] * 8))
            eng.slo_refresh(now_ms=now)  # the fold ride ticks the loop
            now += 1000
        time_util.freeze_time(now)
        eng.slo_refresh(now_ms=now)
        dispatches = {k: v["dispatches"]
                      for k, v in eng.step_timer.snapshot().items()}
        sensed = len(eng.adaptive.status()["senses"])
        return dispatches, sensed

    time_util.freeze_time(BASE_MS)
    try:
        base, _ = run(False)
        with_loop, sensed = run(True)
    finally:
        time_util.unfreeze_time()
        from sentinel_tpu.core.config import config

        config.reset_for_tests()
        st.reset(capacity=512)
    assert sensed == 1, "the A/B run never exercised sensing"
    assert with_loop == base
